# ruff: noqa
"""Non-firing twin: snapshots and atomic lengths only."""


class Batcher:
    def __init__(self):
        self.running = {}  # owner: engine
        self.pool = None   # owner: engine

    def kv_stats(self):
        # engine-side snapshot method: list() before iterating
        return {"in_use": len(list(self.running))}


class Scheduler:
    """The serving/scheduler.py shape: every ledger is engine-owned and
    crosses threads only through the sched_stats() snapshot (or the
    queue-cap check's atomic len, computed by the caller)."""

    def __init__(self):
        self._tenants = {}     # owner: engine
        self.rejections = {}   # owner: engine

    def sched_stats(self):
        # engine-state snapshot: list() before iterating, plain copies out
        return {"tenants": {k: dict(v) for k, v in list(self._tenants.items())}}


class Recorder:
    """The obs/attribution.py shape: engine-owned rings cross threads
    only through the *_stats() snapshot methods."""

    def __init__(self):
        self._slow_ring = []  # owner: engine
        self._recent = []     # owner: engine

    def slow_stats(self):
        # engine-state snapshot: list() before iterating, copies out
        return {"requests": [dict(r) for r in list(self._slow_ring)]}


class Supervisor:
    """The serving/supervisor.py shape: restart/crash ledgers are
    engine-owned (recover() runs in the crashed loop's except block);
    /v1/health crosses the boundary only through the stats() snapshot."""

    def __init__(self):
        self._restart_times = []   # owner: engine
        self._last_crash = None    # owner: engine

    def stats(self):
        # engine-state snapshot: plain copies out
        return {
            "restarts": len(list(self._restart_times)),
            "last_crash": (
                dict(self._last_crash) if self._last_crash else None
            ),
        }


class FleetRegistry:
    """The serving/fleet.py shape: handlers cross into the replica map
    only through the single fleet_stats() snapshot accessor."""

    def __init__(self):
        self._replicas = {}  # owner: engine

    def fleet_stats(self):
        # snapshot accessor: list() before iterating, plain copies out
        return {"replicas": {k: dict(v) for k, v in
                             list(self._replicas.items())}}


class Journal:
    """The plugin/journal.py shape: the event rings and the ownership
    table cross out of the manager loop only through the
    events_payload()/owners() snapshot accessors."""

    def __init__(self):
        self._events = []  # owner: engine
        self._owners = {}  # owner: engine

    def events_payload(self):
        # manager-state snapshot: list() before iterating, copies out
        return {
            "total": len(list(self._events)),
            "events": [dict(e) for e in list(self._events)],
            "owners": {k: dict(v) for k, v in list(self._owners.items())},
        }


class Server:
    def __init__(self, cb, sched, rec, sup, fleet, journal):
        self.cb = cb
        self.sched = sched
        self.rec = rec
        self.sup = sup
        self.fleet = fleet
        self.journal = journal

    async def health(self, request):
        return {
            "active": len(self.cb.running),  # atomic len: sanctioned
            "kv": self.cb.kv_stats(),        # the snapshot boundary
            "sched": self.sched.sched_stats(),  # ditto for the scheduler
            "supervisor": self.sup.stats(),  # ditto for the supervisor
        }

    async def fleet_health(self, request):
        # the PR-15 discipline: ONE snapshot accessor for the whole
        # fleet-health surface, no inline per-replica recomputation
        return self.fleet.fleet_stats()

    async def allocations(self, request):
        return {
            "resident": len(self.journal._events),  # atomic len: sanctioned
            **self.journal.events_payload(),        # the journal boundary
        }

    async def slow(self, request):
        return self.rec.slow_stats()  # the flight-recorder boundary

    def stats(self):  # graftlint: cross-thread
        return {"queued": len(self.cb.running)}
