# ruff: noqa
"""Non-firing twin: snapshots and atomic lengths only."""


class Batcher:
    def __init__(self):
        self.running = {}  # owner: engine
        self.pool = None   # owner: engine

    def kv_stats(self):
        # engine-side snapshot method: list() before iterating
        return {"in_use": len(list(self.running))}


class Server:
    def __init__(self, cb):
        self.cb = cb

    async def health(self, request):
        return {
            "active": len(self.cb.running),  # atomic len: sanctioned
            "kv": self.cb.kv_stats(),        # the snapshot boundary
        }

    def stats(self):  # graftlint: cross-thread
        return {"queued": len(self.cb.running)}
