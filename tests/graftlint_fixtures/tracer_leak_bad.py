# ruff: noqa
"""Firing fixture: host-state writes from inside traced bodies."""
from functools import partial

import jax

_COUNTS = {"steps": 0}


@partial(jax.jit, donate_argnums=(0,))
def bad_step(state, x):
    _COUNTS["steps"] += 1  # BAD: module global mutated at trace time
    state.cache = x        # BAD: attribute write on a parameter
    return state


@jax.jit
def bad_global(x):
    global _TOTAL          # BAD: global declared in a traced body
    _TOTAL = x
    return x


def outer(xs):
    def body(carry, x):
        _COUNTS["last"] = x  # BAD: scan bodies trace like jit bodies
        return carry, x

    return jax.lax.scan(body, 0, xs)
