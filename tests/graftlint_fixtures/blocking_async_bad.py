# ruff: noqa
"""Firing fixture: blocking work on the event loop."""
import subprocess
import time

import jax
import requests


async def handler(request):
    time.sleep(0.5)                  # BAD: stalls every stream
    jax.device_get(request.arr)      # BAD: device sync on the loop
    request.arr.block_until_ready()  # BAD: same, method form
    subprocess.run(["ls"])           # BAD: sync subprocess
    request.task.result()            # BAD: concurrent.futures wait
    request.stop_event.wait()        # BAD: threading.Event wait
    with open("/tmp/x") as f:        # BAD: sync file I/O
        return f.read()


async def proxy_handler(request, replica):
    """The replica router's proxy shape (serving/router.py): a sync
    HTTP client or a sync backoff wait in a fan-out handler stalls
    EVERY stream the router is relaying, not just this request's."""
    raw = await request.read()
    resp = requests.post(            # BAD: sync HTTP to the backend
        f"{replica.url}{request.path}", data=raw,
    )
    if resp.status_code == 429:
        time.sleep(1.0)              # BAD: sync Retry-After backoff
        resp = requests.post(        # BAD: the retry blocks too
            f"{replica.url}{request.path}", data=raw,
        )
    return resp.content
