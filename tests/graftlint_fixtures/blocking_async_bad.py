# ruff: noqa
"""Firing fixture: blocking work on the event loop."""
import subprocess
import time

import jax


async def handler(request):
    time.sleep(0.5)                  # BAD: stalls every stream
    jax.device_get(request.arr)      # BAD: device sync on the loop
    request.arr.block_until_ready()  # BAD: same, method form
    subprocess.run(["ls"])           # BAD: sync subprocess
    request.task.result()            # BAD: concurrent.futures wait
    request.stop_event.wait()        # BAD: threading.Event wait
    with open("/tmp/x") as f:        # BAD: sync file I/O
        return f.read()
