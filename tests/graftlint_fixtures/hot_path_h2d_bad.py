# ruff: noqa
"""Firing fixture: per-step H2D transfers inside registered hot paths."""
import jax
import jax.numpy as jnp
import numpy as np


class Batcher:
    def _decode_dispatch(self, allowed):  # graftlint: hot-path
        knobs = jnp.asarray(self._knob_list)       # BAD: per-step H2D
        dev = jax.device_put(np.asarray(allowed))  # BAD: two more
        mask = jnp.zeros(4, bool)                  # BAD: host-side build
        return self.step(knobs, dev, mask)

    def step(self, *args):  # graftlint: hot-path
        return args

    def _apply_decode_result(self, arrs):  # graftlint: hot-path
        self._budget -= 1  # BAD: host scalar carry, re-fed to a hot call
        return self.step(self._budget)

    def _step_inner(self):  # graftlint: hot-path
        # BAD: re-uploading the (replicated) page table every step —
        # the tp serving path commits it once at admission; a per-step
        # device_put would re-transfer the whole table per token
        pages = jax.device_put(self._page_table_np, self._sharding)
        return self.step(pages)

    def _prefill_grow_row(self, slot):  # graftlint: hot-path
        # BAD: rebuilding + uploading the grown page-table row inside
        # the prefill dispatch hot path — streaming chunk-prefill
        # commits the grown row on the admission-style growth seam
        # (_grow_slot_pages, one upload per chunk as the cursor
        # advances), never per dispatch
        row = jax.device_put(self._grown_row_np)
        return self.step(row, slot)

    def _gather_adapters_step(self, sel):  # graftlint: hot-path
        # BAD: re-uploading the gathered (L, K, d_in, R) LoRA stacks
        # per decode step — the gathered multi-LoRA path commits the
        # compact stacks at admission time (the sel-rebuild seam,
        # _ensure_gathered) and steady-state decode reads the cached
        # device residents; a per-step upload of the adapter blocks
        # would dwarf the step dispatch itself
        stacks = jax.device_put(self._adapter_host_blocks)
        return self.step(stacks, sel)


def serving_cache_attention(q, k, v, length, table):  # graftlint: hot-path=traced
    # the unified-kernel dispatch seam is TRACED (it runs inside the
    # serving jits), where constructors are trace-time constants — but
    # an explicit H2D materializer is still wrong: a host-built table
    # smuggled in here would re-enter the trace as a fresh constant on
    # every shape and re-upload on every dispatch cache miss
    table = jnp.asarray(table)          # BAD: H2D even in a traced seam
    return q, k, v, length, table
