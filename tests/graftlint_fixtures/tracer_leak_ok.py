# ruff: noqa
"""Non-firing twin: purely functional traced bodies; host writes stay
on the host side of the jit boundary."""
from functools import partial

import jax

_COUNTS = {"steps": 0}


@partial(jax.jit, donate_argnums=(0,))
def good_step(state, x):
    new = state.replace(cache=x)  # functional update, returned in carry
    return new


def outer(xs):
    def body(carry, x):
        return carry + x, x

    total, ys = jax.lax.scan(body, 0, xs)
    _COUNTS["steps"] += 1  # host code AFTER the traced call: fine
    return total, ys
