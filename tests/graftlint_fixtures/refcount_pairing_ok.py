# ruff: noqa
"""Non-firing twin: every retain reaches a store, return, or decref."""


class Holder:
    def reserve(self, req, n):
        req._new_pages = self.pool.alloc(n)  # retain-and-record, atomic

    def pin(self, req, entry):
        pin = list(entry.page_ids)
        self.pool.incref(pin)
        req._pinned = pin  # next statement, no raise window

    def extract(self, slot_pages, n):
        ids = tuple(slot_pages[:n])
        self.pool.incref(ids)
        return ids  # ownership handed to the caller

    def transfer(self, req, slot):
        # ownership chain: _new_pages -> _slot_pages (drained below)
        ids = req._new_pages
        self._slot_pages[slot] = ids

    def release(self, req, slot):
        ids = self._slot_pages.pop(slot, None)
        if ids:
            self.pool.decref(ids)
        pins = req._pinned
        if pins:
            self.pool.decref(pins)
        more = req._new_pages
        if more:
            self.pool.decref(more)
