"""Remat policy dial (LlamaConfig.remat_policy): every setting must be a
pure scheduling choice — same loss, same gradients — and an unknown value
must fail loudly. The hardware payoff is measured by the ``remat_tune``
bench workload; these tests pin the property that makes the sweep safe to
apply: switching policies can never change what the model computes.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.train import loss_fn, synthetic_batch
from k8s_gpu_device_plugin_tpu.models.llama import init_params


def _loss_and_grads(cfg):
    params = init_params(jax.random.key(0), cfg)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh=None)

    def scalar_loss(p):
        loss, _ = loss_fn(p, batch, cfg, mesh=None, with_accuracy=False)
        return loss

    loss, grads = jax.jit(jax.value_and_grad(scalar_loss))(params)
    return float(loss), grads


def test_remat_policies_are_numerics_identical():
    base = LlamaConfig.tiny()
    ref_loss, ref_grads = _loss_and_grads(base)
    assert np.isfinite(ref_loss)

    variants = [
        replace(base, remat_policy="save_dots"),
        replace(base, remat_policy="save_nothing"),
        replace(base, remat=False),  # save everything / no checkpoint
    ]
    for cfg in variants:
        loss, grads = _loss_and_grads(cfg)
        # same ops, different schedule: bitwise-equal loss and grads
        assert loss == ref_loss, cfg.remat_policy
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            grads, ref_grads,
        )


def test_unknown_remat_policy_rejected():
    with pytest.raises(ValueError, match="remat_policy"):
        LlamaConfig.tiny(remat_policy="save_everything")


def test_remat_tune_sweep_machinery():
    """The hardware sweep's plumbing, on CPU with a tiny config: every
    variant reports a time or an error string, a broken variant doesn't
    kill the sweep, and 'best' picks among the measured ones."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.train_bench import (
        REMAT_VARIANTS,
        remat_tune,
    )

    variants = REMAT_VARIANTS + (
        ("broken", {"remat_policy": "not_a_policy"}),  # fails in replace()
    )
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec

    r = remat_tune(
        LlamaConfig.tiny(), batch_size=2, seq_len=32, steps=1, warmup=1,
        variants=variants, mesh_spec=MeshSpec(),  # single device: fast CPU
        devices=jax.devices()[:1],
    )
    assert set(r["step_ms"]) == {n for n, _ in variants}
    assert r["step_ms"]["broken"].startswith("error:")
    measured = {k: v for k, v in r["step_ms"].items() if not isinstance(v, str)}
    assert len(measured) == len(REMAT_VARIANTS)  # all real variants ran
    assert r["best"] in measured
    assert set(r["mfu_pct"]) == set(measured)
