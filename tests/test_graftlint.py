"""graftlint: framework + per-checker fixture tests.

Each checker must FIRE on its ``tests/graftlint_fixtures/*_bad.py``
fixture and stay SILENT on the ``*_ok.py`` twin; the framework tests
pin suppressions, baseline matching (incl. strict-mode stale refusal),
and the CLI contract `make analyze` relies on (exit codes + the
one-line JSON summary)."""

import json
import os
import subprocess
import sys

import pytest

from tools.graftlint.checkers import ALL_CHECKERS
from tools.graftlint.core import (
    load_baseline,
    load_project,
    run_checkers,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "graftlint_fixtures")

CHECKER_FIXTURE = {
    "hot-path-h2d": "hot_path_h2d",
    "jit-recompile-hazard": "jit_recompile",
    "tracer-leak": "tracer_leak",
    "thread-ownership": "thread_ownership",
    "refcount-pairing": "refcount_pairing",
    "blocking-in-async": "blocking_async",
}


def _checker(name):
    return next(c for c in ALL_CHECKERS if c.name == name)


def _run_on(path, checkers=None):
    project = load_project([path], root=REPO)
    new, baselined, stale = run_checkers(
        project, checkers or ALL_CHECKERS, baseline={}
    )
    return new


# --- one firing and one non-firing fixture per checker --------------------


@pytest.mark.parametrize("rule", sorted(CHECKER_FIXTURE))
def test_checker_fires_on_bad_fixture(rule):
    bad = os.path.join(FIXTURES, CHECKER_FIXTURE[rule] + "_bad.py")
    found = _run_on(bad, [_checker(rule)])
    assert found, f"{rule} must fire on its bad fixture"
    assert all(v.rule == rule for v in found)


@pytest.mark.parametrize("rule", sorted(CHECKER_FIXTURE))
def test_checker_silent_on_ok_fixture(rule):
    ok = os.path.join(FIXTURES, CHECKER_FIXTURE[rule] + "_ok.py")
    found = _run_on(ok, [_checker(rule)])
    assert found == [], f"{rule} false-positives: {found}"


def test_ok_fixtures_clean_under_every_checker():
    """The ok twins must survive the WHOLE suite, not just their own
    rule (a fixture that trips a neighboring checker would poison the
    pointed-at-fixtures failure test with the wrong rule)."""
    for stem in sorted(CHECKER_FIXTURE.values()):
        ok = os.path.join(FIXTURES, stem + "_ok.py")
        assert _run_on(ok) == []


# --- specific findings the fixtures encode --------------------------------


def test_hot_path_flags_transfer_and_carry():
    bad = os.path.join(FIXTURES, "hot_path_h2d_bad.py")
    keys = {v.key for v in _run_on(bad, [_checker("hot-path-h2d")])}
    assert "jnp.asarray" in keys
    assert "jax.device_put" in keys
    # the constructor family is H2D on the HOST side of a hot path...
    assert "jnp.zeros" in keys
    assert "carry:_budget" in keys
    # ...but a trace-time constant in jitted/traced hot paths: the ok
    # fixture's hot-path=traced function uses jnp.arange and stays
    # silent (covered by test_checker_silent_on_ok_fixture)
    # the kernel-dispatch seam (serving_cache_attention, traced): an
    # explicit H2D materializer fires even under the traced marker —
    # pinned by the bad fixture's traced dispatch function carrying its
    # own jnp.asarray (the ok twin's jnp.full stays silent)
    traced_disp = [
        v for v in _run_on(bad, [_checker("hot-path-h2d")])
        if v.symbol == "serving_cache_attention"
    ]
    assert {v.key for v in traced_disp} == {"jnp.asarray"}
    # the adapter-gather seam: a per-step upload of the compact LoRA
    # stacks inside a registered hot path fires (the ok twin's cached-
    # resident read + unmarked _ensure_gathered regather stay silent,
    # covered by test_checker_silent_on_ok_fixture)
    gather = [
        v for v in _run_on(bad, [_checker("hot-path-h2d")])
        if v.symbol.endswith("_gather_adapters_step")
    ]
    assert {v.key for v in gather} == {"jax.device_put"}
    # the chunk-growth reservation seam: uploading the grown page-table
    # row inside the prefill dispatch hot path fires; the ok twin's
    # host free-list math (window arithmetic, no device touch) and its
    # admission-style _grow_slot_pages upload stay silent (covered by
    # test_checker_silent_on_ok_fixture — the baseline stays EMPTY for
    # this rule, pinned by test_checked_in_baseline_is_valid_and_justified)
    grow = [
        v for v in _run_on(bad, [_checker("hot-path-h2d")])
        if v.symbol.endswith("_prefill_grow_row")
    ]
    assert {v.key for v in grow} == {"jax.device_put"}


def test_thread_ownership_allows_atomic_len():
    bad = os.path.join(FIXTURES, "thread_ownership_bad.py")
    found = _run_on(bad, [_checker("thread-ownership")])
    # the len(self.cb.running), len(self.sup._restart_times),
    # len(self.fleet._replicas) and len(self.journal._events) reads on
    # the handlers must NOT fire; the iteration/copy/pool reads must —
    # the scheduler-shaped ledger reads (serving/scheduler.py state),
    # the flight-recorder ring (obs/attribution.py state), the
    # supervisor's crash-recovery ledgers (serving/supervisor.py
    # state), the fleet registry's replica map recomputed inline
    # (serving/fleet.py state — the PR-15 /fleet/health fix) and the
    # allocation journal's event ring + ownership table
    # (plugin/journal.py state — the PR-16 /debug/allocations surface)
    # fire the same way
    assert len(found) == 11
    assert {v.key for v in found} == {
        "running", "pool", "_tenants", "rejections", "_slow_ring",
        "_last_crash", "_restart_times", "_replicas", "_events",
        "_owners",
    }


def test_thread_ownership_ignores_method_lookups(tmp_path):
    """The owned-name match is receiver-blind, so METHOD calls that
    merely share a name with owned state (task.done(), fut.wait()) must
    not fire — only reads of the attribute as data do."""
    f = tmp_path / "serving" / "h.py"
    f.parent.mkdir()
    f.write_text(
        "class B:\n"
        "    def __init__(self):\n"
        "        self.done = {}  # owner: engine\n"
        "async def h(task, cb):\n"
        "    if task.done():\n"          # method call: exempt
        "        return None\n"
        "    return cb.done\n"           # data read: fires
    )
    found = _run_on(str(f), [_checker("thread-ownership")])
    assert len(found) == 1 and found[0].line == 7


def test_blocking_async_result_wait_only_when_not_awaited():
    bad = os.path.join(FIXTURES, "blocking_async_bad.py")
    keys = {v.key for v in _run_on(bad, [_checker("blocking-in-async")])}
    assert "(...).result" in keys and "(...).wait" in keys
    # the ok twin awaits its Event.wait(): covered by
    # test_checker_silent_on_ok_fixture staying green


def test_refcount_distinguishes_the_four_shapes():
    bad = os.path.join(FIXTURES, "refcount_pairing_bad.py")
    keys = {v.key for v in _run_on(bad, [_checker("refcount-pairing")])}
    assert "alloc-dropped" in keys
    assert any(k.startswith("raise-window") for k in keys)
    assert "alloc-dropped-at-return" in keys
    assert "undrained:_lost" in keys


def test_jit_recompile_covers_each_hazard():
    bad = os.path.join(FIXTURES, "jit_recompile_bad.py")
    keys = {v.key for v in _run_on(bad, [_checker("jit-recompile-hazard")])}
    assert keys >= {
        "jit-immediately-invoked", "jit-in-loop", "jit-method",
        "jit-closure-self", "static-missing:cfg",
        "static-unhashable:shapes",
        # static_argnums resolves to the positional param's name; an
        # out-of-range index surfaces through the missing-param arm
        "static-unhashable:cfgs", "static-missing:<argnum 5>",
    }


# --- suppressions ---------------------------------------------------------


def test_inline_suppression_silences_one_line(tmp_path):
    src = (
        "import time\n"
        "async def h(request):\n"
        "    time.sleep(1)  # graftlint: disable=blocking-in-async\n"
        "    time.sleep(2)\n"
    )
    f = tmp_path / "supp.py"
    f.write_text(src)
    found = _run_on(str(f), [_checker("blocking-in-async")])
    assert len(found) == 1 and found[0].line == 4


def test_comment_line_suppression_covers_next_line(tmp_path):
    src = (
        "import time\n"
        "async def h(request):\n"
        "    # graftlint: disable=blocking-in-async\n"
        "    time.sleep(1)\n"
    )
    f = tmp_path / "supp2.py"
    f.write_text(src)
    assert _run_on(str(f), [_checker("blocking-in-async")]) == []


def test_trailing_suppression_does_not_bleed_downward(tmp_path):
    src = (
        "import time\n"
        "async def h(request):\n"
        "    x = 1  # graftlint: disable=blocking-in-async\n"
        "    time.sleep(1)\n"
    )
    f = tmp_path / "supp3.py"
    f.write_text(src)
    assert len(_run_on(str(f), [_checker("blocking-in-async")])) == 1


# --- baseline semantics ---------------------------------------------------


def _one_violation_project(tmp_path):
    f = tmp_path / "v.py"
    f.write_text(
        "import time\nasync def h(request):\n    time.sleep(1)\n"
    )
    return load_project([str(f)], root=str(tmp_path))


def test_baseline_matches_by_fingerprint_not_line(tmp_path):
    project = _one_violation_project(tmp_path)
    baseline = {"blocking-in-async": [{
        "path": "v.py", "symbol": "h", "key": "time.sleep",
        "reason": "fixture",
    }]}
    new, baselined, stale = run_checkers(
        project, [_checker("blocking-in-async")], baseline
    )
    assert new == [] and len(baselined) == 1 and stale == []


def test_stale_baseline_entries_are_reported(tmp_path):
    project = _one_violation_project(tmp_path)
    baseline = {"blocking-in-async": [
        {"path": "v.py", "symbol": "h", "key": "time.sleep",
         "reason": "fixture"},
        {"path": "v.py", "symbol": "h", "key": "jax.device_get",
         "reason": "fixed long ago: same file, no longer fires"},
    ]}
    _new, _baselined, stale = run_checkers(
        project, [_checker("blocking-in-async")], baseline
    )
    assert len(stale) == 1 and stale[0]["key"] == "jax.device_get"


def test_staleness_is_scoped_to_the_analyzed_paths(tmp_path):
    """A subset run (ANALYZE_PATHS=...) must not misread baseline
    entries for UNANALYZED files as fixed — strict mode over one module
    would otherwise spuriously fail on the rest of the baseline."""
    project = _one_violation_project(tmp_path)
    baseline = {"blocking-in-async": [
        {"path": "v.py", "symbol": "h", "key": "time.sleep",
         "reason": "fixture"},
        {"path": "elsewhere/not_analyzed.py", "symbol": "g",
         "key": "time.sleep", "reason": "lives outside this subset"},
    ]}
    new, baselined, stale = run_checkers(
        project, [_checker("blocking-in-async")], baseline
    )
    assert new == [] and len(baselined) == 1 and stale == []


def test_baseline_count_bounds_same_fingerprint_violations(tmp_path):
    """Fingerprints exclude line numbers (they drift), so the per-entry
    ``count`` is what keeps a NEW violation with an old fingerprint
    from hiding behind the grandfathered one."""
    f = tmp_path / "v.py"
    f.write_text(
        "import time\n"
        "async def h(request):\n"
        "    time.sleep(1)\n"
        "    time.sleep(2)\n"   # second site, same fingerprint
    )
    project = load_project([str(f)], root=str(tmp_path))
    entry = {"path": "v.py", "symbol": "h", "key": "time.sleep",
             "reason": "grandfathered single site", "count": 1}
    new, baselined, stale = run_checkers(
        project, [_checker("blocking-in-async")],
        {"blocking-in-async": [entry]},
    )
    assert len(baselined) == 1 and len(new) == 1  # the excess surfaces
    # raising the count absorbs both; an over-count reads as stale
    new2, baselined2, stale2 = run_checkers(
        project, [_checker("blocking-in-async")],
        {"blocking-in-async": [dict(entry, count=3)]},
    )
    assert new2 == [] and len(baselined2) == 2
    assert len(stale2) == 1 and stale2[0]["fired"] == 2


def test_baseline_requires_reasons(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"r": [{"path": "x.py", "key": "k"}]}))
    with pytest.raises(ValueError, match="reason"):
        load_baseline(str(p))


def test_checked_in_baseline_is_valid_and_justified():
    base = load_baseline(
        os.path.join(REPO, "tools", "graftlint", "baseline.json")
    )
    for rule, entries in base.items():
        assert rule in CHECKER_FIXTURE  # only registered rules
        for e in entries:
            assert len(e["reason"]) > 20  # a real sentence, not "ok"
    # the two invariants PRs 2 and 4 claim outright must hold with NO
    # grandfathering (the acceptance bar for this suite)
    assert "hot-path-h2d" not in base
    assert "thread-ownership" not in base


# --- the tree as shipped, and the CLI contract ----------------------------


def _cli(args, env=None):
    e = dict(os.environ)
    e.pop("GRAFTLINT_STRICT", None)
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint", *args],
        cwd=REPO, capture_output=True, text=True, env=e, timeout=120,
    )


def test_tree_as_shipped_is_clean_strict():
    """`make analyze` must pass on the tree: zero new violations AND no
    stale baseline, over the same default paths the Makefile uses."""
    r = _cli(["--strict"])
    assert r.returncode == 0, r.stdout + r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["violations"] == 0
    assert summary["rules"] == len(ALL_CHECKERS) == 6
    assert summary["files"] > 100  # really walked the tree


def test_seeded_fixture_fails_the_suite_when_pointed_at_it():
    r = _cli(["--no-baseline", os.path.join("tests", "graftlint_fixtures")])
    assert r.returncode == 1
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["violations"] > 0


def test_strict_env_var_refuses_stale_baseline(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    stale = tmp_path / "stale.json"
    # the entry names the ANALYZED file (staleness is path-scoped) but
    # no longer fires in it
    rel = os.path.relpath(str(clean), REPO).replace(os.sep, "/")
    stale.write_text(json.dumps({"blocking-in-async": [{
        "path": rel, "symbol": "h", "key": "time.sleep",
        "reason": "this entry no longer fires anywhere",
    }]}))
    relaxed = _cli([str(clean), "--baseline", str(stale)])
    assert relaxed.returncode == 0  # stale tolerated without strict
    strict = _cli([str(clean), "--baseline", str(stale)],
                  env={"GRAFTLINT_STRICT": "1"})
    assert strict.returncode == 1
    assert "stale" in strict.stdout


def test_cli_errors_on_missing_paths():
    """A typo'd path must error loudly, not silently shrink coverage —
    violations:0 over the subset that happened to exist would read as
    'checked everything'."""
    ok_file = os.path.join("tests", "graftlint_fixtures",
                           "blocking_async_ok.py")
    r = _cli([ok_file, "tests_typo_dir"])
    assert r.returncode == 2
    assert "tests_typo_dir" in r.stderr


def test_cli_json_mode_and_list():
    r = _cli(["--json", os.path.join("tests", "graftlint_fixtures",
                                     "blocking_async_bad.py"),
              "--no-baseline"])
    data = json.loads(r.stdout)
    assert data["summary"]["violations"] == len(data["violations"]) > 0
    names = {v["rule"] for v in data["violations"]}
    assert names == {"blocking-in-async"}
    lst = _cli(["--list"])
    assert lst.returncode == 0
    for c in ALL_CHECKERS:
        assert c.name in lst.stdout
