"""Utils tests: latch, envelope, logging, file watcher (≙ modules/)."""

import json
import logging
import os
import threading
import time

from k8s_gpu_device_plugin_tpu.utils.envelope import failed, success
from k8s_gpu_device_plugin_tpu.utils.latch import Latch
from k8s_gpu_device_plugin_tpu.utils.log import JsonFormatter, LogConfig, init_logger, parse_level
from k8s_gpu_device_plugin_tpu.utils.watch import FileWatcher


def test_latch_idempotent_and_threadsafe():
    latch = Latch()
    assert not latch.is_set()
    results = []

    t = threading.Thread(target=lambda: results.append(latch.wait(5)))
    t.start()
    latch.set()
    latch.set()  # second close is a no-op (CloseOnce semantics)
    t.join(5)
    assert results == [True]
    assert latch.wait(0)


def test_envelope_contract():
    assert success({"a": 1}) == {"code": 200, "data": {"a": 1}, "msg": "success"}
    assert failed("boom") == {"code": 500, "data": None, "msg": "boom"}


def test_parse_level():
    assert parse_level("warn") == logging.WARNING
    assert parse_level("bogus") == logging.INFO


def test_json_formatter_fields():
    record = logging.LogRecord("t", logging.INFO, "f.py", 10, "hello %s", ("x",), None)
    record.fields = {"resource": "google.com/tpu"}
    entry = json.loads(JsonFormatter().format(record))
    assert entry["msg"] == "hello x"
    assert entry["level"] == "info"
    assert entry["resource"] == "google.com/tpu"
    assert "caller" in entry and "ts" in entry


def test_per_level_files(tmp_path):
    logger = init_logger(
        LogConfig(level="debug", file_dir=str(tmp_path), console=False, name="t1")
    )
    logger.debug("d")
    logger.info("i")
    logger.warning("w")
    logger.error("e")
    for h in logger.handlers:
        h.flush()
    files = {p for p in os.listdir(tmp_path)}
    assert files == {"app-debug.log", "app-info.log", "app-warn.log", "app-error.log"}
    # exact-level routing: info file has only the info record
    lines = (tmp_path / "app-info.log").read_text().strip().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["msg"] == "i"


def test_file_watcher_sees_create_and_delete(tmp_path):
    with FileWatcher([str(tmp_path)]) as watcher:
        target = tmp_path / "kubelet.sock"
        target.write_text("")
        deadline = time.time() + 5
        events = []
        while time.time() < deadline:
            events += watcher.poll(0.2)
            if any(e.name == "kubelet.sock" and e.is_create for e in events):
                break
        assert any(e.name == "kubelet.sock" and e.is_create for e in events)

        target.unlink()
        deadline = time.time() + 5
        while time.time() < deadline:
            events += watcher.poll(0.2)
            if any(e.name == "kubelet.sock" and not e.is_create for e in events):
                break
        assert any(e.name == "kubelet.sock" and not e.is_create for e in events)


def test_console_formatter_dev_mode():
    """Dev mode (≙ zap colored console, log.go:173-180): human line with
    colored level, structured fields as k=v; JSON files are unaffected."""
    import logging

    from k8s_gpu_device_plugin_tpu.utils.log import ConsoleFormatter

    record = logging.LogRecord(
        "t", logging.WARNING, "plugin.py", 42, "chip health changed",
        None, None,
    )
    record.fields = {"unhealthy": [3]}
    plain = ConsoleFormatter(color=False).format(record)
    assert "WARNING" in plain and "plugin.py:42" in plain
    assert "chip health changed" in plain and "unhealthy=[3]" in plain
    assert "\x1b[" not in plain
    colored = ConsoleFormatter(color=True).format(record)
    assert "\x1b[33m" in colored and "\x1b[0m" in colored  # yellow WARNING


def test_init_logger_dev_mode_console(tmp_path, capsys):
    import json as _json

    from k8s_gpu_device_plugin_tpu.utils.log import LogConfig, init_logger

    logger = init_logger(
        LogConfig(
            level="info", file_dir=str(tmp_path), dev_mode=True,
            name="test-dev-console",
        )
    )
    logger.info("hello", extra={"fields": {"k": "v"}})
    err = capsys.readouterr().err
    assert "hello" in err and "k=v" in err
    with open(tmp_path / "app-info.log") as f:   # files stay JSON
        entry = _json.loads(f.readline())
    assert entry["msg"] == "hello" and entry["k"] == "v"
