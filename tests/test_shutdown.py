"""Shutdown robustness: a stalled kubelet must not wedge start or SIGTERM.

Regression tests for two defects found by driving the real daemon: the
Register RPC had no deadline (only the dial did, cf. plugin.go:130,141), so
a kubelet that accepts connections but never answers blocked plugin start —
and an in-flight restart — forever, which in turn made the manager ignore
stop() indefinitely.
"""

import asyncio
import time

import pytest

from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.plugin import plugin as plugin_mod
from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
from k8s_gpu_device_plugin_tpu.plugin.testing import FakeKubelet
from k8s_gpu_device_plugin_tpu.utils.latch import Latch


class StalledKubelet(FakeKubelet):
    """Accepts the connection and the RPC, then never answers Register."""

    async def Register(self, request, context):
        await asyncio.sleep(3600)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


def test_register_times_out_against_stalled_kubelet(tmp_path, monkeypatch):
    monkeypatch.setattr(plugin_mod, "DIAL_TIMEOUT_SECONDS", 0.5)

    async def body():
        kubelet = StalledKubelet(str(tmp_path))
        await kubelet.start()
        cfg = Config(kubelet_socket_dir=str(tmp_path), libtpu_path="")
        manager = PluginManager(
            cfg, Latch(), backend=FakeBackend("v5e-4"), health_interval=30
        )
        manager._load_plugins()
        plugin = manager.plugins[0]
        t0 = time.monotonic()
        with pytest.raises(Exception):  # noqa: B017 - any deadline error
            await plugin.start()
        assert time.monotonic() - t0 < 5.0, "Register must hit its deadline"
        await plugin.stop()
        await kubelet.stop()

    run(body())


def test_stop_during_wedged_restart_returns_promptly(tmp_path, monkeypatch):
    """stop() while a restart is stuck re-registering must still tear down."""
    monkeypatch.setattr(plugin_mod, "DIAL_TIMEOUT_SECONDS", 30.0)

    async def body():
        kubelet = FakeKubelet(str(tmp_path))
        await kubelet.start()
        cfg = Config(kubelet_socket_dir=str(tmp_path), libtpu_path="")
        ready = Latch()
        manager = PluginManager(
            cfg, ready, backend=FakeBackend("v5e-4"), health_interval=30
        )
        task = asyncio.create_task(manager.start())
        await asyncio.wait_for(ready.wait_async(), 10)

        # Swap the healthy kubelet for one that never answers, then restart:
        # the re-register leg wedges (30s deadline >> test budget).
        await kubelet.stop()
        stalled = StalledKubelet(str(tmp_path))
        await stalled.start()
        manager.restart()
        await asyncio.sleep(0.3)  # let the restart reach the Register call

        t0 = time.monotonic()
        await manager.stop()
        await asyncio.wait_for(task, 5)
        assert time.monotonic() - t0 < 5.0
        await stalled.stop()

    run(body())
