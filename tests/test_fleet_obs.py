"""Fleet observability plane (obs/fleet_obs.py + serving/router.py):
cross-replica trace stitching, federated metrics, the fleet event
journal, and router-side request timelines that survive failover.

Unit tests cover the pure pieces (relabeling, stitching, journal
paging, the integer-ns timeline invariant); the integration tests run
real 2-replica in-process fleets (serving/testing.py) and pin the HTTP
contract — including the two PR-15 acceptance pins: journal ordering +
determinism under a seeded ``router.midstream`` fault, and a stitched
trace where every span lands in exactly one replica track with no
orphan fragments."""

import asyncio
import logging

import aiohttp
import jax
import pytest
from prometheus_client import CollectorRegistry

from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import ServingMetrics
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.obs.fleet_obs import (
    FleetEventJournal,
    RouterFlightRecorder,
    RouterTimeline,
    federate_metrics,
    spans_from_chrome,
    stitch_spans,
    stitched_trace_payload,
)
from k8s_gpu_device_plugin_tpu.obs.trace import configure
from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane
from k8s_gpu_device_plugin_tpu.serving.testing import (
    inprocess_fleet,
    per_replica_registry_factories,
    stream_generate,
)
from k8s_gpu_device_plugin_tpu.utils.log import get_logger


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


@pytest.fixture()
def tracer():
    t = configure(enabled=True)
    t.clear()
    yield t
    configure(enabled=False)
    t.clear()


# --- federation (pure text transforms) -------------------------------------


def test_relabel_inserts_replica_label_and_keeps_exemplars():
    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.observe_ttft(0.05, "trace-abc")
    m.on_finish("eos")
    from prometheus_client.openmetrics.exposition import generate_latest

    text = generate_latest(reg).decode()
    merged = federate_metrics([("r0", text)], openmetrics=True)
    # every sample line carries replica="r0" and exemplars survive
    from prometheus_client.openmetrics.parser import (
        text_string_to_metric_families,
    )

    fams = {f.name: f for f in text_string_to_metric_families(merged)}
    ttft = fams["tpu_serving_ttft_seconds"]
    for s in ttft.samples:
        assert s.labels.get("replica") == "r0"
    exemplars = [s.exemplar for s in ttft.samples if s.exemplar]
    assert exemplars and exemplars[0].labels["trace_id"] == "trace-abc"
    # the label-bearing series keep their ORIGINAL labels too
    fin = fams["tpu_serving_requests_finished"]
    assert any(
        s.labels.get("reason") == "eos" and s.labels.get("replica") == "r0"
        for s in fin.samples
    )


def test_federate_escapes_gnarly_replica_ids():
    text = "# TYPE x gauge\nx 1.0\n"
    merged = federate_metrics([('we"ird\\id', text)])
    from prometheus_client.parser import text_string_to_metric_families

    fams = list(text_string_to_metric_families(merged))
    sample = next(s for f in fams if f.name == "x" for s in f.samples)
    assert sample.labels["replica"] == 'we"ird\\id'


def test_federate_aggregates_weighted_mfu_and_summed_histograms():
    def scrape(mfu, bw, tps, ttft_obs):
        reg = CollectorRegistry()
        m = ServingMetrics(registry=reg)
        m.set_mfu(mfu, bw)
        m.tokens_per_second.set(tps)
        for x in ttft_obs:
            m.observe_ttft(x)
        from prometheus_client import generate_latest

        return generate_latest(reg).decode()

    merged = federate_metrics([
        ("r0", scrape(40.0, 8.0, 100.0, [0.05, 0.2])),
        ("r1", scrape(20.0, 4.0, 50.0, [0.05])),
    ])
    from prometheus_client.parser import text_string_to_metric_families

    fams = {f.name: f for f in text_string_to_metric_families(merged)}
    # busy-window weighting: (40*100 + 20*50) / 150
    assert fams["tpu_fleet_mfu_pct"].samples[0].value == pytest.approx(
        100.0 / 3.0
    )
    assert fams["tpu_fleet_hbm_bw_util_pct"].samples[0].value == \
        pytest.approx(20.0 / 3.0)
    ttft = fams["tpu_fleet_ttft_seconds"]
    count = next(s for s in ttft.samples if s.name.endswith("_count"))
    total = next(s for s in ttft.samples if s.name.endswith("_sum"))
    assert count.value == 3
    assert total.value == pytest.approx(0.3)
    # bucket-wise: every per-replica bucket ladder entry summed
    inf_bucket = next(
        s for s in ttft.samples
        if s.name.endswith("_bucket") and s.labels["le"] == "+Inf"
    )
    assert inf_bucket.value == 3
    assert fams["tpu_fleet_replicas"].samples[0].value == 2


def test_federate_idle_fleet_reports_zero_not_nan():
    def idle_scrape():
        reg = CollectorRegistry()
        ServingMetrics(registry=reg)
        from prometheus_client import generate_latest

        return generate_latest(reg).decode()

    merged = federate_metrics([("r0", idle_scrape())])
    from prometheus_client.parser import text_string_to_metric_families

    fams = {f.name: f for f in text_string_to_metric_families(merged)}
    assert fams["tpu_fleet_mfu_pct"].samples[0].value == 0.0


# --- stitching (pure) ------------------------------------------------------


def _span(sid, parent, component="serving", replica=None, trace="t" * 32,
          start=0, dur=5):
    attrs = {}
    if replica is not None:
        attrs["replica"] = replica
    return {
        "name": f"s{sid}", "component": component, "trace_id": trace,
        "span_id": sid, "parent_id": parent, "start_us": start,
        "dur_us": dur, "status": "ok", "thread": "", "attrs": attrs,
    }


def test_stitch_assigns_subtrees_and_dedups():
    router_root = _span("a1", None, component="router_http")
    r0_http = _span("b1", "a1", component="serving_http", replica="r0")
    r0_child = _span("b2", "b1")            # inherits r0 via parent chain
    r1_http = _span("c1", "a1", component="serving_http", replica="r1")
    r1_child = _span("c2", "c1")
    all_spans = [router_root, r0_http, r0_child, r1_http, r1_child]
    # every source returns every span (the shared in-process tracer)
    tracks, summary = stitch_spans([
        ("router", list(all_spans)),
        ("r0", list(all_spans)),
        ("r1", list(all_spans)),
    ])
    assert summary["n_spans"] == 5
    assert summary["deduped"] == 10
    assert summary["dropped"] == 0
    assert summary["orphans"] == []
    by_track = dict(tracks)
    assert [s["span_id"] for s in by_track["router"]] == ["a1"]
    assert {s["span_id"] for s in by_track["r0"]} == {"b1", "b2"}
    assert {s["span_id"] for s in by_track["r1"]} == {"c1", "c2"}
    # every span lands in exactly one track
    assert sum(summary["tracks"].values()) == summary["n_spans"]


def test_stitch_reports_orphans_and_router_attr_priority():
    # a fragment whose parent lives in NO fragment is an orphan; a
    # router span carrying a replica attr (the routing-decision attr)
    # still lands on the router track
    router = _span("a1", None, component="router_http", replica="r1")
    orphan = _span("d9", "missing-parent")
    tracks, summary = stitch_spans([("router", [router, orphan])])
    assert summary["orphans"] == ["d9"]
    # the orphan still renders (assigned to its fragment's source
    # track) — reported, not dropped
    assert dict(summary["tracks"]) == {"router": 2}


def test_stitch_counts_idless_spans_as_dropped_not_deduped():
    # a span with no span_id cannot be merged or parented: it is LOST,
    # and the summary must say so instead of miscounting a duplicate
    ok = _span("a1", None, component="router_http")
    idless = dict(_span("", None), span_id="")
    tracks, summary = stitch_spans([("router", [ok, idless, dict(idless)])])
    assert summary["n_spans"] == 1
    assert summary["dropped"] == 2
    assert summary["deduped"] == 0


def test_stitched_trace_payload_renders_process_per_track():
    spans = [
        _span("a1", None, component="router_http"),
        _span("b1", "a1", component="serving_http", replica="r0"),
    ]
    payload = stitched_trace_payload([("router", spans)])
    names = {
        e["args"]["name"] for e in payload["traceEvents"]
        if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert names == {"router", "r0"}
    # round-trips through the chrome-JSON span reconstruction
    back = spans_from_chrome(payload)
    assert {s["span_id"] for s in back} == {"a1", "b1"}
    assert stitched_trace_payload([]) is None


# --- journal (pure) --------------------------------------------------------


def test_journal_sequencing_paging_and_replay():
    j = FleetEventJournal(maxlen=4)
    for i in range(6):
        j.emit("failover", replica=f"r{i % 2}", attempt=1)
    payload = j.events_payload()
    # bounded: the ring kept the NEWEST 4, seqs stay monotonic
    assert payload["total"] == 6
    assert [e["seq"] for e in payload["events"]] == [3, 4, 5, 6]
    # since pages forward; limit keeps the OLDEST of the remainder so
    # consecutive polls walk the ring deterministically
    page = j.events_payload(limit=2, since=3)
    assert [e["seq"] for e in page["events"]] == [4, 5]
    # replay strips exactly the nondeterministic fields
    replay = FleetEventJournal.replay(payload["events"])
    assert all("t" not in e and "trace_id" not in e for e in replay)
    assert replay[0] == {"seq": 3, "kind": "failover", "replica": "r0",
                         "attempt": 1}


def test_journal_rare_events_survive_request_rate_floods():
    """An overload storm (per-request failover/cooldown_429 events)
    must not evict the rare control-plane history — the promotion and
    resume record an operator reaches for minutes into an incident."""
    j = FleetEventJournal(maxlen=8, rare_maxlen=4)
    j.emit("promote", promoted="r2", replaced="r0")
    j.emit("stream_resume", source="r0", target="r1", tokens_at_death=3)
    for i in range(100):  # the storm: far past the main ring's bound
        j.emit("cooldown_429", replica="r0", retry_after_s=1.0)
        j.emit("failover", replica="r1", attempt=1)
    payload = j.events_payload()
    kinds = {e["kind"] for e in payload["events"]}
    assert {"promote", "stream_resume"} <= kinds, kinds
    # the merged view stays one ordered journal: monotonic seqs, the
    # protected events first (they are oldest), paging still works
    seqs = [e["seq"] for e in payload["events"]]
    assert seqs == sorted(seqs) and seqs[:2] == [1, 2]
    assert payload["total"] == 202
    page = j.events_payload(since=1, limit=1)
    assert [e["kind"] for e in page["events"]] == ["stream_resume"]
    # a flood of RARE kinds still bounds the protected ring
    for _ in range(10):
        j.emit("drain", replica="r0")
    assert j.stats()["resident"] <= 8 + 4


# --- timelines (pure) ------------------------------------------------------


def test_router_timeline_segments_sum_exactly():
    tl = RouterTimeline(1, "/v1/generate", t0_ns=1000)
    tl.relay_on("r0")
    tl.advance("resume_gap")
    tl.relay_on("r1")
    tl.resumes = 1
    rec = tl.finalize("resumed", 200)
    # THE invariant: integer-ns segments sum to the observed wall ±0
    assert sum(d for _, _, d in rec["segments"]) == rec["total_ns"]
    assert sum(rec["phases"].values()) == rec["total_ns"]
    assert rec["replicas"] == ["r0", "r1"]
    assert rec["resume_gap_ns"] == rec["phases"]["resume_gap"]
    # phase names: route -> relay:r0 -> resume_gap -> relay:r1 (the
    # final advance CLOSES relay:r1 at the finalize instant)
    assert [s[0] for s in rec["segments"]] == [
        "route", "relay:r0", "resume_gap", "relay:r1",
    ]


def test_flight_recorder_retention_policy():
    rec = RouterFlightRecorder(recent=8, ring=4, slow_ms=0.0)
    fast = rec.start("/v1/generate").finalize("ok", 200)
    rec.on_done(fast)
    resumed_tl = rec.start("/v1/generate")
    resumed_tl.resumes = 1
    resumed = resumed_tl.finalize("resumed", 200)
    rec.on_done(resumed)
    stats = rec.request_stats()
    assert stats["completed"] == 2 and stats["retained"] == 1
    assert [r["rid"] for r in stats["retained_requests"]] == \
        [resumed["rid"]]
    # get() prefers the retained ring, falls back to recent
    assert rec.get(fast["rid"])["outcome"] == "ok"
    assert rec.get(resumed["rid"])["retained"] is True
    assert rec.get(10_000) is None
    assert rec.resume_gap_ms() == [resumed["resume_gap_ns"] / 1e6]


# --- integration: real fleets ----------------------------------------------


async def _drive_resumed_stream(setup, body, *, seed=1, max_new=8,
                                tracing_fleet_kw=None):
    """Run ``body(session, base, ctx, events)`` against a 2-replica
    fleet where ONE streamed request dies mid-relay (seeded
    ``router.midstream``) and resumes — the killed-and-resumed shape
    every integration pin below starts from."""
    cfg, params = setup
    engine_factory, server_factory = per_replica_registry_factories(
        params, cfg
    )
    prompt = [int(seed) + t for t in range(1, 9)]
    async with inprocess_fleet(
        params, cfg, n_replicas=2,
        engine_factory=engine_factory, server_factory=server_factory,
        router_kw=dict(
            dict(policy="rr", health_interval_s=0.1,
                 faults=FaultPlane.from_spec("router.midstream:nth=2")),
            **(tracing_fleet_kw or {}),
        ),
    ) as ctx:
        async with aiohttp.ClientSession() as session:
            for i in range(2):
                async with session.post(
                    f"{ctx.replica_base(i)}/v1/generate",
                    json={"prompt": prompt, "max_new": 2},
                ) as r:
                    assert r.status == 200
            stream = await stream_generate(
                session, ctx.base, prompt=prompt, max_new=max_new
            )
            assert stream["done"] and len(stream["tokens"]) == max_new
            events = ctx.router.journal.events_payload()["events"]
            await body(session, ctx.base, ctx, events)


def test_fleet_events_schema_ordering_and_determinism(setup):
    """/fleet/events acceptance pin: the journal of a seeded
    router.midstream run has the pinned schema and ordering, and two
    same-seed runs replay IDENTICAL journals (wall time and the random
    trace id are the only divergence)."""
    replays = []

    async def body(session, base, ctx, events):
        assert [e["seq"] for e in events] == \
            list(range(1, len(events) + 1))
        resumes = [e for e in events if e["kind"] == "stream_resume"]
        assert len(resumes) == 1
        evt = resumes[0]
        # schema pin: the documented event shape
        assert set(evt) >= {"seq", "kind", "t", "trace_id", "source",
                            "target", "tokens_at_death"}
        assert evt["source"] != evt["target"]
        assert evt["tokens_at_death"] == 2  # nth=2: died on frame 2
        # HTTP surface: paging + pinned 400-on-garbage (the shared
        # parse_trace_query rule, like both /debug/traces planes)
        async with session.get(f"{base}/fleet/events?limit=1") as r:
            page = await r.json()
        assert page["returned"] == 1 and page["total"] == len(events)
        async with session.get(
            f"{base}/fleet/events?since={evt['seq'] - 1}"
        ) as r:
            tail = await r.json()
        assert tail["events"][0]["seq"] == evt["seq"]
        for bad in ("limit=x", "limit=-1", "since=nope"):
            async with session.get(f"{base}/fleet/events?{bad}") as r:
                assert r.status == 400
        replays.append(FleetEventJournal.replay(events))

    run(_drive_resumed_stream(setup, body, seed=31))
    run(_drive_resumed_stream(setup, body, seed=31))
    assert replays[0] == replays[1]


def test_stitched_trace_one_track_per_span_no_orphans(setup, tracer):
    """The stitched-trace acceptance pin: after a killed-and-resumed
    stream, GET /fleet/debug/traces/{id} returns ONE Perfetto document
    where every span lands in exactly one replica track (both relaying
    replicas AND the router present) with no orphan fragments."""

    async def body(session, base, ctx, events):
        resumes = [e for e in events if e["kind"] == "stream_resume"]
        tid = resumes[0]["trace_id"]
        assert tid  # the journal links the event to its trace
        await asyncio.sleep(0.3)  # the span tree closes asynchronously
        async with session.get(f"{base}/fleet/debug/traces/{tid}") as r:
            assert r.status == 200
            stitched = await r.json()
        summ = stitched["fleet"]
        assert summ["trace_id"] == tid
        assert summ["orphans"] == []
        assert {"router", "r0", "r1"} <= set(summ["tracks"])
        # exactly-one-track: track counts partition the span set
        assert sum(summ["tracks"].values()) == summ["n_spans"]
        # and the rendered doc agrees: every complete event's pid maps
        # to exactly one process_name row
        pids = {e["pid"] for e in stitched["traceEvents"]
                if e.get("ph") == "X"}
        names = {e["pid"]: e["args"]["name"]
                 for e in stitched["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert pids <= set(names)
        # the relayed tokens' serving spans: each replica's request
        # subtree (serving component spans) sits on that replica's own
        # track, not the fetching source's
        for evt in stitched["traceEvents"]:
            if evt.get("ph") != "X":
                continue
            replica_attr = evt["args"].get("replica")
            if evt["cat"] == "serving_http" and replica_attr:
                assert names[evt["pid"]] == replica_attr
        # unknown trace -> 404
        async with session.get(
            f"{base}/fleet/debug/traces/{'f' * 32}"
        ) as r:
            assert r.status == 404

    run(_drive_resumed_stream(setup, body, seed=32))


def test_router_timeline_http_surface_and_wall_sum(setup):
    """The failover-aware timeline pin: the resumed stream's router
    timeline is retained, served on /fleet/debug/requests/{rid}, and
    its segments sum EXACTLY (±0 — integer ns) to the wall time the
    router observed, resume gap included."""

    async def body(session, base, ctx, events):
        async with session.get(f"{base}/fleet/debug/requests") as r:
            assert r.status == 200
            stats = await r.json()
        retained = [t for t in stats["retained_requests"] if t["resumes"]]
        assert len(retained) == 1
        tl = retained[0]
        assert sum(d for _, _, d in tl["segments"]) == tl["total_ns"]
        assert sum(tl["phases"].values()) == tl["total_ns"]
        assert tl["resume_gap_ns"] > 0
        assert tl["outcome"] == "resumed"
        assert tl["replicas"] and len(set(tl["replicas"])) == 2
        assert tl["tokens"] == 8
        async with session.get(
            f"{base}/fleet/debug/requests/{tl['rid']}"
        ) as r:
            assert r.status == 200
            assert (await r.json())["rid"] == tl["rid"]
        async with session.get(f"{base}/fleet/debug/requests/zz") as r:
            assert r.status == 400
        async with session.get(
            f"{base}/fleet/debug/requests/999999"
        ) as r:
            assert r.status == 404

    run(_drive_resumed_stream(setup, body, seed=33))


def test_timelines_off_disables_surface(setup):
    cfg, params = setup

    async def body():
        async with inprocess_fleet(
            params, cfg, n_replicas=1,
            engine_kw=dict(n_slots=2, max_len=64, chunked_prefill=8),
            router_kw=dict(timelines=False, health_interval_s=0.2),
        ) as ctx:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"{ctx.base}/v1/generate",
                    json={"prompt": [1, 2, 3, 4], "max_new": 2},
                ) as r:
                    assert r.status == 200
                async with session.get(
                    f"{ctx.base}/fleet/debug/requests"
                ) as r:
                    assert r.status == 404
            assert ctx.router.router_stats()["timelines"] is None

    run(body())


def test_fleet_metrics_federation_over_http(setup):
    """GET /fleet/metrics: parses under both content types, every
    series replica-labeled, aggregates present, and a dead replica
    surfaces as a scrape error instead of failing the pass."""
    cfg, params = setup
    engine_factory, server_factory = per_replica_registry_factories(
        params, cfg
    )

    async def body():
        async with inprocess_fleet(
            params, cfg, n_replicas=2,
            engine_factory=engine_factory, server_factory=server_factory,
            router_kw=dict(health_interval_s=0.1),
        ) as ctx:
            async with aiohttp.ClientSession() as session:
                for i in range(2):
                    async with session.post(
                        f"{ctx.replica_base(i)}/v1/generate",
                        json={"prompt": [5, 6, 7, 8], "max_new": 2},
                    ) as r:
                        assert r.status == 200
                async with session.get(f"{ctx.base}/fleet/metrics") as r:
                    assert r.status == 200
                    classic = await r.text()
                async with session.get(
                    f"{ctx.base}/fleet/metrics",
                    headers={"Accept": "application/openmetrics-text"},
                ) as r:
                    assert "openmetrics" in r.headers["Content-Type"]
                    om = await r.text()
                from prometheus_client.openmetrics.parser import (
                    text_string_to_metric_families as parse_om,
                )
                from prometheus_client.parser import (
                    text_string_to_metric_families as parse_classic,
                )

                fams = {f.name: f for f in parse_classic(classic)}
                tok = fams["tpu_serving_generated_tokens"]
                assert {s.labels["replica"] for s in tok.samples} == \
                    {"r0", "r1"}
                assert "tpu_fleet_mfu_pct" in fams
                assert "tpu_fleet_ttft_seconds" in fams
                om_fams = {f.name for f in parse_om(om)}
                assert "tpu_fleet_mfu_pct" in om_fams

                # kill one replica: federation degrades visibly
                await ctx.kill_replica(1)
                async with session.get(f"{ctx.base}/fleet/metrics") as r:
                    assert r.status == 200
                    partial = await r.text()
                fams = {f.name: f
                        for f in parse_classic(partial)}
                assert fams["tpu_fleet_scrape_errors"].samples[0].value \
                    == 1
                assert fams["tpu_fleet_replicas"].samples[0].value == 1

    run(body())


def test_router_debug_traces_plane_query_surface(setup, tracer):
    """Satellite pin: the router's own /debug/traces accepts the same
    ?limit=/?since= surface as the replica and daemon planes, 400 on
    garbage included."""
    cfg, params = setup

    async def body():
        async with inprocess_fleet(
            params, cfg, n_replicas=1,
            engine_kw=dict(n_slots=2, max_len=64, chunked_prefill=8),
            router_kw=dict(health_interval_s=0.2),
        ) as ctx:
            async with aiohttp.ClientSession() as session:
                for _ in range(2):
                    async with session.post(
                        f"{ctx.base}/v1/generate",
                        json={"prompt": [1, 2, 3, 4], "max_new": 2},
                    ) as r:
                        assert r.status == 200
                await asyncio.sleep(0.2)
                async with session.get(f"{ctx.base}/debug/traces") as r:
                    assert r.status == 200
                    full = await r.json()
                assert full["total"] >= 2
                async with session.get(
                    f"{ctx.base}/debug/traces?limit=1"
                ) as r:
                    page = await r.json()
                assert page["returned"] == 1
                # >=: the health poller's probe spans keep landing in
                # the shared ring between the two reads
                assert page["total"] >= full["total"]
                cutoff = full["traces"][-1]["start_us"]
                async with session.get(
                    f"{ctx.base}/debug/traces?since={cutoff}"
                ) as r:
                    newer = await r.json()
                assert all(
                    t["start_us"] > cutoff for t in newer["traces"]
                )
                for bad in ("limit=x", "limit=-1", "since=nope"):
                    async with session.get(
                        f"{ctx.base}/debug/traces?{bad}"
                    ) as r:
                        assert r.status == 400
                # the single-trace detail endpoint serves chrome JSON
                tid = full["traces"][0]["trace_id"]
                async with session.get(
                    f"{ctx.base}/debug/traces/{tid}"
                ) as r:
                    assert r.status == 200
                    assert "traceEvents" in await r.json()

    run(body())


def test_router_span_attrs_and_log_correlation(setup, tracer):
    """Satellite pin: router spans carry replica/affinity_hit/resumed
    attrs, and the submitted/resumed log lines carry trace_id (via the
    emit-time filter) + a replica field."""
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = Capture(level=logging.DEBUG)
    logger = get_logger()
    logger.addHandler(handler)
    try:
        async def body(session, base, ctx, events):
            await asyncio.sleep(0.2)
            spans = [
                s for t in ctx.router.tracer._finished
                for s in t["spans"]
                if s["component"] == "router_http"
                and s["name"].startswith("POST /v1/generate")
            ]
            tagged = [s for s in spans if "replica" in s["attrs"]]
            assert tagged, spans
            resumed_span = next(
                s for s in tagged if s["attrs"].get("resumed")
            )
            assert resumed_span["attrs"]["replica"] in ("r0", "r1")
            assert "affinity_hit" in resumed_span["attrs"]

        run(_drive_resumed_stream(setup, body, seed=34))
    finally:
        logger.removeHandler(handler)
    submitted = [r for r in records
                 if r.getMessage() == "request submitted to replica"]
    assert submitted
    assert all(getattr(r, "trace_id", None) for r in submitted)
    assert all(r.fields["replica"] for r in submitted)
    resumed_logs = [
        r for r in records
        if r.getMessage() == "resumed mid-stream after replica death"
    ]
    assert resumed_logs
    assert getattr(resumed_logs[0], "trace_id", None)
    assert resumed_logs[0].fields["replica"]


def test_fleet_health_reads_through_fleet_stats(setup):
    """Satellite pin: both health handlers read through the single
    fleet_stats() accessor — the snapshot carries the admitting count
    and the router counters (journal/timeline stats included)."""
    cfg, params = setup

    async def body():
        async with inprocess_fleet(
            params, cfg, n_replicas=2,
            engine_kw=dict(n_slots=2, max_len=64, chunked_prefill=8),
            router_kw=dict(health_interval_s=0.1),
        ) as ctx:
            snap = ctx.router.fleet_stats()
            assert snap["admitting"] == 2
            assert "journal" in snap["router"]
            assert "timelines" in snap["router"]
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{ctx.base}/fleet/health") as r:
                    fleet_health = await r.json()
                assert fleet_health["admitting"] == 2
                assert set(fleet_health["replicas"]) == {"r0", "r1"}
                async with session.get(f"{ctx.base}/v1/health") as r:
                    health = await r.json()
                assert health["admitting"] == 2
                # draining flips the admitting count through the same
                # accessor on both surfaces
                ctx.fleet.get("r0").draining = True
                ctx.fleet.get("r1").draining = True
                async with session.get(f"{ctx.base}/v1/health") as r:
                    assert r.status == 503

    run(body())
