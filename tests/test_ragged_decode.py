"""Ragged decode attention (ops/ragged_decode.py) vs the XLA cache path.

The kernel's claim: identical attention semantics to
generate._cached_attention at T=1 (live rows = positions <= length,
empty slots compute-and-discard, sliding-window floor), while reading
only live kv blocks. Interpret mode runs the same kernel logic on CPU;
all comparisons here are deterministic.
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.ops.ragged_decode import (
    ragged_decode_attention,
)


def _ref(q, k, v, lengths, scale, window=0):
    b, t, hq, hd = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    s = k.shape[1]
    qg = q.reshape(b, 1, hkv, g, hd).astype(jnp.float32)
    scores = jnp.einsum(
        "btkgd,bskd->btkgs", qg, k.astype(jnp.float32)
    ) * scale
    pos = jnp.arange(s)[None, None, None, None, :]
    hi = jnp.maximum(lengths, 1)[:, None, None, None, None]
    keep = pos < hi
    if window > 0:
        lo = jnp.maximum(lengths - window, 0)[:, None, None, None, None]
        keep &= pos >= lo
    scores = jnp.where(keep, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("btkgs,bskd->btkgd", p, v.astype(jnp.float32)).reshape(
        b, 1, hq, hd
    )


@pytest.mark.parametrize(
    "lengths,window",
    [
        ([5, 300, 512], 0),     # ragged mix
        ([0, 17, 256], 0),      # empty slot (compute-and-discard contract)
        ([100, 400, 512], 64),  # sliding-window floor skips low blocks
        ([512, 512, 512], 0),   # fully dense
    ],
)
def test_kernel_matches_reference(lengths, window):
    B, S, Hq, Hkv, hd = 3, 512, 8, 4, 128
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (B, 1, Hq, hd), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, Hkv, hd), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, Hkv, hd), jnp.bfloat16)
    L = jnp.asarray(lengths, jnp.int32)
    got = ragged_decode_attention(
        q, k, v, L, scale=hd ** -0.5, window=window, interpret=True
    )
    want = _ref(q, k, v, L, hd ** -0.5, window)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
    assert err < 0.02, err  # bf16 inputs vs f32 reference


def test_generate_ragged_matches_xla_decode():
    """End to end through generate: the opt-in ragged decode path emits
    the same greedy tokens as the XLA cache path (deterministic on this
    seed/software stack)."""
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (2, 10), 0, cfg.vocab_size, jnp.int32
    )
    ref = generate(params, prompt, cfg, max_new=8)
    got = generate(
        params, prompt, replace(cfg, decode_attn="ragged"), max_new=8
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_generate_ragged_windowed():
    cfg = LlamaConfig.tiny(n_layers=2, sliding_window=8)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(2), (2, 12), 0, cfg.vocab_size, jnp.int32
    )
    ref = generate(params, prompt, cfg, max_new=10)
    got = generate(
        params, prompt, replace(cfg, decode_attn="ragged"), max_new=10
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_batcher_ragged_vector_lengths():
    """Continuous batching is the ragged kernel's raison d'etre: slots at
    wildly different positions in one batch. Per-request parity against
    the same-config generate oracle."""
    from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher

    cfg = LlamaConfig.tiny(n_layers=2, decode_attn="ragged")
    params = init_params(jax.random.key(0), cfg)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(8, 16),
    )
    prompts = {}
    for i, (plen, new) in enumerate([(5, 6), (12, 4), (3, 8)]):
        p = jax.random.randint(
            jax.random.key(800 + i), (plen,), 1, cfg.vocab_size, jnp.int32
        ).tolist()
        rid = cb.submit(p, max_new=new)
        prompts[rid] = (p, new)
    results = cb.run()
    for rid, (p, new) in prompts.items():
        want = np.asarray(
            generate(params, jnp.asarray([p], jnp.int32), cfg, max_new=new)
        )[0].tolist()
        assert results[rid] == want, rid


def test_config_validation():
    with pytest.raises(ValueError, match="decode_attn"):
        LlamaConfig.tiny(decode_attn="pallas")
