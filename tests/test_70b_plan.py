"""Llama-3-70B on a v5p-32 slice: the sharding plan proven without
hardware (BASELINE #5; r4 verdict #6).

``parallel/plan.py`` accounts per-chip HBM from ``jax.eval_shape`` + the
REAL training PartitionSpecs (models/llama.py:param_specs) and pins the
collective placement via device-list strides. These tests are the
committed form of the plan: if someone changes the 70B preset, the specs,
or the remat policies in a way that breaks the v5p-32 fit, this fails in
CI instead of on a slice reservation.

v5p facts used: 95 GiB HBM/chip, 4 chips/host -> tp=4 is exactly
within-host (stride 1 = ICI-adjacent), fsdp=8 spans the 8 hosts.
"""

from dataclasses import replace

import jax.numpy as jnp
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec
from k8s_gpu_device_plugin_tpu.parallel.plan import (
    HBM_GIB,
    axis_strides,
    memory_plan,
)

V5P32 = MeshSpec(dp=1, fsdp=8, tp=4)
CFG70 = LlamaConfig.llama3_70b()


def test_70b_param_accounting_matches_model_size():
    """eval_shape accounting must reproduce the known model size: the
    70B preset's parameters, summed across all 32 chips, are ~70.55B
    weights at 2 bytes each."""
    plan = memory_plan(CFG70, V5P32, batch_size=8, seq_len=8192)
    total_param_gib = plan.params * 32  # norms replicate, but are ~0
    expected_gib = 70.55e9 * 2 / 1024**3
    assert abs(total_param_gib - expected_gib) / expected_gib < 0.02, (
        total_param_gib, expected_gib,
    )


def test_70b_fits_v5p32_with_default_remat():
    """The headline plan: global batch 8 x 8192 tokens, default
    save_dots_attn remat, bf16 params + AdamW — fits 95 GiB with >=10%
    headroom for XLA scratch/collective buffers."""
    plan = memory_plan(CFG70, V5P32, batch_size=8, seq_len=8192)
    assert plan.fits(HBM_GIB["v5p"], headroom=0.10), plan
    # static state alone is small: full ZeRO-3 sharding over all 32 chips
    assert plan.params + plan.grads + plan.opt_state < 20.0, plan


def test_70b_bigger_batch_needs_cheaper_remat():
    """The remat dial is the batch-size dial: bs=32 blows the budget on
    save_dots_attn but fits on save_nothing (full recompute). Pins that
    the policies actually differ in the accounting, the way they differ
    on hardware (remat_tune measures the time side of this trade).
    Every row of docs/scaling.md's table is asserted here or in
    test_70b_fits_v5p32_with_default_remat."""
    rich = memory_plan(CFG70, V5P32, batch_size=32, seq_len=8192)
    assert not rich.fits(HBM_GIB["v5p"]), rich
    lean = memory_plan(
        replace(CFG70, remat_policy="save_nothing"), V5P32,
        batch_size=32, seq_len=8192,
    )
    assert lean.fits(HBM_GIB["v5p"]), lean
    assert lean.activations < rich.activations / 4
    # the remaining published table rows: bs=16 save_dots_attn and
    # bs=64 save_nothing both exceed the budget
    assert not memory_plan(CFG70, V5P32, 16, 8192).fits(HBM_GIB["v5p"])
    assert not memory_plan(
        replace(CFG70, remat_policy="save_nothing"), V5P32, 64, 8192
    ).fits(HBM_GIB["v5p"])


def test_70b_master_weights_variant_fits():
    """f32 master weights double params+grads+opt (cotangents carry the
    f32 param dtype) and add a bf16 compute cast; the plan absorbs it at
    bs=8 by dropping to save_nothing."""
    cfg = replace(
        CFG70, param_dtype=jnp.float32, remat_policy="save_nothing",
    )
    plan = memory_plan(cfg, V5P32, batch_size=8, seq_len=8192)
    base = memory_plan(
        replace(CFG70, remat_policy="save_nothing"), V5P32, 8, 8192
    )
    assert plan.params == pytest.approx(2 * base.params, rel=0.01)
    assert plan.grads == pytest.approx(2 * base.grads, rel=0.01)  # f32 grads
    # the bf16 working copy covers the LAYER stacks only
    # (cast_params_for_compute leaves embed/lm_head/norms in f32), so it
    # is slightly under one full bf16 param set
    assert 0.9 * base.params < plan.compute_cast < base.params
    assert plan.fits(HBM_GIB["v5p"]), plan


def test_plan_guards():
    """remat=False is unmodeled (every intermediate lives through the
    backward) and must be refused; fused_ce only removes the logits row
    when tp==1 lets the fused path actually engage."""
    with pytest.raises(ValueError, match="remat"):
        memory_plan(replace(CFG70, remat=False), V5P32, 8, 8192)
    fused_tp4 = memory_plan(replace(CFG70, fused_ce=True), V5P32, 8, 8192)
    assert fused_tp4.logits_transient > 0, fused_tp4  # fallback still pays
    fused_tp1 = memory_plan(
        replace(CFG70, fused_ce=True),
        MeshSpec(dp=1, fsdp=32, tp=1), 8, 8192,
    )
    assert fused_tp1.logits_transient == 0, fused_tp1


def test_collectives_ride_ici():
    """tp (per-layer all-reduces, latency-critical) must be the
    INNERMOST axis: stride 1 = adjacent device-list entries = ICI
    neighbors on a slice whose device order follows the torus. fsdp's
    stride-4 groups align with whole v5p hosts; dp, when present, is
    outermost (one gradient psum per step tolerates DCN)."""
    strides = axis_strides(V5P32)
    assert strides["tp"] == 1, strides
    assert strides["fsdp"] == 4, strides  # = chips/host on v5p
    with_dp = axis_strides(MeshSpec(dp=2, fsdp=4, tp=4))
    assert with_dp["tp"] == 1
    assert with_dp["dp"] == 16  # outermost: spans half the slice per step
    # sp slots between fsdp and tp (long-context ring attention stays
    # inside a host pair rather than crossing the slice)
    long_ctx = axis_strides(MeshSpec(fsdp=4, sp=2, tp=4))
    assert long_ctx["sp"] == 4 and long_ctx["tp"] == 1


def test_mesh_axis_strides_reads_as_built_mesh():
    """The as-built counterpart: mesh_axis_strides reads the ACTUAL device
    array a Mesh carries (create_device_mesh may permute for physical
    topology), so hardware plans verify the real arrangement, not the
    row-major model."""
    import jax

    from k8s_gpu_device_plugin_tpu.parallel.mesh import make_mesh
    from k8s_gpu_device_plugin_tpu.parallel.plan import mesh_axis_strides

    mesh = make_mesh(MeshSpec(dp=2, sp=2, tp=2), jax.devices()[:8])
    strides = mesh_axis_strides(mesh)
    assert set(strides) == {"dp", "sp", "tp"}
    # every axis reports the distinct id-steps actually present
    assert all(len(v) >= 1 for v in strides.values())


def test_pp_divides_resident_layers():
    """pp=2 halves the per-chip layer stacks (stage dim sharded) and the
    resident activation share in the first-order model."""
    base = memory_plan(CFG70, V5P32, batch_size=8, seq_len=8192)
    pp = memory_plan(
        CFG70, MeshSpec(fsdp=4, tp=4, pp=2), batch_size=8, seq_len=8192
    )
    # layer stacks stay 32-way sharded (pp*fsdp*tp); embed/lm_head shard
    # only over (tp, fsdp)=16, so per-chip params grow by ~their half
    assert pp.params == pytest.approx(base.params, rel=0.05)
    assert pp.activations == pytest.approx(base.activations, rel=0.01)


def test_8b_fits_v5p16_north_star_shape():
    """The north-star shape: Llama-3-8B on v5p-16 (fsdp4 x tp4, tp
    within-host). Batch 16 x 8192 fits with room (50.7 GiB of 95); 32
    needs a cheaper remat policy — the plan names the working points
    before the slice exists."""
    cfg = LlamaConfig.llama3_8b()
    spec = MeshSpec(fsdp=4, tp=4)
    assert memory_plan(cfg, spec, 16, 8192).fits(HBM_GIB["v5p"])
    assert not memory_plan(cfg, spec, 32, 8192).fits(HBM_GIB["v5p"])
    lean = memory_plan(
        replace(cfg, remat_policy="save_nothing"), spec, 32, 8192
    )
    assert lean.fits(HBM_GIB["v5p"]), lean
    strides = axis_strides(spec)
    assert strides["tp"] == 1 and strides["fsdp"] == 4
