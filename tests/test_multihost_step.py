"""Cross-process sharded TRAIN STEP through the Allocate contract (r4
verdict #5: the multi-host story was rendezvous-tested but no sharded
step had ever crossed a process boundary).

``dryrun_multihost`` allocates the exact env contract two daemon stacks
emit for a 2-host v5e-8 slice, spawns one subprocess per worker (4
virtual CPU devices each), rendezvouses via jax.distributed (gloo), and
runs the framework's real train step over ONE GLOBAL dp2(x-process) x
sp2 x tp2 mesh. The decisive assertion lives in the orchestrator: every
rank must report the identical finite loss trajectory.
"""

import pytest

from k8s_gpu_device_plugin_tpu.parallel.multihost_dryrun import dryrun_multihost


def test_two_process_global_train_step():
    # bounded internally: dryrun_multihost kills its workers at 420s
    report = dryrun_multihost(n_processes=2, devices_per_process=4, steps=2)
    assert report["ok"]
    assert report["global_devices"] == 8
    assert report["mesh"]["dp"] == 2  # dp crosses the process boundary
    assert report["mesh"]["tp"] == 2 and report["mesh"]["sp"] == 2
    assert "TPU_WORKER_ID" in report["env_contract_keys"]
    assert "TPU_PROCESS_BOUNDS" in report["env_contract_keys"]


def test_worker_refuses_single_process_env(monkeypatch):
    """The step preflight must fail loudly without a worker contract, not
    silently run a local-only 'success'."""
    for k in ("TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID", "MEGASCALE_NUM_SLICES"):
        monkeypatch.delenv(k, raising=False)
    from k8s_gpu_device_plugin_tpu.parallel.multihost_step import run_step_check

    with pytest.raises(RuntimeError, match="multi-host env contract"):
        run_step_check()
