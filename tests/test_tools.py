"""tools/watchdog.py + tools/harvest.py: the unattended hardware-window
pipeline finally gets tests (it previously shipped on faith — `make
analyze` runs over tools/, so the code it checks should be backed by
something executable too).

No chip, no subprocesses against real hardware: the harvest tests
drive the pure helpers (median/spread discipline, journal resume
predicates, priority rules) against a tmp journal, and the watchdog
tests run ``main()`` with a stubbed harvest pass so every exit rule
(drained queue, stop file, deadline, duplicate instance) is pinned.
"""

import json
import os
import sys

import pytest

# import through the tools namespace package (repo root is on sys.path
# via conftest) — a bare `import watchdog` would collide with the pypi
# filesystem-events package of the same name when both are importable
import tools.watchdog as watchdog  # noqa: E402

harvest = watchdog.harvest  # the same module object watchdog drives


def test_smoke_importable_and_wired_together():
    # watchdog defers its priority rule to harvest's — ONE implementation
    assert watchdog.harvest is harvest
    assert callable(harvest.script_outranked)
    assert harvest.QUEUE and all(len(row) == 3 for row in harvest.QUEUE)
    # every queue row's timeout is positive and names are unique
    names = [n for n, _, _ in harvest.QUEUE]
    assert len(names) == len(set(names))
    assert all(t > 0 for _, _, t in harvest.QUEUE)


# --- harvest: repeat/median/spread discipline ------------------------------


def test_primary_key_picks_first_present_metric():
    assert harvest.primary_key({"mfu_pct": 55.1, "noise": 1}) == "mfu_pct"
    assert harvest.primary_key(
        {"tokens_per_second": 10}) == "tokens_per_second"
    assert harvest.primary_key({"unrelated": "x"}) is None


def test_median_of_returns_a_really_measured_run():
    reps = [{"mfu_pct": 50.0}, {"mfu_pct": 54.0}, {"mfu_pct": 52.0}]
    med, spread = harvest.median_of(reps)
    assert med == {"mfu_pct": 52.0}  # the middle MEASUREMENT, not a mean
    assert spread["metric"] == "mfu_pct"
    assert spread["values"] == [50.0, 54.0, 52.0]
    assert spread["rel_spread_pct"] == pytest.approx(
        100 * (54 - 50) / 52, abs=0.01
    )


def test_median_of_even_count_takes_lower_middle():
    reps = [{"mfu_pct": v} for v in (50.0, 51.0, 52.0, 53.0)]
    med, _ = harvest.median_of(reps)
    assert med["mfu_pct"] == 51.0  # lower-middle: never an interpolation


def test_median_of_single_or_keyless_is_passthrough():
    only = [{"mfu_pct": 50.0}]
    assert harvest.median_of(only) == (only[0], None)
    keyless = [{"a": 1}, {"a": 2}]
    assert harvest.median_of(keyless) == (keyless[0], None)


# --- harvest: journal persistence + resume ---------------------------------


@pytest.fixture
def journal(tmp_path, monkeypatch):
    path = str(tmp_path / "harvest_results.jsonl")
    monkeypatch.setattr(harvest, "RESULTS_PATH", path)
    return path


def test_persist_writes_consolidated_median_row(journal):
    reps = [{"mfu_pct": 50.0}, {"mfu_pct": 54.0}, {"mfu_pct": 52.0}]
    rec = harvest.persist("train", reps[0], repeats=reps)
    lines = [json.loads(line) for line in open(journal)]
    assert lines[-1] == rec
    assert rec["workload"] == "train"
    assert rec["result"] == {"mfu_pct": 52.0}  # adoption reads the median
    assert rec["n_repeats"] == 3 and len(rec["repeats"]) == 3
    assert rec["spread"]["values"] == [50.0, 54.0, 52.0]


def test_persist_single_failure_row(journal):
    rec = harvest.persist("decode", None)
    assert rec["result"] is None
    assert json.loads(open(journal).read())["workload"] == "decode"


def test_landed_rows_shares_bench_predicates(journal, monkeypatch):
    harvest.persist("train", {"mfu_pct": 55.0}, repeats=[{"mfu_pct": 55.0}])
    harvest.persist("decode", None)  # failed: must not count as landed
    # stale rows are bench.journal_row_fresh's call — pin the sharing by
    # forcing its verdict and watching landed_rows() obey it
    monkeypatch.setattr(harvest.bench, "journal_row_fresh", lambda rec: True)
    assert harvest.landed_rows() == {"train"}
    monkeypatch.setattr(harvest.bench, "journal_row_fresh", lambda rec: False)
    assert harvest.landed_rows() == set()


def test_landed_rows_survives_garbage_lines(journal):
    with open(journal, "w") as f:
        f.write("not json\n\n")
    assert harvest.landed_rows() == set()  # no crash, nothing landed


# --- harvest/watchdog: single-instance priority rule -----------------------


def test_script_outranked_start_tick_priority(monkeypatch):
    me = os.getpid()
    monkeypatch.setattr(harvest, "_script_pids", lambda s: [111, 222])
    ticks = {111: 5, 222: 50, me: 20}
    monkeypatch.setattr(
        harvest, "_proc_start_ticks", lambda pid: ticks.get(pid, 1 << 62)
    )
    # pid 111 started earlier than us -> we are outranked
    assert harvest.script_outranked("harvest.py") is True
    ticks[111] = 40  # both peers younger than us -> we win
    assert harvest.script_outranked("harvest.py") is False


def test_watchdog_outranked_delegates_to_harvest(monkeypatch):
    seen = []
    monkeypatch.setattr(
        harvest, "script_outranked",
        lambda script: seen.append(script) or False,
    )
    assert watchdog.outranked() is False
    assert seen == ["watchdog.py"]


# --- watchdog main loop: every exit rule, no real subprocesses -------------


class _FakeProc:
    def __init__(self, rc):
        self._rc = rc
        self.pid = 4242

    def wait(self, timeout=None):
        return self._rc


@pytest.fixture
def wd(tmp_path, monkeypatch):
    """watchdog.main() harness: stop file in tmp, no elder instances,
    no sleeping, scripted harvest return codes."""
    monkeypatch.setattr(sys, "argv", ["watchdog.py"])
    monkeypatch.setattr(watchdog, "STOP_PATH", str(tmp_path / ".stop"))
    monkeypatch.setattr(watchdog, "outranked", lambda: False)
    monkeypatch.setattr(watchdog.time, "sleep", lambda s: None)
    rcs = []
    monkeypatch.setattr(
        watchdog.subprocess, "Popen",
        lambda *a, **kw: _FakeProc(rcs.pop(0)),
    )
    return rcs


def test_watchdog_exits_when_queue_drained(wd, capsys):
    wd.append(3)  # harvest: nothing left to measure
    assert watchdog.main() == 0
    assert "queue drained" in capsys.readouterr().out


def test_watchdog_reenters_immediately_after_landing_rows(wd, capsys):
    wd.extend([0, 3])  # rows landed -> straight back in -> drained
    assert watchdog.main() == 0
    out = capsys.readouterr().out
    assert "re-entering immediately" in out and "queue drained" in out


def test_watchdog_backs_off_on_busy_then_stops_on_stop_file(
    wd, capsys, monkeypatch
):
    wd.append(4)  # chip busy (bench.py owns it)

    real_exists = os.path.exists

    def exists(path):
        if path == watchdog.STOP_PATH:
            # appears after the first pass's back-off
            return len(wd) == 0 and exists.armed
        return real_exists(path)

    exists.armed = False
    monkeypatch.setattr(watchdog.os.path, "exists", exists)
    monkeypatch.setattr(
        watchdog.time, "sleep",
        lambda s: setattr(exists, "armed", True),
    )
    assert watchdog.main() == 0
    out = capsys.readouterr().out
    assert "backing off" in out and "stop file present" in out


def test_watchdog_removes_stale_stop_file_and_runs(wd, capsys):
    open(watchdog.STOP_PATH, "w").close()  # stale leftover, no elder
    wd.append(3)
    assert watchdog.main() == 0
    assert not os.path.exists(watchdog.STOP_PATH)
    assert "stale" in capsys.readouterr().out


def test_watchdog_yields_to_elder_instance(wd, capsys, monkeypatch):
    monkeypatch.setattr(watchdog, "outranked", lambda: True)
    assert watchdog.main() == 4
    assert "already running" in capsys.readouterr().out


def test_watchdog_deadline_stops_the_loop(wd, capsys, monkeypatch):
    wd.extend([1, 1, 1, 1, 1])  # wedged passes forever
    t = [0.0]

    def fake_time():
        t[0] += 5 * 3600.0  # each clock read burns five hours
        return t[0]

    monkeypatch.setattr(watchdog.time, "time", fake_time)
    rc = watchdog.main()
    assert rc == 0
    assert "deadline reached" in capsys.readouterr().out
