"""Disaggregated prefill/decode: KV-page transfer between replicas
(models/batching.py export/install + the /v1/kv/export HTTP seam +
the role-aware router's prefill->decode splice).

Three layers of claims:

- **Bit-exactness**: a stream exported after a few emitted tokens and
  resumed on a DIFFERENT batcher with the transferred pages produces
  tokens AND logprobs identical to an uninterrupted single-replica
  run, across {bf16, int8, int4} caches x tp{1, 2}, greedy and
  seeded; the router's disaggregated splice (and its re-prefill
  fallback when every decode worker is dead) is held to the same pin
  end-to-end over HTTP.
- **Wire fidelity**: re-exporting an installed stream reproduces the
  original blob's valid page bytes (codes AND scale planes) — the
  transfer is a copy, not a re-encode.
- **Pool discipline**: export leaves the source accountable for its
  pages until the cancel lands (then drains to zero), install pays for
  its pages like a cold admission (cancel mid-decode drains to zero),
  and a target without room answers 429 kv_pool_pressure instead of
  parking a live stream behind a full pool.
"""

import asyncio
import dataclasses
import json

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models import paging
from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.testing import (
    inprocess_fleet,
    stream_generate,
)

BUCKETS = (8, 16, 32)
PS = 16  # page size: divides max_len=64 (the test_paged_kv geometry)

ENGINE_KW = dict(
    n_slots=2, max_len=64, prompt_buckets=BUCKETS,
    chunked_prefill=8, kv_layout="paged", kv_page_size=PS,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    # same tiny config as the neighboring serving modules so shared
    # compiles are reused; quant/tp twins compile once here
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _batcher(params, cfg, tp=1, **kw):
    return ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, pipeline_depth=1,
        kv_layout="paged", kv_page_size=PS, tp=tp, **kw,
    )


def _step_until_tokens(cb, rid, n):
    """Step until the request has emitted >= n tokens (still running)."""
    for _ in range(200):
        for req in cb.running.values():
            if req.rid == rid and len(req.out) >= n:
                return
        assert rid not in cb.done, "finished before export point"
        cb.step()
    raise AssertionError(f"request {rid} never reached {n} tokens")


def _finish(cb, rid):
    while rid not in cb.done:
        cb.step()
    return list(cb.done[rid]), list(cb.done_requests[rid].out_logp)


# --- batcher-level round trip ----------------------------------------------


@pytest.mark.parametrize("tp", [1, 2])
@pytest.mark.parametrize("quant", ["none", "int8", "int4"])
def test_export_install_roundtrip_bit_identity(setup, quant, tp):
    cfg0, params = setup
    cfg = dataclasses.replace(cfg0, cache_quant=quant)
    prompt = _prompt(5, 20, cfg)

    # reference: uninterrupted run (same tp — tp=1==tp=2 equality is
    # test_tp_serving's pin; here the axis is the mid-stream handoff)
    ref = _batcher(params, cfg, tp=tp)
    want = _finish(ref, ref.submit(prompt, max_new=8, seed=123))

    # source: decode 3 tokens, export, cancel — pages drain to zero
    src = _batcher(params, cfg, tp=tp)
    rid = src.submit(prompt, max_new=8, seed=123)
    _step_until_tokens(src, rid, 3)
    blob, out, lps = src.export_kv_pages(rid)
    assert blob["cache_quant"] == quant and blob["n_pages"] > 0
    assert len(out) >= 3 and len(lps) == len(out)
    assert src.pool.in_use > 0  # export does NOT release the source
    src.cancel(rid)
    src.run()
    src.pool.check()
    assert src.pool.in_use == 0

    # target: install + continue; the combined stream is the reference
    dst = _batcher(params, cfg, tp=tp)
    rid2 = dst.submit(prompt, max_new=8, seed=123,
                      resume_out=out, resume_logp=lps, kv_pages=blob)
    got = _finish(dst, rid2)
    assert got[0] == want[0], (quant, tp, got[0], want[0])
    assert got[1] == want[1], (quant, tp)  # logprobs bitwise, not approx
    dst.pool.check()
    assert dst.pool.in_use == 0


def test_wire_blob_survives_reinstall_bitwise(setup):
    """Re-exporting an installed stream reproduces the original blob's
    valid page bytes for EVERY plane (codes and scales): the transfer
    copies rows, it never re-encodes them."""
    cfg0, params = setup
    cfg = dataclasses.replace(cfg0, cache_quant="int8")
    prompt = _prompt(5, 20, cfg)
    src = _batcher(params, cfg)
    rid = src.submit(prompt, max_new=8, seed=123)
    _step_until_tokens(src, rid, 3)
    blob, out, lps = src.export_kv_pages(rid)
    src.cancel(rid)

    dst = _batcher(params, cfg)
    rid2 = dst.submit(prompt, max_new=8, seed=123,
                      resume_out=out, resume_logp=lps, kv_pages=blob)
    _step_until_tokens(dst, rid2, len(out) + 1)
    blob2, out2, _ = dst.export_kv_pages(rid2)
    dst.cancel(rid2)
    assert out2[:len(out)] == out
    _, p1 = paging.unpack_kv_wire(blob)
    _, p2 = paging.unpack_kv_wire(blob2)
    assert set(p1) == set(p2)
    # rows past the exported valid count were rewritten by the finish
    # chunk; the FULL pages below it must match byte-for-byte
    full = blob["tokens"] // PS
    assert full >= 1  # the comparison must actually cover pages
    for name in p1:
        a = np.asarray(p1[name][:, :full]).view(np.uint8)
        b = np.asarray(p2[name][:, :blob["n_pages"]][:, :full]).view(
            np.uint8)
        assert np.array_equal(a, b), f"plane {name} re-encoded in flight"


def test_export_refuses_dense_and_unknown(setup):
    cfg, params = setup
    dense = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, kv_layout="dense",
    )
    rid = dense.submit(_prompt(6, 10, cfg), max_new=4)
    with pytest.raises(ValueError, match="paged"):
        dense.export_kv_pages(rid)
    dense.run()

    cb = _batcher(params, cfg)
    with pytest.raises(KeyError):
        cb.export_kv_pages(999)  # never submitted
    rid = cb.submit(_prompt(6, 20, cfg), max_new=4)
    with pytest.raises(ValueError, match="prefill"):
        cb.export_kv_pages(rid)  # still prefilling: no pages to ship
    cb.run()


def test_cancel_mid_transfer_returns_pool_to_baseline(setup):
    """The leak pin: a target that admits transferred pages and is
    cancelled mid-decode must drain back to the empty-pool baseline —
    installed pages retire exactly like cold-admitted ones."""
    cfg, params = setup
    prompt = _prompt(5, 20, cfg)
    src = _batcher(params, cfg)
    rid = src.submit(prompt, max_new=8, seed=123)
    _step_until_tokens(src, rid, 3)
    blob, out, lps = src.export_kv_pages(rid)
    src.cancel(rid)
    src.run()
    assert src.pool.in_use == 0

    dst = _batcher(params, cfg)
    rid2 = dst.submit(prompt, max_new=8, seed=123,
                      resume_out=out, resume_logp=lps, kv_pages=blob)
    _step_until_tokens(dst, rid2, len(out) + 1)  # install happened
    assert dst.pool.in_use >= blob["n_pages"]
    dst.cancel(rid2)
    dst.run()
    dst.pool.check()
    assert dst.pool.in_use == 0


# --- the HTTP seam ----------------------------------------------------------


def test_kv_export_http_seam(setup):
    """Replica-to-replica over HTTP, no router: stream on A, export
    mid-stream via X-Request-Id, resubmit on B with the pages; the
    combined stream is bit-identical to an uninterrupted run."""
    cfg, params = setup
    prompt = _prompt(5, 20, cfg)

    async def body():
        async with inprocess_fleet(params, cfg, n_replicas=2,
                                   engine_kw=ENGINE_KW) as fleet:
            a, b = fleet.replica_base(0), fleet.replica_base(1)
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{b}/v1/generate", json={
                    "prompt": prompt, "max_new": 8, "seed": 123,
                    "logprobs": True,
                }) as r:
                    assert r.status == 200, await r.text()
                    ref = await r.json()

                got = []
                async with s.post(f"{a}/v1/generate", json={
                    "prompt": prompt, "max_new": 8, "seed": 123,
                    "stream": True, "logprobs": True,
                }) as r:
                    assert r.status == 200, await r.text()
                    eid = int(r.headers["X-Request-Id"])
                    exp = None
                    async for line in r.content:
                        t = line.decode().strip()
                        if not t.startswith("data: "):
                            continue
                        evt = json.loads(t[len("data: "):])
                        if "token" in evt:
                            got.append(evt["token"])
                        if len(got) == 3 and exp is None:
                            async with s.post(
                                f"{a}/v1/kv/export/{eid}"
                            ) as ex:
                                assert ex.status == 200, await ex.text()
                                exp = await ex.json()
                        if evt.get("done") or evt.get("error"):
                            break
                # the export snapshot is a superset of what we streamed
                assert exp["resume_out"][:len(got)] == got
                assert len(exp["resume_out"]) >= 3

                async with s.post(f"{b}/v1/generate", json={
                    "prompt": prompt, "max_new": 8, "seed": 123,
                    "logprobs": True,
                    "resume_out": exp["resume_out"],
                    "resume_logprobs": exp["resume_logprobs"],
                    "kv_pages": exp["kv_pages"],
                }) as r:
                    assert r.status == 200, await r.text()
                    cont = await r.json()
                assert exp["resume_out"] + cont["tokens"] == ref["tokens"]
                assert (exp["resume_logprobs"] + cont["logprobs"]
                        == ref["logprobs"])

                # 404 once finished/cancelled; 400 on a garbage id
                async with s.post(f"{a}/v1/kv/export/{eid}") as r:
                    assert r.status == 404
                async with s.post(f"{a}/v1/kv/export/zzz") as r:
                    assert r.status == 400

                for srv in fleet.servers:
                    srv.engine.cb.pool.check()
                    assert srv.engine.cb.pool.in_use == 0

    run(body())


def test_kv_install_pool_pressure_answers_429(setup):
    """A target whose pool FITS the folded stream but cannot hold it
    right now fast-fails the kv_pages submit with kv_pool_pressure
    (-> HTTP 429, the router's cue to re-prefill elsewhere) instead of
    deferring a live stream behind the full pool."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import (
        SchedulerOverloadError,
    )
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params = setup
    prompt = _prompt(5, 20, cfg)
    src = _batcher(params, cfg)
    rid = src.submit(prompt, max_new=8, seed=123)
    _step_until_tokens(src, rid, 3)
    blob, out, lps = src.export_kv_pages(rid)
    src.cancel(rid)

    async def body():
        engine = InferenceEngine(params, cfg, **ENGINE_KW)
        try:
            pool = engine.cb.pool
            # leave one free page: the stream fits the pool's CAPACITY
            # (no 422 refusal) but not what is free right now
            held = pool.alloc(pool.free_pages - 1)
            try:
                with pytest.raises(SchedulerOverloadError) as ei:
                    engine.submit(prompt, max_new=8, seed=123,
                                  resume_out=out, resume_logp=lps,
                                  kv_pages=blob)
                assert ei.value.reason == "kv_pool_pressure"
                assert ei.value.retry_after == 1
            finally:
                pool.decref(held)
            pool.check()
            # with the pressure gone the same submit is admitted
            _, q = engine.submit(prompt, max_new=8, seed=123,
                                 resume_out=out, resume_logp=lps,
                                 kv_pages=blob)
            toks = []
            while True:
                t = await asyncio.wait_for(q.get(), 60)
                if t is None:
                    break
                toks.append(t)
            # only the continuation streams: the resumed prefix was
            # already delivered by whoever relayed the source stream
            assert len(toks) == 8 - len(out)
        finally:
            engine.shutdown()

    run(body())


# --- the role-aware router -------------------------------------------------


DISAGG_KW = dict(roles="prefill=r0 decode=r1,r2", disagg_min_prompt=8)


@pytest.mark.parametrize("quant", ["none", "int8"])
def test_disagg_streams_bit_identical(setup, quant):
    """The end-to-end pin: a long-prompt stream through a roled fleet
    (prefill on r0, KV pages shipped to a decode worker, the stream
    spliced across the hop) is bit-identical — tokens AND logprobs,
    greedy AND seeded — to an unroled colocated run."""
    cfg0, params = setup
    cfg = dataclasses.replace(cfg0, cache_quant=quant)
    prompt = _prompt(5, 20, cfg)
    short = prompt[:5]

    async def body():
        async with inprocess_fleet(params, cfg, n_replicas=1,
                                   engine_kw=ENGINE_KW) as colo:
            async with aiohttp.ClientSession() as s:
                refs = {}
                for seed in (None, 123):  # sequential: XLA:CPU compile
                    refs[seed] = await stream_generate(
                        s, colo.base, prompt=prompt, max_new=8, seed=seed,
                    )

        async with inprocess_fleet(
            params, cfg, n_replicas=3, engine_kw=ENGINE_KW,
            router_kw=dict(DISAGG_KW),
        ) as fleet:
            async with aiohttp.ClientSession() as s:
                for i, seed in enumerate((None, 123)):
                    got = await stream_generate(
                        s, fleet.base, prompt=prompt, max_new=8, seed=seed,
                    )
                    assert not got.get("error"), got
                    assert got["tokens"] == refs[seed]["tokens"], (
                        quant, seed, got["tokens"], refs[seed]["tokens"])
                    assert got["logprobs"] == refs[seed]["logprobs"], (
                        quant, seed)
                    st = fleet.router.router_stats()
                    assert st["kv_transfers"].get("ok", 0) == i + 1, (
                        st["kv_transfers"])
                st = fleet.router.router_stats()
                assert st["kv_transferred_pages"] > 0
                assert len(st["kv_transfer_ms"]) == 2
                assert st["roles"] == {"r0": "prefill", "r1": "decode",
                                       "r2": "decode"}

                # short prompts skip the hop: colocated on a decode
                # worker, no new transfer counted
                sgot = await stream_generate(s, fleet.base, prompt=short,
                                             max_new=6)
                assert len(sgot["tokens"]) == 6 and not sgot.get("error")
                st = fleet.router.router_stats()
                assert st["kv_transfers"].get("ok", 0) == 2

                # the prefill replica never holds pages past the hop
                assert fleet.servers[0].engine.cb.pool.in_use == 0
                for srv in fleet.servers:
                    srv.engine.cb.pool.check()

                if quant == "none":
                    # /fleet/health surfaces roles
                    async with s.get(f"{fleet.base}/fleet/health") as r:
                        snap = await r.json()
                    roles = {rid: rep["role"]
                             for rid, rep in snap["replicas"].items()}
                    assert roles == {"r0": "prefill", "r1": "decode",
                                     "r2": "decode"}
                    assert snap["roles"]["prefill"]["replicas"] == 1
                    # draining the only prefill-capable replica is
                    # refused; draining one of two decode workers is not
                    async with s.post(f"{fleet.base}/fleet/drain/r0") as r:
                        assert r.status == 409
                        assert (await r.json()).get("code") == "role_empty"
                    async with s.post(f"{fleet.base}/fleet/drain/r1") as r:
                        assert r.status == 200, await r.text()
                    async with s.post(
                        f"{fleet.base}/fleet/undrain/r1"
                    ) as r:
                        assert r.status == 200

    run(body())


def test_disagg_transfer_failure_falls_back_bit_identical(setup):
    """Kill every decode worker: the transfer leg finds no target and
    the router degrades to a re-prefill resume on the prefill replica —
    same stream, zero drops, fallback counted (not charged as a
    replica death)."""
    cfg, params = setup
    prompt = _prompt(5, 20, cfg)

    async def body():
        async with inprocess_fleet(params, cfg, n_replicas=1,
                                   engine_kw=ENGINE_KW) as colo:
            async with aiohttp.ClientSession() as s:
                ref = await stream_generate(
                    s, colo.base, prompt=prompt, max_new=8,
                )

        async with inprocess_fleet(
            params, cfg, n_replicas=3, engine_kw=ENGINE_KW,
            router_kw=dict(DISAGG_KW),
        ) as fleet:
            await fleet.kill_replica(1)
            await fleet.kill_replica(2)
            for _ in range(100):  # let the health poller notice
                if sum(1 for r in fleet.fleet.all() if r.alive) == 1:
                    break
                await asyncio.sleep(0.1)
            async with aiohttp.ClientSession() as s:
                got = await stream_generate(s, fleet.base, prompt=prompt,
                                            max_new=8)
                assert not got.get("error"), got
                assert got["tokens"] == ref["tokens"], (
                    got["tokens"], ref["tokens"])
                assert got["logprobs"] == ref["logprobs"]
                st = fleet.router.router_stats()
                assert st["kv_transfers"].get("fallback", 0) >= 1, (
                    st["kv_transfers"])
                assert fleet.servers[0].engine.cb.pool.in_use == 0
                fleet.servers[0].engine.cb.pool.check()

    run(body())
