"""HF checkpoint import: logits parity against the transformers reference.

The strongest oracle in the model stack: a random-init HF LlamaForCausalLM
converted through models/convert.py must produce (numerically) the same
logits from our functional forward as transformers' own implementation —
pinning rope convention, GQA head mapping, RMSNorm placement/epsilon, silu
MLP wiring, and every weight transpose at once.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from k8s_gpu_device_plugin_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    params_from_hf,
)
from k8s_gpu_device_plugin_tpu.models.llama import forward  # noqa: E402


def _tiny_hf(vocab=64, tie=False):
    cfg = transformers.LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=tie,
        max_position_embeddings=128,
    )
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(cfg).eval(), cfg


def test_forward_matches_transformers():
    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)  # f32 for a tight bound
    params = params_from_hf(hf.state_dict(), cfg)

    tokens = np.array([[3, 17, 42, 7, 23, 11, 60, 2]], np.int64)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))

    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)


def test_generate_matches_transformers_greedy():
    """End-to-end: greedy decode over converted weights equals HF's
    greedy generate (token-exact at f32)."""
    from k8s_gpu_device_plugin_tpu.models.generate import generate

    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)

    prompt = np.array([[5, 9, 33, 12]], np.int64)
    with torch.no_grad():
        ref = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0,
        ).numpy()[:, prompt.shape[1]:]
    got = np.asarray(
        generate(params, jnp.asarray(prompt, jnp.int32), cfg, max_new=8)
    )
    np.testing.assert_array_equal(got, ref)


def test_config_mapping():
    _, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    assert cfg.d_model == 64 and cfg.n_layers == 2
    assert cfg.n_heads == 4 and cfg.n_kv_heads == 2
    assert cfg.rope_theta == 10000.0 and cfg.norm_eps == 1e-5


def test_tied_embeddings_accepted_and_verified():
    """Tied checkpoints convert to ONE leaf (family-agnostic since the
    Gemma work); a checkpoint whose 'tied' head actually diverged from
    the embedding is refused instead of silently served wrong."""
    hf, hf_cfg = _tiny_hf(tie=True)
    cfg = config_from_hf(hf_cfg)
    assert cfg.tied_embeddings
    params = params_from_hf(hf.state_dict(), cfg)
    assert "lm_head" not in params

    sd = {k: v.clone() for k, v in hf.state_dict().items()}
    sd["lm_head.weight"] = sd["lm_head.weight"] + 1.0  # untied fine-tune
    with pytest.raises(ValueError, match="differs"):
        params_from_hf(sd, cfg)


def test_missing_weight_raises():
    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    sd = dict(hf.state_dict())
    del sd["model.layers.1.mlp.down_proj.weight"]
    with pytest.raises(KeyError):
        params_from_hf(sd, cfg)


def test_shape_mismatch_raises():
    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    sd = dict(hf.state_dict())
    sd["model.embed_tokens.weight"] = torch.zeros(32, 64)
    with pytest.raises(ValueError, match="embed"):
        params_from_hf(sd, cfg)


def test_rope_scaling_rejected():
    _, hf_cfg = _tiny_hf()
    hf_cfg.rope_scaling = {
        "rope_type": "llama3", "factor": 8.0,
        "low_freq_factor": 1.0, "high_freq_factor": 4.0,
        "original_max_position_embeddings": 8192,
    }
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        config_from_hf(hf_cfg)


def test_non_silu_activation_rejected():
    _, hf_cfg = _tiny_hf()
    hf_cfg.hidden_act = "gelu"
    with pytest.raises(NotImplementedError, match="hidden_act"):
        config_from_hf(hf_cfg)


def test_round_trip_and_hf_load():
    """params_to_hf inverts params_from_hf, and torch can load the result:
    HF forward over the re-imported weights matches the original model."""
    from k8s_gpu_device_plugin_tpu.models.convert import params_to_hf

    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)
    sd = params_to_hf(params, cfg)

    # exact tensor round trip (f32 all the way)
    for name, ref in hf.state_dict().items():
        if "rotary_emb" in name:
            continue
        np.testing.assert_allclose(
            sd[name], ref.detach().float().numpy(), atol=1e-7,
            err_msg=name,
        )

    # and torch accepts it as a real checkpoint
    hf2, _ = _tiny_hf()
    hf2.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()})
    tokens = torch.tensor([[2, 9, 41, 17]])
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(tokens).logits.numpy(), hf(tokens).logits.numpy(), atol=1e-6
        )


def test_params_to_hf_rejects_moe():
    from k8s_gpu_device_plugin_tpu.models.convert import params_to_hf
    from k8s_gpu_device_plugin_tpu.models.llama import (
        LlamaConfig as Cfg, init_params,
    )

    cfg = Cfg.tiny(n_layers=1, n_experts=4)
    params = init_params(jax.random.key(0), cfg)
    with pytest.raises(NotImplementedError, match="MoE"):
        params_to_hf(params, cfg)


def test_params_to_hf_contiguous_and_layer_check():
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.convert import params_to_hf

    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)
    sd = params_to_hf(params, cfg)
    assert all(w.flags["C_CONTIGUOUS"] for w in sd.values())
    with pytest.raises(ValueError, match="stacked layers"):
        params_to_hf(params, replace(cfg, n_layers=1))


def test_mistral_sliding_window_mapped():
    """Mistral-style checkpoints (layout-identical to Llama, trained with
    windowed attention) must carry their window through conversion, and
    our windowed forward must match transformers' MistralForCausalLM."""
    cfg_m = transformers.MistralConfig(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
        max_position_embeddings=128, sliding_window=8,
    )
    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(cfg_m).eval()
    cfg = config_from_hf(cfg_m, dtype=jnp.float32)
    assert cfg.sliding_window == 8
    params = params_from_hf(hf.state_dict(), cfg)
    # 16 tokens > window 8, so the windowed mask is load-bearing here
    tokens = np.arange(1, 17, dtype=np.int64)[None, :]
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)


def _tiny_qwen2(vocab=64):
    cfg = transformers.Qwen2Config(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
        max_position_embeddings=128,
    )
    torch.manual_seed(1)
    m = transformers.Qwen2ForCausalLM(cfg).eval()
    # random biases: zeros would make the bias path vacuously pass
    with torch.no_grad():
        for layer in m.model.layers:
            for proj in ("q_proj", "k_proj", "v_proj"):
                getattr(layer.self_attn, proj).bias.normal_(0.0, 0.5)
    return m, cfg


def test_qwen2_forward_matches_transformers():
    """Qwen2 family: the Llama layout + q/k/v biases. Logits parity with
    transformers' Qwen2ForCausalLM pins the bias wiring (biases are
    randomized — zeros would hide a dropped bias)."""
    hf, hf_cfg = _tiny_qwen2()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.attn_bias
    params = params_from_hf(hf.state_dict(), cfg)
    assert params["layers"]["bq"].shape == (2, 64)

    tokens = np.array([[3, 17, 42, 7, 23, 11, 60, 2]], np.int64)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)


def test_qwen2_generate_matches_transformers_greedy():
    """Decode path carries the biases too (generate's cached-attention
    projections are a separate code path from the training forward)."""
    from k8s_gpu_device_plugin_tpu.models.generate import generate

    hf, hf_cfg = _tiny_qwen2()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)

    prompt = np.array([[5, 9, 33, 12]], np.int64)
    with torch.no_grad():
        ref = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0,
        ).numpy()[:, prompt.shape[1]:]
    got = np.asarray(
        generate(params, jnp.asarray(prompt, jnp.int32), cfg, max_new=8)
    )
    np.testing.assert_array_equal(got, ref)


def test_qwen2_round_trip():
    """params -> HF state dict -> params is exact, biases included."""
    from k8s_gpu_device_plugin_tpu.models.convert import params_to_hf

    hf, hf_cfg = _tiny_qwen2()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)
    sd = params_to_hf(params, cfg)
    assert "model.layers.0.self_attn.q_proj.bias" in sd
    again = params_from_hf(sd, cfg)
    for k in ("bq", "bk", "bv", "wq"):
        np.testing.assert_array_equal(
            np.asarray(params["layers"][k]), np.asarray(again["layers"][k])
        )


def test_llama_attention_bias_o_proj_refused():
    """HF Llama's attention_bias also biases o_proj; converting it would
    half-apply the checkpoint — the unconsumed-tensor check refuses."""
    cfg_hf = transformers.LlamaConfig(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False, attention_bias=True,
    )
    torch.manual_seed(2)
    hf = transformers.LlamaForCausalLM(cfg_hf).eval()
    cfg = config_from_hf(cfg_hf, dtype=jnp.float32)
    assert cfg.attn_bias  # qkv biases ARE consumed...
    with pytest.raises(ValueError, match="unconsumed"):
        params_from_hf(hf.state_dict(), cfg)  # ...o_proj.bias is not


def test_qwen2_sliding_window_gating():
    """Qwen2 ships sliding_window=4096 but DISABLED by default
    (use_sliding_window=False): the conversion must not window a model
    trained with full attention. Layer-partial windows (max_window_layers
    below n_layers) cannot be expressed here and are refused."""
    base = dict(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False,
    )
    off = transformers.Qwen2Config(**base, use_sliding_window=False,
                                   sliding_window=4096)
    assert config_from_hf(off).sliding_window == 0

    partial = transformers.Qwen2Config(
        **base, use_sliding_window=True, sliding_window=4096,
        max_window_layers=2,
    )
    with pytest.raises(NotImplementedError, match="layer-partial"):
        config_from_hf(partial)

    # mwl == n_layers: HF windows layers with idx >= mwl, i.e. NONE —
    # Qwen2-7B's own default shape even with the flag on
    none_windowed = transformers.Qwen2Config(
        **base, use_sliding_window=True, sliding_window=4096,
        max_window_layers=4,
    )
    assert config_from_hf(none_windowed).sliding_window == 0

    # mwl == 0: every layer windowed — expressible here
    all_windowed = transformers.Qwen2Config(
        **base, use_sliding_window=True, sliding_window=4096,
        max_window_layers=0,
    )
    assert config_from_hf(all_windowed).sliding_window == 4096


def _tiny_gemma(vocab=64):
    cfg = transformers.GemmaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rope_theta=10000.0, rms_norm_eps=1e-6,
        max_position_embeddings=128,
    )
    torch.manual_seed(3)
    return transformers.GemmaForCausalLM(cfg).eval(), cfg


def test_gemma_forward_matches_transformers():
    """Gemma family: GeGLU + (1+w) RMSNorm + sqrt(d)-scaled embeddings +
    tied lm_head. Logits parity against transformers pins all four at
    once — any one dropped shifts every logit."""
    hf, hf_cfg = _tiny_gemma()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    assert cfg.tied_embeddings and cfg.norm_offset and cfg.scale_embed
    assert cfg.act == "gelu_tanh"
    params = params_from_hf(hf.state_dict(), cfg)
    assert "lm_head" not in params  # ONE tied leaf

    tokens = np.array([[3, 17, 42, 7, 23, 11, 60, 2]], np.int64)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=2e-3)


def test_gemma_generate_matches_transformers_greedy():
    from k8s_gpu_device_plugin_tpu.models.generate import generate

    hf, hf_cfg = _tiny_gemma()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)

    prompt = np.array([[5, 9, 33, 12]], np.int64)
    with torch.no_grad():
        ref = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0,
        ).numpy()[:, prompt.shape[1]:]
    got = np.asarray(
        generate(params, jnp.asarray(prompt, jnp.int32), cfg, max_new=8)
    )
    np.testing.assert_array_equal(got, ref)


def test_gemma_tied_training_grads_flow_to_one_leaf(tmp_path):
    """The tied head is the SAME tensor as the embedding: a train step
    must move `embed` with gradient contributions from both roles, and
    there is no separate lm_head leaf to drift."""
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state, make_optimizer, make_train_step, synthetic_batch,
    )
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = LlamaConfig.tiny(
        n_layers=2, dtype=jnp.float32, tied_embeddings=True,
        scale_embed=True, norm_offset=True, act="gelu_tanh",
    )
    mesh = make_mesh(MeshSpec(tp=2), jax.devices()[:2])
    opt = make_optimizer(total_steps=2, warmup_steps=0)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    assert "lm_head" not in state["params"]
    before = np.asarray(state["params"]["embed"], np.float32).copy()
    step = make_train_step(cfg, mesh, opt)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 16, mesh)
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    after = np.asarray(state["params"]["embed"], np.float32)
    assert not np.allclose(before, after)  # tied grads actually flow



def test_tied_embeddings_generic_families():
    """Tied embeddings are family-agnostic: a tied Qwen2 (the 0.5B/1.5B
    ship this) converts with ONE tied leaf and matches transformers."""
    cfg_hf = transformers.Qwen2Config(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=True, max_position_embeddings=128,
    )
    torch.manual_seed(5)
    hf = transformers.Qwen2ForCausalLM(cfg_hf).eval()
    cfg = config_from_hf(cfg_hf, dtype=jnp.float32)
    assert cfg.tied_embeddings and cfg.attn_bias
    params = params_from_hf(hf.state_dict(), cfg)
    assert "lm_head" not in params

    tokens = np.array([[3, 17, 42, 7]], np.int64)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)
