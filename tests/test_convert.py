"""HF checkpoint import: logits parity against the transformers reference.

The strongest oracle in the model stack: a random-init HF LlamaForCausalLM
converted through models/convert.py must produce (numerically) the same
logits from our functional forward as transformers' own implementation —
pinning rope convention, GQA head mapping, RMSNorm placement/epsilon, silu
MLP wiring, and every weight transpose at once.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from k8s_gpu_device_plugin_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    params_from_hf,
)
from k8s_gpu_device_plugin_tpu.models.llama import forward  # noqa: E402


def _tiny_hf(vocab=64, tie=False):
    cfg = transformers.LlamaConfig(
        vocab_size=vocab, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=tie,
        max_position_embeddings=128,
    )
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(cfg).eval(), cfg


def test_forward_matches_transformers():
    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)  # f32 for a tight bound
    params = params_from_hf(hf.state_dict(), cfg)

    tokens = np.array([[3, 17, 42, 7, 23, 11, 60, 2]], np.int64)
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))

    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)


def test_generate_matches_transformers_greedy():
    """End-to-end: greedy decode over converted weights equals HF's
    greedy generate (token-exact at f32)."""
    from k8s_gpu_device_plugin_tpu.models.generate import generate

    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)

    prompt = np.array([[5, 9, 33, 12]], np.int64)
    with torch.no_grad():
        ref = hf.generate(
            torch.from_numpy(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0,
        ).numpy()[:, prompt.shape[1]:]
    got = np.asarray(
        generate(params, jnp.asarray(prompt, jnp.int32), cfg, max_new=8)
    )
    np.testing.assert_array_equal(got, ref)


def test_config_mapping():
    _, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    assert cfg.d_model == 64 and cfg.n_layers == 2
    assert cfg.n_heads == 4 and cfg.n_kv_heads == 2
    assert cfg.rope_theta == 10000.0 and cfg.norm_eps == 1e-5


def test_tied_embeddings_rejected():
    _, hf_cfg = _tiny_hf(tie=True)
    with pytest.raises(NotImplementedError, match="tied"):
        config_from_hf(hf_cfg)


def test_missing_weight_raises():
    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    sd = dict(hf.state_dict())
    del sd["model.layers.1.mlp.down_proj.weight"]
    with pytest.raises(KeyError):
        params_from_hf(sd, cfg)


def test_shape_mismatch_raises():
    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    sd = dict(hf.state_dict())
    sd["model.embed_tokens.weight"] = torch.zeros(32, 64)
    with pytest.raises(ValueError, match="embed"):
        params_from_hf(sd, cfg)


def test_rope_scaling_rejected():
    _, hf_cfg = _tiny_hf()
    hf_cfg.rope_scaling = {
        "rope_type": "llama3", "factor": 8.0,
        "low_freq_factor": 1.0, "high_freq_factor": 4.0,
        "original_max_position_embeddings": 8192,
    }
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        config_from_hf(hf_cfg)


def test_non_silu_activation_rejected():
    _, hf_cfg = _tiny_hf()
    hf_cfg.hidden_act = "gelu"
    with pytest.raises(NotImplementedError, match="hidden_act"):
        config_from_hf(hf_cfg)


def test_round_trip_and_hf_load():
    """params_to_hf inverts params_from_hf, and torch can load the result:
    HF forward over the re-imported weights matches the original model."""
    from k8s_gpu_device_plugin_tpu.models.convert import params_to_hf

    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)
    sd = params_to_hf(params, cfg)

    # exact tensor round trip (f32 all the way)
    for name, ref in hf.state_dict().items():
        if "rotary_emb" in name:
            continue
        np.testing.assert_allclose(
            sd[name], ref.detach().float().numpy(), atol=1e-7,
            err_msg=name,
        )

    # and torch accepts it as a real checkpoint
    hf2, _ = _tiny_hf()
    hf2.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()})
    tokens = torch.tensor([[2, 9, 41, 17]])
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(tokens).logits.numpy(), hf(tokens).logits.numpy(), atol=1e-6
        )


def test_params_to_hf_rejects_moe():
    from k8s_gpu_device_plugin_tpu.models.convert import params_to_hf
    from k8s_gpu_device_plugin_tpu.models.llama import (
        LlamaConfig as Cfg, init_params,
    )

    cfg = Cfg.tiny(n_layers=1, n_experts=4)
    params = init_params(jax.random.key(0), cfg)
    with pytest.raises(NotImplementedError, match="MoE"):
        params_to_hf(params, cfg)


def test_params_to_hf_contiguous_and_layer_check():
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.convert import params_to_hf

    hf, hf_cfg = _tiny_hf()
    cfg = config_from_hf(hf_cfg, dtype=jnp.float32)
    params = params_from_hf(hf.state_dict(), cfg)
    sd = params_to_hf(params, cfg)
    assert all(w.flags["C_CONTIGUOUS"] for w in sd.values())
    with pytest.raises(ValueError, match="stacked layers"):
        params_to_hf(params, replace(cfg, n_layers=1))


def test_mistral_sliding_window_mapped():
    """Mistral-style checkpoints (layout-identical to Llama, trained with
    windowed attention) must carry their window through conversion, and
    our windowed forward must match transformers' MistralForCausalLM."""
    cfg_m = transformers.MistralConfig(
        vocab_size=64, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
        max_position_embeddings=128, sliding_window=8,
    )
    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(cfg_m).eval()
    cfg = config_from_hf(cfg_m, dtype=jnp.float32)
    assert cfg.sliding_window == 8
    params = params_from_hf(hf.state_dict(), cfg)
    # 16 tokens > window 8, so the windowed mask is load-bearing here
    tokens = np.arange(1, 17, dtype=np.int64)[None, :]
    with torch.no_grad():
        ref = hf(torch.from_numpy(tokens)).logits.float().numpy()
    got = np.asarray(forward(params, jnp.asarray(tokens, jnp.int32), cfg))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-3)
