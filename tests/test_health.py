"""Wedged-but-present health detection (device/health.py).

The observed failure mode this guards: the tunneled chip's device node
stays present and readable while the runtime hangs forever — node-presence
health would advertise it Healthy indefinitely. The assessor upgrades the
boolean with runtime-gauge staleness (endpoint reachable but silent =
suspect; endpoint gone = workload exited cleanly, NOT suspect) and an
opt-in bounded idle probe. Verdict "Unknown" withdraws the chip from
kubelet (any non-"Healthy" string is unschedulable) without claiming a
confirmed fault.
"""

from __future__ import annotations

import asyncio

import pytest

from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.device.chip import HEALTHY, UNHEALTHY, UNKNOWN
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.device.health import (
    HealthAssessor,
    assessor_from_config,
)
from k8s_gpu_device_plugin_tpu.metrics.runtime_metrics import (
    DUTY_CYCLE,
    HBM_USAGE,
    FakeRuntimeMetricsServer,
    LibtpuUsageReader,
)


class _Clock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class _FakeReader:
    """Scriptable read_status(): a list of (usages, status) frames."""

    def __init__(self, frames):
        self.frames = list(frames)

    def read_status(self):
        if len(self.frames) > 1:
            return self.frames.pop(0)
        return self.frames[0]


def test_stale_gauges_with_reachable_endpoint_mark_unknown():
    """Gauges flowed, then the endpoint keeps answering but serves nothing:
    after stale_after the chip is Unknown (wedged-but-present signature)."""
    clock = _Clock()
    reader = _FakeReader([
        ({0: object(), 1: object()}, "data"),
        ({}, "silent"),
    ])
    a = HealthAssessor(reader=reader, stale_after=30.0, clock=clock)
    node = {0: True, 1: True}

    assert a.assess(node) == {0: HEALTHY, 1: HEALTHY}
    clock.t = 10.0  # within the window: still healthy
    assert a.assess(node) == {0: HEALTHY, 1: HEALTHY}
    clock.t = 45.0  # past stale_after with the endpoint still reachable
    assert a.assess(node) == {0: UNKNOWN, 1: UNKNOWN}


def test_clean_workload_exit_is_not_a_wedge():
    """Gauges flowed, then the endpoint disappears entirely (workload
    exited, chips released): health returns to node-presence, never
    Unknown."""
    clock = _Clock()
    reader = _FakeReader([
        ({0: object()}, "data"),
        ({}, "absent"),
    ])
    a = HealthAssessor(reader=reader, stale_after=30.0, clock=clock)
    node = {0: True}

    assert a.assess(node) == {0: HEALTHY}
    clock.t = 120.0  # way past stale_after — but the endpoint is GONE
    assert a.assess(node) == {0: HEALTHY}


def test_node_absence_stays_unhealthy_and_partial_staleness_is_per_chip():
    """Node-level failure wins outright; staleness is judged per chip (one
    hung chip of a multi-chip workload goes Unknown alone)."""
    clock = _Clock()
    reader = _FakeReader([
        ({0: object(), 1: object()}, "data"),
        ({0: object()}, "data"),  # chip 1's gauges stop; endpoint still up
    ])
    a = HealthAssessor(reader=reader, stale_after=30.0, clock=clock)

    assert a.assess({0: True, 1: True, 2: False}) == {
        0: HEALTHY, 1: HEALTHY, 2: UNHEALTHY,
    }
    clock.t = 45.0
    assert a.assess({0: True, 1: True, 2: False}) == {
        0: HEALTHY, 1: UNKNOWN, 2: UNHEALTHY,
    }


def test_idle_probe_failure_marks_unknown_with_bounded_cadence():
    """No workload anywhere: the opt-in probe runs at most once per
    interval; a hung probe marks chips Unknown until a probe succeeds or
    gauges reappear."""
    clock = _Clock()
    calls = []
    verdict = {"ok": False}

    def probe() -> bool:
        calls.append(clock.t)
        return verdict["ok"]

    reader = _FakeReader([({}, "absent")])
    a = HealthAssessor(
        reader=reader, stale_after=30.0, probe=probe,
        probe_interval=600.0, clock=clock,
    )
    node = {0: True}

    assert a.assess(node) == {0: UNKNOWN}
    clock.t = 300.0  # inside the interval: no second child spawned
    assert a.assess(node) == {0: UNKNOWN}
    assert calls == [0.0]
    clock.t = 700.0  # next interval: probe recovers
    verdict["ok"] = True
    assert a.assess(node) == {0: HEALTHY}
    assert calls == [0.0, 700.0]


def test_probe_never_fires_on_silent_endpoint():
    """A reachable-but-silent endpoint is a live process that may hold the
    single-client runtime lock (e.g. a workload mid-init): the idle probe
    must not race it. Only a fully absent endpoint unlocks the probe."""
    clock = _Clock()
    calls = []
    reader = _FakeReader([({}, "silent")])
    a = HealthAssessor(
        reader=reader, stale_after=30.0,
        probe=lambda: calls.append(clock.t) or False,
        probe_interval=1.0, clock=clock,
    )
    for t in (0.0, 5.0, 10.0):
        clock.t = t
        assert a.assess({0: True}) == {0: HEALTHY}
    assert calls == []  # never probed across three due intervals

    # no-scrape mode (event-loop callers) must also never probe
    reader2 = _FakeReader([({}, "absent")])
    a2 = HealthAssessor(
        reader=reader2, stale_after=30.0,
        probe=lambda: calls.append(clock.t) or False,
        probe_interval=1.0, clock=clock,
    )
    assert a2.assess({0: True}, allow_probe=False, scrape=False) == {0: HEALTHY}
    assert calls == []


def test_reader_cache_ttl_coalesces_scrapes():
    """With cache_ttl_seconds set (the daemon wiring), back-to-back reads
    share one RPC round; the raw default stays uncached."""
    server = FakeRuntimeMetricsServer({HBM_USAGE: {0: 1024}})
    port = server.start()
    cached = LibtpuUsageReader(
        ports=[port], timeout_seconds=2.0, cache_ttl_seconds=60.0
    )
    fresh = LibtpuUsageReader(ports=[port], timeout_seconds=2.0)
    try:
        assert cached.read_status()[1] == "data"
        assert fresh.read_status()[1] == "data"
        server.values.clear()
        assert cached.read_status()[1] == "data"  # served from cache
        assert fresh.read_status()[1] == "silent"  # uncached sees reality
    finally:
        server.stop()
        cached.close()
        fresh.close()


def test_gauges_flowing_retire_probe_failure():
    """A failed idle probe must not outlive direct evidence of liveness:
    once gauges flow, chips are Healthy again immediately."""
    clock = _Clock()
    reader = _FakeReader([
        ({}, "absent"),
        ({0: object()}, "data"),
    ])
    a = HealthAssessor(
        reader=reader, stale_after=30.0, probe=lambda: False,
        probe_interval=600.0, clock=clock,
    )
    assert a.assess({0: True}) == {0: UNKNOWN}
    clock.t = 5.0
    assert a.assess({0: True}) == {0: HEALTHY}


def test_reader_endpoint_status_distinguishes_absent_from_silent():
    """LibtpuUsageReader.read_status: a reachable endpoint with no gauges
    is 'silent'; no listener at all is 'absent'; gauges are 'data'."""
    server = FakeRuntimeMetricsServer(
        {HBM_USAGE: {0: 2 * 1024**3}, DUTY_CYCLE: {0: 87.5}}
    )
    port = server.start()
    reader = LibtpuUsageReader(ports=[port], timeout_seconds=2.0)
    try:
        usages, status = reader.read_status()
        assert status == "data"
        assert usages[0].hbm_used_bytes == 2 * 1024**3
        assert usages[0].duty_cycle_percent == pytest.approx(87.5)

        server.values.clear()  # endpoint still up, nothing published
        usages, status = reader.read_status()
        assert status == "silent" and usages == {}
    finally:
        server.stop()
        reader.close()

    # listener gone: UNAVAILABLE -> absent (the just-stopped server's
    # listener can take a beat to fully close; retry briefly)
    import time

    reader2 = LibtpuUsageReader(ports=[port], timeout_seconds=0.5)
    try:
        for _ in range(20):
            usages, status = reader2.read_status()
            if status == "absent":
                break
            time.sleep(0.2)
        assert status == "absent" and usages == {}
    finally:
        reader2.close()


def test_manager_pushes_unknown_on_stale_runtime_endpoint(tmp_path):
    """End to end through the manager (the VERDICT-required shape): a fake
    runtime endpoint goes stale while staying reachable; the health loop
    pushes a ListAndWatch update whose devices are no longer Healthy."""
    from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
    from k8s_gpu_device_plugin_tpu.plugin.testing import FakeKubelet
    from k8s_gpu_device_plugin_tpu.utils.latch import Latch

    server = FakeRuntimeMetricsServer({HBM_USAGE: {i: 1024 for i in range(4)}})
    port = server.start()
    clock = _Clock()
    assessor = HealthAssessor(
        reader=LibtpuUsageReader(ports=[port], timeout_seconds=2.0),
        stale_after=5.0,
        clock=clock,
    )

    async def body():
        kubelet = FakeKubelet(str(tmp_path))
        await kubelet.start()
        cfg = Config(kubelet_socket_dir=str(tmp_path), libtpu_path="")
        manager = PluginManager(
            cfg, Latch(), backend=FakeBackend("v5e-4"),
            health_interval=0.05, health_assessor=assessor,
        )
        task = asyncio.create_task(manager.start())
        try:
            await kubelet.wait_for_registrations(1)
            plugin = manager.plugins[0]

            async def states() -> set[str]:
                return {c.health for c in plugin.chips.values()}

            await asyncio.sleep(0.3)
            assert await states() == {HEALTHY}

            # endpoint stays reachable but publishes nothing; advance the
            # assessor clock past stale_after
            server.values.clear()
            clock.t = 60.0
            for _ in range(100):
                await asyncio.sleep(0.05)
                if await states() == {UNKNOWN}:
                    break
            assert await states() == {UNKNOWN}
        finally:
            await manager.stop()
            await asyncio.wait_for(task, 10)
            await kubelet.stop()

    try:
        asyncio.run(body())
    finally:
        server.stop()


def test_assessor_reasons_and_scrape_failure_branch():
    """``last_reasons`` names WHY each verdict is what it is — and a
    scrape that raises (the best-effort branch: warning, empty live set,
    liveness history kept) lets previously-seen chips go stale against
    it instead of wedging the assessor."""

    class _FlakyReader:
        def __init__(self):
            self.n = 0

        def read_status(self):
            self.n += 1
            if self.n == 1:
                return {0: object(), 1: object()}, "data"
            raise RuntimeError("scrape exploded")

    clock = _Clock()
    a = HealthAssessor(reader=_FlakyReader(), stale_after=30.0, clock=clock)
    assert a.assess({0: True, 1: True, 2: False}) == {
        0: HEALTHY, 1: HEALTHY, 2: UNHEALTHY,
    }
    assert a.last_reasons == {
        0: "ok", 1: "ok", 2: "node_unhealthy",
    }
    # every later scrape raises; history is KEPT, so the seen chips go
    # stale once the window passes — the scrape-failure branch must not
    # read as a clean workload exit
    clock.t = 10.0
    assert a.assess({0: True, 1: True, 2: False})[0] == HEALTHY
    clock.t = 45.0
    assert a.assess({0: True, 1: True, 2: False}) == {
        0: UNKNOWN, 1: UNKNOWN, 2: UNHEALTHY,
    }
    assert a.last_reasons == {
        0: "stale_gauges", 1: "stale_gauges", 2: "node_unhealthy",
    }

    # probe-demotion reason
    clock2 = _Clock()
    a2 = HealthAssessor(
        reader=_FakeReader([({}, "absent")]), stale_after=30.0,
        probe=lambda: False, probe_interval=600.0, clock=clock2,
    )
    assert a2.assess({0: True}) == {0: UNKNOWN}
    assert a2.last_reasons == {0: "probe_failed"}


def test_manager_health_recovery_and_allocation_journal(tmp_path):
    """The full flap under the fake backend: gauges stop (Unknown,
    reason stale_gauges) then flow again (Healthy) — and the manager's
    allocation journal carries one ``health_transition`` event per chip
    per flip, with the assessor's reason (``recovered`` on the way
    back)."""
    from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
    from k8s_gpu_device_plugin_tpu.plugin.testing import FakeKubelet
    from k8s_gpu_device_plugin_tpu.utils.latch import Latch

    server = FakeRuntimeMetricsServer({HBM_USAGE: {i: 1024 for i in range(4)}})
    port = server.start()
    clock = _Clock()
    assessor = HealthAssessor(
        reader=LibtpuUsageReader(ports=[port], timeout_seconds=2.0),
        stale_after=5.0,
        clock=clock,
    )

    async def body():
        kubelet = FakeKubelet(str(tmp_path))
        await kubelet.start()
        cfg = Config(kubelet_socket_dir=str(tmp_path), libtpu_path="")
        manager = PluginManager(
            cfg, Latch(), backend=FakeBackend("v5e-4"),
            health_interval=0.05, health_assessor=assessor,
        )
        task = asyncio.create_task(manager.start())
        try:
            await kubelet.wait_for_registrations(1)
            plugin = manager.plugins[0]

            async def states() -> set[str]:
                return {c.health for c in plugin.chips.values()}

            async def wait_for(state: str) -> None:
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    if await states() == {state}:
                        return
                assert await states() == {state}

            await asyncio.sleep(0.3)
            assert await states() == {HEALTHY}

            # demote: endpoint reachable but silent past stale_after
            server.values.clear()
            clock.t = 60.0
            await wait_for(UNKNOWN)

            # recover: gauges flow again
            server.values.update({HBM_USAGE: {i: 1024 for i in range(4)}})
            clock.t = 61.0
            await wait_for(HEALTHY)

            events = manager.journal.events_payload()["events"]
            flips = [e for e in events if e["kind"] == "health_transition"]
            down = [e for e in flips if e["new"] == UNKNOWN]
            up = [e for e in flips if e["new"] == HEALTHY]
            # one event per chip per flip, carrying chip id + reason
            assert {e["chip"] for e in down} == {0, 1, 2, 3}
            assert {e["reason"] for e in down} == {"stale_gauges"}
            assert {e["old"] for e in down} == {HEALTHY}
            assert {e["chip"] for e in up} == {0, 1, 2, 3}
            assert {e["reason"] for e in up} == {"recovered"}
            assert {e["old"] for e in up} == {UNKNOWN}
            # seqs are monotonic and unique journal-wide
            seqs = [e["seq"] for e in events]
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        finally:
            await manager.stop()
            await asyncio.wait_for(task, 10)
            await kubelet.stop()

    try:
        asyncio.run(body())
    finally:
        server.stop()


def test_serving_health_reports_replica_identity():
    """The serving plane's /v1/health carries a stable fleet identity:
    ``replica_id`` (the --replicaId flag; hostname:port when unset) and
    ``uptime_s`` — what serving/fleet.py's registry and dashboards tell
    replicas (and restarts: uptime resetting) apart by. Schema pinned
    here so the fleet layer can rely on it."""
    import aiohttp
    import jax

    from k8s_gpu_device_plugin_tpu.models.llama import (
        LlamaConfig,
        init_params,
    )
    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        InferenceServer,
    )

    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)

    async def probe(replica_id: str) -> dict:
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        server = InferenceServer(engine, host="127.0.0.1", port=0,
                                 replica_id=replica_id)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        while server.bound_port is None:
            await asyncio.sleep(0.01)
        try:
            async with aiohttp.ClientSession() as session:
                url = f"http://127.0.0.1:{server.bound_port}/v1/health"
                async with session.get(url) as r:
                    assert r.status == 200
                    first = await r.json()
                await asyncio.sleep(0.05)
                async with session.get(url) as r:
                    second = await r.json()
                return first, second, server.bound_port
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    # pinned schema: the engine surface plus the fleet identity fields
    first, second, port = asyncio.run(probe("pod-7"))
    for key in ("slots", "active", "prefilling", "queued", "alive",
                "replica_id", "uptime_s", "supervisor"):
        assert key in first, f"/v1/health missing {key}"
    assert first["replica_id"] == "pod-7"
    assert second["replica_id"] == "pod-7"  # stable across reads
    assert 0.0 <= first["uptime_s"] <= second["uptime_s"]
    # the supervisor section (serving/supervisor.py crash recovery):
    # schema pinned so fleet dashboards and the router's registry can
    # rely on it — state, the rolling restart budget, replay/resume
    # tallies, and the last crash (null until one happens)
    sup = first["supervisor"]
    for key in ("state", "max_restarts", "window_s", "crashes_total",
                "restarts_total", "replayed_total", "resumed_total",
                "last_crash"):
        assert key in sup, f"supervisor section missing {key}"
    assert sup["state"] == "ok"
    assert sup["restarts_total"] == 0
    assert sup["last_crash"] is None
    assert sup["max_restarts"] >= 1  # recovery is ON by default

    # default identity: hostname:port (the FleetRegistry bare-URL rule)
    import socket

    first, _second, port = asyncio.run(probe(""))
    assert first["replica_id"] == f"{socket.gethostname()}:{port}"


def test_assessor_from_config_wiring():
    """Config knobs: default = staleness-only assessor; 'off' metrics +
    probe off = no assessor; probe 'on' = probe wired alongside the
    reader; a shared reader is honored rather than rebuilt."""
    assert assessor_from_config(Config(runtime_metrics_ports="off")) is None

    a = assessor_from_config(Config())
    assert a is not None and a._probe is None

    a = assessor_from_config(Config(health_idle_probe="on"))
    assert a is not None and a._probe is not None and a._reader is not None

    shared = LibtpuUsageReader(ports=[1])
    a = assessor_from_config(Config(), reader=shared)
    assert a is not None and a._reader is shared

    # probe without gauges would contend with a metrics-less workload for
    # the runtime lock: config refuses it, the factory degrades it
    with pytest.raises(ValueError):
        Config(runtime_metrics_ports="off", health_idle_probe="on").validate()
    a = assessor_from_config(
        Config(runtime_metrics_ports="off", health_idle_probe="on")
    )
    assert a is None  # probe dropped, no reader -> no assessor

    for bad in (
        Config(health_idle_probe="maybe"),
        Config(health_stale_after=0),
        Config(health_idle_probe_interval=0),
        Config(health_idle_probe_timeout=-1),
    ):
        with pytest.raises(ValueError):
            bad.validate()
