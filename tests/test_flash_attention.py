"""Flash attention kernel vs the reference oracle (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.ops.attention import mha_reference
from k8s_gpu_device_plugin_tpu.ops.flash_attention import (
    _HAS_PLTPU,
    flash_attention,
    supports,
)

pytestmark = pytest.mark.skipif(not _HAS_PLTPU, reason="pallas tpu unavailable")


def make_qkv(key, b=1, s=256, hq=4, hkv=2, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, hq, d), dtype),
        jax.random.normal(kk, (b, s, hkv, d), dtype),
        jax.random.normal(kv, (b, s, hkv, d), dtype),
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = make_qkv(jax.random.key(0))
    expected = mha_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_flash_gqa_and_mha():
    q, k, v = make_qkv(jax.random.key(1), hq=4, hkv=4)
    expected = mha_reference(q, k, v)
    got = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_flash_grads_match_reference():
    q, k, v = make_qkv(jax.random.key(2), s=256)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )


@pytest.mark.parametrize("bq,bk", [(128, 128), (128, 256), (256, 128)])
def test_flash_grads_multiblock(bq, bk):
    """Exercise the backward kernels' cross-block accumulation and causal
    block-skip paths (nq>1 and/or nk>1), which the 1024 defaults reduce to
    a single block at test sizes. The backward is tiled independently of
    the forward (block_*_bwd), so both are pinned here."""
    q, k, v = make_qkv(jax.random.key(5), s=256)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk,
                block_q_bwd=bq, block_k_bwd=bk, interpret=True
            )
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )


def test_flash_forward_multiblock_noncausal():
    q, k, v = make_qkv(jax.random.key(6), s=256)
    expected = mha_reference(q, k, v, causal=False)
    got = flash_attention(
        q, k, v, causal=False, block_q=128, block_k=128, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_supports_gates():
    q, k, v = make_qkv(jax.random.key(3))
    assert supports(q, k, v)
    q2, k2, v2 = make_qkv(jax.random.key(3), s=200)  # not block-aligned
    assert not supports(q2, k2, v2)
    q3, k3, v3 = make_qkv(jax.random.key(3), d=32)  # narrow head dim
    assert not supports(q3, k3, v3)
    # non-4D input: supports() must answer False, not raise
    assert not supports(q[0], k[0], v[0])


def test_flash_rejects_unaligned_seq():
    """Tail rows past the last full block would be uninitialized; the entry
    point must refuse rather than silently return garbage."""
    import pytest

    from k8s_gpu_device_plugin_tpu.ops.flash_attention import flash_attention

    q, k, v = make_qkv(jax.random.key(5), s=200)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, interpret=True)


def test_flash_bf16():
    q, k, v = make_qkv(jax.random.key(4), dtype=jnp.bfloat16)
    expected = mha_reference(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expected, np.float32), atol=3e-2
    )


def test_flash_bwd_blocks_differ_from_fwd():
    """Backward tiling independent of forward: grads must match the oracle
    when the two tilings disagree (the fwd lse/residuals feed bwd kernels
    tiled differently)."""
    q, k, v = make_qkv(jax.random.key(7), s=256)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, block_q=256, block_k=256,
                block_q_bwd=128, block_k_bwd=128, interpret=True,
            )
            ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )


def test_tuned_tilings_file_resolution(tmp_path, monkeypatch):
    """flash_tune's persisted winners drive block resolution: exact seq
    match first, nearest shorter seq as fallback, explicit args always
    winning; record_tuned_blocks merges and invalidates the cache."""
    import json

    from k8s_gpu_device_plugin_tpu.ops import flash_attention as fa

    path = tmp_path / "tilings.json"
    monkeypatch.setenv(fa.TUNING_FILE_ENV, str(path))
    fa._tuned_blocks.cache_clear()
    try:
        # no file -> module defaults
        assert fa._resolve_blocks("fwd", 2048) is None

        written = fa.record_tuned_blocks({
            "fwd:2048": (512, 1024), "bwd:2048": (256, 512),
        })
        assert written == str(path)
        assert fa._resolve_blocks("fwd", 2048) == (512, 1024)
        assert fa._resolve_blocks("bwd", 2048) == (256, 512)
        # nearest measured seq <= s serves longer sequences
        assert fa._resolve_blocks("fwd", 8192) == (512, 1024)
        # nothing measured at or below this seq
        assert fa._resolve_blocks("fwd", 1024) is None

        # merge keeps prior entries and the cache reloads
        fa.record_tuned_blocks({"fwd:8192": (1024, 2048)})
        data = json.loads(path.read_text())
        assert data["fwd:2048"] == [512, 1024]
        assert fa._resolve_blocks("fwd", 8192) == (1024, 2048)

        # corrupt/invalid entries are ignored, not fatal
        path.write_text('{"fwd:2048": [0, -5], "bwd:2048": "junk", "x": 1}')
        fa._tuned_blocks.cache_clear()
        assert fa._resolve_blocks("fwd", 2048) is None
        path.write_text("not json")
        fa._tuned_blocks.cache_clear()
        assert fa._tuned_blocks() == {}
    finally:
        fa._tuned_blocks.cache_clear()


def test_tuned_tilings_feed_flash_attention(tmp_path, monkeypatch):
    """End to end: with winners on disk, a plain flash_attention call uses
    them (observable via identical outputs + the kernel accepting only
    dividing blocks), and explicit args still override."""
    import jax
    import jax.numpy as jnp

    from k8s_gpu_device_plugin_tpu.ops import flash_attention as fa

    path = tmp_path / "tilings.json"
    monkeypatch.setenv(fa.TUNING_FILE_ENV, str(path))
    fa.record_tuned_blocks({"fwd:256": (128, 128), "bwd:256": (128, 128)})
    try:
        q = jax.random.normal(jax.random.key(0), (1, 256, 4, 64), jnp.bfloat16)
        k = jax.random.normal(jax.random.key(1), (1, 256, 2, 64), jnp.bfloat16)
        v = jax.random.normal(jax.random.key(2), (1, 256, 2, 64), jnp.bfloat16)
        tuned = fa.flash_attention(q, k, v, interpret=True)
        explicit = fa.flash_attention(
            q, k, v, block_q=128, block_k=128, interpret=True
        )
        assert jnp.allclose(
            tuned.astype(jnp.float32), explicit.astype(jnp.float32)
        )
    finally:
        fa._tuned_blocks.cache_clear()
