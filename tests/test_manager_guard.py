"""Crash-loop guard & fatality propagation (≙ plugin.go:111-127 semantics).

Refined budget semantics (see manager._check_crash_budget): failed start
attempts retry forever on the 30s loop (manager.go:137 — a kubelet outage is
never fatal); SUCCESSFUL restart cycles are metered at 5 per rolling hour
per resource, and the budget survives rebuilds (manager-side, keyed by
resource — stricter than the reference, which zeroes its count on every
rebuild). Exhaustion raises out of ``start()`` so the main.py run group
terminates the daemon (``log.Fatal`` ≙).
"""

import asyncio

import pytest

import k8s_gpu_device_plugin_tpu.plugin.plugin as plugin_mod
from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.main import run_daemon
from k8s_gpu_device_plugin_tpu.plugin.manager import MAX_STARTS, PluginManager
from k8s_gpu_device_plugin_tpu.plugin.testing import FakeKubelet
from k8s_gpu_device_plugin_tpu.utils.latch import Latch


def test_start_failures_retry_forever_without_fatal(monkeypatch, tmp_path):
    """No kubelet -> every start attempt fails -> NOT fatal: the manager must
    still be alive and retrying well past MAX_STARTS attempts."""
    monkeypatch.setattr(plugin_mod, "DIAL_TIMEOUT_SECONDS", 0.1)

    async def body():
        cfg = Config(kubelet_socket_dir=str(tmp_path), libtpu_path="")
        manager = PluginManager(
            cfg,
            Latch(),
            backend=FakeBackend("v5e-4"),
            health_interval=30,
            retry_interval=0.05,
        )
        task = asyncio.create_task(manager.start())
        # > MAX_STARTS failed attempts happen within ~a second at this pace
        await asyncio.sleep(2.0)
        assert not task.done(), task.exception() if task.done() else None
        await manager.stop()
        await asyncio.wait_for(task, 10)

    asyncio.run(body())


def test_restart_storm_exhausts_budget_and_is_fatal(tmp_path):
    """> MAX_STARTS successful restart cycles within the window -> fatal."""

    async def body():
        kubelet = FakeKubelet(str(tmp_path))
        await kubelet.start()
        cfg = Config(kubelet_socket_dir=str(tmp_path), libtpu_path="")
        manager = PluginManager(
            cfg, Latch(), backend=FakeBackend("v5e-4"), health_interval=30
        )
        task = asyncio.create_task(manager.start())
        try:
            await kubelet.wait_for_registrations(1)
            for n in range(2, MAX_STARTS + 2):
                manager.restart()
                if n <= MAX_STARTS:
                    await kubelet.wait_for_registrations(n)
                else:
                    with pytest.raises(RuntimeError, match="crash-looped"):
                        await asyncio.wait_for(task, 10)
        finally:
            if not task.done():
                await manager.stop()
                await asyncio.gather(task, return_exceptions=True)
            await kubelet.stop()

    asyncio.run(body())


def test_run_daemon_exits_on_manager_failure(monkeypatch, tmp_path):
    """A manager whose start() raises must take run_daemon down, not hang.

    (Review finding: the reference's oklog run group exits when any actor
    fails; the first draft of run_daemon awaited stop.wait() forever.)
    """
    import k8s_gpu_device_plugin_tpu.plugin.manager as manager_mod

    def explode(self):
        raise RuntimeError("enumeration exploded")

    monkeypatch.setattr(manager_mod.PluginManager, "_load_plugins", explode)

    async def body():
        cfg = Config(
            kubelet_socket_dir=str(tmp_path),
            web_listen_address="127.0.0.1:0",
            libtpu_path="",
            backend="fake",
        )
        cfg.log.file_dir = ""
        with pytest.raises(RuntimeError, match="enumeration exploded"):
            await asyncio.wait_for(run_daemon(cfg), timeout=30)

    asyncio.run(body())
