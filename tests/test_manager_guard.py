"""Crash-loop guard & fatality propagation (≙ plugin.go:111-127 semantics).

The reference kept the 5-per-hour restart budget per plugin instance (reset
on every rebuild) and its "give up" was log.Fatal. Here the budget lives in
the manager, keyed by resource, and exhaustion raises out of ``start()`` so
the main.py run group terminates the daemon.
"""

import asyncio
import tempfile

import pytest

import k8s_gpu_device_plugin_tpu.plugin.plugin as plugin_mod
from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.main import run_daemon
from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
from k8s_gpu_device_plugin_tpu.utils.latch import Latch


def test_crash_loop_budget_is_fatal(monkeypatch, tmp_path):
    """No kubelet + fast retries -> budget exhausted -> RuntimeError."""
    monkeypatch.setattr(plugin_mod, "DIAL_TIMEOUT_SECONDS", 0.2)

    async def body():
        cfg = Config(kubelet_socket_dir=str(tmp_path), libtpu_path="")
        manager = PluginManager(
            cfg,
            Latch(),
            backend=FakeBackend("v5e-4"),
            health_interval=30,
            retry_interval=0.1,
        )
        with pytest.raises(RuntimeError, match="crash-looped"):
            await asyncio.wait_for(manager.start(), timeout=30)

    asyncio.run(body())


def test_run_daemon_exits_on_manager_failure(monkeypatch, tmp_path):
    """A manager that can never start must take run_daemon down, not hang.

    (Review finding: the reference's oklog run group exits when any actor
    fails; the first draft of run_daemon awaited stop.wait() forever.)
    """
    monkeypatch.setattr(plugin_mod, "DIAL_TIMEOUT_SECONDS", 0.2)
    import k8s_gpu_device_plugin_tpu.plugin.manager as manager_mod

    monkeypatch.setattr(manager_mod, "RETRY_INTERVAL_SECONDS", 0.1)

    async def body():
        cfg = Config(
            kubelet_socket_dir=str(tmp_path),
            web_listen_address="127.0.0.1:0",
            libtpu_path="",
            backend="fake",
        )
        cfg.log.file_dir = ""
        with pytest.raises(RuntimeError, match="crash-looped"):
            await asyncio.wait_for(run_daemon(cfg), timeout=30)

    asyncio.run(body())
