"""Test harness config.

JAX-dependent tests run on a virtual 8-device CPU mesh (multi-chip shardings
are validated without TPU hardware, per SURVEY §4 "multi-node without a
cluster"). Env must be set before the first ``import jax`` anywhere.
"""

import os
import sys

# Force CPU: the session environment pins JAX_PLATFORMS=axon (the tunneled
# real chip) and its sitecustomize imports jax + sets jax_platforms at
# interpreter start, so env vars alone are too late — override through the
# live config before any backend is initialized.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Hermetic kernel-tilings store: a hardware sweep (flash_tune /
# kernel_tune via the bench runner) persists per-generation block
# winners at the repo root, and block choices change which jit traces
# the attention kernels take — tests must see ONE fixed store
# regardless of what a previous bench run recorded on this host.
# Tests that exercise the store itself point the env somewhere else.
os.environ.setdefault(
    "KERNEL_TUNINGS_FILE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".test_kernel_tilings.json"),
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    if _xb.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()
except Exception:  # noqa: BLE001 - best effort; devices check below is the gate
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --- hang visibility ------------------------------------------------------

import faulthandler  # noqa: E402
import threading as _threading  # noqa: E402

# Crash stacks (SIGSEGV/SIGABRT — the intermittent jaxlib compile
# segfault class documented on the fixtures below) always print with
# tracebacks instead of a bare signal death.
faulthandler.enable()

# Dump-on-timeout: the tier-1 gate wraps the suite in `timeout -k 870`,
# which SIGKILLs a deadlocked run with no diagnostics — a stress-test
# deadlock used to eat the whole budget and die silently. Two layers:
#
# - pytest's own faulthandler plugin (faulthandler_timeout=300 in
#   pyproject.toml) dumps all thread stacks when a single test phase
#   hangs. It owns CPython's ONE dump_traceback_later slot (armed per
#   test, cancelled after), so this file must not use that API — a
#   conftest-armed timer would be silently disarmed at test #1.
# - a daemon threading.Timer here covers everything OUTSIDE a test
#   phase (collection, session-fixture finalizers): shortly before the
#   tier-1 wall it dumps every thread's stack via
#   faulthandler.dump_traceback. A Python-level timer cannot fire if a
#   C extension deadlocks while HOLDING the GIL — pytest's C-side timer
#   covers that case for test bodies — but it survives pytest's
#   arm/cancel cycle, which the singleton API does not.
#
# The dump goes to stderr AND to .hang_dump.log at the repo root:
# pytest's fd-level capture owns fd 2 by the time this conftest loads,
# and a SIGKILLed run never replays its capture tmpfile — the log file
# is what survives the kill. A healthy run never creates it. Noise-
# safe either way: exit codes are unaffected.
_HANG_DUMP_S = float(os.environ.get("GRAFT_HANG_DUMP_SECONDS", "840"))
_HANG_DUMP_FILE = os.environ.get(
    "GRAFT_HANG_DUMP_FILE",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".hang_dump.log"),
)
if _HANG_DUMP_S > 0:
    def _dump_stacks_before_the_wall() -> None:
        msg = (
            f"\n=== conftest hang watchdog: {_HANG_DUMP_S:.0f}s elapsed, "
            "dumping all thread stacks before the tier-1 timeout kill "
            f"(also persisted to {_HANG_DUMP_FILE}) ===\n"
        )
        targets = [sys.stderr]
        try:
            targets.append(open(_HANG_DUMP_FILE, "w"))
        except OSError:
            pass
        for t in targets:
            try:
                t.write(msg)
                t.flush()
                faulthandler.dump_traceback(all_threads=True, file=t)
                if t is not sys.stderr:
                    t.close()
            except Exception:  # noqa: BLE001 - diagnostics must not raise
                pass

    _hang_timer = _threading.Timer(_HANG_DUMP_S,
                                   _dump_stacks_before_the_wall)
    _hang_timer.daemon = True  # never outlives a finished run
    _hang_timer.start()


# --- shared fixtures ------------------------------------------------------

import logging  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402


_TESTS_SINCE_CLEAR = 0


@pytest.fixture(autouse=True)
def _bound_compiled_executable_accumulation():
    """Cap how many compiled executables one pytest process accumulates.

    The full suite compiles many hundreds of XLA:CPU programs in one
    process; at that accumulation this jaxlib build segfaults
    intermittently INSIDE a later compile (observed six times across
    full-suite runs — single-threaded, load-independent, at whichever
    heavy-compile test came late enough; every standalone/subset run of
    the same tests passes). Dropping the jit caches every ~20 tests
    frees the earlier executables (and their JIT code memory) so no
    compile ever runs on top of the whole suite's accumulation. Costs
    re-traces after each clear; correctness is unaffected."""
    global _TESTS_SINCE_CLEAR
    yield
    _TESTS_SINCE_CLEAR += 1
    if _TESTS_SINCE_CLEAR >= 20:
        _TESTS_SINCE_CLEAR = 0
        import jax

        jax.clear_caches()


@pytest.fixture(autouse=True)
def _drain_inference_engine_threads():
    """No two tests may ever compile concurrently.

    InferenceEngine.shutdown() joins its worker with a bounded timeout
    (the production stateless-pod stance: the process exits anyway). In
    a long-lived pytest process that bound LEAKS the thread when it is
    mid-compile — stop is already signaled, but the thread outlives the
    test and its compile overlaps the NEXT test's main-thread compile.
    Concurrent XLA:CPU compilation in this jaxlib build segfaults
    intermittently (observed five times across full-suite runs, always
    inside backend_compile_and_load, at whichever test followed leaked
    engines). Joining stragglers between tests removes the overlap."""
    yield
    for t in threading.enumerate():
        if t.name == "inference-engine" and t.is_alive():
            # shutdown() already set _stop: the thread exits as soon as
            # its in-flight step/compile returns. Just outwait it.
            t.join(timeout=300)
            if t.is_alive():
                raise RuntimeError(
                    "inference-engine thread leaked past 300s drain"
                )


@pytest.fixture
def captured_log_records():
    """Attach a capture handler to the project logger for one test.

    (The JSON logger does not propagate to root, so pytest's caplog never
    sees it — capture at the source instead.)
    """
    from k8s_gpu_device_plugin_tpu.utils.log import get_logger

    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record: logging.LogRecord) -> None:
            records.append(record)

    handler = Capture(level=logging.INFO)
    logger = get_logger()
    logger.addHandler(handler)
    try:
        yield records
    finally:
        logger.removeHandler(handler)
