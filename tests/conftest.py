"""Test harness config.

JAX-dependent tests run on a virtual 8-device CPU mesh (multi-chip shardings
are validated without TPU hardware, per SURVEY §4 "multi-node without a
cluster"). Env must be set before the first ``import jax`` anywhere.
"""

import os
import sys

# Force CPU: the session environment pins JAX_PLATFORMS=axon (the tunneled
# real chip) and its sitecustomize imports jax + sets jax_platforms at
# interpreter start, so env vars alone are too late — override through the
# live config before any backend is initialized.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    if _xb.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()
except Exception:  # noqa: BLE001 - best effort; devices check below is the gate
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# --- shared fixtures ------------------------------------------------------

import logging  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def captured_log_records():
    """Attach a capture handler to the project logger for one test.

    (The JSON logger does not propagate to root, so pytest's caplog never
    sees it — capture at the source instead.)
    """
    from k8s_gpu_device_plugin_tpu.utils.log import get_logger

    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record: logging.LogRecord) -> None:
            records.append(record)

    handler = Capture(level=logging.INFO)
    logger = get_logger()
    logger.addHandler(handler)
    try:
        yield records
    finally:
        logger.removeHandler(handler)
