"""Test harness config.

JAX-dependent tests run on a virtual 8-device CPU mesh (multi-chip shardings
are validated without TPU hardware, per SURVEY §4 "multi-node without a
cluster"). Env must be set before the first ``import jax`` anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
