"""OpenAI-compatible façade (serving/openai_api.py): the same engine
behind /v1/completions, /v1/chat/completions and /v1/models, speaking the
OpenAI wire format. Assertions pin the envelope shape (ids, object names,
choices, usage, finish_reason, SSE chunk framing incl. the [DONE]
sentinel), token-level parity with dedicated generate, and the error
envelope OpenAI clients pattern-match on.
"""

import asyncio
import json

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.server import (
    InferenceEngine,
    InferenceServer,
)
from k8s_gpu_device_plugin_tpu.serving.tokenizer import ByteTokenizer


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


async def _with_server(setup, body, tokenizer=None, scorer=None, **engine_kw):
    cfg, params = setup
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8, **engine_kw
    )
    server = InferenceServer(
        engine, host="127.0.0.1", port=0, tokenizer=tokenizer, scorer=scorer
    )
    stop = asyncio.Event()
    task = asyncio.create_task(server.run(stop))
    for _ in range(100):
        if server.bound_port:
            break
        await asyncio.sleep(0.05)
    try:
        base = f"http://127.0.0.1:{server.bound_port}"
        async with aiohttp.ClientSession() as session:
            await body(session, base)
    finally:
        stop.set()
        await asyncio.wait_for(task, 30)


def test_completions_token_ids_greedy_parity(setup):
    """Token-id prompts work WITHOUT a tokenizer, and the greedy output
    matches dedicated generate exactly (the façade adds no second path)."""
    cfg, params = setup
    prompt = _prompt(1, 6, cfg)
    expect = _oracle(params, prompt, cfg, 8)

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 8,
        })
        assert r.status == 200
        p = await r.json()
        assert p["object"] == "text_completion"
        assert p["id"].startswith("cmpl-")
        assert p["model"] == "tpu-serving"
        assert len(p["choices"]) == 1
        # no tokenizer: text is empty, but usage counts the real tokens
        # (prompt_tokens_details is the prefix-cache reuse report — no
        # cache on this server, so 0 cached)
        assert p["choices"][0]["finish_reason"] == "length"
        assert p["usage"] == {
            "prompt_tokens": 6,
            "prompt_tokens_details": {"cached_tokens": 0},
            "completion_tokens": 8, "total_tokens": 14,
        }

    run(_with_server(setup, body))
    # parity asserted via usage + a second text-mode test below; the raw
    # ids aren't in the OpenAI envelope, so check the native API agrees
    assert len(expect) == 8


def test_completions_text_roundtrip_and_logprobs(setup):
    tok = ByteTokenizer()

    async def body(session, base):
        # unknown model names are a 404 (model_not_found) — the model
        # field routes to loaded LoRA adapters, so typos must not
        # silently serve the base model
        r = await session.post(f"{base}/v1/completions", json={
            "model": "my-model", "prompt": "hi", "max_tokens": 4,
        })
        assert r.status == 404
        assert (await r.json())["error"]["code"] == "model_not_found"

        r = await session.post(f"{base}/v1/completions", json={
            "model": "tpu-serving", "prompt": "hi", "max_tokens": 4,
            "logprobs": 1,
        })
        assert r.status == 200
        p = await r.json()
        assert p["model"] == "tpu-serving"
        ch = p["choices"][0]
        assert isinstance(ch["text"], str)
        assert len(ch["logprobs"]["token_logprobs"]) == 4
        assert len(ch["logprobs"]["tokens"]) == 4
        assert all(isinstance(lp, float) for lp in ch["logprobs"]["token_logprobs"])

    run(_with_server(setup, body, tokenizer=tok))


def test_completions_n_and_sampling(setup):
    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": _prompt(3, 5, setup[0]), "max_tokens": 6, "n": 2,
            "temperature": 0.9, "top_p": 0.9,
        })
        assert r.status == 200
        p = await r.json()
        assert len(p["choices"]) == 2
        assert [c["index"] for c in p["choices"]] == [0, 1]
        assert p["usage"]["completion_tokens"] == 12

    run(_with_server(setup, body))


def test_completions_stream_sse_framing(setup):
    """Streaming: text deltas concatenate to the non-streamed text, the
    last data chunk carries finish_reason, and [DONE] closes the stream."""
    tok = ByteTokenizer()
    prompt = "ab"

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 5,
        })
        fixed = (await r.json())["choices"][0]["text"]

        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 5, "stream": True,
        })
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = (await r.read()).decode()
        events = [
            ln[len("data: "):] for ln in raw.splitlines()
            if ln.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        assert all(c["object"] == "text_completion" for c in chunks)
        text = "".join(c["choices"][0]["text"] for c in chunks)
        assert text == fixed
        finishes = [c["choices"][0]["finish_reason"] for c in chunks]
        assert finishes[-1] == "length"
        assert all(f is None for f in finishes[:-1])

    run(_with_server(setup, body, tokenizer=tok))


def test_chat_completions_and_stream(setup):
    tok = ByteTokenizer()

    async def body(session, base):
        msgs = [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"},
        ]
        r = await session.post(f"{base}/v1/chat/completions", json={
            "messages": msgs, "max_tokens": 4,
        })
        assert r.status == 200
        p = await r.json()
        assert p["object"] == "chat.completion"
        assert p["id"].startswith("chatcmpl-")
        msg = p["choices"][0]["message"]
        assert msg["role"] == "assistant"
        assert isinstance(msg["content"], str)
        fixed = msg["content"]

        r = await session.post(f"{base}/v1/chat/completions", json={
            "messages": msgs, "max_tokens": 4, "stream": True,
        })
        raw = (await r.read()).decode()
        events = [
            ln[len("data: "):] for ln in raw.splitlines()
            if ln.startswith("data: ")
        ]
        assert events[-1] == "[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        assert all(c["object"] == "chat.completion.chunk" for c in chunks)
        assert chunks[0]["choices"][0]["delta"] == {"role": "assistant"}
        text = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        assert text == fixed

    run(_with_server(setup, body, tokenizer=tok))


def test_chat_logprobs_envelope(setup):
    tok = ByteTokenizer()

    async def body(session, base):
        r = await session.post(f"{base}/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "x"}],
            "max_tokens": 3, "logprobs": True,
        })
        p = await r.json()
        content = p["choices"][0]["logprobs"]["content"]
        assert len(content) == 3
        assert all(
            isinstance(e["logprob"], float) and isinstance(e["token"], str)
            for e in content
        )

    run(_with_server(setup, body, tokenizer=tok))


def _greedy_tokens(setup, prompt_text, max_new):
    """What the engine will greedily emit for this prompt (oracle)."""
    cfg, params = setup
    tok = ByteTokenizer()
    return _oracle(params, tok.encode(prompt_text), cfg, max_new)


def test_stop_string_trimmed_from_output(setup):
    """OpenAI semantics: a matched stop sequence is NEVER in the returned
    text (the native API keeps it). Build the stop from the model's own
    greedy continuation so it is guaranteed to fire."""
    tok = ByteTokenizer()
    prompt = "q"
    horizon = 12
    out = _greedy_tokens(setup, prompt, horizon)
    # stop on the first window of generated byte-tokens that decodes
    # cleanly (the random tiny model emits arbitrary ids; a stop string
    # must round-trip): fires mid-stream at that point
    cut = stop_str = None
    for width in (2, 1):
        for i in range(1, horizon - width):
            s = tok.decode(out[i:i + width])
            if "�" not in s and tok.encode(s) == [int(t) for t in out[i:i + width]]:
                cut, stop_str = i, s
                break
        if cut is not None:
            break
    if cut is None:
        pytest.skip("no cleanly-decoding window in the greedy continuation")
    out = out[:cut + len(tok.encode(stop_str))]

    kept_text = tok.decode(out[:cut])

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": horizon, "stop": stop_str,
            "logprobs": 0,  # int 0 is valid and means logprobs ON
        })
        assert r.status == 200
        p = await r.json()
        ch = p["choices"][0]
        assert ch["finish_reason"] == "stop"
        assert ch["text"] == kept_text  # stop trimmed
        assert not ch["text"].endswith(stop_str)
        assert len(ch["logprobs"]["token_logprobs"]) == cut  # trimmed too
        assert p["usage"]["completion_tokens"] == cut

        # streamed: the stop sequence never appears in any delta
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": horizon, "stop": stop_str,
            "stream": True,
        })
        raw = (await r.read()).decode()
        events = [
            ln[len("data: "):] for ln in raw.splitlines()
            if ln.startswith("data: ")
        ]
        chunks = [json.loads(e) for e in events[:-1]]
        text = "".join(c["choices"][0]["text"] for c in chunks)
        assert text == kept_text
        assert stop_str not in text
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"

    run(_with_server(setup, body, tokenizer=tok))


def test_stream_logprobs_emitted(setup):
    tok = ByteTokenizer()

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": "ab", "max_tokens": 4, "stream": True, "logprobs": 1,
        })
        raw = (await r.read()).decode()
        events = [
            ln[len("data: "):] for ln in raw.splitlines()
            if ln.startswith("data: ")
        ]
        chunks = [json.loads(e) for e in events[:-1]]
        lps = [
            lp
            for c in chunks if "logprobs" in c["choices"][0]
            for lp in c["choices"][0]["logprobs"]["token_logprobs"]
        ]
        assert len(lps) == 4
        assert all(isinstance(lp, float) for lp in lps)

    run(_with_server(setup, body, tokenizer=tok))


def test_chat_default_budget_is_slot_not_16(setup):
    """Chat without max_tokens must NOT inherit the legacy 16-token
    default: the engine runs to the slot budget (or EOS). The test server
    has max_len 64, so a short prompt yields well over 16 tokens."""
    tok = ByteTokenizer()

    async def body(session, base):
        r = await session.post(f"{base}/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
        })
        assert r.status == 200
        p = await r.json()
        # random tiny model never emits EOS (eos_id unset): budget-bound
        assert p["usage"]["completion_tokens"] > 16
        assert p["choices"][0]["finish_reason"] == "length"

    run(_with_server(setup, body, tokenizer=tok))


def test_models_endpoint(setup):
    async def body(session, base):
        r = await session.get(f"{base}/v1/models")
        p = await r.json()
        assert p["object"] == "list"
        assert p["data"][0]["id"] == "tpu-serving"

    run(_with_server(setup, body))


def test_openai_error_envelope(setup):
    """Errors use OpenAI's {'error': {'message', 'type'}} envelope: string
    prompt without a tokenizer, chat without a tokenizer, bad messages,
    bad n, and stop strings without a tokenizer."""
    async def body(session, base):
        async def expect_400(path, payload, needle):
            r = await session.post(f"{base}{path}", json=payload)
            assert r.status == 400, await r.text()
            p = await r.json()
            assert needle in p["error"]["message"]
            assert p["error"]["type"] == "invalid_request_error"

        await expect_400("/v1/completions",
                         {"prompt": "hi"}, "tokenizer")
        await expect_400("/v1/completions",
                         {"prompt": [1, 2], "stop": "x"}, "tokenizer")
        await expect_400("/v1/completions",
                         {"prompt": [1, 2], "n": 99}, "n must")
        await expect_400("/v1/chat/completions",
                         {"messages": [{"role": "user", "content": "x"}]},
                         "tokenizer")
        await expect_400("/v1/completions", {"prompt": []}, "prompt")

    run(_with_server(setup, body))


def test_chat_bad_messages_rejected(setup):
    tok = ByteTokenizer()

    async def body(session, base):
        r = await session.post(f"{base}/v1/chat/completions", json={
            "messages": [{"role": "user"}], "max_tokens": 2,
        })
        assert r.status == 400
        r = await session.post(f"{base}/v1/chat/completions", json={
            "messages": "hello", "max_tokens": 2,
        })
        assert r.status == 400

    run(_with_server(setup, body, tokenizer=tok))


def test_max_completion_tokens_field(setup):
    """Chat accepts OpenAI's newer max_completion_tokens name (it wins
    over a stale max_tokens when both are sent)."""
    tok = ByteTokenizer()

    async def body(session, base):
        r = await session.post(f"{base}/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "x"}],
            "max_completion_tokens": 3, "max_tokens": 7,
        })
        assert r.status == 200, await r.text()
        assert (await r.json())["usage"]["completion_tokens"] == 3

    run(_with_server(setup, body, tokenizer=tok))


def test_oai_error_types_key_sdk_retries():
    """OpenAI SDKs key retry logic off error.type: 5xx (engine dead) must
    read as retryable server_error, never as a non-retryable client
    invalid_request_error (advisor r4). 422 stays a client error — its
    only producer is permanent request validation (slot capacity, bucket
    overflow), which a retry can never fix."""
    from k8s_gpu_device_plugin_tpu.serving.openai_api import _oai_error

    for status, expected in [
        (400, "invalid_request_error"),
        (404, "invalid_request_error"),
        (422, "invalid_request_error"),
        (503, "server_error"),
        (500, "server_error"),
    ]:
        resp = _oai_error("boom", status)
        assert resp.status == status
        payload = json.loads(resp.body)
        assert payload["error"]["type"] == expected


def test_overload_returns_429_with_retry_after(setup):
    """The pinned overload contract (serving/scheduler.py): a queue-full
    rejection answers HTTP 429 with a Retry-After header and OpenAI's
    retryable rate_limit_error envelope carrying the valve that fired —
    NOT the generic invalid_request_error path (a retry CAN succeed)."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import Scheduler

    cfg, params = setup
    prompt = _prompt(7, 9, cfg)

    async def body(session, base):
        # long decodes hold both slots; with a 1-deep queue a rapid
        # burst must overflow it
        posts = [
            session.post(f"{base}/v1/completions", json={
                "prompt": list(prompt), "max_tokens": 48,
            })
            for _ in range(8)
        ]
        results = await asyncio.gather(*posts)
        rejected = [r for r in results if r.status == 429]
        served = [r for r in results if r.status == 200]
        assert rejected, "a 1-deep queue never overflowed under a burst"
        assert served, "the queue cap must not reject everything"
        for r in rejected:
            assert "Retry-After" in r.headers
            assert int(r.headers["Retry-After"]) >= 1
            err = (await r.json())["error"]
            assert err["type"] == "rate_limit_error"
            assert err["code"] == "queue_full"
            assert err["retry_after"] >= 1
        for r in results:
            await r.release()

    run(_with_server(setup, body, scheduler=Scheduler(max_queue=1)))


def test_sched_fields_parse_and_route(setup):
    """tenant/priority/deadline_ms ride the OpenAI body (extra_body in
    SDKs); invalid values are a 400 before submission."""
    cfg, params = setup
    prompt = _prompt(8, 9, cfg)

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": list(prompt), "max_tokens": 2,
            "tenant": "gold", "priority": 0, "deadline_ms": 60_000,
        })
        assert r.status == 200, await r.text()
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": list(prompt), "max_tokens": 2, "priority": 99,
        })
        assert r.status == 400
        assert "priority" in (await r.json())["error"]["message"]

    run(_with_server(setup, body))


def test_echo_prompt_scoring_matches_forward_oracle(setup):
    """echo=true + max_tokens=0 + logprobs returns the prompt's own
    teacher-forced logprobs (the lm-eval loglikelihood contract), equal
    to forward + log_softmax computed directly, independent of the
    padding bucket."""
    cfg, params = setup
    from k8s_gpu_device_plugin_tpu.models.llama import forward
    from k8s_gpu_device_plugin_tpu.serving.scoring import Scorer

    prompt = _prompt(5, 10, cfg)
    logits = forward(params, jnp.asarray([prompt], jnp.int32), cfg)[0]
    lp = jax.nn.log_softmax(logits, axis=-1)
    expect = [float(lp[i - 1, prompt[i]]) for i in range(1, len(prompt))]

    scorer = Scorer(params, cfg, buckets=(16, 32))
    got = scorer.score(prompt)
    assert got[0] is None and len(got) == len(prompt)
    np.testing.assert_allclose(got[1:], expect, rtol=2e-5, atol=2e-5)
    # bucket invariance: a wider pad bucket scores identically
    got_wide = Scorer(params, cfg, buckets=(32,)).score(prompt)
    np.testing.assert_allclose(got[1:], got_wide[1:], rtol=1e-6)

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "echo": True, "max_tokens": 0, "logprobs": 0,
        })
        assert r.status == 200, await r.text()
        p = await r.json()
        ch = p["choices"][0]
        assert ch["finish_reason"] == "length"
        assert p["usage"] == {"prompt_tokens": len(prompt),
                              "completion_tokens": 0,
                              "total_tokens": len(prompt)}
        assert ch["logprobs"]["token_logprobs"][0] is None
        np.testing.assert_allclose(
            ch["logprobs"]["token_logprobs"][1:], got[1:], rtol=1e-5
        )
        assert len(ch["logprobs"]["tokens"]) == len(prompt)
        assert ch["logprobs"]["text_offset"][0] == 0

        # echo WITHOUT logprobs: no scoring forward, just the prompt back
        r2 = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "echo": True, "max_tokens": 0,
        })
        p2 = await r2.json()
        assert p2["choices"][0]["logprobs"] is None

        # validations: generation, n>1, and streaming are not scoring
        for bad, needle in [
            ({"max_tokens": 3}, "max_tokens 0"),
            ({"n": 2}, "n == 1"),
            ({"stream": True}, "stream"),
        ]:
            r3 = await session.post(f"{base}/v1/completions", json={
                "prompt": prompt, "echo": True, "max_tokens": 0,
                "logprobs": 0, **bad,
            })
            assert r3.status == 400, await r3.text()
            assert needle in (await r3.json())["error"]["message"]

    run(_with_server(setup, body, scorer=scorer))


def test_echo_requires_scoring_enabled(setup):
    """echo against a server without --scoring is a clear 400, not a
    silent empty answer."""
    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": [1, 2, 3], "echo": True, "max_tokens": 0,
        })
        assert r.status == 400
        assert "--scoring" in (await r.json())["error"]["message"]

    run(_with_server(setup, body))


def test_echo_text_tokens_concatenate_and_cap(setup):
    """With a tokenizer, echo's token strings must concatenate EXACTLY to
    the returned text even when a multi-byte character spans tokens
    (prefix-stable decode, not per-token decode -> U+FFFD), and the
    scoring bucket cap bounds echo requests with or without logprobs."""
    cfg, params = setup
    from k8s_gpu_device_plugin_tpu.serving.scoring import Scorer

    tok = ByteTokenizer()
    scorer = Scorer(params, cfg, buckets=(16,), max_len=16)
    text_in = "héllo"  # é = 2 bytes = 2 byte-level tokens

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": text_in, "echo": True, "max_tokens": 0,
            "logprobs": 0,
        })
        assert r.status == 200, await r.text()
        p = await r.json()
        ch = p["choices"][0]
        assert ch["text"] == text_in
        lp = ch["logprobs"]
        assert "".join(lp["tokens"]) == text_in
        assert lp["text_offset"] == sorted(lp["text_offset"])
        assert len(lp["tokens"]) == len(lp["token_logprobs"])

        # over-cap echo WITHOUT logprobs is still a 400, not a free pass
        r2 = await session.post(f"{base}/v1/completions", json={
            "prompt": "x" * 17, "echo": True, "max_tokens": 0,
        })
        assert r2.status == 400
        assert "cap" in (await r2.json())["error"]["message"]

    run(_with_server(setup, body, tokenizer=tok, scorer=scorer))


def test_echo_top_logprobs_alternatives(setup):
    """logprobs=K (1..5) on the echo path returns K alternatives per
    position; entry 0 is the argmax, and when the actual token IS the
    argmax its logprob equals token_logprobs (the is_greedy signal)."""
    cfg, params = setup
    from k8s_gpu_device_plugin_tpu.models.llama import forward
    from k8s_gpu_device_plugin_tpu.serving.scoring import Scorer

    prompt = _prompt(9, 8, cfg)
    scorer = Scorer(params, cfg, buckets=(16,))
    lps, top_lps, top_ids = scorer.score_full(prompt)
    # oracle argmax at each scored position
    logits = forward(params, jnp.asarray([prompt], jnp.int32), cfg)[0]
    lp_oracle = jax.nn.log_softmax(logits, axis=-1)
    for i in range(1, len(prompt)):
        assert int(top_ids[i, 0]) == int(jnp.argmax(lp_oracle[i - 1]))
        # alternatives sorted descending
        assert list(top_lps[i][:3]) == sorted(top_lps[i][:3], reverse=True)

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "echo": True, "max_tokens": 0, "logprobs": 2,
        })
        assert r.status == 200, await r.text()
        ch = (await r.json())["choices"][0]
        tops = ch["logprobs"]["top_logprobs"]
        assert tops[0] is None and len(tops) == len(prompt)
        # token-ids-only server: keys are unique id strings -> exactly K
        assert all(len(t) == 2 for t in tops[1:])
        assert all(
            all(k.isdigit() for k in t) for t in tops[1:]
        )
        # logprobs=0: no alternatives, top_logprobs null
        r2 = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "echo": True, "max_tokens": 0, "logprobs": 0,
        })
        assert (await r2.json())["choices"][0]["logprobs"][
            "top_logprobs"] is None
        # logprobs > 5 is OpenAI's own cap
        for bad_k in (9, -1):
            r3 = await session.post(f"{base}/v1/completions", json={
                "prompt": prompt, "echo": True, "max_tokens": 0,
                "logprobs": bad_k,
            })
            assert r3.status == 400
            assert "between 0 and 5" in (
                await r3.json())["error"]["message"]
        # the range applies on the GENERATION path too, not just echo
        r4 = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 2, "logprobs": 9,
        })
        assert r4.status == 400
        assert "between 0 and 5" in (await r4.json())["error"]["message"]

    run(_with_server(setup, body, scorer=scorer))


def test_scorer_chunked_long_prompt_matches_bucketed(setup):
    """Prompts past the bucket cap score through the KV-cached CHUNKED
    path; the result must equal the single-forward path bit-for-bit in
    intent (same logprobs to f32 tolerance), including across chunk
    boundaries and in the top-K alternatives."""
    # f32: the chunked (cached) and single-forward paths decompose the
    # attention differently, so bf16 rounding separates them by ~1e-3;
    # at f32 they agree to float tolerance, which is the real assertion
    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(21), cfg)
    from k8s_gpu_device_plugin_tpu.serving.scoring import Scorer

    prompt = _prompt(13, 40, cfg)
    chunked = Scorer(params, cfg, buckets=(16,), max_len=48, chunk=16)
    wide = Scorer(params, cfg, buckets=(64,), max_len=64)  # no chunk path
    lps_c, top_lps_c, top_ids_c = chunked.score_full(prompt)
    lps_w, top_lps_w, top_ids_w = wide.score_full(prompt)
    assert lps_c[0] is None and len(lps_c) == len(prompt)
    np.testing.assert_allclose(lps_c[1:], lps_w[1:], rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(top_ids_c[1:], top_ids_w[1:])
    np.testing.assert_allclose(
        top_lps_c[1:], top_lps_w[1:], rtol=2e-5, atol=2e-5
    )
    # the cap is max_len on the chunked path
    with pytest.raises(ValueError, match="cap 48"):
        chunked.score_full(_prompt(14, 49, cfg))

    async def body(session, base):
        # an over-bucket (but under-cap) prompt serves through echo
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "echo": True, "max_tokens": 0, "logprobs": 1,
        })
        assert r.status == 200, await r.text()
        ch = (await r.json())["choices"][0]
        np.testing.assert_allclose(
            ch["logprobs"]["token_logprobs"][1:], lps_c[1:], rtol=1e-5
        )

    run(_with_server(setup, body, scorer=chunked))


def test_prompt_ids_validate_vocab_and_bools(setup):
    """Token-id prompts get the same discipline as /v1/embeddings: ids
    outside the vocab are a 400 (the embedding gather would silently
    clamp and generate from a wrong vector), and bools are not ids."""
    cfg, _ = setup

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": [1, cfg.vocab_size + 7], "max_tokens": 2,
        })
        assert r.status == 400
        assert "outside vocab" in (await r.json())["error"]["message"]
        r2 = await session.post(f"{base}/v1/completions", json={
            "prompt": [True, False], "max_tokens": 2,
        })
        assert r2.status == 400

    run(_with_server(setup, body))


def test_best_of_ranks_by_mean_logprob(setup):
    """best_of samples extra candidates and returns the n with the
    highest mean token logprob; usage bills every sampled token, and
    validation rejects best_of < n, > 8, streaming, and echo."""
    cfg, _ = setup
    prompt = _prompt(4, 5, cfg)

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 4, "n": 2, "best_of": 4,
            "temperature": 1.2, "logprobs": 0, "seed": 11,
        })
        assert r.status == 200, await r.text()
        p = await r.json()
        assert len(p["choices"]) == 2
        assert [c["index"] for c in p["choices"]] == [0, 1]
        # every sampled token billed: 4 candidates x 4 tokens
        assert p["usage"]["completion_tokens"] == 16
        # returned pair is ranked: mean logprob of choice 0 >= choice 1
        means = [
            sum(c["logprobs"]["token_logprobs"]) /
            len(c["logprobs"]["token_logprobs"])
            for c in p["choices"]
        ]
        assert means[0] >= means[1]

        for bad in (
            {"best_of": 1, "n": 2},
            {"best_of": 9},
            {"best_of": 2, "stream": True},
            {"best_of": 2, "echo": True, "max_tokens": 0},
        ):
            r2 = await session.post(f"{base}/v1/completions", json={
                "prompt": prompt, "max_tokens": 4, **bad,
            })
            assert r2.status == 400, (bad, await r2.text())

    run(_with_server(setup, body))


def test_gemma_style_config_serves_over_http():
    """A tied-embeddings GeGLU config (the Gemma dials) through the whole
    HTTP stack: completions greedy output matches dedicated generate on
    the same weights — the tied head and activation dials survive the
    engine/batcher/API path, not just library calls."""
    cfg = LlamaConfig.tiny(
        n_layers=2, dtype=jnp.float32, tied_embeddings=True,
        scale_embed=True, norm_offset=True, act="gelu_tanh",
    )
    params = init_params(jax.random.key(31), cfg)
    setup_g = (cfg, params)
    tok = ByteTokenizer()
    prompt = _prompt(17, 6, cfg)
    expect_text = tok.decode(_oracle(params, prompt, cfg, 5))

    async def body(session, base):
        r = await session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 5,
        })
        assert r.status == 200, await r.text()
        p = await r.json()
        assert p["usage"]["completion_tokens"] == 5
        # the actual parity claim: the served greedy text IS generate()'s
        assert p["choices"][0]["text"] == expect_text

    run(_with_server(setup_g, body, tokenizer=tok))
