"""bench.py wedge budgeting (the round-3 postmortem: 963s of a scarce
hardware window spent discovering the chip was wedged).

Runs the real bench.py as a subprocess with BENCH_TEST_FORCE_WEDGE=1 — the
probe child hangs exactly where a wedged tunnel hangs — and asserts the
outage-mode contract: rc 0, one JSON line with value null + a wedge error,
the chip-free control-plane metric still recorded, the partials journal
carrying every completed workload, and a wall time bounded by minutes, not
the old 963s.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench.py")
PARTIALS = os.path.join(REPO_ROOT, "bench_partials.jsonl")


def test_bench_wedge_mode_fast_exit_with_partials(tmp_path):
    env = {
        **os.environ,
        "BENCH_TEST_FORCE_WEDGE": "1",
        # small corpus: the dataload row must not eat the wedge wall bound
        "BENCH_DATALOAD_TOKENS": str(4 * 1024 * 1024),
        "BENCH_PROBE_TIMEOUT": "3",
        # roundtrip is chip-free; keep the child off any real backend
        "JAX_PLATFORMS": "cpu",
        # no journal: this test asserts the bare-wedge contract; a real
        # harvest_results.jsonl in the repo root must not fill the value
        "BENCH_JOURNAL_PATH": str(tmp_path / "no_journal.jsonl"),
    }
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH],
        cwd=REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=280,
    )
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]

    # exactly one stdout line, parseable JSON, null value + wedge reason
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    payload = json.loads(lines[0])
    assert payload["value"] is None
    assert "unreachable" in payload["error"]
    # the chip-free control-plane metric still made it into the line
    assert payload["control_plane_allocs_per_second"] > 0

    # outage mode is minutes, not 963s: probe (3 attempts x 3s timeout +
    # 2 x 5s backoff = ~19s) + roundtrip; generous CI headroom but far
    # below the old failure mode
    assert wall < 240, f"wedge mode took {wall:.0f}s"

    # partials journal: probe recorded as failed, roundtrip with a result
    with open(PARTIALS) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    by_workload = {r["workload"]: r for r in recs}
    assert by_workload["probe"]["result"] is None
    assert by_workload["probe"]["note"] == "all attempts failed"
    assert by_workload["roundtrip"]["result"]["allocs_per_second"] > 0
    # the host-side native-gather row is chip-free and lands even here —
    # when the native library is built (this test is about wedge budgets,
    # not the native build)
    from k8s_gpu_device_plugin_tpu.data.native_loader import native_available

    if native_available():
        assert payload["dataload_native_speedup"] > 0
        assert by_workload["dataload"]["result"][
            "native_tokens_per_second"] > 0


def test_bench_wedge_adopts_journaled_hardware_values(tmp_path):
    """A wedge at bench time must not erase the round's hardware record:
    bench.py fills missing slots from tools/harvest.py's journal, labels
    each adopted value's age, and surfaces the live failure separately."""
    journal = tmp_path / "harvest_results.jsonl"
    now = time.time()
    rows = [
        # an early baseline train row THEN a tuned re-time: later lines win
        # per workload name, and train_tuned outranks train for the slot
        {"workload": "train", "ts": now - 600, "result": {
            "workload": "train", "mfu_pct": 55.13,
            "tokens_per_second": 31820.2, "step_ms": 514.9,
            "model": {"d_model": 2048}}},
        {"workload": "train_tuned", "ts": now - 300, "result": {
            "workload": "train", "mfu_pct": 57.5,
            "tokens_per_second": 33188.0, "step_ms": 493.7,
            "model": {"d_model": 2048}}},
        {"workload": "matmul", "ts": now - 900, "result": {
            "workload": "matmul", "mfu_pct": 80.72, "tflops": 159.0,
            "device_kind": "TPU v5 lite"}},
        # a failed row must never be adopted
        {"workload": "decode", "ts": now - 200, "result": {
            "error": "backend wedged"}},
        # a stale row (>48h) must never be adopted
        {"workload": "train_int8", "ts": now - 72 * 3600, "result": {
            "workload": "train_int8", "mfu_pct": 90.0,
            "tokens_per_second": 1.0}},
    ]
    journal.write_text(
        "".join(json.dumps(r) + "\n" for r in rows)
        # junk lines the parser must skip without killing the JSON contract
        + "null\n[1,2]\n"
        + json.dumps({"workload": "serve", "ts": None, "result": None}) + "\n"
    )

    t0 = time.monotonic()
    env = {
        **os.environ,
        "BENCH_TEST_FORCE_WEDGE": "1",
        # small corpus: the dataload row must not eat the wedge wall bound
        "BENCH_DATALOAD_TOKENS": str(4 * 1024 * 1024),
        "BENCH_PROBE_TIMEOUT": "3",
        "JAX_PLATFORMS": "cpu",
        "BENCH_JOURNAL_PATH": str(journal),
    }
    proc = subprocess.run(
        [sys.executable, BENCH], cwd=REPO_ROOT, env=env,
        capture_output=True, text=True, timeout=280,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    payload = json.loads(lines[0])

    # the tuned re-time carries the slot; vs_baseline against the 45% star
    assert payload["metric"] == "llama_train_bf16_mfu"
    assert payload["value"] == 57.5
    assert payload["vs_baseline"] == round(57.5 / 45.0, 3)
    assert payload["matmul_bf16_mfu_pct"] == 80.72
    assert "error" not in payload  # the value is real, not a failure
    assert "unreachable" in payload["live_error"]

    # adoption is labeled with ages; failed/stale rows were never adopted.
    # Upper bound allows for bench's own wall time — a loaded box must not
    # flake an assertion about adoption bookkeeping.
    elapsed = time.monotonic() - t0
    adopted = payload["journal"]["adopted_age_seconds"]
    assert set(adopted) == {"matmul", "train_tuned"}
    assert 250 < adopted["train_tuned"] < 310 + elapsed
    assert "decode_tokens_per_second" not in payload
    assert "train_int8_mfu_pct" not in payload
