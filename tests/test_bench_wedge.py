"""bench.py wedge budgeting (the round-3 postmortem: 963s of a scarce
hardware window spent discovering the chip was wedged).

Runs the real bench.py as a subprocess with BENCH_TEST_FORCE_WEDGE=1 — the
probe child hangs exactly where a wedged tunnel hangs — and asserts the
outage-mode contract: rc 0, one JSON line with value null + a wedge error,
the chip-free control-plane metric still recorded, the partials journal
carrying every completed workload, and a wall time bounded by minutes, not
the old 963s.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, "bench.py")
PARTIALS = os.path.join(REPO_ROOT, "bench_partials.jsonl")


def test_bench_wedge_mode_fast_exit_with_partials():
    env = {
        **os.environ,
        "BENCH_TEST_FORCE_WEDGE": "1",
        "BENCH_PROBE_TIMEOUT": "3",
        # roundtrip is chip-free; keep the child off any real backend
        "JAX_PLATFORMS": "cpu",
    }
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, BENCH],
        cwd=REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=280,
    )
    wall = time.monotonic() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]

    # exactly one stdout line, parseable JSON, null value + wedge reason
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    payload = json.loads(lines[0])
    assert payload["value"] is None
    assert "unreachable" in payload["error"]
    # the chip-free control-plane metric still made it into the line
    assert payload["control_plane_allocs_per_second"] > 0

    # outage mode is minutes, not 963s: probe (3 attempts x 3s timeout +
    # 2 x 5s backoff = ~19s) + roundtrip; generous CI headroom but far
    # below the old failure mode
    assert wall < 240, f"wedge mode took {wall:.0f}s"

    # partials journal: probe recorded as failed, roundtrip with a result
    with open(PARTIALS) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    by_workload = {r["workload"]: r for r in recs}
    assert by_workload["probe"]["result"] is None
    assert by_workload["probe"]["note"] == "all attempts failed"
    assert by_workload["roundtrip"]["result"]["allocs_per_second"] > 0
