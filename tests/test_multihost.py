"""Multi-host slice support (SURVEY §7 hard parts; BASELINE config #5).

Covers the placement math (as_slice_member), the Allocate-time env contract
(TPU_PROCESS_BOUNDS / TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / MEGASCALE_*),
config plumbing, and the workload-side WorkerEnv / global-mesh helpers —
all without hardware, per SURVEY §4 "multi-node without a cluster".
"""


import pytest

from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.config.config import load_config
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.device.topology import as_slice_member, parse_topology
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec
from k8s_gpu_device_plugin_tpu.parallel.multihost import (
    WorkerEnv,
    make_global_mesh,
    worker_env,
)
from k8s_gpu_device_plugin_tpu.plugin import api
from k8s_gpu_device_plugin_tpu.plugin.api import pb
from k8s_gpu_device_plugin_tpu.plugin.plugin import SliceMembership

from tests.test_plugin_integration import run, start_stack, stop_stack


# --- placement math -------------------------------------------------------


def test_as_slice_member_v5p_32():
    # v5p-32 = (4,4,2) slice; each v5p host is (2,2,1) = 4 chips => 8 hosts
    host = parse_topology("v5p-4")
    placed = as_slice_member(host, "v5p-32", worker_id=0)
    assert placed.slice_bounds == (4, 4, 2)
    assert placed.host_grid == (2, 2, 2)
    assert placed.num_hosts == 8
    assert placed.is_multihost
    assert placed.worker_index == 0
    assert placed.host_offset == (0, 0, 0)

    last = as_slice_member(host, "v5p-32", worker_id=7)
    assert last.worker_index == 7
    assert last.host_offset == (2, 2, 1)


def test_as_slice_member_worker_index_roundtrips():
    host = parse_topology("v5e-8")  # (2,4) per host
    for wid in range(4):  # v5e-32 would be (8,4)? use explicit shape
        placed = as_slice_member(host, "v5e-4x8", worker_id=wid)
        assert placed.worker_index == wid
        assert placed.num_hosts == 4


def test_as_slice_member_rejects_bad_inputs():
    host = parse_topology("v5p-4")
    with pytest.raises(ValueError, match="out of range"):
        as_slice_member(host, "v5p-32", worker_id=8)
    with pytest.raises(ValueError, match="generation"):
        as_slice_member(host, "v5e-16", worker_id=0)
    with pytest.raises(ValueError, match="tile"):
        as_slice_member(host, "v5p-3x2x1", worker_id=0)


def test_single_host_topology_is_not_multihost():
    topo = parse_topology("v5e-4")
    assert not topo.is_multihost
    assert topo.num_hosts == 1
    assert topo.worker_index == 0
    assert topo.host_grid == (1, 1)


# --- config plumbing ------------------------------------------------------


def test_config_multihost_keys(tmp_path):
    p = tmp_path / "c.yml"
    p.write_text(
        "sliceTopology: v5p-32\n"
        "workerId: 3\n"
        "workerHostnames: h0,h1,h2,h3,h4,h5,h6,h7\n"
        "numSlices: 2\n"
        "sliceId: 1\n"
        "megascaleCoordinator: h0:8080\n"
    )
    cfg = load_config([], config_file=str(p))
    assert cfg.slice_topology == "v5p-32"
    assert cfg.worker_id == 3
    assert cfg.worker_hostname_list == [f"h{i}" for i in range(8)]
    assert cfg.num_slices == 2 and cfg.slice_id == 1
    assert cfg.megascale_coordinator == "h0:8080"


def test_config_rejects_out_of_range_worker():
    cfg = Config(slice_topology="v5p-32", worker_id=2, worker_hostnames="a,b")
    with pytest.raises(ValueError, match="workerId"):
        cfg.validate()
    with pytest.raises(ValueError, match="sliceId"):
        Config(num_slices=1, slice_id=1).validate()


def test_config_multihost_requires_hostnames():
    with pytest.raises(ValueError, match="workerHostnames is required"):
        Config(slice_topology="v5p-32", worker_id=0).validate()


def test_manager_rejects_multislice_hostname_overcount(tmp_path):
    from k8s_gpu_device_plugin_tpu.plugin import PluginManager
    from k8s_gpu_device_plugin_tpu.utils.latch import Latch

    cfg = Config(
        kubelet_socket_dir=str(tmp_path),
        libtpu_path="",
        topology="v5e-4",
        num_slices=2,
        megascale_coordinator="c:8080",
        worker_hostnames="a,b,c",  # copy-paste of the all-slices list
    )
    manager = PluginManager(cfg, Latch(), backend=FakeBackend("v5e-4"))
    with pytest.raises(ValueError, match="exactly one host"):
        manager._load_plugins()


def test_config_multislice_requires_coordinator_and_hostnames():
    with pytest.raises(ValueError, match="megascaleCoordinator"):
        Config(num_slices=2, worker_hostnames="a,b").validate()
    with pytest.raises(ValueError, match="workerHostnames"):
        Config(num_slices=2, megascale_coordinator="c:8080").validate()
    Config(
        num_slices=2, megascale_coordinator="c:8080", worker_hostnames="a"
    ).validate()


def test_config_rejects_shared_replicas_with_distributed():
    """Duplicate worker ranks on one ICI mesh are undefined — refuse."""
    with pytest.raises(ValueError, match="sharedReplicas"):
        Config(
            shared_replicas=2, slice_topology="v5p-32",
            worker_hostnames=",".join(f"h{i}" for i in range(8)),
        ).validate()
    with pytest.raises(ValueError, match="sharedReplicas"):
        Config(
            shared_replicas=2, num_slices=2,
            megascale_coordinator="c:1", worker_hostnames="a",
        ).validate()
    Config(shared_replicas=2).validate()  # sharing alone is fine


# --- Allocate env contract ------------------------------------------------


def _allocate(kubelet, endpoint, ids):
    async def call():
        async with kubelet.plugin_channel(endpoint) as channel:
            stub = api.DevicePluginStub(channel)
            return await stub.Allocate(
                pb.AllocateRequest(
                    container_requests=[pb.ContainerAllocateRequest(devicesIDs=ids)]
                )
            )

    return call()


def test_allocate_whole_host_on_multihost_slice(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(
            tmp_path,
            topology="v5p-4",
            slice_topology="v5p-32",
            worker_id=5,
            worker_hostnames=",".join(f"w{i}" for i in range(8)),
        )
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            ids = [c.id for c in manager.plugins[0].chips.iter_sorted()]
            resp = await _allocate(kubelet, reg.endpoint, ids)
            envs = dict(resp.container_responses[0].envs)
            assert envs["TPU_PROCESS_BOUNDS"] == "2,2,2"
            assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
            assert envs["TPU_WORKER_ID"] == "5"
            assert envs["TPU_WORKER_HOSTNAMES"] == ",".join(
                f"w{i}" for i in range(8)
            )
            assert envs["TPU_ACCELERATOR_TYPE"] == "v5p-32"
            assert "MEGASCALE_NUM_SLICES" not in envs
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_allocate_partial_host_degrades_to_single_process(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(
            tmp_path,
            topology="v5p-4",
            slice_topology="v5p-32",
            worker_id=0,
            worker_hostnames="w0,w1,w2,w3,w4,w5,w6,w7",
        )
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            ids = [c.id for c in manager.plugins[0].chips.iter_sorted()][:2]
            resp = await _allocate(kubelet, reg.endpoint, ids)
            envs = dict(resp.container_responses[0].envs)
            assert envs["TPU_PROCESS_BOUNDS"] == "1,1,1"
            assert "TPU_WORKER_ID" not in envs
            assert envs["TPU_ACCELERATOR_TYPE"] == "v5p-2"
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_allocate_multislice_megascale_envs(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(
            tmp_path,
            topology="v5p-4",
            slice_topology="v5p-8",
            worker_id=1,
            worker_hostnames="w0,w1",
            num_slices=2,
            slice_id=1,
            megascale_coordinator="s0w0:8080",
        )
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            ids = [c.id for c in manager.plugins[0].chips.iter_sorted()]
            resp = await _allocate(kubelet, reg.endpoint, ids)
            envs = dict(resp.container_responses[0].envs)
            assert envs["MEGASCALE_NUM_SLICES"] == "2"
            assert envs["MEGASCALE_SLICE_ID"] == "1"
            assert envs["MEGASCALE_COORDINATOR_ADDRESS"] == "s0w0:8080"
            assert envs["TPU_WORKER_ID"] == "1"
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


# --- workload side --------------------------------------------------------


def test_worker_env_parses_plugin_contract(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "a,b,c,d")
    monkeypatch.setenv("TPU_WORKER_ID", "2")
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
    monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
    env = worker_env()
    assert env == WorkerEnv(
        worker_id=2, hostnames=("a", "b", "c", "d"), num_slices=2, slice_id=1
    )
    assert env.num_workers == 8
    assert env.process_id == 6  # slice 1, worker 2
    assert env.coordinator_host == "a"


def test_worker_env_absent_on_single_process(monkeypatch):
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.delenv("MEGASCALE_NUM_SLICES", raising=False)
    assert worker_env() is None


def test_worker_env_multislice_without_hostnames(monkeypatch):
    """Single-host slices in a multislice job still must init distributed."""
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
    monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
    monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "s0:8080")
    env = worker_env()
    assert env is not None
    assert env.num_workers == 2
    assert env.process_id == 1
    assert env.coordinator_host == "s0"


def test_make_global_mesh_multislice_shape():
    import jax

    spec = MeshSpec.for_devices(8, tp=2)  # dp=4, tp=2
    mesh = make_global_mesh(spec, num_slices=2, devices=jax.devices()[:8])
    assert dict(mesh.shape)["dp"] == 4
    assert dict(mesh.shape)["tp"] == 2

    with pytest.raises(ValueError, match="multiple of num_slices"):
        make_global_mesh(MeshSpec.for_devices(8, tp=2, sp=2), num_slices=4)


def test_membership_defaults():
    m = SliceMembership()
    assert not m.is_multislice
    assert SliceMembership(num_slices=2).is_multislice


def test_worker_env_multislice_coordinator(monkeypatch):
    """Every slice must agree on ONE coordinator — the MEGASCALE address,
    not the slice-local hostnames[0]."""
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "s1w0,s1w1")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
    monkeypatch.setenv("MEGASCALE_SLICE_ID", "1")
    monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "s0w0:8080")
    env = worker_env()
    assert env.coordinator_host == "s0w0"
    # single slice ignores megascale coordinator
    monkeypatch.setenv("MEGASCALE_NUM_SLICES", "1")
    assert worker_env().coordinator_host == "s1w0"


def test_partial_host_never_gets_megascale(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(
            tmp_path,
            topology="v5p-4",
            slice_topology="v5p-8",
            worker_id=0,
            worker_hostnames="w0,w1",
            num_slices=2,
            slice_id=0,
            megascale_coordinator="w0:8080",
        )
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            ids = [c.id for c in manager.plugins[0].chips.iter_sorted()][:2]
            resp = await _allocate(kubelet, reg.endpoint, ids)
            envs = dict(resp.container_responses[0].envs)
            assert "MEGASCALE_NUM_SLICES" not in envs
            assert "TPU_WORKER_ID" not in envs
            assert envs["TPU_PROCESS_BOUNDS"] == "1,1,1"
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_multislice_of_single_host_slices_gets_worker_envs(tmp_path):
    """numSlices>1 with slice == host must still hand out rank/peer envs."""

    async def body():
        kubelet, manager, task, _ = await start_stack(
            tmp_path,
            topology="v5e-4",
            num_slices=2,
            slice_id=1,
            worker_hostnames="me",
            megascale_coordinator="s0:8080",
        )
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            ids = [c.id for c in manager.plugins[0].chips.iter_sorted()]
            resp = await _allocate(kubelet, reg.endpoint, ids)
            envs = dict(resp.container_responses[0].envs)
            assert envs["TPU_WORKER_ID"] == "0"
            assert envs["TPU_WORKER_HOSTNAMES"] == "me"
            assert envs["TPU_PROCESS_BOUNDS"] == "1,1"
            assert envs["MEGASCALE_NUM_SLICES"] == "2"
            assert envs["MEGASCALE_SLICE_ID"] == "1"
            assert envs["MEGASCALE_COORDINATOR_ADDRESS"] == "s0:8080"
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_manager_rejects_hostname_count_mismatch(tmp_path):
    """4 hostnames for an 8-host slice must fail at load, not wedge at runtime."""
    from k8s_gpu_device_plugin_tpu.plugin import PluginManager
    from k8s_gpu_device_plugin_tpu.utils.latch import Latch

    cfg = Config(
        kubelet_socket_dir=str(tmp_path),
        libtpu_path="",
        topology="v5p-4",
        slice_topology="v5p-32",
        worker_id=3,
        worker_hostnames="a,b,c,d",
    )
    manager = PluginManager(cfg, Latch(), backend=FakeBackend("v5p-4"))
    with pytest.raises(ValueError, match="spans 8"):
        manager._load_plugins()
