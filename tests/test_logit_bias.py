"""Per-request logit_bias (OpenAI semantics): added to the RAW logits
before every sampler filter, per slot, sharing one compiled step. The
assertions use bias's two deterministic effects — a -100 ban removes the
greedy argmax token, a +100 force makes a chosen token win — so no
oracle model is needed."""

import asyncio

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.server import (
    InferenceEngine,
    InferenceServer,
)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def test_force_and_ban_through_batcher(setup):
    """+100 forces a chosen token every step; -100 on the unbiased
    greedy choice changes the output; an unbiased neighbor in the SAME
    batch still matches dedicated generate exactly."""
    cfg, params = setup
    prompt = _prompt(1, 5, cfg)
    unbiased = np.asarray(
        generate(params, jnp.asarray([prompt], jnp.int32), cfg, max_new=4)
    )[0].tolist()

    cb = ContinuousBatcher(params, cfg, n_slots=3, max_len=32,
                           chunked_prefill=8)
    forced_tok = 123
    r_force = cb.submit(prompt, max_new=4, logit_bias={forced_tok: 100.0})
    r_ban = cb.submit(prompt, max_new=4,
                      logit_bias={unbiased[0]: -100.0})
    r_plain = cb.submit(prompt, max_new=4)
    done = cb.run()

    assert done[r_force] == [forced_tok] * 4
    assert done[r_ban][0] != unbiased[0]  # the ban moved the first token
    assert done[r_plain] == unbiased      # neighbor unaffected


def test_bias_validation(setup):
    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                           chunked_prefill=8)
    with pytest.raises(ValueError, match="outside vocab"):
        cb.submit([1, 2], max_new=2, logit_bias={cfg.vocab_size: 1.0})
    with pytest.raises(ValueError, match="outside \\[-100, 100\\]"):
        cb.submit([1, 2], max_new=2, logit_bias={5: 101.0})
    with pytest.raises(ValueError, match="at most 300"):
        cb.submit([1, 2], max_new=2,
                  logit_bias={i: 1.0 for i in range(301)})


def test_bias_over_http_both_apis(setup):
    cfg, params = setup
    prompt = _prompt(7, 4, cfg)

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=2, max_len=32,
                                 chunked_prefill=8)
        server = InferenceServer(engine, host="127.0.0.1", port=0)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as s:
                # native API: JSON string keys, forced token
                r = await s.post(f"{base}/v1/generate", json={
                    "prompt": prompt, "max_new": 3,
                    "logit_bias": {"77": 100.0},
                })
                assert r.status == 200, await r.text()
                assert (await r.json())["tokens"] == [77, 77, 77]

                # OpenAI API: same field, usage still counted
                r = await s.post(f"{base}/v1/completions", json={
                    "prompt": prompt, "max_tokens": 3,
                    "logit_bias": {"77": 100},
                })
                assert r.status == 200, await r.text()
                assert (await r.json())["usage"]["completion_tokens"] == 3

                # malformed maps are a 400, not a dead engine
                r = await s.post(f"{base}/v1/generate", json={
                    "prompt": prompt, "max_new": 3,
                    "logit_bias": {"abc": 1.0},
                })
                assert r.status == 400
                r = await s.post(f"{base}/v1/completions", json={
                    "prompt": prompt, "max_tokens": 3,
                    "logit_bias": [1, 2],
                })
                assert r.status == 400
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=300))


def test_speculative_rejects_bias(setup):
    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    cfg, params = setup
    sb = SpeculativeBatcher(params, cfg, params, cfg, n_slots=1,
                            max_len=32, chunked_prefill=8)
    with pytest.raises(ValueError, match="logit_bias"):
        sb.submit([1, 2], max_new=2, logit_bias={5: 1.0})
