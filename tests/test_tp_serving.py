"""Tensor-parallel serving (parallel/tp_serving.py + the tp batcher
path): the sharded decode fast path pinned bit-identical to tp=1.

Three layers of claims, mirroring test_paged_kv.py:

- **Bit-exactness**: greedy and seeded token AND logprob streams are
  identical between tp=1 and tp=2/4 (on the conftest-forced 8-device
  CPU platform) across dense/paged x prefix cache on/off x pipeline
  depth 0/1, over admit/retire/cancel/eviction interleavings — and
  across scheduler preemption/resume. The sharding recipe makes this a
  structural property (column shards + head shards + gather-before-
  reduce; no psum ever splits an accumulation), and these tests keep it
  one.
- **Shard plumbing**: weights/cache/state carry the intended shardings,
  the steady-state decode arguments are committed mesh residents (the
  zero-per-step-H2D contract extends to tp), kv_stats()/health/gauges
  report per-shard AND aggregate views (tp=1 output byte-identical to
  the pre-tp server), and admission accounting under pool pressure
  drains back to baseline on every shard (the PR-6/PR-8 leak-pin
  pattern).
- **Startup validation**: the one mesh-flag rule (MeshSpec.from_flags,
  shared with the trainer CLI) refuses tp values that don't divide the
  device count or the KV-head count with actionable errors; stale
  prefix caches and injected-batcher flag combos are refused like their
  kv_layout twins.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import (
    ContinuousBatcher,
    precompute_prefix,
)
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_TP, MeshSpec
from k8s_gpu_device_plugin_tpu.serving.prefix_cache import (
    PrefixCache,
    prefix_kv_bytes,
)

BUCKETS = (8, 16, 32)
PS = 16  # page size: divides max_len=64 (the test_paged_kv geometry)


@pytest.fixture(scope="module")
def setup():
    # the same tiny config as the neighboring serving modules so shared
    # (tp=1) compiles are reused; the tp twins compile once here.
    # n_kv_heads=4, n_heads=8: tp=2 and tp=4 both divide cleanly.
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _batcher(params, cfg, tp, layout="dense", pc=None, depth=1, n_slots=2,
             chunk=8, **kw):
    return ContinuousBatcher(
        params, cfg, n_slots=n_slots, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=chunk, pipeline_depth=depth, prefix_cache=pc,
        kv_layout=layout, kv_page_size=PS if layout == "paged" else None,
        tp=tp, **kw,
    )


# --- bit-exactness: tp=1 vs tp=2/4 ----------------------------------------
#
# One scheduling scenario (the test_paged_kv shape: staggered waves over
# a shared system prompt, greedy and SEEDED requests mixed, a stop
# sequence that can't fire, a mid-flight cancel, a prefix-cache budget
# small enough that promotion evicts mid-run) replayed across the
# composed matrix. Completed requests must produce identical tokens AND
# logprobs; the cancelled request's partial stream must agree on the
# common prefix.


def _scenario(params, cfg, tp, layout, depth, cache_on):
    pc = None
    if cache_on:
        b = prefix_kv_bytes(cfg, 8) + prefix_kv_bytes(cfg, 16)
        pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=b)
    cb = _batcher(params, cfg, tp, layout, pc=pc, depth=depth)
    sys_a = _prompt(20, 17, cfg)
    rids = []

    def sub(base, tail_key, tail_n, new, seed=None, stop=None):
        p = base + _prompt(tail_key, tail_n, cfg)
        rids.append(cb.submit(p, max_new=new, seed=seed, stop=stop))

    sub(sys_a, 30, 5, 5)
    sub(sys_a, 31, 4, 4, seed=4)
    for _ in range(7):
        cb.step()
    sub(sys_a, 32, 6, 5, seed=5)
    sub([], 33, 9, 4)
    for _ in range(4):
        cb.step()
    cancelled = rids[2]
    cb.cancel(cancelled)
    sub(sys_a, 35, 3, 5, stop=[[cfg.vocab_size - 1, cfg.vocab_size - 1]])
    cb.run()
    if cb.pool is not None:
        cb.pool.check()
    streams = {
        rid: (list(req.out), list(req.out_logp))
        for rid, req in cb.done_requests.items()
    }
    return rids, cancelled, streams, cb


def test_tp_streams_bit_identical_across_matrix(setup):
    cfg, params = setup
    ref_rids, ref_cancel, ref, _ = _scenario(
        params, cfg, 1, "dense", 0, True
    )
    # tp=2 sweeps the composition axes (dense/paged x cache on/off x
    # depth 0/1, pruned to the informative cells like test_paged_kv);
    # tp=4 pins the deepest mesh on the full-feature cell
    cells = [
        (2, "dense", 1, True),
        (2, "paged", 0, True),
        (2, "paged", 1, False),
        (2, "dense", 0, False),
        (4, "paged", 1, True),
    ]
    for tp, layout, depth, cache_on in cells:
        rids, cancelled, streams, cb = _scenario(
            params, cfg, tp, layout, depth, cache_on
        )
        key = (tp, layout, depth, cache_on)
        assert rids == ref_rids and cancelled == ref_cancel, key
        for rid in rids:
            if rid == cancelled:
                toks, lps = streams[rid]
                rt, rl = ref[rid]
                n = min(len(toks), len(rt))
                assert toks[:n] == rt[:n], key
                assert lps[:n] == rl[:n], key
            else:
                # tokens AND logprobs bit-identical: no contraction in
                # the sharded graph ever splits an accumulation
                assert streams[rid] == ref[rid], key
        assert cb.mesh is not None and cb.cfg.tp == tp


def test_tp_preempt_resume_bit_identical(setup):
    """The scheduler's preempt/resume path (fold output into prompt,
    re-prefill, resume the seeded draw index) composes with tp: the
    preempted-then-resumed streams are pinned identical tp=1 vs tp=2."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import SloScheduler

    cfg, params = setup

    def run(tp):
        cb = _batcher(params, cfg, tp, "paged", n_slots=1,
                      scheduler=SloScheduler(preempt=True))
        r_low = cb.submit(_prompt(5, 8, cfg), max_new=24, priority=5)
        for _ in range(6):
            cb.step()
        cb.submit(_prompt(6, 6, cfg), max_new=4, priority=0,
                  deadline_ms=1)
        cb.run()
        assert cb.done_requests[r_low].preemptions >= 1, "never preempted"
        cb.pool.check()
        assert cb.pool.in_use == 0
        return {
            rid: (list(r.out), list(r.out_logp))
            for rid, r in cb.done_requests.items()
        }

    assert run(2) == run(1)


def test_tp_manual_prefix_bit_identical(setup):
    """A manual precompute_prefix prefix (dense rows, traced under the
    serving mesh when cfg.tp>1) inserts into the sharded cache with the
    streams pinned to tp=1."""
    from dataclasses import replace

    cfg, params = setup

    def run(tp):
        tcfg = replace(cfg, tp=tp)
        cb = _batcher(params, tcfg, tp)
        pre = precompute_prefix(
            cb.params, _prompt(40, 9, cfg), tcfg,
            prompt_buckets=BUCKETS,
        )
        rid = cb.submit(_prompt(41, 4, cfg), max_new=5, prefix=pre)
        cb.run()
        req = cb.done_requests[rid]
        return list(req.out), list(req.out_logp)

    assert run(2) == run(1)


def test_tp_speculative_bit_identical(setup):
    """The spec-verify dispatch as a sharded jit: draft+verify rounds
    under tp=2 (paged, both pools) pin to the tp=1 spec streams."""
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    cfg, params = setup
    d_cfg = replace(cfg, n_layers=1)
    d_params = init_params(jax.random.key(1), d_cfg)

    def run(tp):
        sb = SpeculativeBatcher(
            params, cfg, d_params, d_cfg, n_slots=2, max_len=64,
            gamma=3, prompt_buckets=BUCKETS, chunked_prefill=8,
            kv_layout="paged", kv_page_size=PS, tp=tp,
        )
        sb.submit(_prompt(1, 11, cfg), max_new=6)
        sb.submit(_prompt(2, 7, cfg), max_new=5)
        sb.run()
        sb.pool.check()
        sb.draft_pool.check()
        if tp > 1:
            # the shard gauges must mean what the aggregate means
            # (target + draft) and sum back to it exactly
            s = sb.kv_stats()
            assert sum(
                sh["reserved_bytes"] for sh in s["shards"]
            ) == s["reserved_bytes"]
        return {
            rid: (list(r.out), list(r.out_logp))
            for rid, r in sb.done_requests.items()
        }

    assert run(2) == run(1)


def test_tp_speculative_draft_heads_must_divide(setup):
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    cfg, params = setup
    d_cfg = replace(cfg, n_layers=1, n_heads=3, n_kv_heads=3)
    d_params = init_params(jax.random.key(1), d_cfg)
    with pytest.raises(ValueError, match="draft model's"):
        SpeculativeBatcher(
            params, cfg, d_params, d_cfg, n_slots=1, max_len=64,
            prompt_buckets=BUCKETS, chunked_prefill=8, tp=2,
        )


# --- shard plumbing --------------------------------------------------------


def test_weights_and_state_carry_the_intended_shardings(setup):
    from jax.sharding import PartitionSpec as P

    cfg, params = setup
    cb = _batcher(params, cfg, 2, "paged")
    # column-cut projections, replicated reduction weights
    assert cb.params["layers"]["wq"].sharding.spec == P(None, None, AXIS_TP)
    assert cb.params["layers"]["wo"].sharding.spec == P(None, None)
    assert cb.params["lm_head"].sharding.spec == P(None, AXIS_TP)
    # cache on the KV-head axis; table + masks replicated
    assert cb.state.cache.k.sharding.spec == P(
        None, None, None, AXIS_TP, None
    )
    assert cb.state.pages.sharding.spec == P()
    assert cb.state.lengths.sharding.spec == P()


def test_tp_psum_opt_out_row_shards_and_serves(setup):
    """The explicit bit-identity opt-out (cfg.tp_allow_psum / --tpPsum):
    wo and w2 row-shard on their contraction axes — the megatron pairing
    of the column cuts — and the batcher still serves valid streams.
    The DEFAULT (False) keeps the replicated no-psum recipe, pinned by
    the sharding assertions in the test above; here the opt-out's specs
    and its end-to-end viability are pinned (NOT stream bit-identity —
    the psum's split f32 reduction is exactly what the flag trades
    away)."""
    from dataclasses import replace

    from jax.sharding import PartitionSpec as P

    from k8s_gpu_device_plugin_tpu.parallel.tp_serving import (
        serving_param_specs,
    )

    cfg, params = setup
    cfg_p = replace(cfg, tp=2, tp_allow_psum=True)
    specs = serving_param_specs(cfg_p)["layers"]
    assert specs["wo"] == P(None, AXIS_TP, None)
    assert specs["w2"] == P(None, AXIS_TP, None)
    # the default recipe is untouched: replicated reduction weights
    specs_def = serving_param_specs(replace(cfg, tp=2))["layers"]
    assert specs_def["wo"] == P(None, None)

    cb = _batcher(params, cfg_p, 2, "paged")
    assert cb.params["layers"]["wo"].sharding.spec == P(
        None, AXIS_TP, None
    )
    p = _prompt(77, 9, cfg)
    rid = cb.submit(p, max_new=5)
    got = cb.run()[rid]
    assert len(got) == 5
    assert all(0 <= t < cfg.vocab_size for t in got)


def test_steady_state_args_are_committed_mesh_residents(setup):
    """The zero-per-step-H2D contract under tp: every decode-dispatch
    argument the batcher caches is COMMITTED on the tp mesh (an
    uncommitted single-device array would be re-transferred every
    step), and steady-state steps reuse the same cached objects."""
    cfg, params = setup
    cb = _batcher(params, cfg, 2)
    cb.submit(_prompt(50, 9, cfg), max_new=16, seed=3)
    for _ in range(5):
        cb.step()
    assert cb.running, "expected a decoding slot"
    mesh_devs = set(cb.mesh.devices.flat)
    cached = [cb._batch_allowed(), cb._batch_knobs(), cb._eos_dev,
              cb._batch_seeds()]
    for arr in cached:
        assert arr.committed, "cached dispatch arg not committed"
        assert set(arr.sharding.device_set) == mesh_devs
    before = (cb._allowed_cache, cb._knobs_cache, cb._seeds_cache)
    cb.step()
    cb.step()
    assert (cb._allowed_cache, cb._knobs_cache, cb._seeds_cache) \
        == before, "steady-state steps rebuilt a cached dispatch arg"


def test_kv_stats_shard_view(setup):
    cfg, params = setup
    # tp=1: BYTE-identical surface to the pre-tp server (no tp/shards
    # keys) for both layouts — the comparability satellite
    cb1 = _batcher(params, cfg, 1)
    assert set(cb1.kv_stats()) == {"layout", "reserved_bytes"}
    cb1p = _batcher(params, cfg, 1, "paged")
    assert "shards" not in cb1p.kv_stats() and "tp" not in cb1p.kv_stats()
    # tp=2: per-shard AND aggregate; bytes divide exactly, page counts
    # replicate (one host-side table)
    cb = _batcher(params, cfg, 2, "paged")
    s = cb.kv_stats()
    assert s["tp"] == 2 and len(s["shards"]) == 2
    for sh in s["shards"]:
        assert sh["reserved_bytes"] * 2 == s["reserved_bytes"]
        assert sh["pages_total"] == s["pages_total"]
        assert sh["pages_free"] == s["pages_free"]
    # dense tp=2: per-shard reservation halves too
    cbd = _batcher(params, cfg, 2)
    sd = cbd.kv_stats()
    assert sd["shards"][0]["reserved_bytes"] * 2 == sd["reserved_bytes"]


def test_serving_metrics_shard_gauges():
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.set_kv_shards([
        {"shard": 0, "reserved_bytes": 100, "pages_in_use": 3,
         "in_use_bytes": 48},
        {"shard": 1, "reserved_bytes": 100, "pages_in_use": 3,
         "in_use_bytes": 48},
    ])
    v = reg.get_sample_value(
        "tpu_serving_kv_shard_reserved_bytes", {"shard": "1"}
    )
    assert v == 100
    assert reg.get_sample_value(
        "tpu_serving_kv_shard_pages_in_use", {"shard": "0"}
    ) == 3
    m.close()


def test_batcher_pushes_shard_gauges(setup):
    """The batcher's gauge hook feeds per-shard dicts under tp>1 and
    never at tp=1 (the comparability rule)."""
    cfg, params = setup

    class _Rec:
        def __init__(self):
            self.shards = None
            self.calls = 0

        def set_kv_shards(self, shards):
            self.shards = shards
            self.calls += 1

        def set_kv_pages(self, *a): ...
        def set_kv_reserved_bytes(self, *a): ...
        def on_submit(self): ...
        def on_prefill_chunk(self): ...
        def on_prefill_tokens(self, *a): ...
        def on_first_token(self): ...
        def on_step(self, *a): ...
        def on_finish(self, reason): ...

    rec1 = _Rec()
    _batcher(params, cfg, 1, "paged", metrics=rec1)
    assert rec1.calls == 0, "tp=1 must not emit shard gauges"
    rec = _Rec()
    cb = _batcher(params, cfg, 2, "paged", metrics=rec)
    assert rec.calls > 0 and len(rec.shards) == 2
    rid = cb.submit(_prompt(60, 9, cfg), max_new=4)
    cb.run()
    assert cb.done[rid]
    assert rec.shards[0]["pages_in_use"] == 0  # drained back


def test_sharded_admission_under_pool_pressure(setup):
    """Satellite pin: per-shard page-reservation accounting under
    pressure — a pool sized for one request defers the second on EVERY
    shard's free count, cancel-while-queued returns each shard's pool
    free-count to baseline, and the drain leaves all shards at the
    starting free count (the PR-6/PR-8 leak-pin pattern, tp edition)."""
    cfg, params = setup
    # 5 allocatable pages: one 9-prompt/8-new request needs
    # ceil(17/16)=2... size so exactly one request fits
    cb = _batcher(params, cfg, 2, "paged", kv_pages=4, n_slots=2)
    baseline = [s["pages_free"] for s in cb.kv_stats()["shards"]]
    r1 = cb.submit(_prompt(70, 9, cfg), max_new=8)    # needs 2 pages
    r2 = cb.submit(_prompt(71, 9, cfg), max_new=8)    # must defer
    cb.step()
    shards = cb.kv_stats()["shards"]
    assert all(s["pages_free"] < b for s, b in zip(shards, baseline)), \
        "admission did not draw on the (replicated) shard free counts"
    assert cb.pending and cb.pending[0].rid == r2, "r2 should be deferred"
    # cancel the queued request: nothing may leak on any shard
    assert cb.cancel(r2)
    cb.run()
    assert cb.done[r1] is not None
    after = [s["pages_free"] for s in cb.kv_stats()["shards"]]
    assert after == baseline, f"shard free counts leaked: {after}"
    cb.pool.check()


# --- startup validation ----------------------------------------------------


def test_from_flags_shared_rule():
    # 8 virtual devices (conftest): tp=3 doesn't divide
    with pytest.raises(ValueError, match="not divisible"):
        MeshSpec.from_flags(tp=3, n_devices=8, exact=True)
    with pytest.raises(ValueError, match="n_kv_heads"):
        MeshSpec.from_flags(tp=8, n_devices=8, n_kv_heads=4, exact=True)
    with pytest.raises(ValueError, match="needs 16 devices"):
        MeshSpec.from_flags(tp=16, n_devices=8, exact=True)
    # the trainer shape: leftover devices fill dp
    spec = MeshSpec.from_flags(tp=2, n_devices=8)
    assert spec.tp == 2 and spec.dp == 4
    # the serving shape: dp stays 1 (unused chips stay unused)
    spec = MeshSpec.from_flags(tp=2, n_devices=8, n_kv_heads=4, exact=True)
    assert spec.tp == 2 and spec.dp == 1 and spec.num_devices == 2


def test_batcher_tp_must_divide_kv_heads(setup):
    cfg, params = setup  # n_kv_heads=4
    with pytest.raises(ValueError, match="n_kv_heads"):
        _batcher(params, cfg, 8)


def test_engine_refuses_tp_with_injected_batcher(setup):
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params = setup
    with pytest.raises(ValueError, match="injected batcher"):
        InferenceEngine(
            params, cfg,
            batcher=ContinuousBatcher(
                params, cfg, n_slots=1, max_len=64,
                prompt_buckets=BUCKETS,
            ),
            tp=2,
        )


def test_engine_health_reports_shards(setup):
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params = setup
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
        kv_layout="paged", kv_page_size=PS, tp=2,
    )
    try:
        kv = engine.stats()["kv"]
        assert kv["tp"] == 2 and len(kv["shards"]) == 2
        assert kv["shards"][0]["reserved_bytes"] * 2 == kv["reserved_bytes"]
    finally:
        engine.shutdown()


def test_prefix_cache_cannot_move_between_tp_degrees(setup):
    """Like the paged/dense attach guards: entries materialized under
    one mesh (sharded rows) must not be served by a batcher on another
    (or none)."""
    cfg, params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 24)
    cb = _batcher(params, cfg, 2, pc=pc)
    cb.submit(_prompt(80, 17, cfg), max_new=3)
    cb.run()
    assert pc.stats.entries > 0, "nothing promoted"
    with pytest.raises(ValueError, match="tp="):
        _batcher(params, cfg, 1, pc=pc)


def test_serve_bench_tp_skip_is_loud(setup, capsys):
    """A tp that can't shard this config skips the A/B with a printed
    reason and zeroed fields — never silently (the no-silent-caps
    house rule)."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.serve_bench import (
        serve_bench,
    )

    cfg, params = setup
    r = serve_bench(
        cfg, n_slots=2, n_requests=2, max_len=64, prompt_lens=(12,),
        max_new=4, params=params, prompt_buckets=BUCKETS,
        chunked_prefill=8, decode_ab=False, prefix_ab=False,
        paged_ab=False, spec_ab=False, sched_ab=False,
        tp_ab=True, tp_degree=3,  # 3 divides neither 8 devs nor 4 heads
    )
    assert r.tp_degree == 0 and r.tokens_per_second_tp == 0.0
    assert "tp A/B skipped" in capsys.readouterr().err


def test_tp_quantized_cache_streams_and_shard_bytes(setup):
    """The int8 KV cache (dense layout — paged refuses quant) composes
    with tp: scale planes shard on the head axis alongside K/V, so the
    streams pin to tp=1 AND the per-shard byte gauge stays exactly
    aggregate/tp (a replicated scale plane would under-report)."""
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.paging import (
        kv_shard_token_bytes,
        kv_token_bytes,
    )

    cfg, params = setup
    qcfg = replace(cfg, cache_quant="int8")
    assert kv_shard_token_bytes(replace(qcfg, tp=2)) * 2 \
        == kv_token_bytes(qcfg)

    def run(tp):
        cb = _batcher(params, qcfg, tp)
        if tp > 1:
            from jax.sharding import PartitionSpec as P

            assert cb.state.cache.k_scale.sharding.spec == P(
                None, None, None, AXIS_TP, None
            )
            s = cb.kv_stats()
            assert s["shards"][0]["reserved_bytes"] * 2 \
                == s["reserved_bytes"]
        rid = cb.submit(_prompt(95, 10, cfg), max_new=5)
        cb.run()
        req = cb.done_requests[rid]
        return list(req.out), list(req.out_logp)

    assert run(2) == run(1)


def test_tp_streams_match_generate_oracle(setup):
    """Beyond tp=1 equality: tp=2 greedy streams equal dedicated
    ``generate`` over the full prompt (the absolute reference)."""
    from k8s_gpu_device_plugin_tpu.models.generate import generate

    cfg, params = setup
    cb = _batcher(params, cfg, 2, "paged")
    p = _prompt(90, 12, cfg)
    rid = cb.submit(p, max_new=5)
    results = cb.run()
    oracle = np.asarray(
        generate(params, jnp.asarray([p], jnp.int32), cfg, max_new=5)
    )[0].tolist()
    assert results[rid] == oracle
