"""Data pipeline + end-to-end trainer (SURVEY §4 integration strategy:
full workload loop on the virtual 8-device CPU mesh, zero accelerators).
"""

import numpy as np
import pytest

import jax

from k8s_gpu_device_plugin_tpu.data.pipeline import (
    DataLoader,
    MemmapSource,
    SyntheticSource,
)
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.trainer import Trainer, TrainerConfig
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshSpec.for_devices(8, tp=2, sp=2))


# --- sources --------------------------------------------------------------


def test_synthetic_source_is_deterministic():
    s = SyntheticSource(vocab_size=100, seed=7)
    a = s.windows(3, slice(0, 4), 4, 16)
    b = s.windows(3, slice(0, 4), 4, 16)
    assert np.array_equal(a, b)
    c = s.windows(4, slice(0, 4), 4, 16)
    assert not np.array_equal(a, c)
    assert a.shape == (4, 17) and a.dtype == np.int32
    assert a.min() >= 0 and a.max() < 100


def test_memmap_source_windows(tmp_path):
    tokens = np.arange(1000, dtype=np.uint16)
    path = tmp_path / "corpus.bin"
    tokens.tofile(path)
    src = MemmapSource(str(path), dtype="uint16", seed=1)
    w = src.windows(0, slice(0, 2), 2, 8)
    assert w.shape == (2, 9) and w.dtype == np.int32
    # windows are contiguous runs of the corpus
    for row in w:
        assert np.array_equal(row, np.arange(row[0], row[0] + 9))
    # deterministic per step
    assert np.array_equal(w, src.windows(0, slice(0, 2), 2, 8))


def test_memmap_source_rejects_short_corpus(tmp_path):
    path = tmp_path / "tiny.bin"
    np.arange(4, dtype=np.uint16).tofile(path)
    src = MemmapSource(str(path), dtype="uint16")
    with pytest.raises(ValueError, match="shorter than"):
        src.windows(0, slice(0, 1), 1, 64)


# --- loader ---------------------------------------------------------------


def test_loader_yields_sharded_batches(mesh):
    loader = DataLoader(SyntheticSource(100), batch_size=8, seq_len=32, mesh=mesh)
    it = iter(loader)
    batch = next(it)
    assert batch["inputs"].shape == (8, 32)
    assert batch["targets"].shape == (8, 32)
    # next-token alignment: targets are inputs shifted by one
    inp = np.asarray(batch["inputs"])
    tgt = np.asarray(batch["targets"])
    assert np.array_equal(inp[:, 1:], tgt[:, :-1])
    # sharded over the mesh, not replicated on one device
    assert len(batch["inputs"].sharding.device_set) == 8


def test_loader_resume_reproduces_stream(mesh):
    mk = lambda: DataLoader(  # noqa: E731
        SyntheticSource(100, seed=3), batch_size=8, seq_len=16, mesh=mesh,
        prefetch=0,
    )
    a = mk()
    it = iter(a)
    batches = [next(it) for _ in range(4)]
    assert a.state() == {"step": 4}

    b = mk()
    b.seek(2)
    it2 = iter(b)
    resumed = next(it2)
    assert np.array_equal(
        np.asarray(batches[2]["inputs"]), np.asarray(resumed["inputs"])
    )


def test_loader_prefetch_matches_unprefetched(mesh):
    plain = DataLoader(
        SyntheticSource(50, seed=9), batch_size=8, seq_len=16, mesh=mesh, prefetch=0
    )
    pre = DataLoader(
        SyntheticSource(50, seed=9), batch_size=8, seq_len=16, mesh=mesh, prefetch=2
    )
    for a, b in zip(iter(plain), iter(pre)):
        assert np.array_equal(np.asarray(a["inputs"]), np.asarray(b["inputs"]))
        if plain.state()["step"] >= 3:
            break


# --- trainer --------------------------------------------------------------


def _trainer_cfg(**kw) -> TrainerConfig:
    base = dict(
        model=LlamaConfig.tiny(n_layers=2),
        mesh=MeshSpec.for_devices(8, tp=2, sp=2),
        batch_size=8,
        seq_len=32,
        total_steps=6,
        log_every=2,
    )
    base.update(kw)
    return TrainerConfig(**base)


def test_trainer_runs_and_reports(tmp_path):
    result = Trainer(_trainer_cfg()).run()
    assert result.steps_run == 6
    assert np.isfinite(result.final_loss)
    assert result.resumed_from is None
    assert result.tokens_per_second > 0
    assert [h["step"] for h in result.metrics_history] == [2, 4, 6]


def test_trainer_checkpoints_and_resumes(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = _trainer_cfg(
        total_steps=4, checkpoint_dir=ckpt_dir, checkpoint_interval=100
    )
    r1 = Trainer(cfg).run()
    assert r1.steps_run == 4  # final force-save wrote step 4

    cfg2 = _trainer_cfg(
        total_steps=6, checkpoint_dir=ckpt_dir, checkpoint_interval=100
    )
    r2 = Trainer(cfg2).run()
    assert r2.resumed_from == 4
    assert r2.steps_run == 2  # only the remaining steps

    # loss keeps a continuous trajectory (same data stream position)
    r3 = Trainer(_trainer_cfg(total_steps=6)).run()
    assert abs(r2.final_loss - r3.final_loss) < 1e-4


def test_trainer_final_step_on_cadence_boundary(tmp_path):
    """total_steps % checkpoint_interval == 0: the cadence saves the final
    step, then the finally-block force-save hits the same step — orbax
    raises StepAlreadyExistsError even with force=True unless skipped."""
    ckpt_dir = str(tmp_path / "ckpt")
    cfg = _trainer_cfg(
        total_steps=4, checkpoint_dir=ckpt_dir, checkpoint_interval=2
    )
    r1 = Trainer(cfg).run()  # must not raise
    assert r1.steps_run == 4

    cfg2 = _trainer_cfg(
        total_steps=6, checkpoint_dir=ckpt_dir, checkpoint_interval=2
    )
    r2 = Trainer(cfg2).run()
    assert r2.resumed_from == 4
    assert r2.steps_run == 2


def test_trainer_writes_profiler_trace(tmp_path):
    trace_dir = str(tmp_path / "trace")
    cfg = _trainer_cfg(trace_dir=trace_dir, trace_start=1, trace_stop=3)
    Trainer(cfg).run()
    import glob

    dumps = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    assert dumps, "no xplane trace written"


def test_trainer_grad_accum_wiring():
    """grad_accum flows TrainerConfig -> make_train_step and the run trains."""
    result = Trainer(_trainer_cfg(grad_accum=2, total_steps=4)).run()
    assert result.steps_run == 4
    assert np.isfinite(result.final_loss)


def test_trainer_eval_loop():
    """Eval runs on cadence + finally, is deterministic across passes (same
    validation set), and perplexity == exp(loss)."""
    import math

    result = Trainer(
        _trainer_cfg(eval_every=2, eval_batches=2, total_steps=4)
    ).run()
    assert result.final_eval is not None
    assert math.isclose(
        result.final_eval["perplexity"],
        math.exp(result.final_eval["loss"]),
        rel_tol=1e-9,
    )
    assert 0.0 <= result.final_eval["accuracy"] <= 1.0
    evals = [h["eval"] for h in result.metrics_history if "eval" in h]
    assert len(evals) == 1  # step 2 (step 4 is the final eval, not in history)
    assert np.isfinite(evals[0]["loss"])


def test_eval_step_matches_loss_fn(mesh):
    """make_eval_step reports the same loss the train step's loss_fn sees."""
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state,
        make_eval_step,
        make_optimizer,
        synthetic_batch,
    )

    cfg = LlamaConfig.tiny()
    optimizer = make_optimizer(total_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    ev = make_eval_step(cfg, mesh)(state["params"], batch)
    from k8s_gpu_device_plugin_tpu.models.train import loss_fn

    loss_direct, _ = loss_fn(state["params"], batch, cfg, mesh)
    np.testing.assert_allclose(
        float(ev["loss"]), float(loss_direct), rtol=1e-6
    )
    assert 0.0 <= float(ev["accuracy"]) <= 1.0


def test_eval_micro_matches_full_batch(mesh):
    """Microbatched eval (mean of equal-size chunk means) equals the
    full-batch eval to numerical precision."""
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state,
        make_eval_step,
        make_optimizer,
        synthetic_batch,
    )

    cfg = LlamaConfig.tiny()
    optimizer = make_optimizer(total_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 8, 64, mesh)
    full = make_eval_step(cfg, mesh, micro=1)(state["params"], batch)
    chunked = make_eval_step(cfg, mesh, micro=4)(state["params"], batch)
    np.testing.assert_allclose(
        float(chunked["loss"]), float(full["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(chunked["accuracy"]), float(full["accuracy"]), atol=1e-6
    )


def test_trainer_eval_config_validation():
    import pytest

    with pytest.raises(ValueError, match="eval_batches"):
        Trainer(_trainer_cfg(eval_every=2, eval_batches=0))
    with pytest.raises(ValueError, match="silently ignored"):
        cfg = _trainer_cfg()  # eval_every defaults to 0
        t = Trainer(cfg)
        Trainer(cfg, eval_loader=t.loader)


def test_native_loader_bit_identical_to_python_source(tmp_path):
    """NativeMemmapSource must produce BIT-IDENTICAL batches to the
    Python MemmapSource for the same (seed, step, rows) — the sampling
    recipe lives in one place and the C++ gather only moves bytes."""
    from k8s_gpu_device_plugin_tpu.data.native_loader import (
        NativeMemmapSource,
    )
    from k8s_gpu_device_plugin_tpu.data.pipeline import MemmapSource

    path = str(tmp_path / "corpus.bin")
    tokens = np.random.default_rng(0).integers(
        0, 50_000, size=8192
    ).astype(np.uint16)
    tokens.tofile(path)

    py = MemmapSource(path, dtype="uint16", seed=7)
    try:
        nat = NativeMemmapSource(path, dtype="uint16", seed=7)
    except RuntimeError:
        pytest.skip("libdataload.so not built in this environment")
    try:
        rows = np.arange(8)
        for step in (0, 1, 17):
            got = nat.windows(step, rows, 8, 128)
            want = py.windows(step, rows, 8, 128)
            np.testing.assert_array_equal(got, want, err_msg=f"step {step}")
            assert got.dtype == np.int32
        # uint32 path too
        path32 = str(tmp_path / "corpus32.bin")
        tokens.astype(np.uint32).tofile(path32)
        nat32 = NativeMemmapSource(path32, dtype="uint32", seed=7)
        py32 = MemmapSource(path32, dtype="uint32", seed=7)
        np.testing.assert_array_equal(
            nat32.windows(3, rows, 8, 64), py32.windows(3, rows, 8, 64)
        )
        nat32.close()
    finally:
        nat.close()


def test_native_loader_feeds_dataloader(tmp_path):
    """The native source drives the full DataLoader/mesh pipeline."""
    from k8s_gpu_device_plugin_tpu.data.native_loader import (
        NativeMemmapSource,
    )

    path = str(tmp_path / "corpus.bin")
    np.random.default_rng(1).integers(0, 400, size=4096).astype(
        np.uint16
    ).tofile(path)
    try:
        src = NativeMemmapSource(path, dtype="uint16", seed=0)
    except RuntimeError:
        pytest.skip("libdataload.so not built in this environment")
    mesh = make_mesh(MeshSpec(dp=2), jax.devices()[:2])
    loader = DataLoader(src, batch_size=4, seq_len=32, mesh=mesh)
    batch = next(iter(loader))
    assert batch["inputs"].shape == (4, 32)
    assert batch["targets"].shape == (4, 32)
    assert bool((batch["inputs"][:, 1:] == batch["targets"][:, :-1]).all())
    src.close()


def test_native_loader_rejects_bad_input(tmp_path):
    from k8s_gpu_device_plugin_tpu.data.native_loader import (
        NativeMemmapSource,
    )

    with pytest.raises(ValueError):
        NativeMemmapSource("/nonexistent", dtype="float32")
    try:
        with pytest.raises(FileNotFoundError):
            NativeMemmapSource(str(tmp_path / "missing.bin"))
    except RuntimeError:
        pytest.skip("libdataload.so not built in this environment")


def test_make_token_source_factory(tmp_path, monkeypatch):
    """The default-path factory (r4 verdict #7): no path -> synthetic;
    a corpus + built libdataload.so -> the native gather; without the
    library -> the Python memmap. Labels travel with the choice so runs
    can surface which gather fed them."""
    from k8s_gpu_device_plugin_tpu.data import native_loader
    from k8s_gpu_device_plugin_tpu.data.pipeline import (
        MemmapSource,
        SyntheticSource,
        make_token_source,
    )
    from k8s_gpu_device_plugin_tpu.data.native_loader import NativeMemmapSource

    src, label = make_token_source("", vocab_size=100)
    assert isinstance(src, SyntheticSource) and label == "synthetic"

    path = str(tmp_path / "corpus.bin")
    np.random.default_rng(0).integers(
        0, 100, 4096, dtype=np.uint16
    ).tofile(path)

    if native_loader.native_available():
        src, label = make_token_source(path, vocab_size=100)
        assert isinstance(src, NativeMemmapSource) and label == "native-memmap"
        src.close()

    monkeypatch.setattr(native_loader, "native_available", lambda: False)
    src, label = make_token_source(path, vocab_size=100)
    assert isinstance(src, MemmapSource) and label == "python-memmap"


def test_trainer_uses_factory_and_reports_source(tmp_path):
    """A --dataFile trainer run reports which gather served it, and the
    batches came from the corpus (bit-identity between the two gathers is
    pinned by test_native_loader_bit_identical_to_python_source)."""
    from k8s_gpu_device_plugin_tpu.data import native_loader

    path = str(tmp_path / "corpus.bin")
    np.random.default_rng(1).integers(
        0, 512, 1 << 16, dtype=np.uint16
    ).tofile(path)
    cfg = _trainer_cfg(total_steps=2, data_file=path, log_every=100)
    result = Trainer(cfg).run()
    expected = (
        "native-memmap" if native_loader.native_available()
        else "python-memmap"
    )
    assert result.data_source == expected
    assert result.steps_run == 2 and np.isfinite(result.final_loss)

    synth = Trainer(_trainer_cfg(total_steps=1, log_every=100)).run()
    assert synth.data_source == "synthetic"
