"""HTTP control-plane tests (≙ server/, router/, middleware/ behavior)."""

import asyncio
import json

import aiohttp
from prometheus_client import CollectorRegistry

from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.metrics.http_metrics import normalize_status
from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
from k8s_gpu_device_plugin_tpu.server.server import Server
from k8s_gpu_device_plugin_tpu.utils.latch import Latch


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


async def start_http_stack(tmp_path, **cfg_kwargs):
    cfg = Config(
        kubelet_socket_dir=str(tmp_path),
        web_listen_address="127.0.0.1:0",
        libtpu_path="",
        **cfg_kwargs,
    )
    ready = Latch()
    manager = PluginManager(
        cfg, ready, backend=FakeBackend("v5e-4"), health_interval=0.1
    )
    registry = CollectorRegistry()
    server = Server(cfg, manager, ready, registry=registry)
    stop = asyncio.Event()
    mtask = asyncio.create_task(manager.start())
    stask = asyncio.create_task(server.run(stop))
    for _ in range(100):
        if server.port:
            break
        await asyncio.sleep(0.05)
    assert server.port, "server did not bind"
    base = f"http://127.0.0.1:{server.port}"

    async def teardown():
        stop.set()
        await manager.stop()
        await asyncio.gather(mtask, stask, return_exceptions=True)

    return base, manager, teardown


def test_routes_and_envelope(tmp_path):
    async def body():
        base, _, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/") as resp:
                    data = await resp.json()
                    assert resp.status == 200
                    assert data["code"] == 200
                    assert "version" in data["data"]

                async with session.get(f"{base}/health") as resp:
                    data = await resp.json()
                    assert data == {"code": 200, "data": "ok", "msg": "success"}

                async with session.get(f"{base}/nope") as resp:
                    assert resp.status == 404
        finally:
            await teardown()

    run(body())


def test_metrics_exposition(tmp_path):
    async def body():
        base, _, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                await session.get(f"{base}/health")
                await session.get(f"{base}/bogus")
                async with session.get(f"{base}/metrics") as resp:
                    text = await resp.text()
                assert resp.status == 200
                # HTTP middleware metrics (reference echo_http_* contract)
                assert 'tpu_plugin_http_requests_total{' in text
                assert 'handler="/health"' in text
                assert 'handler="/not-found"' in text  # 404 collapse
                assert "tpu_plugin_http_request_duration_seconds_bucket" in text
                # device metrics the reference left unimplemented
                assert 'tpu_plugin_chips{resource="google.com/tpu",state="healthy"} 4.0' in text
                assert "tpu_plugin_chip_hbm_total_bytes" in text
                assert "tpu_plugin_build_info" in text
        finally:
            await teardown()

    run(body())


def test_restart_endpoint_reloads_plugins(tmp_path):
    async def body():
        base, manager, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/restart") as resp:
                    data = await resp.json()
                    assert data["code"] == 200
            # restart event consumed by manager loop
            await asyncio.sleep(0.5)
            assert not manager._restart_event.is_set()
        finally:
            await teardown()

    run(body())


def test_cors_headers(tmp_path):
    async def body():
        base, _, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.options(f"{base}/health") as resp:
                    assert resp.status == 204
                    assert resp.headers["Access-Control-Allow-Origin"] == "*"
                async with session.get(f"{base}/health") as resp:
                    assert resp.headers["Access-Control-Allow-Origin"] == "*"
        finally:
            await teardown()

    run(body())


def test_usage_gauges_scrape_runtime_metrics(tmp_path):
    """A workload-published runtime-metrics endpoint (faked) must surface as
    populated hbm_used/duty_cycle/tensorcore gauges via GET /metrics."""
    from k8s_gpu_device_plugin_tpu.metrics import runtime_metrics as rm

    fake = rm.FakeRuntimeMetricsServer({
        rm.HBM_USAGE: {0: 12_000_000_000, 1: 8_500_000_000},
        rm.DUTY_CYCLE: {0: 87.5, 1: 12.0},
        rm.TENSORCORE_UTIL: {0: 64.2, 1: 3.3},
    })
    port = fake.start()

    async def body():
        base, _, teardown = await start_http_stack(
            tmp_path,
            runtime_metrics_ports=str(port),
            runtime_metrics_cache_ttl=0,  # back-to-back scrapes must be fresh
        )
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/metrics") as resp:
                    text = await resp.text()
            assert 'tpu_plugin_chip_hbm_used_bytes{chip="0"} 1.2e+010' in text
            assert 'tpu_plugin_chip_hbm_used_bytes{chip="1"} 8.5e+09' in text
            assert 'tpu_plugin_chip_duty_cycle_percent{chip="0"} 87.5' in text
            assert 'tpu_plugin_chip_tensorcore_utilization{chip="0"} 64.2' in text
            assert 'tpu_plugin_chip_tensorcore_utilization{chip="1"} 3.3' in text

            # gauges move when the workload's numbers move
            fake.values[rm.DUTY_CYCLE][0] = 42.0
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/metrics") as resp:
                    text = await resp.text()
            assert 'tpu_plugin_chip_duty_cycle_percent{chip="0"} 42.0' in text

            # workload exits -> endpoint gone -> gauges read idle, not stale
            fake.stop()
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/metrics") as resp:
                    text = await resp.text()
            assert 'tpu_plugin_chip_duty_cycle_percent{chip="0"} 0.0' in text
            assert 'tpu_plugin_chip_hbm_used_bytes{chip="0"} 0.0' in text
        finally:
            await teardown()

    try:
        run(body())
    finally:
        fake.stop()


def test_usage_reader_absent_endpoint_is_silent(tmp_path):
    """No workload holding the chips -> no endpoint -> empty usage, no error."""
    from k8s_gpu_device_plugin_tpu.metrics.runtime_metrics import LibtpuUsageReader

    reader = LibtpuUsageReader(ports=[1], timeout_seconds=0.2)  # nothing listens
    assert reader.read() == {}
    reader.close()


def test_recovery_middleware_and_access_log(tmp_path, captured_log_records):
    """Handler exceptions become an enveloped 500 (≙ echo Recover,
    server/server.go:40-43) and every request leaves a structured
    access-log line."""
    records = captured_log_records

    async def body():
        base, manager, teardown = await start_http_stack(tmp_path)
        try:
            # /restart delegates to manager.restart -> make it panic for real
            def boom():
                raise RuntimeError("boom")

            manager.restart = boom
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/restart") as resp:
                    assert resp.status == 500
                    data = await resp.json()
                    assert data["code"] == 500
                    assert data["msg"] == "internal server error"
                    assert resp.headers["Access-Control-Allow-Origin"] == "*"
                async with session.get(f"{base}/health") as resp:
                    assert resp.status == 200
            messages = [r.getMessage() for r in records]
            assert "handler panic recovered" in messages
            access = [r for r in records if r.getMessage() == "http request"]
            assert len(access) >= 2  # one per request, including the 500
        finally:
            await teardown()

    run(body())


def test_normalize_status():
    assert normalize_status(200) == "2xx"
    assert normalize_status(404) == "4xx"
    assert normalize_status(503) == "5xx"
    assert normalize_status(700) == "700"
