"""HTTP control-plane tests (≙ server/, router/, middleware/ behavior)."""

import asyncio
import json

import aiohttp
import pytest
from prometheus_client import CollectorRegistry

from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.metrics.http_metrics import normalize_status
from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
from k8s_gpu_device_plugin_tpu.server.server import Server
from k8s_gpu_device_plugin_tpu.utils.latch import Latch


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


async def start_http_stack(tmp_path, **cfg_kwargs):
    cfg = Config(
        kubelet_socket_dir=str(tmp_path),
        web_listen_address="127.0.0.1:0",
        libtpu_path="",
        **cfg_kwargs,
    )
    ready = Latch()
    manager = PluginManager(
        cfg, ready, backend=FakeBackend("v5e-4"), health_interval=0.1
    )
    registry = CollectorRegistry()
    server = Server(cfg, manager, ready, registry=registry)
    stop = asyncio.Event()
    mtask = asyncio.create_task(manager.start())
    stask = asyncio.create_task(server.run(stop))
    for _ in range(100):
        if server.port:
            break
        await asyncio.sleep(0.05)
    assert server.port, "server did not bind"
    base = f"http://127.0.0.1:{server.port}"

    async def teardown():
        stop.set()
        await manager.stop()
        await asyncio.gather(mtask, stask, return_exceptions=True)

    return base, manager, teardown


def test_routes_and_envelope(tmp_path):
    async def body():
        base, _, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/") as resp:
                    data = await resp.json()
                    assert resp.status == 200
                    assert data["code"] == 200
                    assert "version" in data["data"]

                async with session.get(f"{base}/health") as resp:
                    data = await resp.json()
                    assert data == {"code": 200, "data": "ok", "msg": "success"}

                async with session.get(f"{base}/nope") as resp:
                    assert resp.status == 404
        finally:
            await teardown()

    run(body())


def test_metrics_exposition(tmp_path):
    async def body():
        base, _, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                await session.get(f"{base}/health")
                await session.get(f"{base}/bogus")
                async with session.get(f"{base}/metrics") as resp:
                    text = await resp.text()
                assert resp.status == 200
                # HTTP middleware metrics (reference echo_http_* contract)
                assert 'tpu_plugin_http_requests_total{' in text
                assert 'handler="/health"' in text
                assert 'handler="/not-found"' in text  # 404 collapse
                assert "tpu_plugin_http_request_duration_seconds_bucket" in text
                # device metrics the reference left unimplemented
                assert 'tpu_plugin_chips{resource="google.com/tpu",state="healthy"} 4.0' in text
                assert "tpu_plugin_chip_hbm_total_bytes" in text
                assert "tpu_plugin_build_info" in text
        finally:
            await teardown()

    run(body())


def test_restart_endpoint_reloads_plugins(tmp_path):
    async def body():
        base, manager, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/restart") as resp:
                    data = await resp.json()
                    assert data["code"] == 200
            # restart event consumed by manager loop
            await asyncio.sleep(0.5)
            assert not manager._restart_event.is_set()
        finally:
            await teardown()

    run(body())


def test_cors_headers(tmp_path):
    async def body():
        base, _, teardown = await start_http_stack(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.options(f"{base}/health") as resp:
                    assert resp.status == 204
                    assert resp.headers["Access-Control-Allow-Origin"] == "*"
                async with session.get(f"{base}/health") as resp:
                    assert resp.headers["Access-Control-Allow-Origin"] == "*"
        finally:
            await teardown()

    run(body())


def test_normalize_status():
    assert normalize_status(200) == "2xx"
    assert normalize_status(404) == "4xx"
    assert normalize_status(503) == "5xx"
    assert normalize_status(700) == "700"
