"""Sub-slice partitioning tests (≙ MIG semantics, device/mig.go + resources.go)."""

import pytest

from k8s_gpu_device_plugin_tpu.device.slices import (
    SliceProfile,
    enumerate_placements,
    partition_host,
    supported_profiles,
)
from k8s_gpu_device_plugin_tpu.device.topology import parse_topology


def test_profile_parse_and_name():
    p = SliceProfile.parse("2x2")
    assert p.shape == (2, 2)
    assert p.name == "2x2"
    assert p.num_chips == 4
    with pytest.raises(ValueError):
        SliceProfile.parse("2xx2")
    with pytest.raises(ValueError):
        SliceProfile.parse("0x2")


def test_supported_profiles_v5e8():
    topo = parse_topology("v5e-8")  # 2x4
    names = {p.name for p in supported_profiles(topo)}
    # divisors of (2,4), strictly smaller than 8 chips
    assert names == {"1x1", "1x2", "1x4", "2x1", "2x2"}


def test_supported_profiles_v5p8():
    topo = parse_topology("v5p-8")  # 2x2x2
    names = {p.name for p in supported_profiles(topo)}
    assert "1x1x1" in names
    assert "2x2x1" in names
    assert "2x2x2" not in names  # whole host is not a strict sub-slice


def test_placements_are_disjoint_tiling():
    topo = parse_topology("v5e-8")
    placements = enumerate_placements(topo, SliceProfile.parse("2x2"))
    assert len(placements) == 2
    cells = [c for p in placements for c in p.coords()]
    assert len(cells) == len(set(cells)) == 8


def test_partition_full_host():
    topo = parse_topology("v5e-8")
    plan = [SliceProfile.parse("2x2"), SliceProfile.parse("2x2")]
    placements = partition_host(topo, plan)
    assert len(placements) == 2
    all_cells = {c for p in placements for c in p.coords()}
    assert len(all_cells) == 8


def test_partition_mixed_shapes():
    topo = parse_topology("v5e-8")
    plan = [SliceProfile.parse(s) for s in ("2x2", "1x2", "1x1", "1x1")]
    placements = partition_host(topo, plan)
    covered = [c for p in placements for c in p.coords()]
    assert len(covered) == len(set(covered)) == 8


def test_partition_overflow_raises():
    topo = parse_topology("v5e-4")
    plan = [SliceProfile.parse("2x2"), SliceProfile.parse("1x1")]
    with pytest.raises(ValueError, match="does not fit"):
        partition_host(topo, plan)


def test_placement_chip_indices_match_topology():
    topo = parse_topology("v5p-8")
    placements = enumerate_placements(topo, SliceProfile.parse("2x2x1"))
    seen = []
    for p in placements:
        seen.extend(p.chip_indices(topo))
    assert sorted(seen) == list(range(8))
