"""Speculative decoding: losslessness oracle + acceptance accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.speculative import speculative_generate


def _prompt(p=8):
    return jnp.arange(1, p + 1, dtype=jnp.int32)[None, :]


def test_self_draft_accepts_everything():
    """Draft == target: every proposal matches, so each round advances by
    gamma and the output equals plain greedy decode."""
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    max_new, gamma = 13, 4
    toks, rounds = speculative_generate(
        params, cfg, params, cfg, _prompt(), max_new=max_new, gamma=gamma
    )
    ref = generate(params, _prompt(), cfg, max_new=max_new)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))
    # first token comes from prefill; the remaining 12 need ceil(12/4)=3
    # full-acceptance rounds
    assert int(rounds) == -(-(max_new - 1) // gamma)


def test_weak_draft_is_still_lossless():
    """A different (differently-seeded, shallower) draft proposes mostly
    wrong tokens; the output must STILL equal target-only greedy decode —
    acceptance only shortcuts compute, never changes tokens."""
    cfg_t = LlamaConfig.tiny(n_layers=2)
    cfg_d = LlamaConfig.tiny(n_layers=1)
    params_t = init_params(jax.random.key(0), cfg_t)
    params_d = init_params(jax.random.key(7), cfg_d)
    max_new = 12
    toks, rounds = speculative_generate(
        params_t, cfg_t, params_d, cfg_d, _prompt(), max_new=max_new, gamma=3
    )
    ref = generate(params_t, _prompt(), cfg_t, max_new=max_new)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))
    # a bad draft costs more rounds than a perfect one, never more than
    # one per emitted token
    assert -(-(max_new - 1) // 3) <= int(rounds) <= max_new - 1


def test_single_token_needs_no_rounds():
    cfg = LlamaConfig.tiny(n_layers=1)
    params = init_params(jax.random.key(0), cfg)
    toks, rounds = speculative_generate(
        params, cfg, params, cfg, _prompt(), max_new=1
    )
    ref = generate(params, _prompt(), cfg, max_new=1)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))
    assert int(rounds) == 0


def test_validation():
    cfg = LlamaConfig.tiny(n_layers=1)
    params = init_params(jax.random.key(0), cfg)
    cfg_v = LlamaConfig.tiny(n_layers=1, vocab_size=256)
    params_v = init_params(jax.random.key(1), cfg_v)
    with pytest.raises(ValueError, match="vocab mismatch"):
        speculative_generate(params, cfg, params_v, cfg_v, _prompt(), max_new=4)
    with pytest.raises(NotImplementedError, match="batch-1"):
        speculative_generate(
            params, cfg, params, cfg, jnp.zeros((2, 8), jnp.int32), max_new=4
        )
    with pytest.raises(NotImplementedError, match="bf16-only"):
        cfg_q = LlamaConfig.tiny(n_layers=1, quant="int8")
        speculative_generate(params, cfg_q, params, cfg, _prompt(), max_new=4)


def test_accept_round_marginal_is_target_distribution():
    """The speculative-sampling theorem, tested directly on _accept_round
    with gamma=1: draft proposes d ~ q, the round keeps it w.p. min(1,p/q)
    or resamples from the residual — the emitted token's marginal must be
    exactly p. 4000 trials over an 8-token vocab; empirical frequencies
    must match p well within 4-sigma multinomial noise."""
    from k8s_gpu_device_plugin_tpu.models.speculative import _accept_round

    v = 8
    kp, kq = jax.random.split(jax.random.key(42))
    p = jax.nn.softmax(jax.random.normal(kp, (v,)) * 1.5)
    q = jax.nn.softmax(jax.random.normal(kq, (v,)) * 1.5)

    def one(key):
        kd, ka = jax.random.split(key)
        d = jax.random.categorical(kd, jnp.log(q))[None].astype(jnp.int32)
        n, bonus, count = _accept_round(
            ka, d, q[None, :], p[None, :]
        )
        return jnp.where(n > 0, d[0], bonus)

    trials = 4000
    toks = jax.vmap(one)(jax.random.split(jax.random.key(0), trials))
    counts = np.bincount(np.asarray(toks), minlength=v)
    expected = np.asarray(p) * trials
    sigma = np.sqrt(expected * (1 - np.asarray(p)))
    assert (np.abs(counts - expected) < 4 * sigma + 1).all(), (
        counts, expected.round(1)
    )


def test_sampled_self_draft_accepts_everything():
    """Draft == target => p == q => acceptance probability 1: every round
    advances gamma tokens, same as the greedy self-draft case. The draft's
    T=1 forwards and the target's T=gamma verify forward may tile
    differently on some backends, so p/q can dip fractionally below 1 —
    allow one stray rejection rather than pinning bitwise agreement."""
    from k8s_gpu_device_plugin_tpu.models.sampling import Sampler

    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    max_new, gamma = 13, 4
    toks, rounds = speculative_generate(
        params, cfg, params, cfg, _prompt(), max_new=max_new, gamma=gamma,
        sampler=Sampler(temperature=0.9), key=jax.random.key(5),
    )
    assert toks.shape == (1, max_new)
    floor = -(-(max_new - 1) // gamma)
    assert floor <= int(rounds) <= floor + 1


def test_sampled_with_filters_runs_and_stays_in_vocab():
    from k8s_gpu_device_plugin_tpu.models.sampling import Sampler

    cfg_t = LlamaConfig.tiny(n_layers=2)
    cfg_d = LlamaConfig.tiny(n_layers=1)
    params_t = init_params(jax.random.key(0), cfg_t)
    params_d = init_params(jax.random.key(7), cfg_d)
    toks, rounds = speculative_generate(
        params_t, cfg_t, params_d, cfg_d, _prompt(), max_new=10, gamma=3,
        sampler=Sampler(temperature=0.8, top_k=20, top_p=0.95),
        key=jax.random.key(11),
    )
    a = np.asarray(toks)
    assert a.shape == (1, 10)
    assert (a >= 0).all() and (a < cfg_t.vocab_size).all()
    assert 1 <= int(rounds) <= 9


def test_accept_round_gamma2_marginals():
    """Multi-position statistical oracle (gamma=2): slot 0's marginal over
    ALL trials must be p_0, and slot 1's marginal CONDITIONAL on slot 0
    being a kept draft token (n >= 1) must be p_1 — pinning the cumprod
    prefix count, interior residual row, and bonus slot placement that the
    gamma=1 test cannot see."""
    from k8s_gpu_device_plugin_tpu.models.speculative import _accept_round

    v = 8
    ks = jax.random.split(jax.random.key(7), 4)
    p = jax.nn.softmax(jax.random.normal(ks[0], (2, v)) * 1.5, axis=-1)
    q = jax.nn.softmax(jax.random.normal(ks[1], (2, v)) * 1.5, axis=-1)

    def one(key):
        kd0, kd1, ka = jax.random.split(key, 3)
        d = jnp.stack([
            jax.random.categorical(kd0, jnp.log(q[0])),
            jax.random.categorical(kd1, jnp.log(q[1])),
        ]).astype(jnp.int32)
        n, bonus, count = _accept_round(ka, d, q, p)
        slot0 = jnp.where(n > 0, d[0], bonus)
        slot1 = jnp.where(n > 1, d[1], bonus)
        return slot0, slot1, n

    trials = 8000
    s0, s1, n = jax.vmap(one)(jax.random.split(jax.random.key(1), trials))
    s0, s1, n = np.asarray(s0), np.asarray(s1), np.asarray(n)

    # slot 0 marginal == p_0 over all trials
    counts0 = np.bincount(s0, minlength=v)
    exp0 = np.asarray(p[0]) * trials
    sig0 = np.sqrt(exp0 * (1 - np.asarray(p[0])))
    assert (np.abs(counts0 - exp0) < 4 * sig0 + 1).all(), (counts0, exp0)

    # slot 1 marginal == p_1 conditional on n >= 1 (slot 1 exists & valid)
    sel = s1[n >= 1]
    counts1 = np.bincount(sel, minlength=v)
    exp1 = np.asarray(p[1]) * len(sel)
    sig1 = np.sqrt(exp1 * (1 - np.asarray(p[1])))
    assert len(sel) > 1200  # enough mass for the bound to mean something
    assert (np.abs(counts1 - exp1) < 4 * sig1 + 1).all(), (counts1, exp1)


def test_speculative_with_tp_sharded_target():
    """Multi-chip serving composes with speculation: a tp-sharded target
    verifies a single-device draft's proposals, still lossless."""
    from k8s_gpu_device_plugin_tpu.models.llama import param_shardings
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg_t = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    cfg_d = LlamaConfig.tiny(n_layers=1, dtype=jnp.float32)
    params_t = init_params(jax.random.key(0), cfg_t)
    params_d = init_params(jax.random.key(7), cfg_d)
    mesh = make_mesh(MeshSpec(dp=1, tp=4), jax.devices()[:4])
    sharded_t = jax.device_put(params_t, param_shardings(cfg_t, mesh))
    toks, _ = speculative_generate(
        sharded_t, cfg_t, params_d, cfg_d, _prompt(), max_new=8, gamma=3
    )
    ref = generate(params_t, _prompt(), cfg_t, max_new=8)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))
