"""Rolling (bounded) KV cache vs the unbounded windowed decode oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.rolling import (
    _ring_from_prefill,
    rolling_generate,
)
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler


def _cfg(window=8, **kw):
    return LlamaConfig.tiny(
        n_layers=2, sliding_window=window, dtype=jnp.float32, **kw
    )


@pytest.mark.parametrize(
    "prompt_len,max_new,window",
    [
        (4, 6, 8),    # prompt < window
        (12, 6, 8),   # prompt > window
        (6, 20, 8),   # generation wraps the ring twice
    ],
)
def test_rolling_matches_unbounded_windowed_decode(prompt_len, max_new, window):
    cfg = _cfg(window)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (2, prompt_len), 0, cfg.vocab_size, jnp.int32
    )
    ref = generate(params, prompt, cfg, max_new=max_new)
    got = rolling_generate(params, prompt, cfg, max_new=max_new)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_rolling_moe_matches_unbounded():
    cfg = _cfg(8, n_experts=4, capacity_factor=8.0)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(2), (1, 10), 0, cfg.vocab_size, jnp.int32
    )
    ref = generate(params, prompt, cfg, max_new=10)
    got = rolling_generate(params, prompt, cfg, max_new=10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_ring_from_prefill_layout():
    """Slot s must hold the key whose position is congruent to s (mod W),
    for both the short-prompt (pad) and wrapped layouts."""
    L, B, H, hd = 1, 1, 1, 1
    w = 4
    # P = 6 > W: positions 2..5 live; slot s holds position with pos%4==s
    kv = jnp.arange(6, dtype=jnp.float32).reshape(L, B, 6, H, hd)
    ring = _ring_from_prefill(kv, 6, w)
    np.testing.assert_array_equal(
        np.asarray(ring).ravel(), [4.0, 5.0, 2.0, 3.0]
    )
    # P = 3 < W: slots 0..2 hold 0..2, slot 3 zero
    kv = jnp.arange(3, dtype=jnp.float32).reshape(L, B, 3, H, hd)
    ring = _ring_from_prefill(kv, 3, w)
    np.testing.assert_array_equal(np.asarray(ring).ravel(), [0.0, 1.0, 2.0, 0.0])


def test_rolling_sampled_runs_and_stays_in_vocab():
    cfg = _cfg(8)
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
    toks = rolling_generate(
        params, prompt, cfg, max_new=10, key=jax.random.key(3),
        sampler=Sampler(temperature=0.8, top_k=20),
    )
    a = np.asarray(toks)
    assert a.shape == (1, 10)
    assert (a >= 0).all() and (a < cfg.vocab_size).all()


def test_rolling_validation():
    cfg_full = LlamaConfig.tiny(n_layers=1, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg_full)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="sliding_window"):
        rolling_generate(params, prompt, cfg_full, max_new=2)


@pytest.mark.parametrize(
    "prompt_len,max_new,window",
    [
        (4, 6, 8),    # prompt < window
        (12, 6, 8),   # prompt > window
        (6, 20, 8),   # generation wraps the ring twice
    ],
)
@pytest.mark.parametrize("cache_quant", ["int8", "int4"])
def test_rolling_quantized_cache_matches_unbounded(prompt_len, max_new,
                                                   window, cache_quant):
    """Ring + quantized KV cache: token-exact against the unbounded
    windowed generate with the same cache_quant (both sides quantize each
    written row with the one shared _quantize_kv recipe, so in-window
    rows carry identical codes and scales)."""
    cfg = _cfg(window, cache_quant=cache_quant)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(3), (2, prompt_len), 0, cfg.vocab_size, jnp.int32
    )
    ref = generate(params, prompt, cfg, max_new=max_new)
    got = rolling_generate(params, prompt, cfg, max_new=max_new)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
