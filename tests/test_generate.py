"""KV-cache generation vs the full-context forward oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import KVCache, generate, prefill
from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
)


def _greedy_oracle(params, prompt, cfg, max_new):
    """Iterative full-context forward + argmax (no cache) — the oracle."""
    tokens = prompt
    out = []
    for _ in range(max_new):
        logits = forward(params, tokens, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_greedy_generate_matches_full_context_oracle():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 7), 0, cfg.vocab_size,
                                jnp.int32)
    got = generate(params, prompt, cfg, max_new=6, temperature=0.0)
    expected = _greedy_oracle(params, prompt, cfg, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_prefill_logits_match_forward():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(2), (2, 9), 0, cfg.vocab_size,
                                jnp.int32)
    cache = KVCache.init(cfg, 2, 16)
    last, cache = prefill(params, prompt, cache, cfg)
    full = forward(params, prompt, cfg)[:, -1]
    np.testing.assert_allclose(
        np.asarray(last), np.asarray(full), atol=2e-2, rtol=2e-2
    )
    assert cache.k.shape == (2, 2, 16, cfg.n_kv_heads, cfg.head_dim)


def test_sampled_generate_shapes_and_determinism():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    a = generate(params, prompt, cfg, max_new=5, key=jax.random.key(7),
                 temperature=1.0)
    b = generate(params, prompt, cfg, max_new=5, key=jax.random.key(7),
                 temperature=1.0)
    c = generate(params, prompt, cfg, max_new=5, key=jax.random.key(8),
                 temperature=1.0)
    assert a.shape == (1, 5)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.dtype == jnp.int32
    # different key must change the sample (near-uniform random-init model;
    # a constant-key bug would make these identical)
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_generate_rejects_quantized_config():
    """int8 configs must be refused: the decode block is bf16-only and
    silently decoding with different numerics than training would let
    greedy tokens drift from the full-context oracle."""
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny(n_layers=1, quant="int8")
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(NotImplementedError, match="bf16-only"):
        generate(params, prompt, cfg, max_new=2)


def test_moe_generate_matches_full_context_oracle():
    """MoE decode (dense-mix of all experts by renormalized top-k gates)
    must reproduce the training forward's routing exactly when capacity
    never drops a token (capacity_factor ample) — token-exact greedy
    equality with the full-context oracle.

    f32: at bf16, K/V written by different-T forwards differ by ~1e-3
    (legitimate rounding of reordered einsums), enough to flip near-tie
    argmaxes; f32 shrinks that noise ~1e-7 so exact equality is a
    meaningful assertion about the MATH, not float luck."""
    cfg = LlamaConfig.tiny(
        n_layers=2, n_experts=4, capacity_factor=8.0, dtype=jnp.float32
    )
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(3), (2, 6), 0, cfg.vocab_size,
                                jnp.int32)
    got = generate(params, prompt, cfg, max_new=5)
    expected = _greedy_oracle(params, prompt, cfg, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_moe_prefill_logits_match_forward():
    cfg = LlamaConfig.tiny(n_layers=1, n_experts=4, capacity_factor=8.0)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(4), (1, 8), 0, cfg.vocab_size,
                                jnp.int32)
    cache = KVCache.init(cfg, 1, 12)
    last, _ = prefill(params, prompt, cache, cfg)
    full = forward(params, prompt, cfg)[:, -1]
    np.testing.assert_allclose(
        np.asarray(last), np.asarray(full), atol=2e-2, rtol=2e-2
    )


def test_moe_speculative_is_lossless():
    """Greedy speculative decode over an MoE target/draft still equals
    target-only greedy decode."""
    from k8s_gpu_device_plugin_tpu.models.speculative import (
        speculative_generate,
    )

    cfg_t = LlamaConfig.tiny(
        n_layers=2, n_experts=4, capacity_factor=8.0, dtype=jnp.float32
    )
    cfg_d = LlamaConfig.tiny(n_layers=1, dtype=jnp.float32)
    params_t = init_params(jax.random.key(0), cfg_t)
    params_d = init_params(jax.random.key(9), cfg_d)
    prompt = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
    toks, _ = speculative_generate(
        params_t, cfg_t, params_d, cfg_d, prompt, max_new=8, gamma=3
    )
    ref = generate(params_t, prompt, cfg_t, max_new=8)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))


def test_moe_prefill_chunked_matches_unchunked():
    """Prompts longer than the MoE prefill chunk go through the scanned
    path; routing is per-token so the result must equal a direct (small-T)
    computation — checked by comparing against the full-context forward."""
    import k8s_gpu_device_plugin_tpu.models.generate as gen

    cfg = LlamaConfig.tiny(
        n_layers=1, n_experts=4, capacity_factor=8.0, dtype=jnp.float32
    )
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(5), (1, 20), 0, cfg.vocab_size, jnp.int32
    )
    orig = gen._MOE_PREFILL_CHUNK
    gen._MOE_PREFILL_CHUNK = 8  # force the scan path (with a ragged tail)
    try:
        cache = KVCache.init(cfg, 1, 24)
        last, _ = prefill(params, prompt, cache, cfg)
    finally:
        gen._MOE_PREFILL_CHUNK = orig
    full = forward(params, prompt, cfg)[:, -1]
    np.testing.assert_allclose(
        np.asarray(last), np.asarray(full), atol=1e-4, rtol=1e-4
    )


def test_master_weight_params_decode_in_compute_dtype():
    """param_dtype=f32 checkpoints must decode identically to the same
    weights stored in bf16 — the decode path casts to compute dtype
    instead of silently running f32 matmuls against the bf16 cache."""
    cfg32 = LlamaConfig.tiny(n_layers=2, param_dtype=jnp.float32)
    cfg16 = LlamaConfig.tiny(n_layers=2)
    p32 = init_params(jax.random.key(0), cfg32)
    p16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), p32)
    prompt = jnp.arange(1, 9, dtype=jnp.int32)[None, :]
    a = generate(p32, prompt, cfg32, max_new=6)
    b = generate(p16, prompt, cfg16, max_new=6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_int8_cache_decode_close_to_bf16_cache():
    """cache_quant="int8": generation runs end-to-end with an int8 cache
    and the prefill logits stay within per-head quantization error (~0.4%
    of amax per K/V row) of the bf16-cache path."""
    from dataclasses import replace

    cfg = LlamaConfig.tiny(n_layers=2)
    cfg_q = replace(cfg, cache_quant="int8")
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(6), (2, 10), 0, cfg.vocab_size,
                                jnp.int32)

    cache = KVCache.init(cfg_q, 2, 16)
    assert cache.k.dtype == jnp.int8 and cache.k_scale.dtype == jnp.float32
    last_q, cache = prefill(params, prompt, cache, cfg_q)
    last, _ = prefill(params, prompt, KVCache.init(cfg, 2, 16), cfg)
    # logits differ only by cache quantization noise
    np.testing.assert_allclose(
        np.asarray(last_q), np.asarray(last), atol=0.15, rtol=0.1
    )
    # cache scales were actually written for the prompt positions
    assert float(jnp.abs(cache.k_scale[:, :, :10]).sum()) > 0

    toks = generate(params, prompt, cfg_q, max_new=6)
    assert toks.shape == (2, 6)
    assert (np.asarray(toks) >= 0).all()


def test_int8_cache_quantize_roundtrip_error_bound():
    from k8s_gpu_device_plugin_tpu.models.generate import _quantize_kv

    x = jax.random.normal(jax.random.key(0), (2, 8, 4, 64), jnp.float32)
    q, s = _quantize_kv(x)
    deq = q.astype(jnp.float32) * s
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    # symmetric int8: |x - deq| <= scale/2 = amax/254 per row
    assert float(jnp.max(jnp.abs(x - deq) / amax)) <= (1 / 254) + 1e-6


def test_generate_with_tp_sharded_params():
    """Multi-chip serving: tp-sharded params flow through the jitted decode
    via GSPMD (no code path changes) and emit the same tokens as a
    single-device run."""
    from k8s_gpu_device_plugin_tpu.models.llama import param_shardings
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (2, 8), 0, cfg.vocab_size, jnp.int32
    )
    ref = generate(params, prompt, cfg, max_new=5)
    mesh = make_mesh(MeshSpec(dp=1, tp=4), jax.devices()[:4])
    sharded = jax.device_put(params, param_shardings(cfg, mesh))
    out = generate(sharded, prompt, cfg, max_new=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_eos_stops_each_row_independently():
    """Rows pad everything strictly after their first EOS; the EOS itself
    is kept, rows without EOS are untouched."""
    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab_size,
                                jnp.int32)
    base = np.asarray(generate(params, prompt, cfg, max_new=6))
    # choose the token row 0 emits at step 2 as the EOS id
    eos = int(base[0, 2])
    got = np.asarray(
        generate(params, prompt, cfg, max_new=6, eos_id=eos, pad_id=-1)
    )
    for r in range(2):
        hits = np.where(base[r] == eos)[0]
        if hits.size:
            cut = hits[0]
            np.testing.assert_array_equal(got[r, :cut + 1], base[r, :cut + 1])
            assert (got[r, cut + 1:] == -1).all()
        else:
            np.testing.assert_array_equal(got[r], base[r])
    # row 0 definitely has one
    assert (got[0, 3:] == -1).all()


def test_prefix_cached_continuation_matches_fresh_generate():
    """One prefill, many branches: each generate_from continuation must be
    token-identical to a fresh generate with the same prompt/key/sampler
    (same decode loop, same key schedule), and the shared state is never
    mutated between branches."""
    from k8s_gpu_device_plugin_tpu.models.generate import (
        generate_from,
        prefill_prompt,
    )
    from k8s_gpu_device_plugin_tpu.models.sampling import Sampler

    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 9), 0, cfg.vocab_size,
                                jnp.int32)
    cache, logits = prefill_prompt(params, prompt, cfg, max_new_capacity=8)

    # greedy branch
    a = generate_from(params, prompt, cache, logits, cfg, max_new=6)
    np.testing.assert_array_equal(
        np.asarray(a), np.asarray(generate(params, prompt, cfg, max_new=6))
    )
    # two sampled branches from the SAME state with different keys
    s = Sampler(temperature=0.9, top_k=30)
    b1 = generate_from(params, prompt, cache, logits, cfg, max_new=6,
                       key=jax.random.key(7), sampler=s)
    b2 = generate_from(params, prompt, cache, logits, cfg, max_new=6,
                       key=jax.random.key(8), sampler=s)
    ref1 = generate(params, prompt, cfg, max_new=6, key=jax.random.key(7),
                    sampler=s)
    np.testing.assert_array_equal(np.asarray(b1), np.asarray(ref1))
    assert not np.array_equal(np.asarray(b1), np.asarray(b2))
    # capacity guard
    import pytest

    with pytest.raises(ValueError, match="free rows"):
        generate_from(params, prompt, cache, logits, cfg, max_new=9)


def test_int4_cache_decode_end_to_end():
    """cache_quant="int4": the native narrow dtype rides the exact same
    plumbing as int8 (shared _cache_write / scale placement); prefill
    logits stay within the coarser int4 quantization error of the bf16
    path and generation completes with valid tokens."""
    from dataclasses import replace

    cfg = LlamaConfig.tiny(n_layers=2)
    cfg_q = replace(cfg, cache_quant="int4")
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(7), (2, 10), 0,
                                cfg.vocab_size, jnp.int32)

    cache = KVCache.init(cfg_q, 2, 16)
    assert cache.k.dtype == jnp.int4 and cache.k_scale.dtype == jnp.float32
    last_q, cache = prefill(params, prompt, cache, cfg_q)
    last, _ = prefill(params, prompt, KVCache.init(cfg, 2, 16), cfg)
    # ~16x coarser codes than int8: wider but still bounded noise
    np.testing.assert_allclose(
        np.asarray(last_q), np.asarray(last), atol=1.5, rtol=0.5
    )
    assert float(jnp.abs(cache.k_scale[:, :, :10]).sum()) > 0

    toks = generate(params, prompt, cfg_q, max_new=6)
    assert toks.shape == (2, 6)
    assert (np.asarray(toks) >= 0).all()


def test_int4_cache_quantize_roundtrip_error_bound():
    from k8s_gpu_device_plugin_tpu.models.generate import _quantize_kv

    x = jax.random.normal(jax.random.key(0), (2, 8, 4, 64), jnp.float32)
    q, s = _quantize_kv(x, jnp.int4)
    assert q.dtype == jnp.int4
    deq = q.astype(jnp.float32) * s
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    # symmetric int4 over [-7, 7]: |x - deq| <= scale/2 = amax/14 per row
    assert float(jnp.max(jnp.abs(x - deq) / amax)) <= (1 / 14) + 1e-6
