"""Inference HTTP server: concurrent requests, streaming, health, errors.

The engine thread drives real jitted decode steps on the CPU backend; the
assertions pin the API contract AND token-level parity with dedicated
``generate`` — the HTTP/threading layer must be invisible to outputs.
"""

import asyncio
import json

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.server import (
    InferenceEngine,
    InferenceServer,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


async def _with_server(setup, body, tokenizer=None, **engine_kw):
    cfg, params = setup
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8, **engine_kw
    )
    server = InferenceServer(
        engine, host="127.0.0.1", port=0, tokenizer=tokenizer
    )
    stop = asyncio.Event()
    task = asyncio.create_task(server.run(stop))
    for _ in range(100):
        if server.bound_port:
            break
        await asyncio.sleep(0.05)
    try:
        base = f"http://127.0.0.1:{server.bound_port}"
        async with aiohttp.ClientSession() as session:
            await body(session, base)
    finally:
        stop.set()
        await asyncio.wait_for(task, 30)


def test_concurrent_generate_matches_oracle(setup):
    """3 concurrent POSTs over 2 slots: each response's tokens equal the
    dedicated-generate oracle for its prompt."""
    cfg, params = setup
    prompts = {i: _prompt(200 + i, 5 + 3 * i, cfg) for i in range(3)}

    async def body(session, base):
        async def one(i):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": prompts[i], "max_new": 4 + i,
            }) as r:
                assert r.status == 200
                return i, (await r.json())["tokens"]

        results = dict(await asyncio.gather(*(one(i) for i in range(3))))
        for i, toks in results.items():
            assert toks == _oracle(params, prompts[i], cfg, 4 + i), i

    run(_with_server(setup, body))


def test_streaming_tokens_arrive_incrementally(setup):
    """SSE stream: every data line is one token, the stream closes with
    done, and the collected tokens equal the oracle."""
    cfg, params = setup
    p = _prompt(210, 6, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 5, "stream": True,
        }) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/event-stream")
            tokens, done = [], False
            async for line in r.content:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                evt = json.loads(line[len("data: "):])
                if evt.get("done"):
                    done = True
                    break
                tokens.append(evt["token"])
            assert done
            assert tokens == _oracle(params, p, cfg, 5)

    run(_with_server(setup, body))


def test_health_and_validation(setup):
    async def body(session, base):
        async with session.get(f"{base}/v1/health") as r:
            assert r.status == 200
            stats = await r.json()
            assert stats["slots"] == 2
        # malformed bodies -> 400
        for bad in ({}, {"prompt": "text"}, {"prompt": []},
                    {"prompt": [1, "x"]}):
            async with session.post(f"{base}/v1/generate", json=bad) as r:
                assert r.status == 400, bad
        # over capacity -> 422
        async with session.post(f"{base}/v1/generate", json={
            "prompt": list(range(1, 60)), "max_new": 30,
        }) as r:
            assert r.status == 422

    run(_with_server(setup, body))


def test_native_overload_429_and_sched_health(setup):
    """Native-API twin of the OpenAI 429 pin: queue-full answers 429
    with Retry-After and the structured overload body, and /v1/health
    carries the scheduler's queue + per-tenant snapshot."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import Scheduler

    cfg, params = setup
    prompt = _prompt(31, 9, cfg)

    async def body(session, base):
        posts = [
            session.post(f"{base}/v1/generate", json={
                "prompt": list(prompt), "max_new": 48, "tenant": "gold",
            })
            for _ in range(8)
        ]
        results = await asyncio.gather(*posts)
        rejected = [r for r in results if r.status == 429]
        served = [r for r in results if r.status == 200]
        assert rejected and served
        for r in rejected:
            assert int(r.headers["Retry-After"]) >= 1
            p = await r.json()
            assert p["code"] == "overloaded"
            assert p["reason"] == "queue_full"
        async with session.get(f"{base}/v1/health") as r:
            stats = await r.json()
            sched = stats["sched"]
            assert sched["policy"] == "fifo"
            assert sched["max_queue"] == 1
            assert sched["rejections"]["queue_full"] == len(rejected)
            assert "gold" in sched["tenants"]
        for r in results:
            await r.release()

    run(_with_server(setup, body, scheduler=Scheduler(max_queue=1)))


def test_metrics_endpoint_exports_serving_counters(setup):
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    registry = CollectorRegistry()
    metrics = ServingMetrics(registry=registry)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": _prompt(220, 5, cfg), "max_new": 3,
        }) as r:
            assert r.status == 200

    async def with_metrics():
        engine = InferenceEngine(
            params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
            metrics=metrics,
        )
        server = InferenceServer(
            engine, host="127.0.0.1", port=0, registry=registry
        )
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as session:
                await body(session, base)
                async with session.get(f"{base}/metrics") as r:
                    assert r.status == 200
                    text = await r.text()
                    assert "tpu_serving_generated_tokens_total 3.0" in text
                    assert "tpu_serving_requests_submitted_total 1.0" in text
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    run(with_metrics())


def test_load_params_from_train_checkpoint(tmp_path, setup):
    """Serving round trip with the framework's own checkpoints: train a
    couple of steps with checkpointing on, then load_params restores the
    trained params (not random init) for the engine."""
    from k8s_gpu_device_plugin_tpu.models.checkpoint import TrainCheckpointer
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
        synthetic_batch,
    )
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh
    from k8s_gpu_device_plugin_tpu.serving.server import load_params

    cfg, _ = setup
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1,
                               total_steps=10)
    state = init_train_state(jax.random.key(3), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(4), cfg, 2, 32, mesh)
    step = make_train_step(cfg, mesh, optimizer)
    for _ in range(2):
        state, _m = step(state, batch)
    ckpt = TrainCheckpointer(str(tmp_path), async_save=False, save_interval=1)
    assert ckpt.save(state, step=2, force=True)
    ckpt.wait()
    ckpt.close()

    params = load_params(cfg, str(tmp_path))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(state["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # trained params serve: greedy decode through the engine matches
    # dedicated generate on the SAME restored params
    p = _prompt(230, 5, cfg)
    oracle = _oracle(params, p, cfg, 3)

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        try:
            _, q = engine.submit(p, 3)
            toks = []
            while True:
                t = await asyncio.wait_for(q.get(), 120)
                if t is None:
                    break
                toks.append(t[0])
            assert toks == oracle
        finally:
            engine.shutdown()

    run(body())


def test_dead_engine_fails_fast_not_forever(setup):
    """With recovery OFF (restart budget 0), a dead engine loop closes
    in-flight streams with a STRUCTURED error frame (never a bare
    end-of-stream that reads as a clean short completion), /v1/health
    goes 503, and new submits are rejected — nothing hangs."""
    from k8s_gpu_device_plugin_tpu.serving.supervisor import (
        EngineSupervisor,
        StreamError,
    )

    cfg, params = setup

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8,
                                 supervisor=EngineSupervisor(max_restarts=0))
        try:
            # sabotage the batcher so the next step raises inside the loop
            _, q = engine.submit(_prompt(240, 5, cfg), 3)
            engine.cb.step = None  # TypeError on next loop iteration
            item = await asyncio.wait_for(q.get(), 60)
            while not isinstance(item, StreamError):  # tokens may precede
                assert item is not None, "bare EOS: silent truncation"
                item = await asyncio.wait_for(q.get(), 60)
            assert item.code == "engine_dead"
            assert await asyncio.wait_for(q.get(), 60) is None  # then EOS
            assert engine.stats()["alive"] is False
            with pytest.raises(RuntimeError):
                engine.submit(_prompt(241, 5, cfg), 3)
        finally:
            engine.shutdown()

    run(body())


def test_engine_recovers_from_sabotaged_step_by_default(setup):
    """The same sabotage with the DEFAULT engine: the supervisor
    rebuilds the batcher in place and the stream completes — an engine
    crash is a latency blip, not an outage."""
    cfg, params = setup
    p = _prompt(242, 5, cfg)
    oracle = _oracle(params, p, cfg, 3)

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        try:
            _, q = engine.submit(p, 3)
            engine.cb.step = None  # TypeError on next loop iteration
            toks = []
            while True:
                item = await asyncio.wait_for(q.get(), 120)
                if item is None:
                    break
                toks.append(item[0])
            assert toks == oracle  # recovered AND bit-identical
            assert engine.stats()["alive"] is True
            assert engine.stats()["supervisor"]["restarts_total"] == 1
        finally:
            engine.shutdown()

    run(body())


def test_done_map_does_not_leak(setup):
    """Served requests must not accumulate in the batcher's done map
    (a long-running server would otherwise retain every token list)."""
    cfg, params = setup

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        try:
            for i in range(3):
                _, q = engine.submit(_prompt(250 + i, 4, cfg), 3)
                while await asyncio.wait_for(q.get(), 120) is not None:
                    pass
            assert engine.cb.done == {}
            assert engine._streams == {} and engine._rid_to_eid == {}
        finally:
            engine.shutdown()

    run(body())


def test_n_completions_and_stop_api(setup):
    """n>1 returns that many independently decoded completions (greedy =>
    identical; the API contract is shape + parity), and a stop list is
    honored; n>1 with stream is rejected."""
    cfg, params = setup
    p = _prompt(260, 5, cfg)
    oracle = _oracle(params, p, cfg, 4)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "n": 2,
        }) as r:
            assert r.status == 200
            d = await r.json()
            assert d["completions"] == [oracle, oracle]  # greedy
            assert d["tokens"] == oracle
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "stop": [oracle[:2]],
        }) as r:
            d = await r.json()
            assert d["tokens"] == oracle[:2]
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "n": 2, "stream": True,
        }) as r:
            assert r.status == 400
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "stop": [["x"]],
        }) as r:
            assert r.status == 400

    run(_with_server(setup, body))


def test_logprobs_in_api_responses(setup):
    """'logprobs': true returns finite per-token logprobs aligned with
    tokens, in both JSON and SSE modes."""
    cfg, params = setup
    p = _prompt(270, 5, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "logprobs": True,
        }) as r:
            d = await r.json()
            assert len(d["logprobs"]) == len(d["tokens"]) == 4
            assert all(isinstance(x, float) and x <= 0.0 for x in d["logprobs"])
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 3, "stream": True, "logprobs": True,
        }) as r:
            events = []
            async for line in r.content:
                line = line.decode().strip()
                if line.startswith("data: "):
                    events.append(json.loads(line[6:]))
            assert events[-1] == {"done": True}
            assert all("logprob" in e for e in events[:-1])

    run(_with_server(setup, body))


def test_text_api_end_to_end(setup):
    """Tokenizer seam: text in -> encoded prompt -> decoded text out, with
    token-level parity against the id path; streaming closes with the
    decoded text; stop_text retires like encoded stop; text without a
    tokenizer is a clean 400."""
    from k8s_gpu_device_plugin_tpu.serving.tokenizer import ByteTokenizer

    cfg, params = setup
    tok = ByteTokenizer()
    text = "Hello TPU"
    ids = tok.encode(text)
    oracle = _oracle(params, ids, cfg, 5)
    want_text = tok.decode(oracle)

    async def body(session, base):
        # text request == id request, decoded
        async with session.post(f"{base}/v1/generate", json={
            "text": text, "max_new": 5,
        }) as r:
            assert r.status == 200
            d = await r.json()
            assert d["tokens"] == oracle
            assert d["text"] == want_text
        async with session.post(f"{base}/v1/generate", json={
            "prompt": ids, "max_new": 5,
        }) as r:
            assert (await r.json())["tokens"] == oracle

        # streaming: per-token events, decoded text on the closing event
        async with session.post(f"{base}/v1/generate", json={
            "text": text, "max_new": 5, "stream": True,
        }) as r:
            events = []
            async for line in r.content:
                line = line.decode().strip()
                if line.startswith("data: "):
                    events.append(json.loads(line[6:]))
            assert [e["token"] for e in events[:-1]] == oracle
            assert events[-1]["done"] is True
            assert events[-1]["text"] == want_text

        # stop_text: the first generated token as a stop string retires
        # the request right after emitting it (tokens kept, like EOS)
        stop_str = tok.decode([oracle[0]])
        if tok.encode(stop_str) == [oracle[0]]:  # decodable byte only
            async with session.post(f"{base}/v1/generate", json={
                "text": text, "max_new": 5, "stop_text": [stop_str],
            }) as r:
                d = await r.json()
                assert d["tokens"] == oracle[:1]

        # n > 1 greedy: identical completions, all decoded
        async with session.post(f"{base}/v1/generate", json={
            "text": text, "max_new": 4, "n": 2,
        }) as r:
            d = await r.json()
            assert d["completions_text"] == [d["text"]] * 2

        # both text and prompt is an error
        async with session.post(f"{base}/v1/generate", json={
            "text": text, "prompt": ids, "max_new": 2,
        }) as r:
            assert r.status == 400

    run(_with_server(setup, body, tokenizer=tok))


def test_text_request_without_tokenizer_is_400(setup):
    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "text": "hi", "max_new": 2,
        }) as r:
            assert r.status == 400
            assert "tokenizer" in (await r.json())["error"]
        async with session.post(f"{base}/v1/generate", json={
            "prompt": [1, 2], "max_new": 2, "stop_text": ["x"],
        }) as r:
            assert r.status == 400

    run(_with_server(setup, body))


def test_byte_tokenizer_roundtrip():
    from k8s_gpu_device_plugin_tpu.serving.tokenizer import (
        ByteTokenizer,
        load_tokenizer,
    )

    tok = ByteTokenizer()
    for s in ("hello", "héllo ✓", ""):
        assert tok.decode(tok.encode(s)) == s
    assert all(0 <= i < 256 for i in tok.encode("héllo ✓"))
    assert load_tokenizer("") is None
    assert isinstance(load_tokenizer("byte"), ByteTokenizer)


def test_byte_tokenizer_out_of_range_ids_become_replacement_chars():
    from k8s_gpu_device_plugin_tpu.serving.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    # valid bytes round-trip even when interleaved with invalid ids; each
    # out-of-range id is one U+FFFD, never clamped onto a real byte
    assert tok.decode([104, 105, 300, 104]) == "hi�h"
    assert tok.decode([500, 501]) == "��"
    assert tok.decode(list("hé".encode())) == "hé"  # multi-byte run intact


def test_hf_stop_encoding_uses_no_special_tokens():
    """encode_plain (the stop-string path) must not prepend BOS — a BOS'd
    stop sequence can never match generated output. Verified against the
    seam contract with a fake that mimics HF add_special_tokens."""
    from k8s_gpu_device_plugin_tpu.serving.tokenizer import ByteTokenizer

    class BosTokenizer(ByteTokenizer):
        BOS = 999

        def encode(self, text):
            return [self.BOS] + super().encode(text)

        def encode_plain(self, text):
            return ByteTokenizer.encode(self, text)

    tok = BosTokenizer()
    assert tok.encode("ab")[0] == tok.BOS
    assert tok.encode_plain("ab") == [97, 98]


def test_stop_text_encoding_to_nothing_is_400(setup):
    """A stop_text entry the tokenizer normalizes away must be a 400, not
    a silently-disarmed stop."""
    from k8s_gpu_device_plugin_tpu.serving.tokenizer import ByteTokenizer

    class StrippingTokenizer(ByteTokenizer):
        def encode_plain(self, text):
            return ByteTokenizer.encode(self, text.strip())

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "text": "hi", "max_new": 2, "stop_text": ["   "],
        }) as r:
            assert r.status == 400
            assert "encodes to no tokens" in (await r.json())["error"]

    run(_with_server(setup, body, tokenizer=StrippingTokenizer()))


def test_client_disconnect_cancels_request(setup):
    """An SSE consumer that disconnects mid-stream must free its slot:
    the engine cancels the request (metrics reason 'cancelled') instead
    of decoding to the token budget."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    reg = CollectorRegistry()
    metrics = ServingMetrics(registry=reg)
    p = _prompt(500, 5, cfg)

    async def body():
        engine = InferenceEngine(
            params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
            metrics=metrics,
        )
        server = InferenceServer(engine, host="127.0.0.1", port=0)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        base = f"http://127.0.0.1:{server.bound_port}"
        try:
            session = aiohttp.ClientSession()
            resp = await session.post(f"{base}/v1/generate", json={
                "prompt": p, "max_new": 50, "stream": True,
            })
            got = 0
            async for line in resp.content:
                if line.decode().strip().startswith("data: "):
                    got += 1
                    if got >= 2:
                        break
            await session.close()  # disconnect mid-stream

            def cancelled():
                return reg.get_sample_value(
                    "tpu_serving_requests_finished_total",
                    {"reason": "cancelled"},
                )

            for _ in range(100):
                if cancelled() == 1 and not engine.cb.running:
                    break
                await asyncio.sleep(0.05)
            assert cancelled() == 1
            assert not engine.cb.running and not engine.cb.pending

            # the engine stays fully serviceable afterwards
            async with aiohttp.ClientSession() as s2:
                async with s2.post(f"{base}/v1/generate", json={
                    "prompt": p, "max_new": 3,
                }) as r:
                    assert (await r.json())["tokens"] == _oracle(
                        params, p, cfg, 3
                    )
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    run(body())
    metrics.close()


def test_per_request_sampling_over_http(setup):
    """Sampling knobs ride the JSON request: an explicit greedy override
    (temperature 0) matches the oracle even on a sampled-default server;
    a sampled request returns valid tokens; invalid knobs are a 400."""
    from k8s_gpu_device_plugin_tpu.models.sampling import Sampler

    cfg, params = setup
    p = _prompt(700, 5, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "temperature": 0.0,
        }) as r:
            assert r.status == 200
            assert (await r.json())["tokens"] == _oracle(params, p, cfg, 4)
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "temperature": 0.9, "top_k": 10,
            "repetition_penalty": 1.2,
        }) as r:
            assert r.status == 200
            toks = (await r.json())["tokens"]
            assert len(toks) == 4
            assert all(0 <= t < cfg.vocab_size for t in toks)
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 4, "top_p": 1.5,
        }) as r:
            assert r.status == 400  # Sampler's own validation

    run(_with_server(setup, body, sampler=Sampler(temperature=1.0)))


def test_trim_stop_suffix_shortest_match():
    """The engine halts on the FIRST stop suffix that completes, so the
    trim must remove the shortest matching suffix — client list order
    (stop=["ab","b"] on output "...a b") must not eat a legitimately
    generated token (advisor r4)."""
    from k8s_gpu_device_plugin_tpu.serving.tokenizer import trim_stop_suffix

    a, b = 97, 98
    # output ends [a, b]; stops: "ab"=[a,b] listed BEFORE "b"=[b]
    assert trim_stop_suffix([1, 2, a, b], [[a, b], [b]]) == [1, 2, a]
    # order-independent: reversed list gives the same answer
    assert trim_stop_suffix([1, 2, a, b], [[b], [a, b]]) == [1, 2, a]
    # only the long one matches -> it trims
    assert trim_stop_suffix([1, 2, a, b], [[a, b], [3]]) == [1, 2]
    # no match -> untouched
    assert trim_stop_suffix([1, 2], [[9]]) == [1, 2]
