"""Speculative continuous batching vs the plain batcher / generate oracle.

f32 models: the T=gamma verify and T=1 decode are different XLA programs,
so bf16 near-tie argmaxes could flip; at f32 greedy parity is token-exact
(same caveat as models/speculative.py, pinned there and here).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler
from k8s_gpu_device_plugin_tpu.models.spec_batching import SpeculativeBatcher


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    draft_cfg = LlamaConfig.tiny(n_layers=1, d_model=64, n_heads=4,
                                 n_kv_heads=2, d_ff=128, dtype=jnp.float32)
    draft_params = init_params(jax.random.key(1), draft_cfg)
    return cfg, params, draft_cfg, draft_params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


def test_spec_batching_matches_generate(setup):
    """3 requests over 2 slots with an unrelated draft: every stream must
    equal dedicated generate (acceptance only reorders WORK, never
    output), including slot reuse."""
    cfg, params, draft_cfg, draft_params = setup
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=2, max_len=64, gamma=3, chunked_prefill=4,
    )
    specs = [(400, 5, 6), (401, 9, 4), (402, 3, 7)]
    prompts = {}
    for key, plen, max_new in specs:
        p = _prompt(key, plen, cfg)
        rid = sb.submit(p, max_new=max_new)
        prompts[rid] = (p, max_new)
    results = sb.run()
    for rid, (p, max_new) in prompts.items():
        assert results[rid] == _oracle(params, p, cfg, max_new), rid


def test_spec_batching_selfdraft_accepts_everything(setup):
    """draft == target: every proposal verifies, so rounds emit gamma
    tokens and the step count collapses (~max_new/gamma decode rounds).
    Output parity must still hold."""
    cfg, params, _, _ = setup
    sb = SpeculativeBatcher(
        params, cfg, params, cfg,
        n_slots=1, max_len=64, gamma=4, chunked_prefill=8,
    )
    p = _prompt(410, 6, cfg)
    rid = sb.submit(p, max_new=8)
    steps = 0
    while sb.pending or sb.running or sb.prefilling:
        sb.step()
        steps += 1
    assert sb.done[rid] == _oracle(params, p, cfg, 8)
    # 1 admit/prefill step + ceil((8-1)/4)=2 spec rounds (+1 slack)
    assert steps <= 5, steps


def test_spec_batching_eos_and_logprobs(setup):
    """EOS retirement mid-round drops the tail exactly like the plain
    batcher; logprobs align with tokens."""
    cfg, params, draft_cfg, draft_params = setup
    p = _prompt(420, 5, cfg)
    oracle = _oracle(params, p, cfg, 6)
    eos = oracle[2]
    if eos in oracle[:2]:
        pytest.skip("random oracle collision")
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=1, max_len=64, gamma=3, chunked_prefill=4, eos_id=eos,
    )
    rid = sb.submit(p, max_new=6)
    sb.run()
    req = sb.done_requests[rid]
    assert req.out == oracle[:3]          # stopped AT the eos
    assert len(req.out_logp) == len(req.out)
    assert all(lp <= 0.0 for lp in req.out_logp)


def test_spec_batching_guards(setup):
    cfg, params, draft_cfg, draft_params = setup
    with pytest.raises(ValueError, match="repetition_penalty"):
        SpeculativeBatcher(
            params, cfg, draft_params, draft_cfg, n_slots=1, max_len=64,
            gamma=3, chunked_prefill=4,
            sampler=Sampler(temperature=0.7, repetition_penalty=1.2),
        )
    with pytest.raises(ValueError, match="chunked_prefill"):
        SpeculativeBatcher(
            params, cfg, draft_params, draft_cfg, n_slots=1, max_len=64,
            gamma=3,
        )
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=1, max_len=32, gamma=4, chunked_prefill=4,
    )
    with pytest.raises(ValueError, match="gamma"):
        sb.submit(list(range(1, 21)), max_new=10)  # 20+10+4 > 32
    with pytest.raises(ValueError, match="resume"):
        # no resume path: rounds share one sampler with no per-request
        # draw index (the router's cross-replica resume must 422, not
        # crash the engine thread)
        sb.submit([1, 2, 3], max_new=8, resume_out=[4, 5])
    # shared prefixes are SUPPORTED now (the target serves the cached
    # rows, the draft re-prefills them) — pinned end to end with the
    # oracle comparison in tests/test_spec_fastpath.py
    assert SpeculativeBatcher.supports_prefix_cache is True
    assert SpeculativeBatcher.supports_paged_kv is True


def test_speculative_engine_serves_over_http(setup):
    """A SpeculativeBatcher injected into the inference engine serves
    token streams identical to dedicated generate."""
    import asyncio

    import aiohttp

    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        InferenceServer,
    )

    cfg, params, draft_cfg, draft_params = setup
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=2, max_len=64, gamma=3, chunked_prefill=8,
    )
    p = _prompt(430, 5, cfg)
    oracle = _oracle(params, p, cfg, 5)

    async def body():
        engine = InferenceEngine(params, cfg, batcher=sb)
        server = InferenceServer(engine, host="127.0.0.1", port=0)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"http://127.0.0.1:{server.bound_port}/v1/generate",
                    json={"prompt": p, "max_new": 5},
                ) as r:
                    assert r.status == 200
                    assert (await r.json())["tokens"] == oracle
                # gamma reservation propagates through validate()
                async with session.post(
                    f"http://127.0.0.1:{server.bound_port}/v1/generate",
                    json={"prompt": list(range(1, 56)), "max_new": 8},
                ) as r:
                    assert r.status == 422  # 55+8+3 > 64
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=300))


def test_sampled_spec_selfdraft_full_acceptance(setup):
    """Sampled mode, draft == target: q == p at every position, so
    min(1, p/q) = 1 accepts every proposal and rounds emit gamma tokens
    — a deterministic property of the rejection rule (the distributional
    exactness of _accept_round is statistically pinned in
    tests/test_speculative.py)."""
    cfg, params, _, _ = setup
    sb = SpeculativeBatcher(
        params, cfg, params, cfg,
        n_slots=1, max_len=64, gamma=4, chunked_prefill=8,
        sampler=Sampler(temperature=0.8, top_k=50),
    )
    p = _prompt(440, 6, cfg)
    rid = sb.submit(p, max_new=9)
    steps = 0
    while sb.pending or sb.running or sb.prefilling:
        sb.step()
        steps += 1
    out = sb.done[rid]
    assert len(out) == 9
    assert all(0 <= t < cfg.vocab_size for t in out)
    # 1 prefill step + 2 full-acceptance rounds (8 tokens) covers the
    # budget; slack for the retirement step
    assert steps <= 5, steps


def test_sampled_spec_streams_complete_with_small_draft(setup):
    """Sampled mode with a genuinely different draft: all requests finish
    with full budgets, tokens in range, logprobs aligned."""
    cfg, params, draft_cfg, draft_params = setup
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=2, max_len=64, gamma=3, chunked_prefill=4,
        sampler=Sampler(temperature=0.9, top_p=0.9),
    )
    rids = [sb.submit(_prompt(450 + i, 4 + i, cfg), max_new=6)
            for i in range(3)]
    results = sb.run()
    for rid in rids:
        assert len(results[rid]) == 6
        assert all(0 <= t < cfg.vocab_size for t in results[rid])
        req = sb.done_requests[rid]
        assert len(req.out_logp) == 6
