"""Fused lm_head+cross-entropy (ops/fused_ce.py) vs the unfused reference."""

import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.train import cross_entropy, loss_fn
from k8s_gpu_device_plugin_tpu.ops.fused_ce import (
    _pad_chunks,
    fused_linear_cross_entropy,
)


def _ref_loss(x, w, targets):
    logits = jnp.dot(
        x, w, preferred_element_type=jnp.float32
    )
    return cross_entropy(logits, targets, with_accuracy=False)[0]


def test_pad_chunks_fixed_size():
    # chunk stays FIXED; awkward vocabs pad the tail instead of shrinking
    assert _pad_chunks(32000, 4096) == (8, 8 * 4096)
    assert _pad_chunks(4096, 4096) == (1, 4096)
    assert _pad_chunks(50257, 4096) == (13, 13 * 4096)  # GPT-2: 13 steps, not 1733
    assert _pad_chunks(7, 4096) == (1, 7)


@pytest.mark.parametrize("vocab,chunk", [(512, 128), (500, 128), (512, 512)])
def test_fused_matches_reference_loss_and_grads(vocab, chunk):
    kx, kw, kt = jax.random.split(jax.random.key(0), 3)
    b, s, d = 2, 16, 64
    x = jax.random.normal(kx, (b, s, d), jnp.bfloat16)
    w = jax.random.normal(kw, (d, vocab), jnp.bfloat16) * 0.1
    t = jax.random.randint(kt, (b, s), 0, vocab, jnp.int32)

    loss_f = fused_linear_cross_entropy(x, w, t, chunk=chunk)
    loss_r = _ref_loss(x, w, t)
    assert jnp.allclose(loss_f, loss_r, atol=2e-3, rtol=2e-3)

    gf = jax.grad(lambda x, w: fused_linear_cross_entropy(x, w, t, chunk=chunk),
                  argnums=(0, 1))(x, w)
    gr = jax.grad(lambda x, w: _ref_loss(x, w, t), argnums=(0, 1))(x, w)
    for f, r in zip(gf, gr):
        f32, r32 = f.astype(jnp.float32), r.astype(jnp.float32)
        denom = jnp.linalg.norm(r32) + 1e-12
        assert float(jnp.linalg.norm(f32 - r32) / denom) < 0.05


def test_loss_fn_fused_path_matches_unfused():
    from k8s_gpu_device_plugin_tpu.models.llama import init_params

    cfg = LlamaConfig.tiny(n_layers=2)
    cfg_f = LlamaConfig.tiny(n_layers=2, fused_ce=True)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 65), 0, cfg.vocab_size,
                                jnp.int32)
    batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}

    l_ref, m_ref = loss_fn(params, batch, cfg, None, with_accuracy=False)
    l_fused, m_fused = loss_fn(params, batch, cfg_f, None, with_accuracy=False)
    assert jnp.allclose(l_ref, l_fused, atol=2e-3, rtol=2e-3)
    assert float(m_fused["accuracy"]) == -1.0

    # with_accuracy=True forces the unfused fallback (fused has no logits)
    l_acc, m_acc = loss_fn(params, batch, cfg_f, None, with_accuracy=True)
    assert float(m_acc["accuracy"]) >= 0.0


def test_fused_matches_unfused_on_sp_mesh():
    """Fused path under a real sp-sharded mesh (the reshape folding the
    sharded S axis into tokens must stay representable, no silent gather
    of the vocab axis since tp == 1)."""
    import numpy as np

    from k8s_gpu_device_plugin_tpu.models.llama import init_params
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = LlamaConfig.tiny(n_layers=2, attn_impl="ring")
    cfg_f = LlamaConfig.tiny(n_layers=2, attn_impl="ring", fused_ce=True)
    mesh = make_mesh(MeshSpec.for_devices(4, sp=2), jax.devices()[:4])
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(
        jax.random.key(1), (4, 65), 0, cfg.vocab_size, jnp.int32
    )
    batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}

    l_ref, _ = jax.jit(
        lambda p, b: loss_fn(p, b, cfg, mesh, with_accuracy=False)
    )(params, batch)
    l_fused, _ = jax.jit(
        lambda p, b: loss_fn(p, b, cfg_f, mesh, with_accuracy=False)
    )(params, batch)
    assert np.isclose(float(l_ref), float(l_fused), atol=2e-3, rtol=2e-3)


def test_fused_ce_with_moe_aux_losses():
    """MoE + fused CE: aux losses still ride out of the hidden-state path."""
    from k8s_gpu_device_plugin_tpu.models.llama import init_params

    cfg = LlamaConfig.tiny(n_layers=2, n_experts=4, fused_ce=True)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(
        jax.random.key(1), (2, 33), 0, cfg.vocab_size, jnp.int32
    )
    batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
    total, metrics = loss_fn(params, batch, cfg, None, with_accuracy=False)
    assert "moe_load_balance" in metrics and "moe_router_z" in metrics
    assert float(total) > float(metrics["loss"]) - 1e-6  # aux terms added
