"""Beam search: greedy equivalence, score exactness, exhaustive oracle."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.beam import beam_search
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
)


def _setup(vocab=16):
    cfg = LlamaConfig.tiny(
        n_layers=2, vocab_size=vocab, dtype=jnp.float32
    )
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
    return cfg, params, prompt


def _seq_logprob(params, prompt, cfg, seq):
    """Exact cumulative log-probability of ``seq`` after ``prompt`` via the
    full-context forward (the oracle for beam scores)."""
    tokens = jnp.concatenate([prompt, seq[None, :]], axis=1)
    logits = forward(params, tokens, cfg).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = prompt.shape[1]
    total = 0.0
    for j in range(seq.shape[0]):
        total += float(logp[0, p - 1 + j, int(seq[j])])
    return total


def test_beam_one_is_greedy():
    cfg, params, prompt = _setup()
    seqs, scores = beam_search(params, prompt, cfg, max_new=6, beam=1)
    ref = generate(params, prompt, cfg, max_new=6)
    np.testing.assert_array_equal(np.asarray(seqs), np.asarray(ref))


def test_beam_scores_are_exact_logprobs():
    cfg, params, prompt = _setup()
    seqs, scores = beam_search(params, prompt, cfg, max_new=4, beam=3)
    for r in range(3):
        expected = _seq_logprob(params, prompt, cfg, seqs[r])
        np.testing.assert_allclose(float(scores[r]), expected, atol=1e-4)
    # sorted descending
    s = np.asarray(scores)
    assert (s[:-1] >= s[1:] - 1e-7).all()


def test_beam_at_vocab_width_is_exhaustive_for_two_steps():
    """beam == vocab keeps every length-1 prefix, so for max_new=2 the
    search is exact: its best sequence must match brute-force enumeration
    of all vocab^2 continuations."""
    cfg, params, prompt = _setup(vocab=12)
    seqs, scores = beam_search(params, prompt, cfg, max_new=2, beam=12)
    # brute force, one batched forward over all 144 continuations
    pairs = jnp.asarray(
        list(itertools.product(range(12), range(12))), jnp.int32
    )                                                        # (144, 2)
    p = prompt.shape[1]
    tokens = jnp.concatenate(
        [jnp.broadcast_to(prompt, (144, p)), pairs], axis=1
    )
    logp = jax.nn.log_softmax(
        forward(params, tokens, cfg).astype(jnp.float32), axis=-1
    )
    lps = np.asarray(
        jnp.take_along_axis(
            logp[:, p - 1], pairs[:, 0:1], axis=1
        )[:, 0]
        + jnp.take_along_axis(logp[:, p], pairs[:, 1:2], axis=1)[:, 0]
    )
    best = int(np.argmax(lps))
    assert tuple(np.asarray(seqs[0]).tolist()) == tuple(
        np.asarray(pairs[best]).tolist()
    )
    np.testing.assert_allclose(float(scores[0]), lps[best], atol=1e-4)


def test_beam_beats_or_matches_greedy():
    cfg, params, prompt = _setup()
    _, scores = beam_search(params, prompt, cfg, max_new=5, beam=4)
    greedy = generate(params, prompt, cfg, max_new=5)
    greedy_lp = _seq_logprob(params, prompt, cfg, greedy[0])
    assert float(scores[0]) >= greedy_lp - 1e-5


def test_beam_validation():
    cfg, params, prompt = _setup()
    with pytest.raises(ValueError, match="beam"):
        beam_search(params, prompt, cfg, max_new=2, beam=0)
    with pytest.raises(NotImplementedError, match="one prompt"):
        beam_search(
            params, jnp.zeros((2, 4), jnp.int32), cfg, max_new=2, beam=2
        )


def test_beam_exceeding_vocab_rejected():
    cfg, params, prompt = _setup(vocab=16)
    with pytest.raises(ValueError, match="vocab_size"):
        beam_search(params, prompt, cfg, max_new=2, beam=17)


def test_beam_one_is_greedy_moe():
    """Beam rides _forward_cached, so MoE configs work unchanged."""
    cfg = LlamaConfig.tiny(
        n_layers=1, n_experts=4, capacity_factor=8.0, dtype=jnp.float32
    )
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
    seqs, _ = beam_search(params, prompt, cfg, max_new=4, beam=1)
    ref = generate(params, prompt, cfg, max_new=4)
    np.testing.assert_array_equal(np.asarray(seqs), np.asarray(ref))


def test_beam_with_tp_sharded_params():
    from k8s_gpu_device_plugin_tpu.models.llama import param_shardings
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg, params, prompt = _setup()
    ref_seqs, ref_scores = beam_search(params, prompt, cfg, max_new=4, beam=3)
    mesh = make_mesh(MeshSpec(dp=1, tp=4), jax.devices()[:4])
    sharded = jax.device_put(params, param_shardings(cfg, mesh))
    seqs, scores = beam_search(sharded, prompt, cfg, max_new=4, beam=3)
    np.testing.assert_array_equal(np.asarray(seqs), np.asarray(ref_seqs))
    np.testing.assert_allclose(
        np.asarray(scores), np.asarray(ref_scores), atol=1e-4
    )
