"""Config tier tests (≙ main.go:37-52 defaults <- yaml <- flags)."""

import pytest

from k8s_gpu_device_plugin_tpu.config import Config, load_config


def test_defaults(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = load_config([])
    assert cfg.web_listen_address == "9002"
    assert cfg.slice_strategy == "none"
    assert cfg.benchmark is False
    assert cfg.log.level == "debug"


def test_yaml_tier(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "config.yml").write_text(
        """
webListenAddress: "127.0.0.1:9100"
sliceStrategy: mixed
slicePlan: "2x2,2x2"
benchmark: true
log:
  level: info
  fileDir: /tmp/logs
"""
    )
    cfg = load_config([])
    assert cfg.web_listen_address == "127.0.0.1:9100"
    assert cfg.slice_strategy == "mixed"
    assert cfg.slice_plan == "2x2,2x2"
    assert cfg.benchmark is True
    assert cfg.log.level == "info"
    assert cfg.log.file_dir == "/tmp/logs"


def test_flags_override_yaml(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "config.yml").write_text("sliceStrategy: mixed\n")
    cfg = load_config(["--sliceStrategy", "single", "--sliceShape", "2x2"])
    assert cfg.slice_strategy == "single"
    assert cfg.slice_shape == "2x2"


def test_mig_strategy_alias(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "config.yml").write_text("migStrategy: single\n")
    assert load_config([]).slice_strategy == "single"


def test_invalid_strategy_rejected(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "config.yml").write_text("sliceStrategy: bogus\n")
    with pytest.raises(ValueError):
        load_config([])


def test_listen_addr_forms():
    cfg = Config()
    assert cfg.listen_addr == ("0.0.0.0", 9002)
    cfg.web_listen_address = "127.0.0.1:8080"
    assert cfg.listen_addr == ("127.0.0.1", 8080)


def test_log_dev_mode_plumbing(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "config.yml").write_text(
        "log:\n  level: info\n  devMode: true\n"
    )
    cfg = load_config([])
    assert cfg.log.dev_mode is True
    # flag overrides the file default (three-tier contract), BOTH directions
    cfg = load_config(["--logDevMode", "false"])
    assert cfg.log.dev_mode is False
    (tmp_path / "config.yml").write_text("log:\n  level: info\n")
    cfg = load_config([])
    assert cfg.log.dev_mode is False
    cfg = load_config(["--logDevMode"])
    assert cfg.log.dev_mode is True
