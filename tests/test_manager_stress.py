"""Concurrency stress of the manager's restart/health/kubelet-flap loops.

SURVEY §5 race-detection row: the reference shipped two data races (busy-poll
restart flag, health slice mutation) that Go's -race would have caught. The
rebuild replaced them with asyncio events owned by one loop; this stress
hammers every concurrent seam at once — rapid health flips, overlapping
restart requests, kubelet socket churn — and asserts the stack converges to a
registered, healthy steady state with no deadlock and no leaked tasks.
"""

import asyncio

import pytest

from k8s_gpu_device_plugin_tpu.plugin import api
from k8s_gpu_device_plugin_tpu.plugin.api import pb

from test_plugin_integration import start_stack, stop_stack


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout=timeout))


def test_restart_health_kubelet_flap_stress(tmp_path):
    async def body():
        kubelet, manager, task, backend = await start_stack(
            tmp_path, topology="v5e-8"
        )
        try:
            await kubelet.wait_for_registrations(1)

            # The crash-loop budget is 5 starts/hour/resource BY DESIGN
            # (manager-side guard, ≙ plugin.go:111 numbers), so the storm
            # stays within it: 1 initial + <=3 coalesced restart cycles.
            # Pressure comes from concurrency, not volume: health flips
            # hammer the ListAndWatch push path while restarts tear the
            # plugin down and the kubelet socket churns underneath.
            async def health_flapper():
                for i in range(120):
                    backend.set_unhealthy(i % 8)
                    await asyncio.sleep(0.005)
                    backend.set_healthy(i % 8)
                    await asyncio.sleep(0.005)

            async def restart_spammer():
                # bursts coalesce via the restart event (one cycle per burst)
                for _ in range(2):
                    for _ in range(10):
                        manager.restart()
                    await asyncio.sleep(0.5)

            async def kubelet_flapper():
                await asyncio.sleep(0.25)
                await kubelet.stop()
                await asyncio.sleep(0.05)
                await kubelet.start()

            await asyncio.gather(
                health_flapper(), restart_spammer(), kubelet_flapper()
            )

            # convergence: every restart trigger produced a re-registration
            # (initial + >=1 per coalesced burst/flap; exact count depends
            # on coalescing, but the last cycle must complete)
            await kubelet.wait_for_registrations(3, timeout=35)
            backend.set_healthy(*range(8))
            await asyncio.sleep(1.0)  # let any in-flight cycle settle

            # ...and the re-registered plugin serves a fully healthy list
            reg = kubelet.registrations[-1]
            for _ in range(3):  # endpoint may still be re-binding mid-restart
                try:
                    async with kubelet.plugin_channel(reg.endpoint) as channel:
                        stub = api.DevicePluginStub(channel)
                        stream = stub.ListAndWatch(pb.Empty())
                        resp = await asyncio.wait_for(stream.read(), 10)
                    break
                except Exception:  # noqa: BLE001 - retry against re-binds
                    await asyncio.sleep(0.5)
                    reg = kubelet.registrations[-1]
            else:
                pytest.fail("plugin endpoint never served after the storm")
            assert len(resp.devices) == 8
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_concurrent_restarts_collapse_to_one(tmp_path):
    """N overlapping restart() calls must coalesce (event semantics), not
    queue N teardown/re-register cycles."""

    async def body():
        kubelet, manager, task, _ = await start_stack(tmp_path)
        try:
            await kubelet.wait_for_registrations(1)
            for _ in range(25):
                manager.restart()  # no await between: all within one loop tick
            await kubelet.wait_for_registrations(2, timeout=20)
            await asyncio.sleep(1.5)  # give any spurious extra cycles time
            assert len(kubelet.registrations) <= 4
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())
