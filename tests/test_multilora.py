"""Multi-LoRA serving (models/lora_serving.py): many adapters behind one
continuous batcher. The oracle is the training-side ``merge_lora`` — for
every request, serving through the stacked per-row-delta path must match
dedicated ``generate`` on that adapter's MERGED weights. f32 configs make
the two computation orders numerically tight."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import (
    ContinuousBatcher,
    precompute_prefix,
)
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.lora import (
    LoraConfig,
    init_lora_params,
    merge_lora,
)
from k8s_gpu_device_plugin_tpu.models.lora_serving import (
    AdapterSet,
    attach_adapters,
    lora_delta,
    one_hot_sel,
    stack_adapters,
)


def _rand_b(lp, seed):
    """Training inits B to zeros (step-0 = base); tests need nonzero
    deltas, so randomize B."""
    out = {}
    for i, (t, ab) in enumerate(sorted(lp.items())):
        k = jax.random.fold_in(jax.random.key(seed), i)
        out[t] = {
            "a": ab["a"],
            "b": 0.3 * jax.random.normal(k, ab["b"].shape, ab["b"].dtype),
        }
    return out


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    lc1 = LoraConfig(rank=4, alpha=8.0, targets=("wq", "wo", "w2"))
    lc2 = LoraConfig(rank=8, alpha=16.0)  # attn targets, different rank
    lp1 = _rand_b(init_lora_params(jax.random.key(1), cfg, lc1), 10)
    lp2 = _rand_b(init_lora_params(jax.random.key(2), cfg, lc2), 11)
    aset = stack_adapters(cfg, [("alpha", lp1, lc1), ("beta", lp2, lc2)])
    merged = {
        -1: params,
        0: merge_lora(params, lp1, lc1),
        1: merge_lora(params, lp2, lc2),
    }
    return cfg, params, aset, merged


def _oracle(merged, prompt, cfg, max_new):
    out = generate(merged, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def test_mixed_adapters_one_batch_match_merged_oracles(setup):
    """Base + two different-rank adapters decoding TOGETHER, each request
    token-identical to generate() on its own merged weights."""
    cfg, params, aset, merged = setup
    cb = ContinuousBatcher(params, cfg, n_slots=3, max_len=64,
                           chunked_prefill=8, adapters=aset)
    want = {}
    rids = {}
    for adapter, seed in ((-1, 50), (0, 51), (1, 52)):
        prompt = _prompt(seed, 6, cfg)
        rids[adapter] = cb.submit(prompt, max_new=8, adapter=adapter)
        want[adapter] = _oracle(merged[adapter], prompt, cfg, 8)
    done = cb.run()
    for adapter, rid in rids.items():
        assert done[rid] == want[adapter], f"adapter {adapter}"


def test_bucketed_prefill_path_and_reuse(setup):
    """The non-chunked (bucketed prefill_insert) path serves adapters
    too, and a slot reused across different adapters stays exact."""
    cfg, params, aset, merged = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           prompt_buckets=(8, 16), adapters=aset)
    for adapter, seed in ((0, 60), (-1, 61), (1, 62)):  # serial reuse
        prompt = _prompt(seed, 5, cfg)
        rid = cb.submit(prompt, max_new=6, adapter=adapter)
        done = cb.run()
        assert done[rid] == _oracle(merged[adapter], prompt, cfg, 6), adapter


def test_adapter_prefix_compatibility(setup):
    """Prefix rows depend on the weights that prefilled them: a matching
    (adapter, prefix) pair serves exactly; a mismatch is rejected."""
    cfg, params, aset, merged = setup
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           chunked_prefill=8, adapters=aset)
    sys_prompt = _prompt(70, 9, cfg)
    suffix = _prompt(71, 4, cfg)
    # prefix prefilled UNDER adapter 0: the batcher method gathers the
    # adapter into the compact stacks and remaps sel (under gathered
    # serving cb.params' stack POSITION differs from the registry index,
    # so the module-level call would prefill the wrong rows)
    prefix = cb.precompute_shared_prefix(sys_prompt, adapter=0)
    rid = cb.submit(suffix, max_new=6, prefix=prefix, adapter=0)
    done = cb.run()
    assert done[rid] == _oracle(merged[0], sys_prompt + suffix, cfg, 6)

    with pytest.raises(ValueError, match="prefix was prefilled"):
        cb.submit(suffix, max_new=6, prefix=prefix, adapter=1)
    with pytest.raises(ValueError, match="prefix was prefilled"):
        cb.submit(suffix, max_new=6, prefix=prefix)  # base vs adapter-0


def test_submit_rejects_base_prefix_for_adapter_request(setup):
    """The remaining direction of the submit()-side weights guard: rows
    prefilled with the BASE model (adapter=-1, plain params) must not
    serve an adapter request. (The adapter->other-adapter and
    adapter->base directions are pinned above; the base pairing's
    serving exactness is pinned by test_batching's shared-prefix tests;
    precompute-side argument guards further below.)"""
    cfg, params, aset, merged = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           chunked_prefill=8, adapters=aset)
    base_prefix = precompute_prefix(params, _prompt(75, 9, cfg), cfg)
    suffix = _prompt(76, 4, cfg)
    for adapter in (0, 1):
        with pytest.raises(ValueError, match="prefix was prefilled"):
            cb.submit(suffix, max_new=5, prefix=base_prefix,
                      adapter=adapter)
    # the base pairing passes the guard (no dispatch: just queued)
    assert cb.submit(suffix, max_new=5, prefix=base_prefix) >= 0
    cb.pending.clear()


def test_adapter_validation(setup):
    cfg, params, aset, _ = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                           chunked_prefill=8, adapters=aset)
    with pytest.raises(ValueError, match="out of range"):
        cb.submit([1, 2], max_new=2, adapter=2)
    plain = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                              chunked_prefill=8)
    with pytest.raises(ValueError, match="out of range"):
        plain.submit([1, 2], max_new=2, adapter=0)


def test_stack_adapters_validation(setup):
    cfg, params, aset, _ = setup
    lc = LoraConfig(rank=2)
    lp = init_lora_params(jax.random.key(9), cfg, lc)
    with pytest.raises(ValueError, match="duplicate"):
        stack_adapters(cfg, [("x", lp, lc), ("x", lp, lc)])
    with pytest.raises(ValueError, match="at least one"):
        stack_adapters(cfg, [])
    assert aset.index_of("beta") == 1
    with pytest.raises(KeyError, match="unknown adapter"):
        aset.index_of("nope")
    with pytest.raises(ValueError, match=">= n_adapters"):
        one_hot_sel(5, 2)


def test_lora_delta_zero_sel_is_zero(setup):
    """All-zeros selection (a base-model row) contributes exactly 0."""
    cfg, params, aset, _ = setup
    h = jax.random.normal(jax.random.key(1), (2, 3, cfg.d_model), jnp.float32)
    a = aset.leaves["lora_wq_a"][0]
    b = aset.leaves["lora_wq_b"][0]
    sel = jnp.zeros((2, aset.n), jnp.float32)
    assert np.all(np.asarray(lora_delta(h, a, b, sel)) == 0.0)


def test_http_both_apis_route_adapters(setup):
    """End-to-end over HTTP: the native 'adapter' field and the OpenAI
    'model' field reach the same stacks; each response matches the
    merged-weights oracle; unknown names answer 400 (native) / 404
    (OpenAI, model_not_found)."""
    import asyncio

    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        InferenceServer,
    )

    cfg, params, aset, merged = setup

    async def body():
        engine = InferenceEngine(
            params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
            adapters=aset,
        )
        server = InferenceServer(engine, host="127.0.0.1", port=0)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            import aiohttp

            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as s:
                prompt = _prompt(80, 5, cfg)
                # native API, adapter by name
                r = await s.post(f"{base}/v1/generate", json={
                    "prompt": prompt, "max_new": 6, "adapter": "beta",
                })
                assert r.status == 200, await r.text()
                toks = (await r.json())["tokens"]
                assert toks == _oracle(merged[1], prompt, cfg, 6)

                # OpenAI API, adapter via the model field
                r = await s.post(f"{base}/v1/completions", json={
                    "model": "alpha", "prompt": prompt, "max_tokens": 6,
                })
                assert r.status == 200, await r.text()
                p = await r.json()
                assert p["model"] == "alpha"
                assert p["usage"]["completion_tokens"] == 6

                # base model still routes (default + explicit id)
                r = await s.post(f"{base}/v1/completions", json={
                    "prompt": prompt, "max_tokens": 4,
                })
                assert r.status == 200

                # /v1/models lists base + adapters
                r = await s.get(f"{base}/v1/models")
                ids = [m["id"] for m in (await r.json())["data"]]
                assert ids == ["tpu-serving", "alpha", "beta"]

                # unknown names
                r = await s.post(f"{base}/v1/generate", json={
                    "prompt": prompt, "max_new": 4, "adapter": "nope",
                })
                assert r.status == 400
                assert "unknown adapter" in (await r.json())["error"]
                r = await s.post(f"{base}/v1/completions", json={
                    "model": "nope", "prompt": prompt, "max_tokens": 4,
                })
                assert r.status == 404
                assert (await r.json())["error"]["code"] == "model_not_found"
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=300))


def test_speculative_engine_rejects_adapters(setup):
    """A batcher serving no adapters (the speculative engine never gets
    stacks) rejects adapter submits at validation, not mid-loop."""
    import asyncio

    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params, _, _ = setup

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        try:
            with pytest.raises(ValueError, match="out of range"):
                engine.submit(_prompt(90, 4, cfg), 4, adapter=0)
        finally:
            engine.shutdown()

    asyncio.run(asyncio.wait_for(body(), timeout=120))


def test_guards_from_review(setup):
    """The silent-wrong-output guards: speculative batchers refuse
    stacks; precompute_prefix refuses an adapter without its count;
    engine refuses adapters alongside an injected batcher."""
    import asyncio

    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params, aset, _ = setup
    with pytest.raises(ValueError, match="does not support LoRA"):
        SpeculativeBatcher(params, cfg, params, cfg, n_slots=1, max_len=32,
                           chunked_prefill=8, adapters=aset)
    with pytest.raises(ValueError, match="needs n_adapters"):
        precompute_prefix(params, [1, 2, 3], cfg, adapter=0)

    async def body():
        cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                               chunked_prefill=8)
        with pytest.raises(ValueError, match="injected batcher"):
            InferenceEngine(params, cfg, batcher=cb, adapters=aset)

    asyncio.run(asyncio.wait_for(body(), timeout=60))


def test_precompute_prefix_requires_stacked_params(setup):
    """Passing the BASE tree (no stacked leaves) with an adapter would
    prefill base rows tagged with the adapter — rejected loudly."""
    cfg, params, aset, _ = setup
    with pytest.raises(ValueError, match="no stacked LoRA leaves"):
        precompute_prefix(params, [1, 2, 3], cfg, adapter=0,
                          n_adapters=aset.n)


def test_lora_decode_bench_machinery(setup):
    """The hardware workload's plumbing on CPU with a tiny config: both
    arms run, report positive step times, and a finite overhead."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.decode_bench import (
        lora_decode_bench,
    )

    cfg, _, _, _ = setup
    r = lora_decode_bench(cfg, batch=2, ctx_len=16, steps=3,
                          n_adapters=2, rank=4, repeats=1)
    assert r.base_step_ms > 0 and r.lora_step_ms > 0
    assert np.isfinite(r.overhead_pct)
    assert r.n_adapters == 2 and r.batch == 2


def test_all_per_request_features_compose_in_one_batch(setup):
    """One batch mixing every per-request dial: a base-model row with a
    +100 forced token, an adapter row greedy (oracle-pinned against its
    merged weights), and an adapter row with a per-request sampler —
    all sharing one compiled decode step."""
    from k8s_gpu_device_plugin_tpu.models.sampling import Sampler

    cfg, params, aset, merged = setup
    cb = ContinuousBatcher(params, cfg, n_slots=3, max_len=64,
                           chunked_prefill=8, adapters=aset)
    p1, p2, p3 = (_prompt(s, 6, cfg) for s in (200, 201, 202))
    r_forced = cb.submit(p1, max_new=4, logit_bias={42: 100.0})
    r_adapter = cb.submit(p2, max_new=6, adapter=1)
    r_both = cb.submit(p3, max_new=5, adapter=0,
                       sampler=Sampler(temperature=0.8, top_k=20))
    done = cb.run()
    assert done[r_forced] == [42] * 4
    assert done[r_adapter] == _oracle(merged[1], p2, cfg, 6)
    out = done[r_both]
    assert len(out) == 5 and all(0 <= t < cfg.vocab_size for t in out)


def test_adapters_compose_with_quantized_cache(setup):
    """Multi-LoRA + int8 KV cache: the adapter deltas touch projections,
    the cache quantization touches storage — a batcher running both
    matches generate() on merged weights with the same quantized cache."""
    from dataclasses import replace

    cfg, params, aset, merged = setup
    qcfg = replace(cfg, cache_quant="int8")
    cb = ContinuousBatcher(params, qcfg, n_slots=2, max_len=64,
                           chunked_prefill=8, adapters=aset)
    prompt = _prompt(210, 6, cfg)
    rid = cb.submit(prompt, max_new=6, adapter=1)
    done = cb.run()
    assert done[rid] == _oracle(merged[1], prompt, qcfg, 6)


def test_load_adapters_rejects_moe_mlp_targets(tmp_path):
    """An externally-produced adapter carrying w1/w2/w3 factors must be
    REJECTED on an MoE config at load time — the MoE decode path never
    reads mlp adapter leaves, so accepting it would silently serve a
    partially-applied adapter (advisor r4)."""
    from k8s_gpu_device_plugin_tpu.models.checkpoint import TrainCheckpointer
    from k8s_gpu_device_plugin_tpu.serving.server import load_adapters

    dense = LlamaConfig.tiny(dtype=jnp.float32)
    lc = LoraConfig(rank=2, targets=("wq", "w1"))
    lp = init_lora_params(jax.random.key(3), dense, lc)
    d = str(tmp_path / "adapter")
    ckpt = TrainCheckpointer(d, async_save=False, save_interval=1)
    try:
        ckpt.save({"lora": lp}, step=0, force=True)
    finally:
        ckpt.close()

    moe = LlamaConfig.tiny(
        dtype=jnp.float32, n_experts=4, n_experts_per_token=2,
        capacity_factor=4.0,
    )
    with pytest.raises(ValueError, match="MoE expert MLPs"):
        load_adapters(moe, f"bad={d}")
    # the same checkpoint loads fine on the dense config it was made for
    aset = load_adapters(dense, f"good={d}")
    assert aset.index_of("good") == 0
