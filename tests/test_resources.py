"""Resource naming tests (≙ resource/resource.go:32-66 table tests)."""

import pytest

from k8s_gpu_device_plugin_tpu.device.topology import parse_topology
from k8s_gpu_device_plugin_tpu.resource.naming import (
    Resource,
    ResourceName,
    ResourcePattern,
)
from k8s_gpu_device_plugin_tpu.resource.resources import discover_resources


def test_auto_prefix():
    r = Resource.new("*", "tpu")
    assert str(r.name) == "google.com/tpu"


def test_explicit_prefix_preserved():
    r = Resource.new("*", "example.com/accel")
    assert str(r.name) == "example.com/accel"


def test_name_split():
    prefix, base = ResourceName("google.com/tpu").split_name()
    assert (prefix, base) == ("google.com", "tpu")


def test_shared_suffix():
    n = ResourceName("google.com/tpu")
    assert not n.is_shared
    s = n.shared()
    assert str(s) == "google.com/tpu.shared"
    assert s.is_shared
    assert s.shared() == s


def test_name_length_limit():
    with pytest.raises(ValueError, match="exceeds"):
        Resource.new("*", "x" * 64)


def test_pattern_wildcards():
    assert ResourcePattern("*").matches("v5e")
    assert ResourcePattern("v5*").matches("v5p")
    assert not ResourcePattern("v5*").matches("v4")
    assert ResourcePattern("2x2").matches("2x2")
    assert not ResourcePattern("2x2").matches("2x2x1")


def test_discover_none_single():
    for strategy in ("none", "single"):
        (r,) = discover_resources(strategy)
        assert str(r.name) == "google.com/tpu"


def test_discover_mixed_from_plan():
    resources = discover_resources("mixed", slice_plan="2x2,1x2,1x2")
    names = [str(r.name) for r in resources]
    assert names == ["google.com/tpu-slice-2x2", "google.com/tpu-slice-1x2"]


def test_discover_mixed_default_plan():
    topo = parse_topology("v5e-8")
    (r,) = discover_resources("mixed", topo)
    assert str(r.name) == "google.com/tpu-slice-2x2"


def test_discover_mixed_requires_topology_or_plan():
    with pytest.raises(ValueError):
        discover_resources("mixed")
