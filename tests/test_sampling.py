"""Samplers: greedy/temperature/top-k/top-p semantics and generate wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.sampling import (
    Sampler,
    _apply_top_k,
    _apply_top_p,
    sample_logits,
)


def test_greedy_is_argmax():
    logits = jnp.array([[0.1, 3.0, -1.0], [5.0, 0.0, 4.9]])
    toks = sample_logits(logits, jax.random.key(0), Sampler())
    np.testing.assert_array_equal(np.asarray(toks), [1, 0])


def test_top_k_masks_all_but_k():
    logits = jnp.array([[1.0, 5.0, 3.0, 2.0]])
    masked = _apply_top_k(logits, 2)
    # tokens 1 (5.0) and 2 (3.0) survive; the rest are -inf-ish
    assert np.asarray(masked[0, 1]) == 5.0
    assert np.asarray(masked[0, 2]) == 3.0
    assert np.asarray(masked[0, 0]) < -1e29
    assert np.asarray(masked[0, 3]) < -1e29


def test_top_k_sampling_never_leaves_the_set():
    logits = jnp.tile(jnp.array([[0.0, 10.0, 9.0, 8.0]]), (64, 1))
    keys = jax.random.split(jax.random.key(1), 64)
    toks = jax.vmap(
        lambda l, k: sample_logits(l[None], k, Sampler(temperature=5.0, top_k=2))
    )(logits, keys)
    assert set(np.asarray(toks).ravel().tolist()) <= {1, 2}


def test_top_p_keeps_threshold_crosser():
    # probs ~ [0.97, 0.02, ...]: top_p=0.5 must keep exactly the top token
    logits = jnp.array([[10.0, 6.0, 1.0, 0.0]])
    masked = _apply_top_p(logits, 0.5)
    assert np.asarray(masked[0, 0]) == 10.0
    assert np.asarray(masked[0, 1]) < -1e29
    # top_p just over the top token's mass keeps the second as well
    masked2 = _apply_top_p(logits, 0.99)
    assert np.asarray(masked2[0, 1]) == 6.0


def test_top_p_never_empty():
    """Even tiny p keeps the single highest-probability token."""
    logits = jnp.array([[2.0, 1.0, 0.0]])
    masked = _apply_top_p(logits, 1e-6)
    toks = sample_logits(
        logits, jax.random.key(0), Sampler(temperature=1.0, top_p=1e-6)
    )
    assert np.asarray(masked[0, 0]) == 2.0
    np.testing.assert_array_equal(np.asarray(toks), [0])


def test_sampler_validation():
    with pytest.raises(ValueError):
        Sampler(temperature=-1.0)
    with pytest.raises(ValueError):
        Sampler(top_k=-1)
    with pytest.raises(ValueError):
        Sampler(top_p=0.0)
    with pytest.raises(ValueError):
        Sampler(top_p=1.5)


def test_generate_accepts_sampler():
    from k8s_gpu_device_plugin_tpu.models.generate import generate
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny(n_layers=1)
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.zeros((2, 8), jnp.int32)
    toks = generate(
        params, prompt, cfg, max_new=4, key=jax.random.key(3),
        sampler=Sampler(temperature=0.8, top_k=50, top_p=0.9),
    )
    assert toks.shape == (2, 4)
    assert toks.dtype == jnp.int32
    # greedy via sampler matches greedy via temperature=0 shorthand
    g1 = generate(params, prompt, cfg, max_new=4)
    g2 = generate(params, prompt, cfg, max_new=4, sampler=Sampler())
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_generate_rejects_temperature_and_sampler():
    from k8s_gpu_device_plugin_tpu.models.generate import generate
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny(n_layers=1)
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="inside the Sampler"):
        generate(
            params, prompt, cfg, max_new=2,
            temperature=0.8, sampler=Sampler(top_k=50),
        )


def test_repetition_penalty_rule():
    from k8s_gpu_device_plugin_tpu.models.sampling import (
        apply_repetition_penalty,
    )

    logits = jnp.array([[2.0, -1.0, 0.5, 3.0]])
    presence = jnp.array([[True, True, False, False]])
    out = np.asarray(apply_repetition_penalty(logits, presence, 2.0))
    np.testing.assert_allclose(out, [[1.0, -2.0, 0.5, 3.0]])


def test_repetition_penalty_needs_presence():
    logits = jnp.zeros((1, 8))
    with pytest.raises(ValueError, match="presence"):
        sample_logits(
            logits, jax.random.key(0), Sampler(repetition_penalty=1.5)
        )
    with pytest.raises(ValueError, match="repetition_penalty"):
        Sampler(repetition_penalty=0.5)


def test_repetition_penalty_breaks_greedy_loops():
    """A model stuck repeating one token under greedy decoding must break
    the loop under a strong penalty; without the penalty the loop persists
    (this random tiny model happens to cycle quickly)."""
    from k8s_gpu_device_plugin_tpu.models.generate import generate
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny(n_layers=1, vocab_size=32, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    base = np.asarray(generate(params, prompt, cfg, max_new=16))
    pen = np.asarray(
        generate(
            params, prompt, cfg, max_new=16,
            sampler=Sampler(repetition_penalty=4.0),
        )
    )
    # the penalized run must produce strictly more distinct tokens
    assert len(set(pen[0].tolist())) > len(set(base[0].tolist()))
    # and every token still in vocab
    assert (pen >= 0).all() and (pen < 32).all()


def test_repetition_penalty_rejected_in_speculative():
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
    from k8s_gpu_device_plugin_tpu.models.speculative import (
        speculative_generate,
    )

    cfg = LlamaConfig.tiny(n_layers=1, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    s = Sampler(repetition_penalty=1.5)
    with pytest.raises(NotImplementedError, match="repetition_penalty"):
        speculative_generate(
            params, cfg, params, cfg, prompt, max_new=2, sampler=s
        )


def test_repetition_penalty_in_rolling_matches_generate():
    """Greedy + penalty is deterministic, and rolling's windowed decode
    with a penalty must equal the unbounded windowed generate with the
    same penalty (presence threading is identical)."""

    from k8s_gpu_device_plugin_tpu.models.generate import generate
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
    from k8s_gpu_device_plugin_tpu.models.rolling import rolling_generate

    cfg = LlamaConfig.tiny(
        n_layers=2, vocab_size=32, sliding_window=8, dtype=jnp.float32
    )
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.arange(1, 7, dtype=jnp.int32)[None, :]
    s = Sampler(repetition_penalty=3.0)
    ref = generate(params, prompt, cfg, max_new=12, sampler=s)
    got = rolling_generate(params, prompt, cfg, max_new=12, sampler=s)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
