"""Long-context serving: streaming chunk-prefill + sliding-window KV.

The contract pinned here (models/batching.py incremental reservation,
models/paging.py recycle, serving/scheduler.py page-relief preemption,
and the structured request_too_large surface on both HTTP planes):

- **Admission past the old wall**: with ``sliding_window`` set, a
  prompt whose FULL reservation outsizes the page pool admits through
  the windowed peak bound, serves end-to-end bit-identical to the
  dedicated-generate oracle, and its peak pool footprint stays
  O(window + chunk) — not O(prompt).
- **Recycling discipline**: out-of-window pages return to the pool
  mid-stream (counted by ``pages_recycled_total``), retirement still
  drains to exactly zero, and the refcount sweep stays clean — under
  plain runs, injected pool.alloc chaos, and cancel-mid-growth.
- **Structured refusals**: ``RequestTooLargeError`` carries
  ``{prompt_tokens, max_new, limit}``, and both the native and the
  OpenAI surface serialize those fields into the 422 body.
"""

import asyncio
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import (
    ContinuousBatcher,
    RequestTooLargeError,
)
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.paging import PagePool
from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

PS = 16       # page size
W = 16        # sliding window
BUCKETS = (8, 16, 32, 64, 128)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2, sliding_window=W)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=max_new
    )
    return np.asarray(out)[0].tolist()


def _batcher(params, cfg, kv_pages, n_slots=1, max_len=128, **kw):
    return ContinuousBatcher(
        params, cfg, n_slots=n_slots, max_len=max_len,
        prompt_buckets=BUCKETS, chunked_prefill=8, kv_layout="paged",
        kv_page_size=PS, kv_pages=kv_pages, **kw,
    )


class _Rec:
    """metrics duck-type recording the long-context hooks."""

    def __init__(self):
        self.rejected = []
        self.deferred = []
        self.recycled = 0

    def on_kv_admission_rejected(self, reason):
        self.rejected.append(reason)

    def on_prefill_chunk_deferred(self, reason):
        self.deferred.append(reason)

    def on_kv_pages_recycled(self, n):
        self.recycled += n

    def on_submit(self): ...
    def on_prefill_chunk(self): ...
    def on_first_token(self): ...
    def on_step(self, *a): ...
    def on_finish(self, reason): ...
    def set_kv_pages(self, *a): ...
    def set_kv_reserved_bytes(self, *a): ...


# --- the host allocator's recycle seam --------------------------------------


def test_pool_recycle_counts_only_true_frees():
    pool = PagePool(8, 16)
    a = pool.alloc(4)
    pool.incref(a[:1])  # a prefix holds page a[0] too
    assert pool.recycle(a[:2]) == 1       # a[0] survives its other holder
    assert pool.recycled_total == 1
    assert pool.recycle([a[0]]) == 1      # the prefix lets go
    assert pool.recycled_total == 2
    freed = pool.decref(a[2:])            # retire-time release: NOT recycle
    assert freed == a[2:] and pool.recycled_total == 2
    assert pool.in_use == 0
    pool.check()


# --- admission past the old request_too_large wall --------------------------


def test_windowed_prompt_past_pool_wall_serves_o_window(setup):
    """The acceptance pin: a prompt whose full reservation outsizes the
    pool admits through the windowed peak bound, streams bit-identical
    to the oracle, and peaks at O(window + chunk) pages."""
    cfg, params = setup
    rec = _Rec()
    # 6 allocatable pages = 96 token rows; the request's full worst case
    # is 120 rows = 8 pages -> refused without a window
    cb = _batcher(params, cfg, kv_pages=6 + 1, metrics=rec)
    assert cb._incremental_reserve is True
    p = _prompt(300, 100, cfg)
    rid = cb.submit(p, max_new=20)
    results = cb.run(max_steps=400)
    assert results[rid] == _oracle(params, p, cfg, 20)
    # peak footprint: bounded by the admission formula, strictly under
    # the full reservation the dense rule would have demanded
    full = cb.pool.pages_for_tokens(120)
    bound = cb.pool.pages_for_tokens(cb._windowed_peak_tokens(20))
    assert cb.pool.peak_in_use <= bound < full
    assert cb._pages_recycled > 0
    assert cb.pool.recycled_total == cb._pages_recycled == rec.recycled
    assert cb.pool.in_use == 0  # retirement drained what recycling left
    cb.pool.check()
    s = cb.kv_stats()
    assert s["attn_window"] == W
    assert s["pages_recycled_total"] == cb._pages_recycled
    assert rec.rejected == []  # admitted first try: no pressure spell


def test_full_causal_twin_is_refused_at_the_pool_wall(setup):
    """The SAME pool without a window refuses the same request — with
    the structured fields the HTTP surfaces serialize."""
    _, _ = setup
    cfg0 = LlamaConfig.tiny(n_layers=2)  # window 0: full causal
    params0 = init_params(jax.random.key(0), cfg0)
    rec = _Rec()
    cb = _batcher(params0, cfg0, kv_pages=6 + 1, metrics=rec)
    assert cb._incremental_reserve is False
    with pytest.raises(RequestTooLargeError, match="KV pages") as ei:
        cb.submit(_prompt(300, 100, cfg0), max_new=20)
    assert ei.value.prompt_tokens == 100 and ei.value.max_new == 20
    assert ei.value.limit == 6 * PS  # the pool in tokens
    assert ei.value.body() == {
        "prompt_tokens": 100, "max_new": 20, "limit": 96,
    }
    assert rec.rejected == ["request_too_large"]


def test_slot_wall_reports_structured_fields(setup):
    cfg, params = setup
    cb = _batcher(params, cfg, kv_pages=12, max_len=64)
    with pytest.raises(RequestTooLargeError, match="slot capacity") as ei:
        cb.submit(_prompt(301, 50, cfg), max_new=30)
    assert ei.value.body() == {
        "prompt_tokens": 50, "max_new": 30, "limit": 64,
    }


def test_window_zero_and_dense_opt_out_of_incremental(setup):
    """window=0 / dense / speculative rows keep today's full-reservation
    path: the growth seam is a no-op compare for them (bit-identity with
    main is the existing matrix tests' job — here we pin the flag)."""
    cfg0 = LlamaConfig.tiny(n_layers=2)
    params0 = init_params(jax.random.key(0), cfg0)
    assert _batcher(params0, cfg0, kv_pages=12)._incremental_reserve \
        is False
    cfg, params = setup
    dense = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8,
    )
    assert dense._incremental_reserve is False
    assert dense.kv_stats()["attn_window"] == W  # surfaced regardless


# --- chaos: growth under injected pool pressure -----------------------------


def test_pool_alloc_fault_mid_prompt_defers_chunk_not_request(setup):
    """Injected pool.alloc failures during chunk growth defer the NEXT
    chunk only: the request keeps its slot and pages, the deferral is
    counted with reason=pool_pressure, and the stream completes
    bit-identical to the no-fault run."""
    cfg, params = setup
    p = _prompt(310, 100, cfg)
    baseline = _batcher(params, cfg, kv_pages=6 + 1)
    rb = baseline.submit(p, max_new=20)
    want = baseline.run(max_steps=400)[rb]
    want_lp = list(baseline.done_requests[rb].out_logp)

    rec = _Rec()
    # hit 1 is the admission reservation; hits 2.. are growth calls
    # (fired only when grow > 0) — nth=2:times=3 lands all three fires
    # MID-PROMPT, deterministically
    cb = _batcher(
        params, cfg, kv_pages=6 + 1, metrics=rec,
        faults=FaultPlane.from_spec("pool.alloc:nth=2:times=3"),
    )
    rid = cb.submit(p, max_new=20)
    results = cb.run(max_steps=400)
    assert results[rid] == want
    assert list(cb.done_requests[rid].out_logp) == want_lp
    assert cb._chunks_deferred == 3
    assert rec.deferred == ["pool_pressure"] * 3
    assert rec.rejected == []  # the REQUEST was never re-queued
    assert cb.pool.in_use == 0
    cb.pool.check()


def test_cancel_mid_growth_returns_pool_to_baseline(setup):
    """Cancel after the reservation has grown AND recycling has zeroed
    early ledger entries: release must free exactly the live pages
    (the PR-6 leak pattern, now with holes in the ledger)."""
    cfg, params = setup
    cb = _batcher(params, cfg, kv_pages=6 + 1)
    rid = cb.submit(_prompt(311, 100, cfg), max_new=20)
    for _ in range(8):  # mid-prefill: grown past the tranche, recycling
        cb.step()
    assert rid in {r.rid for r in cb.prefilling.values()}
    assert cb.pool.in_use > 0
    slot = next(s for s, r in cb.prefilling.items() if r.rid == rid)
    assert cb._recycle_lo.get(slot, 0) > 0  # holes exist in the ledger
    cb.cancel(rid)
    cb.run(max_steps=50)
    assert cb.pool.in_use == 0
    cb.pool.check()


# --- recycled rows refuse the seams that need the early prompt --------------


def test_export_refused_after_recycle_prompts_reprefill(setup):
    cfg, params = setup
    cb = _batcher(params, cfg, kv_pages=8 + 1)
    rid = cb.submit(_prompt(312, 40, cfg), max_new=30)
    while rid not in {r.rid for r in cb.running.values()}:
        cb.step()
    with pytest.raises(ValueError, match="re-prefill"):
        cb.export_kv_pages(rid)
    cb.cancel(rid)
    cb.run(max_steps=50)
    cb.pool.check()


def test_prefix_promotion_skips_recycled_rows_keeps_short_ones(setup):
    from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache

    cfg, params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = _batcher(params, cfg, kv_pages=8 + 1, prefix_cache=pc)
    # long prompt: its first page is recycled by finish time — the
    # promotion boundary rows no longer exist, so no entry may form
    r_long = cb.submit(_prompt(313, 40, cfg), max_new=4)
    cb.run(max_steps=200)
    assert r_long in cb.done_requests
    assert pc.stats.promotions == 0
    # short prompt (inside the window): nothing recycled mid-prefill,
    # promotion proceeds exactly as before
    r_short = cb.submit(_prompt(314, 17, cfg), max_new=4)
    cb.run(max_steps=200)
    assert r_short in cb.done_requests
    assert pc.stats.promotions > 0
    cb.pool.check()


# --- scheduler: page-relief preemption ranking ------------------------------


def test_preempt_victim_ranked_by_page_relief_under_windowed_pool():
    """With recycling live, out-length stops being a KV proxy: a pool-
    pressured head must evict the victim holding the most pages, not
    the longest decode. window=0 keeps the original ranking."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import SloScheduler

    def req(rid, priority, out_n, deadline=None, defer=False):
        return types.SimpleNamespace(
            rid=rid, tenant="t", priority=priority, max_new=20,
            out=[0] * out_n, deadline=deadline, defer_counted=defer,
        )

    head = types.SimpleNamespace(
        rid=9, tenant="t", priority=0, max_new=4, out=[],
        deadline=0.0, defer_counted=True,
    )
    cb = types.SimpleNamespace(
        pending=[head],
        # slot 0: long decode, mostly recycled (2 live pages);
        # slot 1: short decode, 6 live pages
        running={0: req(1, 5, 10), 1: req(2, 5, 2)},
        prefilling={}, n_slots=2, chunk=8, supports_preemption=True,
        _slot_pages={0: [0, 0, 0, 7, 8], 1: [1, 2, 3, 4, 5, 6]},
        window=W, metrics=None,
    )
    sched = SloScheduler(preempt=True)
    assert sched._preempt_slot(cb, now=1.0, rejects=[]) == 1
    cb.window = 0  # full causal: the original longest-decode ranking
    sched2 = SloScheduler(preempt=True)
    assert sched2._preempt_slot(cb, now=1.0, rejects=[]) == 0


# --- observability ----------------------------------------------------------


def test_serving_metrics_longctx_surface():
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.on_kv_pages_recycled(5)
    m.on_kv_pages_recycled(2)
    m.on_prefill_chunk_deferred("pool_pressure")
    g = reg.get_sample_value
    pre = "tpu_serving"
    assert g(f"{pre}_kv_pages_recycled_total") == 7
    assert g(f"{pre}_prefill_chunks_deferred_total",
             {"reason": "pool_pressure"}) == 1
    m.close()


def test_attn_window_alias_and_health(setup):
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params = setup
    assert cfg.attn_window == cfg.sliding_window == W
    assert LlamaConfig.tiny().attn_window == 0
    engine = InferenceEngine(
        params, cfg, n_slots=1, max_len=64, chunked_prefill=8,
        kv_layout="paged", kv_page_size=PS, prefill_reserve_chunks=3,
    )
    try:
        assert engine.cb.reserve_chunks == 3
        kv = engine.stats()["kv"]
        assert kv["attn_window"] == W
        assert kv["pages_recycled_total"] == 0
    finally:
        engine.shutdown()
    with pytest.raises(ValueError, match="prefill_reserve_chunks"):
        InferenceEngine(
            params, cfg,
            batcher=ContinuousBatcher(
                params, cfg, n_slots=1, max_len=64,
                prompt_buckets=BUCKETS,
            ),
            prefill_reserve_chunks=3,
        )


# --- the structured 422 on both HTTP surfaces -------------------------------


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


async def _with_server(setup, body):
    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        InferenceServer,
    )

    cfg, params = setup
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
    )
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    stop = asyncio.Event()
    task = asyncio.create_task(server.run(stop))
    for _ in range(100):
        if server.bound_port:
            break
        await asyncio.sleep(0.05)
    try:
        import aiohttp

        base = f"http://127.0.0.1:{server.bound_port}"
        async with aiohttp.ClientSession() as session:
            await body(session, base)
    finally:
        stop.set()
        await asyncio.wait_for(task, 30)


def test_native_422_carries_structured_fields(setup):
    cfg, params = setup
    p = _prompt(320, 50, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 30,
        }) as r:
            assert r.status == 422
            err = (await r.json())["error"]
        assert err["code"] == "request_too_large"
        assert err["prompt_tokens"] == 50
        assert err["max_new"] == 30
        assert err["limit"] == 64

    _run(_with_server(setup, body))


def test_openai_422_carries_structured_fields(setup):
    cfg, params = setup
    p = _prompt(321, 50, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/completions", json={
            "prompt": p, "max_tokens": 30,
        }) as r:
            assert r.status == 422
            err = (await r.json())["error"]
        assert err["type"] == "invalid_request_error"
        assert err["code"] == "request_too_large"
        assert err["prompt_tokens"] == 50
        assert err["max_new"] == 30
        assert err["limit"] == 64

    _run(_with_server(setup, body))
