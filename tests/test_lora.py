"""LoRA: zero-init identity, frozen-base training, merge equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
)
from k8s_gpu_device_plugin_tpu.models.lora import (
    LoraConfig,
    init_lora_params,
    init_lora_state,
    make_lora_train_step,
    merge_lora,
)
from k8s_gpu_device_plugin_tpu.models.train import synthetic_batch
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh


def _setup(targets=("wq", "wk", "wv", "wo")):
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    lora = LoraConfig(rank=4, alpha=8.0, targets=targets)
    lp = init_lora_params(jax.random.key(1), cfg, lora)
    return cfg, params, lora, lp


def test_zero_init_is_identity():
    """B = 0 => merged model == base model exactly at step 0."""
    cfg, params, lora, lp = _setup()
    tokens = jnp.arange(16, dtype=jnp.int32)[None, :]
    base = forward(params, tokens, cfg)
    merged = forward(merge_lora(params, lp, lora), tokens, cfg)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(base), atol=1e-6)


def test_lora_training_reduces_loss_and_freezes_base():
    cfg, params, lora, lp = _setup()
    mesh = make_mesh(MeshSpec(dp=2), jax.devices()[:2])
    optimizer = optax.adam(1e-2)
    state = init_lora_state(jax.random.key(1), cfg, lora, optimizer)
    batch = synthetic_batch(jax.random.key(2), cfg, 4, 32, mesh)
    step = make_lora_train_step(params, cfg, mesh, lora, optimizer)

    base_before = jax.tree.map(lambda x: np.asarray(x, np.float32), params)
    first = None
    for _ in range(12):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first  # overfit one batch through the factors alone
    # the base pytree is untouched (it is a closure constant)
    for a, b in zip(jax.tree.leaves(base_before), jax.tree.leaves(params)):
        np.testing.assert_array_equal(a, np.asarray(b, np.float32))
    # only the targeted factors changed; every b is now nonzero somewhere
    assert any(
        float(jnp.abs(state["lora"][t]["b"]).sum()) > 0
        for t in lora.targets
    )


def test_mlp_targets_work():
    cfg, params, lora, lp = _setup(targets=("w1", "w2", "w3"))
    tokens = jnp.arange(8, dtype=jnp.int32)[None, :]
    merged = forward(merge_lora(params, lp, lora), tokens, cfg)
    assert bool(jnp.isfinite(merged).all())


def test_validation():
    with pytest.raises(ValueError, match="rank"):
        LoraConfig(rank=0)
    with pytest.raises(ValueError, match="untargetable"):
        LoraConfig(targets=("embed",))
    cfg = LlamaConfig.tiny(n_layers=1, n_experts=4)
    with pytest.raises(NotImplementedError, match="MoE"):
        init_lora_params(
            jax.random.key(0), cfg, LoraConfig(targets=("w1",))
        )


def test_moe_attention_targets_allowed():
    cfg = LlamaConfig.tiny(n_layers=1, n_experts=4)
    lp = init_lora_params(jax.random.key(0), cfg, LoraConfig())
    assert set(lp) == {"wq", "wk", "wv", "wo"}
