"""Decode feature-interaction sweep: every pairwise-composable knob combo
runs end-to-end and produces sane tokens.

The serving stack has grown orthogonal levers (sliding window, int8 KV
cache, int8 weights, samplers with penalty, EOS); each has its own oracle
tests, but interactions are where regressions hide — this sweep is cheap
insurance that the cross-product keeps executing.

The ``--draftPreset`` axis: speculative decoding now composes with the
fast path (paged KV, prefix cache, pipelined rounds — pinned end to end
in tests/test_spec_fastpath.py), so the sweep here pins the REMAINING
boundary — every combination the speculative round genuinely cannot
thread (per-request sampler overrides, logit-bias planes, per-request
seeds, repetition penalty) fails with an actionable error message at
submit/construction, never a silent fallback, while the identical
submit sails through the plain batcher.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
    quantize_weights_int8,
)
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler

BASE = LlamaConfig.tiny(n_layers=2, vocab_size=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def base_params():
    # module-scoped fixture, not import-time init: collection stays cheap
    # when these tests are deselected, but the 24 combos still share one
    # parameter build
    return init_params(jax.random.key(0), BASE)


@pytest.mark.parametrize("window", [0, 8])
@pytest.mark.parametrize("cache_quant", ["none", "int8", "int4"])
@pytest.mark.parametrize("int8_weights", [False, True])
@pytest.mark.parametrize(
    "sampler",
    [
        None,  # greedy
        Sampler(temperature=0.8, top_k=16, top_p=0.9),
        Sampler(temperature=0.7, repetition_penalty=1.3),
    ],
)
def test_decode_knobs_compose(window, cache_quant, int8_weights, sampler,
                              base_params):
    cfg = replace(BASE, sliding_window=window, cache_quant=cache_quant)
    params = (
        quantize_weights_int8(base_params) if int8_weights else base_params
    )
    prompt = jnp.arange(1, 13, dtype=jnp.int32)[None, :]
    toks = generate(
        params, prompt, cfg, max_new=8, key=jax.random.key(3),
        sampler=sampler, eos_id=5, pad_id=0,
    )
    a = np.asarray(toks)
    assert a.shape == (1, 8)
    assert (a >= 0).all() and (a < cfg.vocab_size).all()
    # eos contract holds in every combination: strictly-after positions pad
    hits = np.where(a[0] == 5)[0]
    if hits.size:
        assert (a[0, hits[0] + 1:] == 0).all()


def test_attn_bias_composes_with_batching_and_int8_weights():
    """Qwen2-style q/k/v biases through the continuous batcher and the
    int8 weight-quantized decode: both must match dedicated generate on
    the same (biased) weights — the bias is a base-model leaf that
    quantization and slot batching must carry untouched."""
    from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher

    cfg = replace(BASE, attn_bias=True)
    params = init_params(jax.random.key(3), cfg)
    # zeros init would make the bias path vacuous — randomize
    params["layers"]["bq"] = 0.5 * jax.random.normal(
        jax.random.key(4), params["layers"]["bq"].shape, jnp.float32
    )
    params["layers"]["bk"] = 0.5 * jax.random.normal(
        jax.random.key(5), params["layers"]["bk"].shape, jnp.float32
    )
    prompt = list(range(2, 9))
    oracle = np.asarray(generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=6
    ))[0].tolist()

    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                           chunked_prefill=8)
    rid = cb.submit(prompt, max_new=6)
    assert cb.run()[rid] == oracle

    qparams = quantize_weights_int8(params)
    got = np.asarray(generate(
        qparams, jnp.asarray([prompt], jnp.int32), cfg, max_new=6
    ))[0].tolist()
    # int8 weights perturb logits, not the mechanism: tokens must be valid
    # and the biased path must EXECUTE (shape errors/dropped biases crash)
    assert len(got) == 6 and all(0 <= t < cfg.vocab_size for t in got)


# --- the --draftPreset axis --------------------------------------------------


def _spec_batcher(params, **kw):
    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    # self-draft: the composition gates don't depend on the draft's size
    return SpeculativeBatcher(
        params, BASE, params, BASE, n_slots=1, max_len=32, gamma=2,
        chunked_prefill=8, **kw,
    )


@pytest.mark.parametrize("knob", ["sampler", "logit_bias", "seed"])
@pytest.mark.parametrize("spec", [False, True])
def test_per_request_knobs_compose_or_refuse_with_speculative(
    spec, knob, base_params
):
    """Per-request knobs x speculative decoding: the plain batcher
    accepts every one of them; the speculative batcher refuses each
    with a pinned, actionable message (the round threads ONE sampler,
    no bias planes, no per-row key streams) — and its engine-facing
    capability flag agrees, so the HTTP layer 422s instead of silently
    falling back."""
    kwargs = {
        "sampler": dict(sampler=Sampler(temperature=0.5, top_k=8)),
        "logit_bias": dict(logit_bias={3: 1.0}),
        "seed": dict(seed=7),
    }[knob]
    if not spec:
        from k8s_gpu_device_plugin_tpu.models.batching import (
            ContinuousBatcher,
        )

        cb = ContinuousBatcher(base_params, BASE, n_slots=1, max_len=32,
                               chunked_prefill=8)
        assert cb.submit([1, 2, 3], max_new=2, **kwargs) >= 0  # queued
        return
    sb = _spec_batcher(base_params)
    message = {
        "sampler": "per-request samplers",
        "logit_bias": "logit_bias",
        "seed": "per-request seeds",
    }[knob]
    with pytest.raises(ValueError, match=message):
        sb.submit([1, 2, 3], max_new=2, **kwargs)
    flag = {
        "sampler": "per_request_sampler",
        "logit_bias": "per_request_bias",
        "seed": "per_request_seed",
    }[knob]
    assert getattr(sb, flag) is False


# --- quantized x paged x tp x pipelined ------------------------------------


KERNEL_CFG = LlamaConfig.tiny(n_layers=2, head_dim_override=64,
                              decode_attn="ragged")


@pytest.fixture(scope="module")
def kernel_params():
    # head_dim_override=64 puts the tiny config ON the unified kernel's
    # gates (the stock tiny head_dim of 16 is the documented fallback)
    return init_params(jax.random.key(0), KERNEL_CFG)


@pytest.mark.parametrize("tp", [1, 2])
@pytest.mark.parametrize("cache_quant", ["int8", "int4"])
def test_quantized_paged_composes_on_kernel(cache_quant, tp, kernel_params):
    """The quantized-paged composition matrix: {int8,int4} x paged x
    {tp=1,tp>1} x pipelined decode all serve through the unified
    ragged-paged kernel — the fallback-visibility gauge stays at ZERO
    on the xla arm (no silent XLA-gather fallback), and the stream is
    bit-identical to the dense twin of the same quantized config."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )
    from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher

    cfg = replace(KERNEL_CFG, cache_quant=cache_quant)
    reg = CollectorRegistry()
    metrics = ServingMetrics(registry=reg)
    prompts = [list(range(1, 6)), list(range(3, 15))]
    try:
        cb = ContinuousBatcher(
            kernel_params, cfg, n_slots=2, max_len=64,
            prompt_buckets=(8, 16, 32), chunked_prefill=8,
            pipeline_depth=1, kv_layout="paged", kv_page_size=16, tp=tp,
            metrics=metrics,
        )
        assert cb.attn_plan["decode"]["backend"] == "pallas"
        assert cb.attn_plan["verify"]["backend"] == "pallas"
        # the fallback-visibility gauge: xla arm pinned at zero
        assert reg.get_sample_value(
            "tpu_serving_decode_attn_backend",
            {"mode": "decode", "backend": "xla"},
        ) == 0
        assert reg.get_sample_value(
            "tpu_serving_decode_attn_backend",
            {"mode": "decode", "backend": "pallas"},
        ) == 1
        rids = [cb.submit(p, max_new=4) for p in prompts]
        got = cb.run()
    finally:
        metrics.close()
    dense = ContinuousBatcher(
        kernel_params, cfg, n_slots=2, max_len=64,
        prompt_buckets=(8, 16, 32), chunked_prefill=8, pipeline_depth=1,
    )
    rids_d = [dense.submit(p, max_new=4) for p in prompts]
    want = dense.run()
    assert [got[r] for r in rids] == [want[r] for r in rids_d]


def test_speculative_composition_matrix(base_params):
    """The docs/serving.md composition matrix, pinned: repetition
    penalty refuses at construction (actionable, not silent), while the
    fast-path trio — paged KV (draft pool included), automatic prefix
    cache, pipelined rounds — all CONSTRUCT together (their stream
    exactness is pinned in tests/test_spec_fastpath.py)."""
    from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache

    with pytest.raises(ValueError, match="repetition_penalty"):
        _spec_batcher(
            base_params,
            sampler=Sampler(temperature=0.7, repetition_penalty=1.2),
        )
    pc = PrefixCache(BASE, buckets=(8, 16), budget_bytes=1 << 20)
    sb = _spec_batcher(
        base_params, prefix_cache=pc, kv_layout="paged", kv_page_size=8,
        pipeline_depth=1,
    )
    assert sb.pool is not None and sb.draft_pool is not None
    assert sb.prefix_cache is pc
    assert sb.pipeline_depth == 1
