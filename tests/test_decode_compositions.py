"""Decode feature-interaction sweep: every pairwise-composable knob combo
runs end-to-end and produces sane tokens.

The serving stack has grown orthogonal levers (sliding window, int8 KV
cache, int8 weights, samplers with penalty, EOS); each has its own oracle
tests, but interactions are where regressions hide — this sweep is cheap
insurance that the cross-product keeps executing.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
    quantize_weights_int8,
)
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler

BASE = LlamaConfig.tiny(n_layers=2, vocab_size=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def base_params():
    # module-scoped fixture, not import-time init: collection stays cheap
    # when these tests are deselected, but the 24 combos still share one
    # parameter build
    return init_params(jax.random.key(0), BASE)


@pytest.mark.parametrize("window", [0, 8])
@pytest.mark.parametrize("cache_quant", ["none", "int8", "int4"])
@pytest.mark.parametrize("int8_weights", [False, True])
@pytest.mark.parametrize(
    "sampler",
    [
        None,  # greedy
        Sampler(temperature=0.8, top_k=16, top_p=0.9),
        Sampler(temperature=0.7, repetition_penalty=1.3),
    ],
)
def test_decode_knobs_compose(window, cache_quant, int8_weights, sampler,
                              base_params):
    cfg = replace(BASE, sliding_window=window, cache_quant=cache_quant)
    params = (
        quantize_weights_int8(base_params) if int8_weights else base_params
    )
    prompt = jnp.arange(1, 13, dtype=jnp.int32)[None, :]
    toks = generate(
        params, prompt, cfg, max_new=8, key=jax.random.key(3),
        sampler=sampler, eos_id=5, pad_id=0,
    )
    a = np.asarray(toks)
    assert a.shape == (1, 8)
    assert (a >= 0).all() and (a < cfg.vocab_size).all()
    # eos contract holds in every combination: strictly-after positions pad
    hits = np.where(a[0] == 5)[0]
    if hits.size:
        assert (a[0, hits[0] + 1:] == 0).all()


def test_attn_bias_composes_with_batching_and_int8_weights():
    """Qwen2-style q/k/v biases through the continuous batcher and the
    int8 weight-quantized decode: both must match dedicated generate on
    the same (biased) weights — the bias is a base-model leaf that
    quantization and slot batching must carry untouched."""
    from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher

    cfg = replace(BASE, attn_bias=True)
    params = init_params(jax.random.key(3), cfg)
    # zeros init would make the bias path vacuous — randomize
    params["layers"]["bq"] = 0.5 * jax.random.normal(
        jax.random.key(4), params["layers"]["bq"].shape, jnp.float32
    )
    params["layers"]["bk"] = 0.5 * jax.random.normal(
        jax.random.key(5), params["layers"]["bk"].shape, jnp.float32
    )
    prompt = list(range(2, 9))
    oracle = np.asarray(generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=6
    ))[0].tolist()

    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                           chunked_prefill=8)
    rid = cb.submit(prompt, max_new=6)
    assert cb.run()[rid] == oracle

    qparams = quantize_weights_int8(params)
    got = np.asarray(generate(
        qparams, jnp.asarray([prompt], jnp.int32), cfg, max_new=6
    ))[0].tolist()
    # int8 weights perturb logits, not the mechanism: tokens must be valid
    # and the biased path must EXECUTE (shape errors/dropped biases crash)
    assert len(got) == 6 and all(0 <= t < cfg.vocab_size for t in got)
