"""Engine supervisor: crash recovery with stream-true error reporting.

The contract pinned here (serving/supervisor.py + the serving/server.py
crash boundary):

- an induced mid-decode engine crash with recovery enabled leaves
  greedy AND seeded token + logprob streams bit-identical to an
  uninterrupted run (dense and paged layouts), with zero re-emitted
  tokens;
- queued requests replay in their original admission order;
- transient injected pool-alloc failures defer admissions, they never
  kill the engine;
- an exhausted restart budget degrades to the dead state, and every
  stream then ends with a STRUCTURED error frame on both HTTP
  surfaces — never the old bare end-of-stream None that read exactly
  like a short, successful completion.
"""

import asyncio
import json

import jax
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler
from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane
from k8s_gpu_device_plugin_tpu.serving.server import (
    InferenceEngine,
    InferenceServer,
    drain_queue,
)
from k8s_gpu_device_plugin_tpu.serving.supervisor import (
    EngineSupervisor,
    StreamError,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _mk_engine(params, cfg, *, faults=None, supervisor=None,
               kv_layout="dense", n_slots=2, prefix_cache=None,
               **kw):
    return InferenceEngine(
        params, cfg, n_slots=n_slots, max_len=64, chunked_prefill=8,
        kv_layout=kv_layout,
        kv_page_size=8 if kv_layout == "paged" else None,
        faults=faults, supervisor=supervisor, prefix_cache=prefix_cache,
        **kw,
    )


def _requests(cfg, n=5, max_new=12):
    """n mixed requests: greedy and per-request-seeded sampling —
    the two stream classes the resume pin covers."""
    out = []
    for i in range(n):
        prompt = [1 + (7 * i + j) % (cfg.vocab_size - 1) for j in range(5)]
        sampled = i % 2 == 0
        out.append(dict(
            prompt=prompt, max_new=max_new,
            sampler=Sampler(temperature=0.8) if sampled else None,
            seed=(100 + i) if sampled else None,
        ))
    return out


def _drain_all(engine, reqs):
    async def body():
        subs = [
            engine.submit(r["prompt"], r["max_new"], sampler=r["sampler"],
                          seed=r["seed"])
            for r in reqs
        ]
        return [await drain_queue(q) for _, q in subs]

    return run(body())


@pytest.mark.parametrize("kv_layout", ["dense", "paged"])
def test_mid_decode_crash_resumes_bit_identical(setup, kv_layout):
    """The acceptance pin: crash mid-decode, recover, and every stream
    (greedy + seeded, tokens AND logprobs) is bit-identical to an
    uninterrupted run — nothing lost, nothing re-emitted."""
    cfg, params = setup
    reqs = _requests(cfg)

    eng = _mk_engine(params, cfg, kv_layout=kv_layout)
    try:
        baseline = _drain_all(eng, reqs)
    finally:
        eng.shutdown()
    assert all(e is None for _, _, e in baseline)

    eng = _mk_engine(
        params, cfg, kv_layout=kv_layout,
        faults=FaultPlane.from_spec("decode.apply:nth=6"),
        supervisor=EngineSupervisor(max_restarts=3, window_s=60.0),
    )
    try:
        chaotic = _drain_all(eng, reqs)
        sup = eng.supervisor.stats()
    finally:
        eng.shutdown()
    assert sup["restarts_total"] == 1, sup
    assert sup["state"] == "ok"
    assert sup["resumed_total"] + sup["replayed_total"] >= 1
    assert sup["last_crash"]["error"].startswith("FaultError")
    for (bt, bl, be), (ct, cl, ce) in zip(baseline, chaotic):
        assert be is None and ce is None
        assert ct == bt          # token stream bit-identical
        assert cl == bl          # logprob stream bit-identical
        # zero re-emitted tokens: exact length, no duplicated prefix
        assert len(ct) == len(bt)


def test_queued_requests_replay_in_admission_order(setup):
    """One slot, three queued requests, crash during the first: after
    recovery every stream completes and the COMPLETION order matches
    the submission order (the supervisor re-admits in rid order)."""
    cfg, params = setup
    eng = _mk_engine(
        params, cfg, n_slots=1,
        faults=FaultPlane.from_spec("decode.apply:nth=4"),
        supervisor=EngineSupervisor(max_restarts=2, window_s=60.0),
    )
    reqs = _requests(cfg, n=3, max_new=6)
    finish_order = []

    async def body():
        subs = [
            eng.submit(r["prompt"], r["max_new"], sampler=r["sampler"],
                       seed=r["seed"])
            for r in reqs
        ]

        async def one(i, q):
            toks, _, err = await drain_queue(q)
            finish_order.append(i)
            return toks, err

        return await asyncio.gather(
            *(one(i, q) for i, (_, q) in enumerate(subs))
        )

    try:
        results = run(body())
        sup = eng.supervisor.stats()
    finally:
        eng.shutdown()
    assert sup["restarts_total"] == 1
    for toks, err in results:
        assert err is None
        assert len(toks) == 6
    assert finish_order == [0, 1, 2]


def test_pool_alloc_faults_defer_instead_of_killing(setup):
    """Injected transient page-allocation failures read as pool
    pressure: admissions defer and retry, streams complete, and the
    engine never restarts."""
    cfg, params = setup
    eng = _mk_engine(
        params, cfg, kv_layout="paged",
        faults=FaultPlane.from_spec("pool.alloc:p=0.5:seed=11:times=6"),
    )
    try:
        results = _drain_all(eng, _requests(cfg, n=6, max_new=6))
        sup = eng.supervisor.stats()
    finally:
        eng.shutdown()
    assert all(e is None and len(t) == 6 for t, _, e in results)
    assert sup["restarts_total"] == 0
    assert sup["crashes_total"] == 0


def test_prefill_dispatch_crash_replays_unstarted_requests(setup):
    """A crash in the chunked-prefill dispatch (no tokens emitted yet)
    replays the request from scratch — streams still complete and
    match the no-fault run."""
    cfg, params = setup
    reqs = _requests(cfg, n=3, max_new=6)
    eng = _mk_engine(params, cfg)
    try:
        baseline = _drain_all(eng, reqs)
    finally:
        eng.shutdown()
    eng = _mk_engine(
        params, cfg,
        faults=FaultPlane.from_spec("prefill.dispatch:nth=2"),
        supervisor=EngineSupervisor(max_restarts=2, window_s=60.0),
    )
    try:
        chaotic = _drain_all(eng, reqs)
        sup = eng.supervisor.stats()
    finally:
        eng.shutdown()
    assert sup["restarts_total"] == 1
    for (bt, _, _), (ct, _, ce) in zip(baseline, chaotic):
        assert ce is None and ct == bt


def test_paged_prefix_cache_resets_and_recovers(setup):
    """On the paged layout the prefix cache's promoted entries hold
    page ids of the DEAD pool: recovery resets the cache (no stale
    aliasing), re-attaches it, and promotion works again after."""
    from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache

    cfg, params = setup
    pc = PrefixCache(cfg, buckets=(8, 16, 32), budget_bytes=64 << 20)
    eng = _mk_engine(
        params, cfg, kv_layout="paged", prefix_cache=pc,
        prompt_buckets=(8, 16, 32),  # promotion boundaries the prompts cover
        faults=FaultPlane.from_spec("decode.apply:nth=10"),
        supervisor=EngineSupervisor(max_restarts=2, window_s=60.0),
    )
    shared = [3] * 16  # covers a promotable bucket boundary
    reqs = [dict(prompt=shared + [5 + i], max_new=6, sampler=None,
                 seed=None) for i in range(4)]
    try:
        first = _drain_all(eng, reqs)
        assert eng.supervisor.stats()["restarts_total"] == 1
        assert all(e is None and len(t) == 6 for t, _, e in first)
        # the cache survived as an OBJECT, reset, re-attached, and
        # promotion still works on the rebuilt pool
        assert eng.cb.prefix_cache is pc
        second = _drain_all(eng, reqs)
        assert all(e is None and len(t) == 6 for t, _, e in second)
        assert pc.stats.entries > 0  # post-restart promotion happened
    finally:
        eng.shutdown()


def test_prefix_cache_reset_drops_entries_without_release_hook():
    cfg = LlamaConfig.tiny(n_layers=2)
    from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache

    released = []
    pc = PrefixCache(cfg, buckets=(8,), budget_bytes=64 << 20)
    pc.release_entry = released.append
    pc.on_prefill_done(list(range(1, 12)), -1, lambda p: ("entry", p))
    assert pc.stats.entries == 1 and pc.stats.nodes > 0
    hits_before = pc.stats.hits
    pc.reset()
    assert released == []  # the dead pool must NOT see decrefs
    assert pc.stats.entries == 0
    assert pc.stats.nodes == 0
    assert pc.stats.resident_bytes == 0
    assert pc.stats.hits == hits_before  # cumulative counters survive
    assert pc.match(list(range(1, 12)), -1, count=False) is None


def test_restart_budget_exhaustion_degrades_to_dead_with_error_frames(setup):
    """Budget 1 + a fault that fires on every decode apply past the
    threshold: the first crash recovers, the second exhausts the
    budget — the engine dies, every stream carries a structured
    StreamError frame (never a bare None), health flips to 503-dead,
    and new submits are refused."""
    cfg, params = setup
    eng = _mk_engine(
        params, cfg,
        faults=FaultPlane.from_spec("decode.apply:nth=4:times=1000"),
        supervisor=EngineSupervisor(max_restarts=1, window_s=60.0),
    )
    try:
        results = _drain_all(eng, _requests(cfg, n=3, max_new=8))
        sup = eng.supervisor.stats()
        stats = eng.stats()
        with pytest.raises(RuntimeError, match="dead"):
            run_submit_dead(eng)
    finally:
        eng.shutdown()
    assert sup["restarts_total"] == 1
    assert sup["state"] == "dead"
    assert sup["crashes_total"] == 2
    assert stats["alive"] is False
    assert stats["supervisor"]["state"] == "dead"
    errs = [e for _, _, e in results]
    assert all(isinstance(e, StreamError) for e in errs), errs
    assert all(e.code == "engine_dead" for e in errs)
    assert any("restart budget exhausted" in e.message for e in errs)


def run_submit_dead(eng):
    async def body():
        eng.submit([1, 2, 3], 4)

    return run(body())


def test_zero_budget_supervisor_dies_with_structured_error(setup):
    """max_restarts=0 is the recovery-off switch — but the dead path
    still reports structurally (the satellite fix stands alone)."""
    cfg, params = setup
    eng = _mk_engine(
        params, cfg,
        faults=FaultPlane.from_spec("decode.apply:nth=3"),
        supervisor=EngineSupervisor(max_restarts=0),
    )
    try:
        results = _drain_all(eng, _requests(cfg, n=2, max_new=8))
    finally:
        eng.shutdown()
    assert all(isinstance(e, StreamError) and e.code == "engine_dead"
               for _, _, e in results)


def test_metrics_count_restarts(setup):
    """tpu_serving_engine_restarts_total (+ replay/resume twins) ride
    ServingMetrics through a recovery."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    registry = CollectorRegistry()
    metrics = ServingMetrics(registry=registry)
    eng = _mk_engine(
        params, cfg, metrics=metrics,
        faults=FaultPlane.from_spec("decode.apply:nth=6"),
        supervisor=EngineSupervisor(max_restarts=2, window_s=60.0),
    )
    try:
        _drain_all(eng, _requests(cfg, n=4, max_new=8))
    finally:
        eng.shutdown()
    assert registry.get_sample_value(
        "tpu_serving_engine_restarts_total") == 1.0
    replayed = registry.get_sample_value(
        "tpu_serving_engine_replayed_requests_total") or 0.0
    resumed = registry.get_sample_value(
        "tpu_serving_engine_resumed_requests_total") or 0.0
    assert replayed + resumed >= 1.0
    metrics.close()


def test_flight_recorder_retains_restart_survivors(setup):
    """The attribution layer always keeps requests that lived through
    a restart in the flight-recorder ring, with the restart count on
    the record."""
    from k8s_gpu_device_plugin_tpu.obs.attribution import RequestAttributor

    cfg, params = setup
    att = RequestAttributor(slow_ms=60_000.0)  # a threshold nothing hits
    eng = _mk_engine(
        params, cfg, attribution=att,
        faults=FaultPlane.from_spec("decode.apply:nth=6"),
        supervisor=EngineSupervisor(max_restarts=2, window_s=60.0),
    )
    try:
        _drain_all(eng, _requests(cfg, n=4, max_new=8))
    finally:
        eng.shutdown()
    slow = att.slow_stats()
    assert slow["captured"] >= 1
    assert any(r.get("restarts", 0) >= 1 for r in slow["requests"])
    # mid-flight survivors only: nothing else tripped the 60s threshold
    assert all(r.get("restarts", 0) >= 1 for r in slow["requests"])


def _sse_lines(body: bytes) -> list[dict]:
    events = []
    for line in body.decode().split("\n"):
        line = line.strip()
        if line.startswith("data: ") and line != "data: [DONE]":
            events.append(json.loads(line[len("data: "):]))
    return events


@pytest.mark.parametrize("surface", [
    "native_stream", "native_json", "oai_json", "oai_stream",
])
def test_http_surfaces_deliver_structured_error_frames(setup, surface):
    """The satellite pin: a mid-stream engine death reaches the client
    as a structured error on BOTH surfaces — native SSE error event /
    503 body, OpenAI server_error envelope (streamed and not) — never
    a clean short completion."""
    import aiohttp

    cfg, params = setup
    eng = _mk_engine(
        params, cfg,
        faults=FaultPlane.from_spec("decode.apply:nth=3"),
        supervisor=EngineSupervisor(max_restarts=0),
    )
    server = InferenceServer(eng, host="127.0.0.1", port=0)

    async def body():
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        while server.bound_port is None:
            await asyncio.sleep(0.01)
        base = f"http://127.0.0.1:{server.bound_port}"
        prompt = [1, 5, 7, 11, 2]
        try:
            async with aiohttp.ClientSession() as s:
                if surface == "native_stream":
                    async with s.post(f"{base}/v1/generate", json={
                        "prompt": prompt, "max_new": 10, "stream": True,
                    }) as r:
                        assert r.status == 200
                        events = _sse_lines(await r.read())
                    assert not any(e.get("done") for e in events)
                    err = [e for e in events if "error" in e]
                    assert err and err[-1]["error"]["code"] == "engine_dead"
                elif surface == "native_json":
                    async with s.post(f"{base}/v1/generate", json={
                        "prompt": prompt, "max_new": 10,
                    }) as r:
                        assert r.status == 503
                        out = await r.json()
                    assert out["code"] == "engine_dead"
                elif surface == "oai_json":
                    async with s.post(f"{base}/v1/completions", json={
                        "model": "tpu-serving", "prompt": prompt,
                        "max_tokens": 10,
                    }) as r:
                        assert r.status == 503
                        out = await r.json()
                    assert out["error"]["type"] == "server_error"
                    assert out["error"]["code"] == "engine_dead"
                else:  # oai_stream
                    async with s.post(f"{base}/v1/completions", json={
                        "model": "tpu-serving", "prompt": prompt,
                        "max_tokens": 10, "stream": True,
                    }) as r:
                        assert r.status == 200
                        raw = await r.read()
                        events = _sse_lines(raw)
                    err = [e for e in events if "error" in e]
                    assert err and err[-1]["error"]["code"] == "engine_dead"
                    assert err[-1]["error"]["type"] == "server_error"
                    assert raw.decode().rstrip().endswith("data: [DONE]")
                    assert not any(
                        c.get("finish_reason")
                        for e in events for c in e.get("choices", [])
                    )
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    run(body())


def test_fallback_publish_closes_retired_streams():
    """When the normal post-crash publish raises against the torn
    batcher, the fallback must still CLOSE the streams of requests
    that retired between the last publish and the crash — their rids
    never reach the rebuilt batcher, so nothing else ever would (a
    handler awaiting that queue would hang forever)."""
    import threading
    from types import SimpleNamespace

    class FakeEngine:
        def __init__(self):
            self._lock = threading.Lock()
            self._streams = {}
            self._published = {}
            self._rid_to_eid = {}
            self._finished_info = {}
            self.pushed = []

        def _push(self, rid, out, logp):
            self.pushed.append((rid, tuple(out)))

    async def body():
        loop = asyncio.get_running_loop()
        eng = FakeEngine()
        live_q, done_q, rej_q = (asyncio.Queue() for _ in range(3))
        live = SimpleNamespace(rid=4, out=[9], out_logp=[-0.5])
        retired = SimpleNamespace(
            rid=5, out=[1, 2], out_logp=[-0.1, -0.2], cached_tokens=3,
            timeline=None, reject_reason=None,
        )
        rejected = SimpleNamespace(
            rid=6, out=[], out_logp=[], cached_tokens=0, timeline=None,
            reject_reason="pool_pressure",
        )
        old = SimpleNamespace(
            pending=[], prefilling={}, running={0: live},
            done_requests={5: retired, 6: rejected},
            done={5: [1, 2], 6: []}, scheduler=None,
        )
        eng._rid_to_eid = {4: 70, 5: 77, 6: 78}
        eng._streams = {70: (loop, live_q), 77: (loop, done_q),
                        78: (loop, rej_q)}
        eng._published = {70: 1, 77: 0, 78: 0}
        EngineSupervisor._fallback_publish(eng, old)
        await asyncio.sleep(0)  # drain call_soon_threadsafe callbacks
        # retired stream: tokens pushed AND closed; maps cleaned; the
        # wrap-up info recorded (cached_tokens)
        assert (5, (1, 2)) in eng.pushed
        assert done_q.get_nowait() is None
        assert 77 not in eng._streams and 5 not in eng._rid_to_eid
        assert eng._finished_info[77] == {"cached_tokens": 3}
        # REJECTED-while-queued retiree: the rejection disposition must
        # survive to the handler (429, not a clean zero-token done)
        assert rej_q.get_nowait() is None
        assert eng._finished_info[78]["reject_reason"] == "pool_pressure"
        assert eng._finished_info[78]["retry_after"] == 1
        assert old.done_requests == {} and old.done == {}
        # live stream: pushed but NOT closed (it resumes on the rebuild)
        assert (4, (9,)) in eng.pushed
        assert 70 in eng._streams and 4 in eng._rid_to_eid
        assert live_q.empty()

    run(body())


def test_injected_batcher_refuses_supervisor_and_faults(setup):
    cfg, params = setup
    from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher

    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                           chunked_prefill=8)
    with pytest.raises(ValueError, match="rebuild recipe"):
        InferenceEngine(params, cfg, batcher=cb,
                        supervisor=EngineSupervisor())
    with pytest.raises(ValueError, match="fault plane"):
        InferenceEngine(params, cfg, batcher=cb,
                        faults=FaultPlane.from_spec("decode.apply:nth=1"))
    # no supervisor section on health for injected batchers (no recipe)
    eng = InferenceEngine(params, cfg, batcher=cb)
    try:
        assert "supervisor" not in eng.stats()
    finally:
        eng.shutdown()


def test_recover_preserves_submit_anchored_deadline():
    """Supervisor x scheduler interplay, white-box half: a restart
    survivor keeps its ORIGINAL submit-anchored absolute deadline —
    recovery folds the prompt and requeues, it never re-anchors the
    SLO clock (queue wait across a crash still counts against the
    deadline, exactly like queue wait across a preemption)."""
    from types import SimpleNamespace

    from k8s_gpu_device_plugin_tpu.models.batching import _Request

    sup = EngineSupervisor(max_restarts=2, window_s=60.0)
    req = _Request(rid=7, prompt=[1, 2, 3], max_new=8)
    req.deadline = 123.456          # absolute perf_counter instant
    req.t_submit = 100.0
    req.out = [5, 6]
    req.out_logp = [-0.1, -0.2]
    req.slot = 0
    old = SimpleNamespace(
        pending=[], prefilling={}, running={0: req},
        done_requests={}, done={}, prefix_cache=None, pool=None,
        _next_rid=8,
    )
    new = SimpleNamespace(pending=[], _next_rid=0, metrics=None)
    eng = SimpleNamespace(cb=old, _publish=lambda: None,
                          _make_batcher=lambda: new)
    sup.recover(eng)
    assert eng.cb is new
    survivor = new.pending[0]
    assert survivor is req
    assert survivor.deadline == 123.456      # NOT re-anchored
    assert survivor.t_submit == 100.0        # the original clock
    assert survivor.prompt == [1, 2, 3, 5, 6]  # the fold
    assert survivor.prefilled_out == 2
    assert survivor.restarts == 1
    assert new._next_rid == 8


def test_restart_survivors_count_deadline_miss_once(setup):
    """Supervisor x scheduler interplay, integration half: requests
    with a deadline that cannot be met crash mid-decode, resume, and
    complete — each counts exactly ONE deadline miss (retirement-time
    accounting; the resumed re-admission neither re-counts nor
    re-charges), and a generous deadline across the same crash counts
    zero."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import Scheduler

    cfg, params = setup
    eng = _mk_engine(
        params, cfg, scheduler=Scheduler(),
        faults=FaultPlane.from_spec("decode.apply:nth=6"),
        supervisor=EngineSupervisor(max_restarts=2, window_s=60.0),
    )
    reqs = _requests(cfg, n=4, max_new=8)

    async def body():
        subs = [
            eng.submit(r["prompt"], r["max_new"], sampler=r["sampler"],
                       seed=r["seed"],
                       # 1ms: missed by construction; tenant "gold"
                       # gets an hour (zero misses through the crash)
                       tenant="gold" if i == 0 else None,
                       deadline_ms=3_600_000 if i == 0 else 1)
            for i, r in enumerate(reqs)
        ]
        return [await drain_queue(q) for _, q in subs]

    try:
        results = run(body())
        sup = eng.supervisor.stats()
        sched = eng.stats()["sched"]
    finally:
        eng.shutdown()
    assert sup["restarts_total"] == 1
    assert all(e is None and len(t) == 8 for t, _, e in results)
    assert sched["tenants"]["default"]["deadline_misses"] == 3
    assert sched["tenants"]["default"]["retired"] == 3
    assert sched["tenants"]["gold"]["deadline_misses"] == 0
    assert sched["tenants"]["gold"]["retired"] == 1


def test_open_loop_run_counts_truncated_separately():
    """The harness satellite: open_loop_run reports requests that
    VANISHED (admitted, never retired) as ``truncated`` — a separate
    bucket from rejected/retried_ok."""
    from types import SimpleNamespace

    from k8s_gpu_device_plugin_tpu.benchmark.workloads.serve_bench import (
        open_loop_run,
    )

    class LossyCB:
        """Completes every request except rid 1, which silently
        vanishes — the failure shape the counter exists to expose."""

        scheduler = None

        def __init__(self):
            self.pending = []
            self.prefilling = {}
            self.running = {}
            self.done_requests = {}
            self._n = 0

        def submit(self, prompt, max_new, **kw):
            rid = self._n
            self._n += 1
            self.pending.append(rid)
            return rid

        def step(self):
            if not self.pending:
                return
            rid = self.pending.pop(0)
            if rid == 1:
                return  # vanished: no retirement, no disposition
            self.done_requests[rid] = SimpleNamespace(
                reject_reason=None, deadline=None, preemptions=0,
                t_submit=0.0, t_first_tok=0.1, t_done=0.2,
                out=[1, 2],
            )

    trace = [
        {"t": 0.0, "tenant": "t", "priority": 1, "deadline_ms": None,
         "prompt": [1, 2], "max_new": 2, "phase": "base"}
        for _ in range(3)
    ]
    out = open_loop_run(LossyCB(), trace)
    assert out["truncated"] == 1
    assert out["rejected"] == 0
    assert len(out["per_request"]) == 2
