"""Benchmark machinery tests at CPU scale (numbers are not meaningful on
CPU; shape/finiteness/plumbing are what is asserted)."""

import jax
import pytest

from k8s_gpu_device_plugin_tpu.benchmark.workloads.allreduce_sweep import (
    allreduce_sweep,
)
from k8s_gpu_device_plugin_tpu.benchmark.workloads.matmul_mfu import (
    detect_generation,
    matmul_mfu,
)
from k8s_gpu_device_plugin_tpu.benchmark.workloads.roundtrip import (
    control_plane_roundtrip,
)
from k8s_gpu_device_plugin_tpu.benchmark.workloads.train_bench import train_mfu
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec


def test_matmul_mfu_machinery():
    result = matmul_mfu(n=256, iters=8, repeats=1)
    assert result.tflops > 0
    assert result.seconds > 0
    assert result.mfu == pytest.approx(result.tflops / result.peak_tflops)


def test_detect_generation_defaults():
    assert detect_generation(jax.devices()[0]) in ("v4", "v5e", "v5p", "v6e")


def test_allreduce_sweep_machinery():
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    points = allreduce_sweep(sizes_mb=(0.25, 1), iters=3, warmup=1)
    assert len(points) == 2
    for p in points:
        assert p.algbw_gbps > 0
        assert p.busbw_gbps == pytest.approx(
            p.algbw_gbps * 2 * (len(jax.devices()) - 1) / len(jax.devices())
        )
    assert points[1].bytes_per_device > points[0].bytes_per_device


def test_train_mfu_machinery():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = LlamaConfig.tiny(attn_impl="ring")
    result = train_mfu(
        cfg,
        batch_size=4,
        seq_len=64,
        mesh_spec=MeshSpec(dp=1, tp=2, sp=2),
        steps=2,
        warmup=1,
        devices=jax.devices()[:4],
    )
    assert result.tflops_per_chip > 0
    assert result.tokens_per_second > 0
    assert result.n_devices == 4


def test_control_plane_roundtrip(tmp_path):
    result = control_plane_roundtrip(
        topology="v5e-4", iters=10, socket_dir=str(tmp_path)
    )
    assert result.allocations == 10
    assert result.allocs_per_second > 0
    assert result.registrations >= 1


def test_step_breakdown_cpu():
    """Differential breakdown machinery end-to-end on a tiny CPU config."""
    import jax

    from k8s_gpu_device_plugin_tpu.benchmark.workloads.step_breakdown import (
        step_breakdown,
    )
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig

    r = step_breakdown(
        LlamaConfig.tiny(n_layers=2), batch_size=2, seq_len=64, repeats=1,
        devices=jax.devices()[:1],
    )
    assert set(r.variants_ms) == {
        "full", "fwd_bwd", "fwd", "dummy_loss", "ref_attn"
    }
    assert all(v > 0 for v in r.variants_ms.values())
    assert {"optimizer", "backward", "cross_entropy", "flash_vs_xla_attn"} <= set(
        r.attributed_ms
    )
    assert r.flops_per_step > 0


def test_decode_bench_cpu_smoke():
    """decode_bench end-to-end on CPU with a tiny config: positive numbers,
    sane shapes, prefill < full-generate time accounting holds."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.decode_bench import (
        decode_bench,
    )
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny(n_layers=2)
    r = decode_bench(cfg, batch=2, prompt_len=16, new_tokens=4, repeats=1)
    assert r.decode_tokens_per_second > 0
    assert r.decode_step_ms > 0
    assert r.prefill_ms > 0
    assert r.hbm_gb_per_second > 0
    assert r.batch == 2 and r.prompt_len == 16 and r.new_tokens == 4
