"""Benchmark machinery tests at CPU scale (numbers are not meaningful on
CPU; shape/finiteness/plumbing are what is asserted)."""

import jax
import pytest

from k8s_gpu_device_plugin_tpu.benchmark.workloads.allreduce_sweep import (
    allreduce_sweep,
)
from k8s_gpu_device_plugin_tpu.benchmark.workloads.matmul_mfu import (
    detect_generation,
    matmul_mfu,
)
from k8s_gpu_device_plugin_tpu.benchmark.workloads.roundtrip import (
    control_plane_roundtrip,
)
from k8s_gpu_device_plugin_tpu.benchmark.workloads.train_bench import train_mfu
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec


def test_matmul_mfu_machinery():
    result = matmul_mfu(n=256, iters=8, repeats=1)
    assert result.tflops > 0
    assert result.seconds > 0
    assert result.mfu == pytest.approx(result.tflops / result.peak_tflops)


def test_detect_generation_defaults():
    assert detect_generation(jax.devices()[0]) in ("v4", "v5e", "v5p", "v6e")


def test_allreduce_sweep_machinery():
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    points = allreduce_sweep(sizes_mb=(0.25, 1), iters=3, warmup=1)
    assert len(points) == 2
    for p in points:
        assert p.algbw_gbps > 0
        assert p.busbw_gbps == pytest.approx(
            p.algbw_gbps * 2 * (len(jax.devices()) - 1) / len(jax.devices())
        )
    assert points[1].bytes_per_device > points[0].bytes_per_device


def test_train_mfu_machinery():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = LlamaConfig.tiny(attn_impl="ring")
    result = train_mfu(
        cfg,
        batch_size=4,
        seq_len=64,
        mesh_spec=MeshSpec(dp=1, tp=2, sp=2),
        steps=2,
        warmup=1,
        devices=jax.devices()[:4],
    )
    assert result.tflops_per_chip > 0
    assert result.tokens_per_second > 0
    assert result.n_devices == 4


def test_control_plane_roundtrip(tmp_path):
    result = control_plane_roundtrip(
        topology="v5e-4", iters=10, socket_dir=str(tmp_path)
    )
    assert result.allocations == 10
    assert result.allocs_per_second > 0
    assert result.registrations >= 1


def test_step_breakdown_cpu():
    """Differential breakdown machinery end-to-end on a tiny CPU config."""
    import jax

    from k8s_gpu_device_plugin_tpu.benchmark.workloads.step_breakdown import (
        step_breakdown,
    )
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig

    r = step_breakdown(
        LlamaConfig.tiny(n_layers=2), batch_size=2, seq_len=64, repeats=1,
        devices=jax.devices()[:1],
    )
    assert set(r.variants_ms) == {
        "full", "fwd_bwd", "fwd", "dummy_loss", "ref_attn"
    }
    assert all(v > 0 for v in r.variants_ms.values())
    assert {"optimizer", "backward", "cross_entropy", "flash_vs_xla_attn"} <= set(
        r.attributed_ms
    )
    assert r.flops_per_step > 0


def test_decode_bench_cpu_smoke():
    """decode_bench end-to-end on CPU with a tiny config: positive numbers,
    sane shapes, prefill < full-generate time accounting holds."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.decode_bench import (
        decode_bench,
    )
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny(n_layers=2)
    r = decode_bench(cfg, batch=2, prompt_len=16, new_tokens=4, repeats=1)
    assert r.decode_tokens_per_second > 0
    assert r.decode_step_ms > 0
    assert r.prefill_ms > 0
    assert r.hbm_gb_per_second > 0
    assert r.batch == 2 and r.prompt_len == 16 and r.new_tokens == 4


def test_fused_adamw_matches_optax_chain():
    """The hand-fused AdamW (opt_tune's candidate) must reproduce the
    production optax.chain(clip_by_global_norm, adamw) trajectory on a
    small f32 tree — same moments, same params, several steps deep.
    Constant lr isolates the update math from the schedule."""
    import jax.numpy as jnp
    import optax

    from k8s_gpu_device_plugin_tpu.ops.fused_optim import fused_adamw_update

    key = jax.random.key(7)
    k1, k2, k3 = jax.random.split(key, 3)
    params = {
        "w": jax.random.normal(k1, (16, 8), jnp.float32),
        "b": jax.random.normal(k2, (8,), jnp.float32),
    }
    lr, b1, b2, eps, wd, clip = 1e-3, 0.9, 0.95, 1e-8, 0.1, 1.0
    ref_opt = optax.chain(
        optax.clip_by_global_norm(clip),
        optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd),
    )
    ref_state = ref_opt.init(params)
    ref_params = params
    fused_params = params
    mu = jax.tree.map(jnp.zeros_like, params)
    nu = jax.tree.map(jnp.zeros_like, params)
    count = jnp.zeros((), jnp.int32)

    for step in range(4):
        grads = jax.tree.map(
            lambda p: jnp.sin(p + step).astype(p.dtype), ref_params
        )
        updates, ref_state = ref_opt.update(grads, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, updates)
        # sin-shaped grads keep the clip scale engaged on every step
        fused_params, mu, nu, count = fused_adamw_update(
            fused_params, grads, mu, nu, count,
            lr=lr, b1=b1, b2=b2, eps=eps, weight_decay=wd, clip=clip,
        )
        for a, b in zip(jax.tree.leaves(ref_params), jax.tree.leaves(fused_params)):
            assert jnp.allclose(a, b, atol=1e-6), f"diverged at step {step}"


def test_fused_adamw_clip_engages():
    """With grads far above the clip norm, fused and optax must still agree
    (the clip scale folds into the fused elementwise pass)."""
    import jax.numpy as jnp
    import optax

    from k8s_gpu_device_plugin_tpu.ops.fused_optim import fused_adamw_update

    params = {"w": jnp.ones((32, 4), jnp.float32)}
    grads = {"w": jnp.full((32, 4), 100.0, jnp.float32)}  # norm >> clip
    lr, clip = 1e-2, 1.0
    ref_opt = optax.chain(
        optax.clip_by_global_norm(clip),
        optax.adamw(lr, b1=0.9, b2=0.95, eps=1e-8, weight_decay=0.1),
    )
    state = ref_opt.init(params)
    updates, _ = ref_opt.update(grads, state, params)
    ref_params = optax.apply_updates(params, updates)
    fused_params, _, _, _ = fused_adamw_update(
        params, grads,
        jax.tree.map(jnp.zeros_like, params),
        jax.tree.map(jnp.zeros_like, params),
        jnp.zeros((), jnp.int32),
        lr=lr, b1=0.9, b2=0.95, eps=1e-8, weight_decay=0.1, clip=clip,
    )
    assert jnp.allclose(ref_params["w"], fused_params["w"], atol=1e-6)


def test_opt_tune_machinery():
    """opt_tune runs end-to-end on CPU at tiny scale and reports both
    variants plus the floor."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.opt_tune import opt_tune
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig

    r = opt_tune(cfg=LlamaConfig.tiny(n_layers=2), repeats=1, iters=2)
    assert set(r.variants_ms) == {"optax", "fused", "hbm_floor"}
    assert r.variants_ms["optax"] > 0
    assert r.variants_ms["fused"] > 0
    assert r.param_count > 0


def test_flash_tune_survives_failing_configs():
    """A tiling the backend rejects must not kill the sweep (on hardware
    that failure is a remote-compile 500; on CPU every non-interpret Pallas
    config fails, which exercises the same per-config recovery path)."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.flash_tune import (
        flash_tune,
    )

    r = flash_tune(
        batch=1, seq=256, n_heads=2, n_kv_heads=1, head_dim=64,
        blocks=((128, 128), (256, 128)), repeats=1, iters=1,
    )
    # every config either timed (float) or recorded its failure (str) —
    # and the sweep itself returned instead of raising
    assert set(r.fwd_ms) == {"128x128", "256x128"}
    for v in list(r.fwd_ms.values()) + list(r.bwd_ms.values()):
        assert isinstance(v, (float, str))
    assert r.best_fwd in ("128x128", "256x128", "none")


def test_decode_bench_int4_smoke():
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.decode_bench import (
        decode_bench,
    )
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny(n_layers=2)
    r = decode_bench(cfg, batch=2, prompt_len=16, new_tokens=4, repeats=1,
                     weight_quant="int4")
    assert r.decode_tokens_per_second > 0
