"""MoE layer tests: routing/dispatch numerics vs a dense per-expert oracle,
aux-loss properties, capacity-drop behavior, and expert-parallel sharded
training on the virtual 8-device CPU mesh.

The reference framework has no MoE (SURVEY.md §2: parallelism absent in
reference); the oracle here IS the spec: with capacity ample, each token's
output must equal the top-k gate-weighted sum of its experts' SwiGLU FFNs.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    forward_with_aux,
    init_params,
)
from k8s_gpu_device_plugin_tpu.models.moe import (
    expert_capacity,
    load_balance_loss,
    make_dispatch_combine,
    moe_mlp,
    moe_param_init,
    router_topk,
)
from k8s_gpu_device_plugin_tpu.models.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
    synthetic_batch,
)
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh


def moe_cfg(**overrides):
    base = dict(n_experts=4, n_experts_per_token=2, capacity_factor=4.0)
    base.update(overrides)
    return LlamaConfig.tiny(**base)


def single_layer(cfg, key):
    """One layer's MoE params, unstacked from the (L, ...) pytree."""
    stacked = moe_param_init(key, cfg)
    return jax.tree.map(lambda w: w[0], stacked)


def dense_oracle(h, layer, cfg):
    """Per-token loop-free oracle: run EVERY expert on EVERY token, then
    combine with the top-k gates. Correct whenever nothing is dropped."""
    logits = h.astype(jnp.float32) @ layer["router"].astype(jnp.float32)
    gates, idx, _ = router_topk(logits, cfg.n_experts_per_token)
    outs = []
    for e in range(cfg.n_experts):
        gate = jax.nn.silu(
            (h @ layer["moe_w1"][e]).astype(jnp.float32)
        ).astype(h.dtype)
        up = h @ layer["moe_w3"][e]
        outs.append((gate * up) @ layer["moe_w2"][e])
    outs = jnp.stack(outs, axis=2)  # (B,S,E,D)
    onehot = jax.nn.one_hot(idx, cfg.n_experts, dtype=jnp.float32)  # (B,S,k,E)
    weights = jnp.sum(onehot * gates[..., None], axis=2)  # (B,S,E)
    return jnp.einsum("bse,bsed->bsd", weights.astype(h.dtype), outs)


def test_moe_matches_dense_oracle():
    cfg = moe_cfg(dtype=jnp.float32)
    layer = single_layer(cfg, jax.random.key(0))
    h = jax.random.normal(jax.random.key(1), (2, 16, cfg.d_model), jnp.float32)
    got, aux = moe_mlp(h, layer, cfg)
    want = dense_oracle(h, layer, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)
    assert float(aux["moe_load_balance"]) >= 1.0 - 1e-5


def test_dispatch_combine_shapes_and_mass():
    gates = jnp.array([[[0.7, 0.3], [0.6, 0.4], [1.0, 0.0]]])  # (1,3,2)
    idx = jnp.array([[[0, 1], [0, 2], [3, 0]]])
    dispatch, combine = make_dispatch_combine(gates, idx, n_experts=4, capacity=4)
    assert dispatch.shape == (1, 3, 4, 4)
    # every slot landed (capacity ample): combine mass per token == 1
    np.testing.assert_allclose(np.asarray(jnp.sum(combine, axis=(2, 3))), 1.0)
    # expert 0 received tokens 0,1,2 in order at positions 0,1,2
    assert float(dispatch[0, 0, 0, 0]) == 1.0
    assert float(dispatch[0, 1, 0, 1]) == 1.0
    assert float(dispatch[0, 2, 0, 2]) == 1.0


def test_capacity_drops_tokens_not_numerics():
    """Tiny capacity: overflow slots are dropped (less combine mass), and
    the layer still produces finite outputs."""
    cfg = moe_cfg(capacity_factor=0.25, dtype=jnp.float32)
    layer = single_layer(cfg, jax.random.key(0))
    h = jax.random.normal(jax.random.key(1), (2, 32, cfg.d_model), jnp.float32)
    out, _ = moe_mlp(h, layer, cfg)
    assert bool(jnp.all(jnp.isfinite(out)))
    # with the same inputs and ample capacity, outputs differ (drops happened)
    ample = dataclasses.replace(cfg, capacity_factor=8.0)
    full, _ = moe_mlp(h, layer, ample)
    assert not np.allclose(np.asarray(out), np.asarray(full))


def test_routing_groups_match_ungrouped_when_capacity_ample():
    """Group-local capacity competition must be numerics-neutral when no
    tokens are dropped; only dispatch-tensor shapes change."""
    cfg = moe_cfg(capacity_factor=8.0, dtype=jnp.float32, moe_group_size=8)
    layer = single_layer(cfg, jax.random.key(0))
    h = jax.random.normal(jax.random.key(1), (2, 32, cfg.d_model), jnp.float32)
    grouped, aux_g = moe_mlp(h, layer, cfg)
    ungrouped, _ = moe_mlp(h, layer, cfg.with_group_size(0))
    np.testing.assert_allclose(
        np.asarray(grouped), np.asarray(ungrouped), atol=1e-5
    )
    assert bool(jnp.isfinite(aux_g["moe_load_balance"]))


def test_group_size_always_divides():
    from k8s_gpu_device_plugin_tpu.models.moe import _group_size

    assert _group_size(4096, 32768) == 4096
    assert _group_size(4096, 10000) == 2500  # largest divisor <= 4096
    assert _group_size(4096, 9973) == 1      # prime: per-token groups
    assert _group_size(0, 128) == 128        # disabled -> one group
    assert _group_size(256, 128) == 128      # request >= seq -> one group
    for req, s in [(4096, 10000), (7, 30), (13, 64)]:
        g = _group_size(req, s)
        assert s % g == 0 and g <= max(req, s)


def test_odd_seq_len_routes_through_groups():
    """A seq length not divisible by the requested group size must still be
    grouped (smaller divisor groups), never the quadratic fallthrough."""
    cfg = moe_cfg(capacity_factor=8.0, dtype=jnp.float32, moe_group_size=8)
    layer = single_layer(cfg, jax.random.key(0))
    h = jax.random.normal(jax.random.key(1), (1, 24, cfg.d_model), jnp.float32)
    out, _ = moe_mlp(h, layer, cfg)  # 24 % 8 == 0 -> groups of 8
    out_odd, _ = moe_mlp(
        jax.random.normal(jax.random.key(2), (1, 30, cfg.d_model), jnp.float32),
        layer,
        cfg,  # 30 % 8 != 0 -> groups of 6
    )
    assert bool(jnp.all(jnp.isfinite(out))) and bool(jnp.all(jnp.isfinite(out_odd)))


def test_load_balance_loss_uniform_is_one():
    b, s, E = 4, 32, 8
    probs = jnp.full((b, s, E), 1.0 / E)
    # perfectly balanced assignments: round-robin over experts
    idx = (jnp.arange(s)[None, :, None] + jnp.arange(2)[None, None, :]) % E
    idx = jnp.broadcast_to(idx, (b, s, 2))
    loss = load_balance_loss(probs, idx, E)
    np.testing.assert_allclose(float(loss), 1.0, rtol=1e-5)


def test_load_balance_loss_collapsed_is_high():
    b, s, E = 2, 16, 8
    probs = jnp.zeros((b, s, E)).at[..., 0].set(1.0)
    idx = jnp.zeros((b, s, 2), jnp.int32)
    loss = load_balance_loss(probs, idx, E)
    assert float(loss) == pytest.approx(E, rel=1e-5)


def test_expert_capacity_floor():
    cfg = moe_cfg(n_experts=64, n_experts_per_token=2, capacity_factor=1.0)
    # 8 tokens over 64 experts: ideal capacity <1, floor keeps k slots
    assert expert_capacity(cfg, 8) >= 2


def test_moe_forward_aux_and_flops():
    cfg = moe_cfg()
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits, aux = forward_with_aux(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert set(aux) == {"moe_load_balance", "moe_router_z"}
    assert all(bool(jnp.isfinite(v)) for v in aux.values())
    dense = LlamaConfig.tiny()
    # activated-param FLOPs: k=2 experts ~ 2x dense MLP term
    assert cfg.flops_per_token() > dense.flops_per_token()


def test_moe_train_step_ep_sharded():
    """Full train step with a real ep axis: dp=2, ep=2, tp=2 over 8 CPU
    devices; loss finite and decreasing over a few overfit steps."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh(MeshSpec(dp=2, ep=2, tp=2), jax.devices()[:8])
    cfg = moe_cfg(n_layers=2)
    optimizer = make_optimizer(total_steps=10, warmup_steps=0, learning_rate=1e-2)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, batch_size=4, seq_len=32, mesh=mesh)
    step = make_train_step(cfg, mesh, optimizer)
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert "moe_load_balance" in metrics


def test_moe_sharded_matches_unsharded():
    """The ep/tp-sharded forward must equal the single-device forward —
    sharding is an implementation detail, not a numerics change."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = moe_cfg(n_layers=1, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    want, _ = forward_with_aux(params, tokens, cfg)
    mesh = make_mesh(MeshSpec(ep=2, tp=2), jax.devices()[:4])
    from k8s_gpu_device_plugin_tpu.models.llama import param_shardings

    sharded = jax.device_put(params, param_shardings(cfg, mesh))
    got, _ = jax.jit(
        lambda p, t: forward_with_aux(p, t, cfg, mesh)
    )(sharded, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)
