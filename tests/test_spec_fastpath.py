"""Speculative decoding on the fast serving path (models/spec_batching.py
+ paged KV + prefix cache + overlapped rounds).

Three layers of claims:

- **Bit-exactness inside the speculative matrix**: greedy token AND
  logprob streams are identical across kv_layout {dense, paged} x
  prefix cache {on, off} x pipeline_depth {0, 1}, over admit/retire/
  cancel/stop/eviction interleavings — the paged gather reproduces the
  dense verify view value-for-value, a cache hit replays the exact rows
  a cold prefill computes (target aliased, draft re-prefilled on the
  cold chunk grid), and the overlapped round only ever DROPS tokens.
- **Greedy parity with the non-speculative path**: tokens equal the
  plain ContinuousBatcher's (and the ``generate`` oracle) exactly at
  f32; logprobs agree to float tolerance only — the T=gamma verify and
  the T=1 decode are different XLA programs (the models/speculative.py
  caveat), so the logprob pin across the two PATHS is allclose while
  the pin across the speculative MATRIX is bitwise.
- **Pool discipline**: the draft pool mirrors every admission with the
  same trap-page/refcount semantics, drains at retirement, defers under
  draft pool pressure, and prefix hits still move zero KV rows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models import batching
from k8s_gpu_device_plugin_tpu.models.batching import (
    ContinuousBatcher,
    precompute_prefix,
)
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.spec_batching import SpeculativeBatcher
from k8s_gpu_device_plugin_tpu.serving.prefix_cache import (
    PrefixCache,
    prefix_kv_bytes,
)

BUCKETS = (8, 16, 32)
PS = 16  # divides max_len=64; boundary 8 is page-UNALIGNED (COW case)
GAMMA = 3


@pytest.fixture(scope="module")
def setup():
    # the same f32 configs as tests/test_spec_batching.py so the dense
    # spec compiles are shared across the two modules; the paged twins
    # compile once here
    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    draft_cfg = LlamaConfig.tiny(n_layers=1, d_model=64, n_heads=4,
                                 n_kv_heads=2, d_ff=128, dtype=jnp.float32)
    draft_params = init_params(jax.random.key(1), draft_cfg)
    return cfg, params, draft_cfg, draft_params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


def _spec(setup, layout, pc=None, depth=1, n_slots=2, **kw):
    cfg, params, draft_cfg, draft_params = setup
    return SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=n_slots, max_len=64, gamma=GAMMA, chunked_prefill=8,
        prompt_buckets=BUCKETS, pipeline_depth=depth, prefix_cache=pc,
        kv_layout=layout, kv_page_size=PS if layout == "paged" else None,
        **kw,
    )


# --- the matrix: dense/paged x cache on/off x depth 0/1 ---------------------
#
# One scheduling scenario per configuration: staggered waves behind two
# shared system prompts (promotion, hits, a re-miss after eviction under
# a deliberately tight byte budget), a mid-flight cancel, and a stop
# sequence — interleavings identical across configurations by
# construction, so completed streams must be bit-identical.


def _scenario(setup, layout, depth, cache_on):
    cfg = setup[0]
    pc = None
    if cache_on:
        b = prefix_kv_bytes(cfg, 8) + prefix_kv_bytes(cfg, 16)
        if layout == "paged":
            from dataclasses import replace

            b = prefix_kv_bytes(
                replace(cfg, kv_layout="paged", kv_page_size=PS), 16
            ) * 2
        pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=b)
    sb = _spec(setup, layout, pc=pc, depth=depth)
    sys_a = _prompt(520, 17, cfg)
    sys_b = _prompt(521, 18, cfg)
    rids = []

    def sub(base, tail_key, tail_n, new, stop=None):
        p = base + _prompt(tail_key, tail_n, cfg)
        rids.append(sb.submit(p, max_new=new, stop=stop))

    # wave 1: two requests behind sys_a (promotes its boundaries)
    sub(sys_a, 530, 5, 5)
    sub(sys_a, 531, 4, 4)
    for _ in range(7):
        sb.step()
    # wave 2: sys_a again (hit) + sys_b (miss -> promote -> evict under
    # the tight budget)
    sub(sys_a, 532, 6, 5)
    sub(sys_b, 533, 5, 6)
    for _ in range(4):
        sb.step()
    cancelled = rids[2]
    sb.cancel(cancelled)
    # wave 3: both prefixes again (hits + re-misses post-eviction); a
    # stop sequence that can't fire exercises the matching
    sub(sys_b, 534, 4, 4)
    sub(sys_a, 535, 3, 5,
        stop=[[cfg.vocab_size - 1, cfg.vocab_size - 1]])
    sb.run()
    streams = {
        rid: (list(req.out), list(req.out_logp))
        for rid, req in sb.done_requests.items()
    }
    if sb.pool is not None:
        sb.pool.check()
        sb.draft_pool.check()
    return rids, cancelled, streams, pc, sb


def test_spec_matrix_bit_identical_streams(setup):
    """dense/depth0/cache-on is the reference; dense/depth1/cache-OFF
    pins the cache and the overlap, paged/depth1/cache-on pins the
    paged layout riding both. supports_* flags are pinned flipped."""
    assert SpeculativeBatcher.supports_paged_kv is True
    assert SpeculativeBatcher.supports_prefix_cache is True
    runs = {
        key: _scenario(setup, *key)
        for key in [("dense", 0, True), ("dense", 1, False),
                    ("paged", 1, True)]
    }
    ref_rids, ref_cancel, ref_streams, _, _ = runs[("dense", 0, True)]
    for key, (rids, cancelled, streams, pc, sb) in runs.items():
        assert rids == ref_rids and cancelled == ref_cancel
        for rid in rids:
            if rid == cancelled:
                # the cancel lands at a run-dependent depth; the common
                # prefix must still be bit-identical
                toks, lps = streams[rid]
                rt, rl = ref_streams[rid]
                n = min(len(toks), len(rt))
                assert toks[:n] == rt[:n], key
                assert lps[:n] == rl[:n], key
            else:
                assert streams[rid][0] == ref_streams[rid][0], key
                assert streams[rid][1] == ref_streams[rid][1], key
        if pc is not None:  # the cache machinery must actually engage
            assert pc.stats.promotions > 0 and pc.stats.hits > 0, key
            assert pc.stats.evictions > 0, key
        st = sb.spec_stats()
        assert st["rounds"] > 0 and st["tokens_accepted"] > 0


def test_spec_greedy_parity_with_plain_path(setup):
    """The acceptance bar vs the NON-speculative path: same scenario
    traffic through a plain ContinuousBatcher — tokens exactly equal
    (f32), logprobs allclose (T=gamma verify vs T=1 decode are
    different XLA programs; the models/speculative.py caveat)."""
    cfg, params, _, _ = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8,
    )
    sys_a = _prompt(520, 17, cfg)
    sys_b = _prompt(521, 18, cfg)
    plain = {}
    for base, key, n, new in [(sys_a, 530, 5, 5), (sys_a, 531, 4, 4),
                              (sys_b, 533, 5, 6), (sys_b, 534, 4, 4),
                              (sys_a, 535, 3, 5)]:
        p = base + _prompt(key, n, cfg)
        rid = cb.submit(p, max_new=new)
        plain[(key, n)] = rid
    cb.run()
    # 1:1 comparison (no cancel): every stream pinned to the plain
    # path AND the generate oracle
    sb = _spec(setup, "dense", depth=1)
    spec_rids = {}
    for base, key, n, new in [(sys_a, 530, 5, 5), (sys_b, 533, 5, 6),
                              (sys_a, 535, 3, 5)]:
        p = base + _prompt(key, n, cfg)
        spec_rids[sb.submit(p, max_new=new)] = ((key, n), p, new)
    sb.run()
    for rid, (pk, p, new) in spec_rids.items():
        spec_req = sb.done_requests[rid]
        plain_req = cb.done_requests[plain[pk]]
        assert spec_req.out == plain_req.out, pk
        assert spec_req.out == _oracle(params, p, cfg, new), pk
        np.testing.assert_allclose(
            np.asarray(spec_req.out_logp), np.asarray(plain_req.out_logp),
            atol=1e-4, rtol=1e-4,
        )


def test_spec_manual_prefix_supported(setup):
    """submit(prefix=...) stops being refused: the target serves the
    precomputed rows, the draft re-prefills them, and the stream equals
    the full-prompt oracle."""
    cfg, params, _, _ = setup
    sb = _spec(setup, "dense")
    sys_p = _prompt(540, 12, cfg)
    prefix = precompute_prefix(params, sys_p, cfg,
                               prompt_buckets=BUCKETS)
    suffix = _prompt(541, 6, cfg)
    rid = sb.submit(suffix, max_new=5, prefix=prefix)
    out = sb.run()[rid]
    assert out == _oracle(params, sys_p + suffix, cfg, 5)


# --- pool discipline ---------------------------------------------------------


def test_spec_paged_zero_copy_and_drained_pools(setup):
    """Prefix hits move zero KV rows under the paged spec batcher (the
    PR-4 claim, now holding with a draft cache in the loop), and BOTH
    pools drain to exactly the surviving cache entries' pages."""
    cfg, params, _, _ = setup
    batching.reset_kv_copy_counts()
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 26)
    sb = _spec(setup, "paged", pc=pc)
    sys_p = _prompt(550, 20, cfg)
    for k, n, new in [(551, 5, 5), (552, 4, 4)]:
        p = sys_p + _prompt(k, n, cfg)
        rid = sb.submit(p, max_new=new)
        sb.run()
        assert sb.done[rid] == _oracle(params, p, cfg, new)
    assert pc.stats.hits >= 1 and pc.stats.promotions >= 1
    counts = batching.kv_copy_counts()
    assert counts["rows"] == 0, counts
    sb.pool.check()
    sb.draft_pool.check()
    # target pool: only the promoted entries' pages survive retirement;
    # the draft pool has no prefix entries, so it drains to zero
    assert sb.draft_pool.in_use == 0
    assert sb.pool.in_use > 0  # the cache's pins


def test_spec_draft_pool_pressure_defers_then_admits(setup):
    """A draft pool with room for ONE request: the second defers under
    pool pressure (counted once) and admits after the first retires —
    streams exact throughout, both pools drained after."""
    cfg, params, _, _ = setup

    class _Rec:
        def __init__(self):
            self.rejected = []

        def on_kv_admission_rejected(self, reason):
            self.rejected.append(reason)

        def on_submit(self): ...
        def on_prefill_chunk(self): ...
        def on_first_token(self): ...
        def on_step(self, *a): ...
        def on_finish(self, reason): ...

    rec = _Rec()
    # per request: ceil((9 + 20 + 3)/16) = 2 draft pages; a 2-page draft
    # pool (3 with trap) can hold exactly one at a time, while the
    # target pool keeps dense-equivalent capacity
    sb = _spec(setup, "paged", metrics=rec, draft_kv_pages=2 + 1)
    p1, p2 = _prompt(560, 9, cfg), _prompt(561, 9, cfg)
    r1 = sb.submit(p1, max_new=20)
    r2 = sb.submit(p2, max_new=20)
    results = sb.run()
    assert results[r1] == _oracle(params, p1, cfg, 20)
    assert results[r2] == _oracle(params, p2, cfg, 20)
    assert rec.rejected.count("pool_pressure") == 1
    sb.pool.check()
    sb.draft_pool.check()
    assert sb.pool.in_use == 0 and sb.draft_pool.in_use == 0
    # a request outsizing the DRAFT pool is refused at submit
    with pytest.raises(ValueError, match="draft KV pages"):
        sb.submit(_prompt(562, 20, cfg), max_new=25)
    assert rec.rejected.count("request_too_large") == 1


# --- the verify kernel -------------------------------------------------------


def test_paged_verify_kernel_matches_gather(setup):
    """ops/paged_attention.py's multi-query verify variant in interpret
    mode vs the XLA gather reference — same table, same base positions,
    windowed and unwindowed; plus the shape gates."""
    from k8s_gpu_device_plugin_tpu.ops import paged_attention

    b, ps, n_pages, hkv, hq, hd, npg, t = 3, 8, 16, 2, 8, 64, 4, 4
    kp = jax.random.normal(
        jax.random.key(1), (n_pages, ps, hkv, hd), jnp.bfloat16
    )
    vp = jax.random.normal(
        jax.random.key(2), (n_pages, ps, hkv, hd), jnp.bfloat16
    )
    q = jax.random.normal(jax.random.key(3), (b, t, hq, hd), jnp.bfloat16)
    table = jnp.asarray(
        np.random.RandomState(0).choice(
            np.arange(1, n_pages), (b, npg), replace=False
        ),
        jnp.int32,
    )
    base = jnp.asarray([5, 17, 27], jnp.int32)
    assert paged_attention.supports_verify(q, kp, table,
                                           require_pltpu=False)

    def ref(window):
        kd = kp[table].reshape(b, npg * ps, hkv, hd).astype(jnp.float32)
        vd = vp[table].reshape(b, npg * ps, hkv, hd).astype(jnp.float32)
        qf = q.astype(jnp.float32).reshape(b, t, hkv, hq // hkv, hd)
        s = jnp.einsum("btkgd,bskd->btkgs", qf, kd) * hd ** -0.5
        pos = jnp.arange(npg * ps)[None, None, None, None, :]
        q_pos = (base[:, None, None, None, None]
                 + jnp.arange(t)[None, :, None, None, None])
        keep = pos <= q_pos
        if window:
            keep &= q_pos - pos < window
        s = jnp.where(keep, s, -1e30)
        pr = jax.nn.softmax(s, -1)
        return jnp.einsum("btkgs,bskd->btkgd", pr, vd).reshape(
            b, t, hq, hd
        )

    for window in (0, 12):
        out = paged_attention.paged_verify_attention(
            q, kp, vp, table, base, scale=hd ** -0.5, window=window,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref(window)),
            atol=5e-2, rtol=5e-2,
        )

    # shape gates: T=1 belongs to the decode kernel, huge windows
    # (prefill chunks) to the gather, ragged page sizes to nobody
    assert not paged_attention.supports_verify(
        jnp.zeros((b, 1, hq, hd), jnp.bfloat16), kp, table,
        require_pltpu=False,
    )
    assert not paged_attention.supports_verify(
        jnp.zeros((b, 32, hq, hd), jnp.bfloat16), kp, table,
        require_pltpu=False,
    )
    assert not paged_attention.supports_verify(
        q, jnp.zeros((n_pages, 12, hkv, hd), jnp.bfloat16), table,
        require_pltpu=False,
    )

    # the routing gate: a T>1 paged read that is NOT a verify window (a
    # small prefill chunk has the same shape) must stay on the bitwise
    # XLA gather even under decode_attn="ragged" — only the explicit
    # verify flag may route onto the flash kernel, whose accumulation
    # is allclose-not-bitwise to the gather
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.generate import _cached_attention

    cfg = LlamaConfig.tiny(n_layers=1, d_model=512, n_heads=8,
                           n_kv_heads=2, d_ff=256)
    vcfg = replace(cfg, kv_layout="paged", kv_page_size=ps,
                   decode_attn="ragged")
    chunk_like = _cached_attention(q, kp, vp, None, None, base, vcfg,
                                   pages=table)
    gather = _cached_attention(
        q, kp, vp, None, None, base, replace(vcfg, decode_attn="auto"),
        pages=table,
    )
    assert np.array_equal(
        np.asarray(chunk_like, np.float32), np.asarray(gather, np.float32)
    )
    verified = _cached_attention(q, kp, vp, None, None, base, vcfg,
                                 pages=table, verify=True)
    np.testing.assert_allclose(
        np.asarray(verified, np.float32), np.asarray(gather, np.float32),
        atol=5e-2, rtol=5e-2,
    )


# --- metrics & health surfaces ----------------------------------------------


def test_spec_metrics_surface():
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.on_spec_round(4, [4, 2, 1])
    g = reg.get_sample_value
    pre = "tpu_serving"
    assert g(f"{pre}_spec_rounds_total") == 1
    assert g(f"{pre}_spec_tokens_drafted_total") == 12
    assert g(f"{pre}_spec_tokens_accepted_total") == 7
    assert g(f"{pre}_spec_accepted_per_round_count") == 3
    assert g(f"{pre}_spec_accepted_per_round_sum") == 7
    m.close()
    m2 = ServingMetrics(registry=reg)  # names freed by close()
    m2.close()


def test_spec_stats_and_kv_comparability(setup):
    """The two health satellites: spec_stats() exposes acceptance, and
    kv_stats() folds the draft cache into reserved_bytes (with the
    target/draft split kept visible) so spec-vs-plain HBM comparisons
    are apples-to-apples."""
    cfg, params, draft_cfg, _ = setup
    from k8s_gpu_device_plugin_tpu.models.paging import kv_token_bytes

    sb = _spec(setup, "paged")
    p = _prompt(570, 6, cfg)
    rid = sb.submit(p, max_new=6)
    assert sb.run()[rid] == _oracle(params, p, cfg, 6)
    st = sb.spec_stats()
    assert st["gamma"] == GAMMA and st["rounds"] > 0
    assert 0.0 < st["acceptance_rate"] <= 1.0
    assert 1.0 <= st["accepted_per_round"] <= GAMMA
    kv = sb.kv_stats()
    assert kv["reserved_bytes"] == (
        kv["target_reserved_bytes"] + kv["draft_reserved_bytes"]
    )
    assert kv["draft"]["layout"] == "paged"
    assert kv["draft"]["reserved_bytes"] == (
        sb.draft_pool.n_pages * PS * kv_token_bytes(draft_cfg)
    )
    # dense spec reports the draft's dense reservation the same way
    sd = _spec(setup, "dense")
    kvd = sd.kv_stats()
    assert kvd["draft"]["layout"] == "dense"
    assert kvd["reserved_bytes"] == (
        kvd["target_reserved_bytes"] + kvd["draft_reserved_bytes"]
    )


def test_engine_health_reports_spec(setup):
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params, _, _ = setup
    sb = _spec(setup, "paged")
    engine = InferenceEngine(params, cfg, batcher=sb)
    try:
        stats = engine.stats()
        assert stats["spec"]["gamma"] == GAMMA
        assert "acceptance_rate" in stats["spec"]
        assert stats["kv"]["draft_reserved_bytes"] > 0
    finally:
        engine.shutdown()
