"""Topology model tests (≙ reference device metadata seams, device/device.go)."""

import pytest

from k8s_gpu_device_plugin_tpu.device.topology import (
    GENERATIONS,
    HostTopology,
    as_slice_member,
    parse_topology,
)


def test_parse_known_shapes():
    assert parse_topology("v5e-4").bounds == (2, 2)
    assert parse_topology("v5e-8").bounds == (2, 4)
    assert parse_topology("v5p-8").bounds == (2, 2, 2)
    assert parse_topology("v5p-16").bounds == (4, 2, 2)
    assert parse_topology("v5p-32").bounds == (4, 4, 2)
    assert parse_topology("v5e-1").bounds == (1, 1)


def test_parse_explicit_shape():
    topo = parse_topology("v5e-2x4")
    assert topo.bounds == (2, 4)
    assert topo.generation.name == "v5e"
    # 2D shape on a 3D generation pads with trailing 1s
    assert parse_topology("v5p-2x2").bounds == (2, 2, 1)


def test_parse_fallback_factorization():
    topo = parse_topology("v5e-2")
    assert sorted(topo.bounds) == [1, 2]


def test_parse_rejects_garbage():
    with pytest.raises(ValueError):
        parse_topology("h100-8")
    with pytest.raises(ValueError):
        parse_topology("v5e")
    with pytest.raises(ValueError):
        parse_topology("v5e-2x2x2")  # 3D shape on 2D generation


def test_coords_and_index_roundtrip():
    topo = parse_topology("v5p-8")
    coords = topo.coords()
    assert len(coords) == 8
    for c in coords:
        assert coords[topo.index_of(c)] == c


def test_neighbors_mesh_interior_and_edge():
    topo = parse_topology("v5e-8")  # 2x4, no torus closure (< 4x4)
    assert topo.wraparound == (False, False)
    assert len(topo.neighbors((1, 1))) == 3
    assert len(topo.neighbors((0, 0))) == 2


def test_parse_topology_sets_generation_wraparound():
    # v5e/v6e: 4x4-and-larger slices are wired as tori
    assert parse_topology("v5e-16").wraparound == (True, True)   # 4x4
    assert parse_topology("v5e-4x8").wraparound == (True, True)
    assert parse_topology("v5e-4").wraparound == (False, False)  # 2x2
    assert parse_topology("v5e-8").wraparound == (False, False)  # 2x4
    # v4/v5p: OCS closes cube-multiple axes; 2-extent axes stay meshes
    assert parse_topology("v5p-32").wraparound == (True, True, False)  # 4x4x2
    assert parse_topology("v5p-64").wraparound == (True, True, True)   # 4x4x4
    assert parse_topology("v5p-8").wraparound == (False, False, False)
    # a boundary chip on the closed 4x4 torus has a full set of 4 links
    assert len(parse_topology("v5e-16").neighbors((0, 0))) == 4


def test_neighbors_torus_wrap():
    topo = HostTopology(
        generation=GENERATIONS["v5e"], bounds=(4, 4), wraparound=(True, True)
    )
    assert len(topo.neighbors((0, 0))) == 4


def test_generation_table_sane():
    for gen in GENERATIONS.values():
        assert gen.hbm_bytes > 0
        assert gen.peak_bf16_tflops > 0
        assert gen.ici_dims in (2, 3)
        assert len(gen.default_host_shape) == gen.ici_dims


def test_as_slice_member_host_local_wraparound():
    """A host tile inherits the slice's torus closure only on axes it spans
    entirely (host_grid == 1 there); split axes wrap between hosts, which
    host-local allocation must not count."""
    host = parse_topology("v5e-2x4")  # (2, 4) host tile
    placed = as_slice_member(host, "v5e-4x4", worker_id=0)
    # slice (4,4) wraps both axes; host spans axis1 fully (grid (2,1))
    assert placed.host_grid == (2, 1)
    assert placed.wraparound == (False, True)
    # boundary chip gains its ring link on the spanned axis only
    assert (0, 0) in placed.neighbors((0, 3))

    small = as_slice_member(parse_topology("v5e-4"), "v5e-8", worker_id=0)
    assert small.wraparound == (False, False)  # 2x4 slice: no torus at all
