"""Perfetto/Chrome trace-event exporter (obs/export.py), direct tests.

The exporter was previously covered only incidentally through the HTTP
endpoints; this pins the conversion contract itself: every span record
becomes exactly one complete ("ph": "X") event, nested and CROSS-THREAD
spans keep their parent/child pairing through the args, components map
stably to track ids with one thread_name metadata event each, and the
file writer round-trips through JSON.
"""

import json
import threading

from k8s_gpu_device_plugin_tpu.obs.export import (
    to_chrome_trace,
    write_trace_file,
)
from k8s_gpu_device_plugin_tpu.obs.trace import Tracer, attach


def _build_trace():
    """One trace: serving root -> nested child (same thread) + a child
    ended on ANOTHER thread (the engine-hop shape), components split
    across two tracks."""
    tr = Tracer()
    tr.enabled = True
    root = tr.span("request", component="serving", rid=7)
    with attach(root):
        with tr.span("prefill", component="serving", bucket=32):
            pass
        cross = tr.span("decode_dispatch", component="serving_engine",
                        step=3)

    def end_on_worker():
        cross.end()

    t = threading.Thread(target=end_on_worker, name="engine-worker")
    t.start()
    t.join()
    root.end()
    spans = tr.get_trace(root.trace_id)
    assert spans is not None and len(spans) == 3
    return root, spans


def test_round_trip_event_pairing_and_track_ids():
    root, spans = _build_trace()
    doc = to_chrome_trace(spans)
    events = doc["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    # one complete event per span record, nothing invented or dropped
    assert len(complete) == len(spans) == 3
    by_name = {e["name"]: e for e in complete}

    # parent/child pairing survives: both children point at the root's
    # span_id, the root at None — the same ids the span records carry
    root_ev = by_name["request"]
    assert root_ev["args"]["parent_id"] is None
    assert root_ev["args"]["span_id"] == root.span_id
    for child in ("prefill", "decode_dispatch"):
        assert by_name[child]["args"]["parent_id"] == root.span_id
        assert by_name[child]["args"]["trace_id"] == root.trace_id

    # the cross-thread child records the worker thread it ENDED on
    assert by_name["decode_dispatch"]["args"]["thread"] == "engine-worker"

    # components -> stable track ids; one thread_name metadata event per
    # component, labeled with the component
    tids = {e["cat"]: e["tid"] for e in complete}
    assert set(tids) == {"serving", "serving_engine"}
    assert tids["serving"] != tids["serving_engine"]
    assert by_name["prefill"]["tid"] == root_ev["tid"]
    meta_by_tid = {e["tid"]: e["args"]["name"] for e in meta}
    assert meta_by_tid[tids["serving"]] == "serving"
    assert meta_by_tid[tids["serving_engine"]] == "serving_engine"

    # nesting is temporal: the child's window sits inside the root's
    assert root_ev["ts"] <= by_name["prefill"]["ts"]
    assert (by_name["prefill"]["ts"] + by_name["prefill"]["dur"]
            <= root_ev["ts"] + root_ev["dur"] + 1)  # 1us floor on dur

    # attrs ride through args, JSON-serializable
    assert root_ev["args"]["rid"] == 7
    assert by_name["prefill"]["args"]["bucket"] == 32
    assert by_name["decode_dispatch"]["args"]["step"] == 3


def test_zero_duration_spans_get_visible_floor():
    _, spans = _build_trace()
    for s in spans:
        s["dur_us"] = 0
    doc = to_chrome_trace(spans)
    assert all(
        e["dur"] >= 1 for e in doc["traceEvents"] if e["ph"] == "X"
    )


def test_non_serializable_attrs_are_stringified():
    _, spans = _build_trace()
    spans[0]["attrs"] = {"obj": object(), "ok": 1.5, "none": None}
    doc = to_chrome_trace(spans)
    json.dumps(doc)  # must not raise
    ev = next(e for e in doc["traceEvents"]
              if e["ph"] == "X" and e["name"] == spans[0]["name"])
    assert isinstance(ev["args"]["obj"], str)
    assert ev["args"]["ok"] == 1.5
    assert ev["args"]["none"] is None


def test_write_trace_file_round_trips(tmp_path):
    _, spans = _build_trace()
    path = write_trace_file(spans, str(tmp_path / "sub" / "trace.json"))
    with open(path) as f:
        loaded = json.load(f)
    assert loaded == json.loads(json.dumps(to_chrome_trace(spans)))
    assert loaded["displayTimeUnit"] == "ms"
