"""Allocate env contract end-to-end: plugin -> subprocess workload.

The control-plane half (daemon boots, Allocate answers, env contract lands
in a real subprocess) runs everywhere with the fake backend; the subprocess
asserts it received the exact ContainerAllocateResponse envs. The
real-chip half (subprocess actually computes on an allocated accelerator)
is exercised by ``bench.py`` / ``runner allocated`` on TPU hosts and is
skipped here (the CPU-mesh test env has no local accelerator).
"""

import json
import os
import subprocess
import sys

from k8s_gpu_device_plugin_tpu.benchmark.workloads.allocated_matmul import (
    _CHILD_CODE,
    allocated_matmul,
)


def test_allocate_env_contract_reaches_subprocess(tmp_path):
    result = allocated_matmul(topology="v5e-4", size=2, socket_dir=str(tmp_path))
    # control plane: the plugin answered with a concrete wiring
    assert result.backend_used in ("fake", "native")
    assert len(result.allocated_ids) == 2
    envs = result.envs
    assert envs["TPU_VISIBLE_CHIPS"]
    assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"]
    assert envs["TPU_ACCELERATOR_TYPE"].startswith("v5e")
    # workload side: the subprocess ran under that env and reported back
    # (cpu here — the test env has no local accelerator; device identity on
    # real chips is asserted by the runner's `allocated` workload)
    assert result.device_platform in ("cpu", "tpu")
    assert result.device_kind


def test_child_sees_allocate_envs(tmp_path):
    """The env block handed to the subprocess is exactly the allocation's."""
    probe = (
        "import os, json;"
        "print(json.dumps({k: v for k, v in os.environ.items()"
        " if k.startswith('TPU_')}))"
    )
    result = allocated_matmul(topology="v5e-4", size=4, socket_dir=str(tmp_path))
    env = {**os.environ, **result.envs}
    # -S: a sitecustomize in this environment mutates TPU_* vars at
    # interpreter start; the probe checks what the PLUGIN handed over
    proc = subprocess.run(
        [sys.executable, "-S", "-c", probe], env=env, capture_output=True, text=True
    )
    seen = json.loads(proc.stdout)
    for key, val in result.envs.items():
        if key.startswith("TPU_"):
            assert seen[key] == val


def test_allocated_workload_over_native_backend(tmp_path, monkeypatch):
    """The Allocate env contract fed by the NATIVE enumerator (synthetic
    /dev/accel tree): TPU_VISIBLE_CHIPS et al. must come from the C++
    core's enumeration, not the fake backend (r2 verdict weak #1 noted the
    bench only ever exercised 'fake')."""
    from tests.test_native_backend import ensure_lib

    ensure_lib()
    root = tmp_path / "host"
    (root / "dev").mkdir(parents=True)
    (root / "etc").mkdir()
    (root / "etc" / "machine-id").write_text("allocnative0001\n")
    accel = root / "sys" / "class" / "accel"
    for i in range(4):
        (root / "dev" / f"accel{i}").write_text("")
        dev_dir = accel / f"accel{i}" / "device"
        dev_dir.mkdir(parents=True)
        (dev_dir / "numa_node").write_text("0\n")
        (dev_dir / "device").write_text("0x0063\n")  # v5e
    monkeypatch.setenv("TPUENUM_ROOT", str(root))

    sock = tmp_path / "sock"
    sock.mkdir()
    result = allocated_matmul(topology="auto", size=2, socket_dir=str(sock))
    assert result.backend_used == "native"
    assert len(result.allocated_ids) == 2
    # env contract derived from the native enumeration
    chips = {c for c in result.envs["TPU_VISIBLE_CHIPS"].split(",")}
    assert chips <= {"0", "1", "2", "3"} and len(chips) == 2
    assert result.envs["TPU_ACCELERATOR_TYPE"].startswith("v5e")
    assert result.device_kind  # subprocess ran under the env and reported
