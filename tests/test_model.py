"""Model + parallelism tests on the virtual 8-device CPU mesh.

Correctness oracles: ring attention and Ulysses attention must match the
plain f32 reference attention on identical inputs; the sharded train step
must produce finite, decreasing loss on a tiny overfit batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
    param_specs,
)
from k8s_gpu_device_plugin_tpu.models.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
    synthetic_batch,
)
from k8s_gpu_device_plugin_tpu.ops.attention import mha_reference
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh
from k8s_gpu_device_plugin_tpu.parallel.ring_attention import ring_attention
from k8s_gpu_device_plugin_tpu.parallel.ulysses import ulysses_attention


def require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


@pytest.fixture(scope="module")
def sp_mesh():
    require_devices(4)
    return make_mesh(MeshSpec(dp=1, sp=4), jax.devices()[:4])


def make_qkv(key, b=2, s=64, hq=8, hkv=4, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, hq, d), dtype)
    k = jax.random.normal(kk, (b, s, hkv, d), dtype)
    v = jax.random.normal(kv, (b, s, hkv, d), dtype)
    return q, k, v


def test_ring_attention_matches_reference(sp_mesh):
    q, k, v = make_qkv(jax.random.key(0))
    expected = mha_reference(q, k, v, causal=True)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, sp_mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ring_attention_non_causal(sp_mesh):
    q, k, v = make_qkv(jax.random.key(1))
    expected = mha_reference(q, k, v, causal=False)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, sp_mesh, causal=False)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ulysses_matches_reference(sp_mesh):
    q, k, v = make_qkv(jax.random.key(2))
    expected = mha_reference(q, k, v, causal=True)
    got = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, sp_mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    q, k, v = make_qkv(jax.random.key(3), hq=6, hkv=6)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, sp_mesh)


def test_forward_shapes_single_device():
    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((2, 32), jnp.int32)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_param_specs_cover_params():
    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.key(0), cfg)
    specs = param_specs(cfg)
    assert jax.tree.structure(params) == jax.tree.structure(
        specs, is_leaf=lambda x: not isinstance(x, dict)
    )


def test_sharded_train_step_loss_decreases():
    require_devices(8)
    mesh = make_mesh(MeshSpec(dp=1, fsdp=2, tp=2, sp=2), jax.devices()[:8])
    cfg = LlamaConfig.tiny(attn_impl="ring")
    optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=50)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    train_step = make_train_step(cfg, mesh, optimizer)

    first_loss = None
    for _ in range(8):
        state, metrics = train_step(state, batch)
        if first_loss is None:
            first_loss = float(metrics["loss"])
    last_loss = float(metrics["loss"])
    assert np.isfinite(first_loss) and np.isfinite(last_loss)
    assert last_loss < first_loss  # overfitting one batch must reduce loss
    assert float(metrics["grad_norm"]) > 0


def test_graft_entry_single():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
    assert bool(jnp.isfinite(out).all())


def test_graft_entry_multichip():
    require_devices(8)
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
