"""Model + parallelism tests on the virtual 8-device CPU mesh.

Correctness oracles: ring attention and Ulysses attention must match the
plain f32 reference attention on identical inputs; the sharded train step
must produce finite, decreasing loss on a tiny overfit batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
    param_specs,
)
from k8s_gpu_device_plugin_tpu.models.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
    synthetic_batch,
)
from k8s_gpu_device_plugin_tpu.ops.attention import mha_reference
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh
from k8s_gpu_device_plugin_tpu.parallel.ring_attention import ring_attention
from k8s_gpu_device_plugin_tpu.parallel.ulysses import ulysses_attention


def require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


@pytest.fixture(scope="module")
def sp_mesh():
    require_devices(4)
    return make_mesh(MeshSpec(dp=1, sp=4), jax.devices()[:4])


def make_qkv(key, b=2, s=64, hq=8, hkv=4, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, hq, d), dtype)
    k = jax.random.normal(kk, (b, s, hkv, d), dtype)
    v = jax.random.normal(kv, (b, s, hkv, d), dtype)
    return q, k, v


def test_ring_attention_matches_reference(sp_mesh):
    q, k, v = make_qkv(jax.random.key(0))
    expected = mha_reference(q, k, v, causal=True)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, sp_mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_path_matches_reference(sp_mesh, causal):
    """Shard shapes that pass _flash_ok (s=512/sp=4 -> lq=128, d=64): the
    Pallas flash kernel + lse softmax-merge path, values AND grads."""
    from k8s_gpu_device_plugin_tpu.parallel.ring_attention import _flash_ok

    assert _flash_ok(128, 128, 64), "shapes no longer hit the flash path"
    q, k, v = make_qkv(jax.random.key(2), b=1, s=512, hq=4, hkv=2, d=64)

    def ref_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, sp_mesh, causal=causal) ** 2)

    expected = mha_reference(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, sp_mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-4)

    grads_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    grads_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for gr, gg in zip(grads_ref, grads_ring):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(gr), atol=5e-3, rtol=1e-3
        )


def test_ring_attention_non_causal(sp_mesh):
    q, k, v = make_qkv(jax.random.key(1))
    expected = mha_reference(q, k, v, causal=False)
    got = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, sp_mesh, causal=False)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ulysses_matches_reference(sp_mesh):
    q, k, v = make_qkv(jax.random.key(2))
    expected = mha_reference(q, k, v, causal=True)
    got = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, sp_mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ulysses_rejects_indivisible_heads(sp_mesh):
    q, k, v = make_qkv(jax.random.key(3), hq=6, hkv=6)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, sp_mesh)


def test_forward_shapes_single_device():
    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((2, 32), jnp.int32)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


def test_param_specs_cover_params():
    cfg = LlamaConfig.tiny()
    params = init_params(jax.random.key(0), cfg)
    specs = param_specs(cfg)
    assert jax.tree.structure(params) == jax.tree.structure(
        specs, is_leaf=lambda x: not isinstance(x, dict)
    )


def test_sharded_train_step_loss_decreases():
    require_devices(8)
    mesh = make_mesh(MeshSpec(dp=1, fsdp=2, tp=2, sp=2), jax.devices()[:8])
    cfg = LlamaConfig.tiny(attn_impl="ring")
    optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=50)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    train_step = make_train_step(cfg, mesh, optimizer)

    first_loss = None
    for _ in range(8):
        state, metrics = train_step(state, batch)
        if first_loss is None:
            first_loss = float(metrics["loss"])
    last_loss = float(metrics["loss"])
    assert np.isfinite(first_loss) and np.isfinite(last_loss)
    assert last_loss < first_loss  # overfitting one batch must reduce loss
    assert float(metrics["grad_norm"]) > 0


def test_graft_entry_single():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
    assert bool(jnp.isfinite(out).all())


def test_graft_entry_multichip():
    require_devices(8)
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_graft_entry_forces_cpu_before_backend_init():
    """The driver scenario: fresh process, a NON-cpu platform pinned in the
    env, no host-device-count flag. _acquire_devices must reach the virtual
    CPU mesh without ever initializing the pinned platform (round-1 failure:
    it hung inside jax.devices() on a wedged tunneled backend)."""
    import os
    import subprocess
    import sys

    env = {
        k: v for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["JAX_PLATFORMS"] = "tpu"  # pinned non-cpu platform (no TPU attached)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "from __graft_entry__ import _acquire_devices\n"
        "devices = _acquire_devices(8)\n"
        "assert len(devices) == 8, devices\n"
        "assert devices[0].platform == 'cpu', devices[0]\n"
        "print('fallback-ok')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "fallback-ok" in proc.stdout


def test_lm_head_matmul_numerics_and_grads():
    """bf16-operand head projection: f32 accumulation keeps logits close to
    the full-f32 product, and the custom vjp produces grads matching
    autodiff of the plain dot to bf16 precision."""
    import jax
    import jax.numpy as jnp

    from k8s_gpu_device_plugin_tpu.models.llama import _lm_head_matmul

    key = jax.random.key(7)
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (4, 32, 64), jnp.bfloat16)
    w = jax.random.normal(kw, (64, 128), jnp.bfloat16)

    out = _lm_head_matmul(x, w)
    assert out.dtype == jnp.float32
    ref = jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))
    assert jnp.allclose(out, ref, atol=2e-1, rtol=2e-2)

    def loss_new(x, w):
        return jnp.sum(jnp.sin(_lm_head_matmul(x, w)))

    def loss_ref(x, w):
        return jnp.sum(jnp.sin(jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))))

    gx, gw = jax.grad(loss_new, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    assert gx.dtype == x.dtype and gw.dtype == w.dtype
    assert jnp.allclose(gx.astype(jnp.float32), rx.astype(jnp.float32), atol=0.5, rtol=0.1)
    assert jnp.allclose(gw.astype(jnp.float32), rw.astype(jnp.float32), atol=0.5, rtol=0.1)

def test_grad_accum_matches_unaccumulated():
    """grad_accum=A must produce the same update as one full-batch step:
    same loss metric and (up to bf16 grad-cast noise) the same params.

    Plain SGD, not make_optimizer: the warmup schedule's LR is 0.0 at the
    first step, which would zero both updates and make the param
    comparison vacuous (init == init)."""
    import optax

    require_devices(4)
    mesh = make_mesh(MeshSpec(dp=2, tp=2), jax.devices()[:4])
    cfg = LlamaConfig.tiny()
    optimizer = optax.sgd(1e-2)
    batch = synthetic_batch(jax.random.key(1), cfg, 8, 64, mesh)

    state1 = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    step1 = make_train_step(cfg, mesh, optimizer, grad_accum=1)
    state1, m1 = step1(state1, batch)

    state4 = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    step4 = make_train_step(cfg, mesh, optimizer, grad_accum=4)
    state4, m4 = step4(state4, batch)

    # each microbatch is a uniform mean over equally many tokens, so the
    # mean-of-means equals the full-batch mean
    np.testing.assert_allclose(
        float(m1["loss"]), float(m4["loss"]), rtol=2e-2
    )
    for a, b in zip(
        jax.tree.leaves(state1["params"]), jax.tree.leaves(state4["params"])
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=5e-2
        )
    assert float(m4["grad_norm"]) > 0


def test_grad_accum_rejects_indivisible_batch():
    require_devices(2)
    mesh = make_mesh(MeshSpec(dp=2), jax.devices()[:2])
    cfg = LlamaConfig.tiny()
    optimizer = make_optimizer(total_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 6, 64, mesh)
    step = make_train_step(cfg, mesh, optimizer, grad_accum=4)
    import pytest

    with pytest.raises(ValueError, match="not divisible"):
        step(state, batch)


def test_grad_accum_params_actually_move():
    """Companion to the equivalence test: the sgd update must be nonzero,
    or the param comparison there would be vacuous."""
    import optax

    require_devices(2)
    mesh = make_mesh(MeshSpec(dp=2), jax.devices()[:2])
    cfg = LlamaConfig.tiny()
    optimizer = optax.sgd(1e-2)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    before = jax.tree.map(lambda x: np.asarray(x, np.float32), state["params"])
    batch = synthetic_batch(jax.random.key(1), cfg, 8, 64, mesh)
    step = make_train_step(cfg, mesh, optimizer, grad_accum=2)
    state, _ = step(state, batch)
    moved = any(
        not np.array_equal(a, np.asarray(b, np.float32))
        for a, b in zip(
            jax.tree.leaves(before), jax.tree.leaves(state["params"])
        )
    )
    assert moved


def test_master_weights_forward_matches_bf16_storage():
    """param_dtype=f32 must not change the computation: weights are cast to
    the compute dtype before every matmul, so logits match a bf16-stored
    model whose weights are the cast of the same f32 values."""
    cfg32 = LlamaConfig.tiny(param_dtype=jnp.float32)
    cfg16 = LlamaConfig.tiny()
    p32 = init_params(jax.random.key(0), cfg32)
    p16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), p32)
    assert p32["layers"]["wq"].dtype == jnp.float32
    tokens = jnp.arange(16, dtype=jnp.int32)[None, :]
    np.testing.assert_allclose(
        np.asarray(forward(p32, tokens, cfg32)),
        np.asarray(forward(p16, tokens, cfg16)),
        atol=1e-6,
    )


def test_master_weights_retain_sub_ulp_updates():
    """The reason master weights exist: an SGD update far below the bf16
    ulp must move f32 params while leaving bf16 params bit-identical."""
    import optax

    require_devices(2)
    mesh = make_mesh(MeshSpec(dp=2), jax.devices()[:2])
    batch = synthetic_batch(
        jax.random.key(1), LlamaConfig.tiny(), 4, 32, mesh
    )
    # lr chosen so a typical update (lr * grad) lands BETWEEN the f32 ulp
    # (~2e-9 at weight scale 0.02) and the bf16 ulp (~1e-4): f32 retains
    # it, bf16 rounds it away
    tiny_lr = optax.sgd(1e-4)

    def moved_fraction(cfg):
        state = init_train_state(jax.random.key(0), cfg, mesh, tiny_lr)
        before = jax.tree.map(lambda x: np.asarray(x, np.float64), state["params"])
        state, _ = make_train_step(cfg, mesh, tiny_lr)(state, batch)
        after = jax.tree.map(lambda x: np.asarray(x, np.float64), state["params"])
        changed = total = 0
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            changed += int((a != b).sum())
            total += a.size
        return changed / total

    # master weights accumulate the sub-ulp update almost everywhere;
    # bf16 rounds it away except at near-zero weights whose ulp is tiny
    assert moved_fraction(LlamaConfig.tiny(param_dtype=jnp.float32)) > 0.5
    assert moved_fraction(LlamaConfig.tiny()) < 0.01


def test_master_weights_moe_router_stays_f32_and_trains():
    require_devices(4)
    mesh = make_mesh(MeshSpec(dp=1, tp=2, ep=2), jax.devices()[:4])
    cfg = LlamaConfig.tiny(
        n_experts=4, param_dtype=jnp.float32, capacity_factor=4.0
    )
    optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=20)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    assert state["params"]["layers"]["router"].dtype == jnp.float32
    assert state["params"]["layers"]["moe_w1"].dtype == jnp.float32
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    step = make_train_step(cfg, mesh, optimizer)
    first = None
    for _ in range(6):
        state, m = step(state, batch)
        if first is None:
            first = float(m["loss"])
    assert float(m["loss"]) < first


def test_ulysses_routes_through_dispatcher(sp_mesh, monkeypatch):
    """Ulysses must call the dispatching attention entry point (flash on
    TPU), not the score-materializing reference directly."""
    import importlib

    attn_mod = importlib.import_module("k8s_gpu_device_plugin_tpu.ops.attention")

    calls = []
    orig = attn_mod.attention

    def spy(*args, **kw):
        calls.append(1)
        return orig(*args, **kw)

    monkeypatch.setattr(attn_mod, "attention", spy)
    q, k, v = make_qkv(jax.random.key(4))
    out = ulysses_attention(q, k, v, sp_mesh, causal=True)
    assert calls, "ulysses bypassed the attention dispatcher"
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(mha_reference(q, k, v, causal=True)),
        atol=2e-5,
    )


def test_fused_opt_train_step_matches_optax():
    """impl="fused" must walk the SAME trajectory as the optax chain: same
    params and same loss curve over several sharded steps (bit-level drift
    from reassociated f32 elementwise math stays within tight tolerance)."""
    require_devices(8)
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2), jax.devices()[:8])
    cfg = LlamaConfig.tiny()
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)

    def run(impl):
        optimizer = make_optimizer(
            learning_rate=1e-2, warmup_steps=1, total_steps=50, impl=impl
        )
        state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
        step = make_train_step(cfg, mesh, optimizer)
        losses = []
        for _ in range(4):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return state, losses

    s_opt, l_opt = run("optax")
    s_fused, l_fused = run("fused")
    assert l_fused == pytest.approx(l_opt, rel=1e-4)
    for a, b in zip(
        jax.tree.leaves(s_opt["params"]), jax.tree.leaves(s_fused["params"])
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0, atol=2e-2,  # bf16 params: one ulp at |w|~1
        )
    # fused opt state is a plain pytree dict (checkpointable) with the
    # param shardings on the moments
    assert set(s_fused["opt_state"]) == {"mu", "nu", "count"}
    mu_leaf = jax.tree.leaves(s_fused["opt_state"]["mu"])[0]
    p_leaf = jax.tree.leaves(s_fused["params"])[0]
    assert mu_leaf.sharding == p_leaf.sharding
    assert int(s_fused["opt_state"]["count"][()]) == 4


@pytest.mark.parametrize("window", [5, 16, 48])
def test_windowed_ring_attention_einsum_path(sp_mesh, window):
    """Sliding-window ring attention (einsum fallback shapes) vs the
    windowed full-context oracle: global window masking must survive the
    ring decomposition at every W regime (W < shard, W ~ shard, W > S/2)."""
    q, k, v = make_qkv(jax.random.key(7))
    expected = mha_reference(q, k, v, causal=True, window=window)
    got = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, sp_mesh, causal=True, window=window
        )
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-5
    )


@pytest.mark.parametrize("window", [100, 300])
def test_windowed_ring_attention_flash_path(sp_mesh, window):
    """Flash-path shapes (lq=128): W=100 exercises diagonal-windowed +
    straddling + fully-outside branches; W=300 adds fully-inside. Values
    AND grads vs the windowed oracle."""
    from k8s_gpu_device_plugin_tpu.parallel.ring_attention import _flash_ok

    assert _flash_ok(128, 128, 64)
    q, k, v = make_qkv(jax.random.key(8), b=1, s=512, hq=4, hkv=2, d=64)

    def ref_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True, window=window) ** 2)

    def ring_loss(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, sp_mesh, causal=True, window=window) ** 2
        )

    expected = mha_reference(q, k, v, causal=True, window=window)
    got = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, sp_mesh, causal=True, window=window
        )
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=2e-2, rtol=2e-2
    )
    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    got_grads = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for rg, gg in zip(ref_grads, got_grads):
        np.testing.assert_allclose(
            np.asarray(gg), np.asarray(rg), atol=5e-2, rtol=5e-2
        )


def test_windowed_model_forward_ring_matches_single_device(sp_mesh):
    """A sliding-window config forwards identically under ring/sp and on
    a single shard (the dispatcher no longer rejects windowed sp)."""
    cfg = LlamaConfig.tiny(sliding_window=24, attn_impl="ring")
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0,
                                cfg.vocab_size, jnp.int32)
    sharded = forward(params, tokens, cfg, sp_mesh)
    single = forward(params, tokens, cfg, None)
    np.testing.assert_allclose(
        np.asarray(sharded, np.float32), np.asarray(single, np.float32),
        atol=5e-2,
    )
