"""Automatic prefix caching (serving/prefix_cache.py + the batcher's
submit-match/promotion hooks).

Two layers of claims:

- **Radix-tree mechanics** (host-only, stub rows): bucket-aligned
  matching, longest-match, the len-1 cap, adapter keying, min-hit
  promotion, LRU eviction under the HBM byte budget.
- **Bit-exactness**: greedy and seeded token AND logprob streams are
  identical with the cache on vs off, across admit/retire/cancel/
  eviction interleavings — a cache hit replays the exact K/V rows the
  full prefill would have computed, so the cache is invisible in the
  outputs and only visible in the prefill-token accounting.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import (
    ContinuousBatcher,
    _precompute_prefix,
    precompute_prefix,
)
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.prefix_cache import (
    PrefixCache,
    prefix_kv_bytes,
)

BUCKETS = (8, 16, 32)


@pytest.fixture(scope="module")
def setup():
    # the SAME config and batcher shapes as the neighboring serving test
    # modules, so the forward/decode jit compiles are shared across the
    # suite (the tier-1 run is wall-clock-tight; only the prefix-path
    # jits — extract/insert/precompute — are this module's own)
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=max_new
    )
    return np.asarray(out)[0].tolist()


def _make_cache(cfg, budget_bytes=1 << 26, **kw):
    return PrefixCache(cfg, buckets=BUCKETS, budget_bytes=budget_bytes, **kw)


def _batcher(params, cfg, pc, depth=1, n_slots=2, kv_layout="dense"):
    return ContinuousBatcher(
        params, cfg, n_slots=n_slots, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, pipeline_depth=depth, prefix_cache=pc,
        kv_layout=kv_layout,
        kv_page_size=16 if kv_layout == "paged" else None,
    )


# --- radix-tree mechanics (stub rows: no model, no device) ------------------


def _stub_insert(pc, tokens, adapter=-1):
    pc.on_prefill_done(tokens, adapter, lambda p: f"rows[:{p}]")


def test_radix_longest_match_at_boundaries(setup):
    cfg, _ = setup
    pc = _make_cache(cfg)
    base = _prompt(1, 32, cfg)
    _stub_insert(pc, base)  # promotes boundaries 8, 16, 32
    assert pc.stats.entries == 3

    # longest boundary prefix wins, capped at len-1: a 32-token prompt
    # equal to the cached prefix may only match 16 (one suffix token
    # must remain to sample from)
    state, n = pc.match(base, -1)
    assert n == 16 and state.tokens == tuple(base[:16])
    state, n = pc.match(base + _prompt(2, 3, cfg), -1)
    assert n == 32 and state.tokens == tuple(base)
    # divergence after 16: the 8- and 16-boundaries still match
    state, n = pc.match(base[:16] + _prompt(3, 10, cfg), -1)
    assert n == 16
    state, n = pc.match(base[:8] + _prompt(4, 10, cfg), -1)
    assert n == 8
    # divergence inside the first bucket: miss
    assert pc.match(_prompt(5, 20, cfg), -1) is None
    assert pc.stats.misses == 1 and pc.stats.hits == 4


def test_radix_adapter_keying(setup):
    """The same token prefix under different adapters is two distinct
    cache lines — a hit can never cross weights."""
    cfg, _ = setup
    pc = _make_cache(cfg)
    toks = _prompt(6, 16, cfg)
    _stub_insert(pc, toks, adapter=0)
    assert pc.match(toks + [1, 2], adapter=0) is not None
    assert pc.match(toks + [1, 2], adapter=-1) is None
    assert pc.match(toks + [1, 2], adapter=1) is None
    state, _ = pc.match(toks + [1, 2], adapter=0)
    assert state.adapter == 0  # submit's weights guard can never fire


def test_match_gated_and_capped_by_chunk_window(setup):
    """With a chunk size bound (the batcher sets it), matches that skip
    no chunk dispatch are refused — savings are whole-chunk-granular:
    the scheduler runs fixed-C chunks from the prefix boundary plus the
    same finish chunk either way — and reuse accounting reports the
    dispatch work actually skipped."""
    cfg, _ = setup
    pc = _make_cache(cfg)
    pc.chunk = 8  # what ContinuousBatcher.__init__ binds
    base = _prompt(11, 16, cfg)
    _stub_insert(pc, base)
    assert pc.match(base[:8], -1) is None       # len == chunk: refused
    # len 9, matched 8: the cold run's [0,8) chunk dispatch is skipped
    # (the finish window computes [1,9) in both runs)
    assert pc.match(base[:8] + [1], -1) is not None
    assert pc.stats.tokens_saved == 8
    _, n = pc.match(base + [1, 2], -1)          # len 18, matched 16
    assert n == 16
    assert pc.stats.tokens_saved == 8 + 16      # two chunks skipped
    # a match that skips zero dispatches (the chunk grid just shifts:
    # ceil(16/8) == ceil(12/8) intermediate+finish dispatches) is
    # refused and counted as a miss, not a phantom-savings hit
    pc2 = _make_cache(cfg)
    pc2.chunk = 8
    pc2.buckets = (4, 8, 16, 32)
    _stub_insert(pc2, base[:4])
    assert pc2.effective_reuse(4, 16) == 0
    assert pc2.match(base[:4] + _prompt(12, 12, cfg), -1) is None
    assert pc2.stats.misses == 1 and pc2.stats.hits == 0


def test_min_hits_defers_promotion(setup):
    cfg, _ = setup
    pc = _make_cache(cfg, min_hits=2)
    toks = _prompt(7, 16, cfg)
    _stub_insert(pc, toks)
    assert pc.stats.entries == 0  # seen once: counted, not materialized
    _stub_insert(pc, toks)
    assert pc.stats.entries == 2  # second sighting: boundaries 8 and 16
    assert pc.match(toks + [1], -1) is not None


def test_lru_eviction_under_byte_budget(setup):
    cfg, _ = setup
    b8 = prefix_kv_bytes(cfg, 8)
    pc = _make_cache(cfg, budget_bytes=2 * b8)  # room for two 8-entries
    p1, p2, p3 = (_prompt(k, 8, cfg) for k in (8, 9, 10))
    _stub_insert(pc, p1)
    _stub_insert(pc, p2)
    assert pc.stats.entries == 2 and pc.stats.evictions == 0
    pc.match(p1 + [1], -1)  # touch p1: p2 becomes LRU
    _stub_insert(pc, p3)
    assert pc.stats.entries == 2 and pc.stats.evictions == 1
    assert pc.stats.resident_bytes <= 2 * b8
    assert pc.match(p1 + [1], -1) is not None  # survivor (recently used)
    assert pc.match(p2 + [1], -1) is None      # the LRU victim
    assert pc.match(p3 + [1], -1) is not None
    # an entry bigger than the whole budget is skipped, not evicted-for
    pc_small = _make_cache(cfg, budget_bytes=b8 // 2)
    _stub_insert(pc_small, p1)
    assert pc_small.stats.entries == 0


def test_prefix_kv_bytes_tracks_cache_dtype(setup):
    """The budget is denominated in real HBM bytes: int8 halves the bf16
    row cost (plus scale planes), int4 halves it again."""
    cfg, _ = setup
    from dataclasses import replace

    bf16 = prefix_kv_bytes(cfg, 64)
    i8 = prefix_kv_bytes(replace(cfg, cache_quant="int8"), 64)
    i4 = prefix_kv_bytes(replace(cfg, cache_quant="int4"), 64)
    assert bf16 == 2 * 64 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2
    assert i8 < bf16 and i4 < i8


def test_cache_requires_chunked_prefill_and_opt_out(setup):
    cfg, params = setup
    pc = _make_cache(cfg)
    with pytest.raises(ValueError, match="chunked_prefill"):
        ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                          prompt_buckets=BUCKETS, prefix_cache=pc)

    class _NoPrefix(ContinuousBatcher):
        supports_prefix_cache = False  # a subclass opting out

    with pytest.raises(ValueError, match="does not support"):
        _NoPrefix(params, cfg, n_slots=1, max_len=64,
                  prompt_buckets=BUCKETS, chunked_prefill=8,
                  prefix_cache=pc)

    # the batcher rebinds a fresh cache's ladder to its own, but a cache
    # already holding entries promoted on a DIFFERENT ladder is refused
    # (its tree edges span those boundaries; re-keying would corrupt it)
    pc2 = _make_cache(cfg)
    _stub_insert(pc2, _prompt(99, 16, cfg))
    with pytest.raises(ValueError, match="different bucket ladder"):
        ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                          prompt_buckets=(16, 64), chunked_prefill=8,
                          prefix_cache=pc2)


# --- bit-exactness: cache on vs off ----------------------------------------
#
# One scheduling scenario, run four ways (cache {on, off} x pipeline
# {0, 1}): staggered waves over shared system prompts — greedy and
# seeded requests MIXED in the same batch — a cancel landing mid-flight,
# and a byte budget small enough that promotions evict live entries
# mid-run. Completed requests must produce identical tokens AND logprobs
# in all four runs; the cancelled request's partial stream must agree on
# the common prefix.


def _scenario(params, cfg, cache_on, depth):
    # room for ONE {8, 16} boundary set: promoting the second system
    # prompt's boundaries must evict the first's mid-run
    b = prefix_kv_bytes(cfg, 8) + prefix_kv_bytes(cfg, 16)
    pc = _make_cache(cfg, budget_bytes=b) if cache_on else None
    cb = _batcher(params, cfg, pc, depth=depth)
    sys_a = _prompt(20, 17, cfg)
    sys_b = _prompt(21, 18, cfg)
    rids = []

    def sub(base, tail_key, tail_n, new, seed=None):
        p = base + _prompt(tail_key, tail_n, cfg)
        rids.append(cb.submit(p, max_new=new, seed=seed))

    # wave 1: two requests sharing sys_a (promotions happen here); one
    # greedy, one seeded — both exactness regimes in one batch
    sub(sys_a, 30, 5, 5)
    sub(sys_a, 31, 4, 4, seed=4)
    for _ in range(7):
        cb.step()
    # wave 2: sys_a again (should hit) + sys_b (miss, then promote)
    sub(sys_a, 32, 6, 5, seed=5)
    sub(sys_b, 33, 5, 6)
    for _ in range(4):
        cb.step()
    cancelled = rids[2]
    cb.cancel(cancelled)  # mid-flight: pending, prefilling or decoding
    # wave 3: both prefixes again — under this budget the sys_b
    # promotions evicted sys_a entries, so this mixes hits and re-misses
    sub(sys_b, 34, 4, 4, seed=7)
    sub(sys_a, 35, 3, 5)
    cb.run()
    streams = {
        rid: (list(req.out), list(req.out_logp))
        for rid, req in cb.done_requests.items()
    }
    return rids, cancelled, streams, pc


def test_cache_on_off_bit_identical(setup):
    cfg, params = setup
    # (off, 1) is omitted: pipelined==sync with no cache is already
    # test_pipelined_decode's pinned claim — here the cache is the axis
    runs = {
        (on, depth): _scenario(params, cfg, on, depth)
        for on, depth in [(False, 0), (True, 0), (True, 1)]
    }
    ref_rids, ref_cancel, ref_streams, _ = runs[(False, 0)]
    for key, (rids, cancelled, streams, pc) in runs.items():
        assert rids == ref_rids and cancelled == ref_cancel
        for i, rid in enumerate(rids):
            if rid == cancelled:
                # cancel lands at a run-dependent generation depth (the
                # cache changes how many steps prefill takes), so the
                # partial streams may differ in LENGTH across runs — but
                # their common prefix must still be bit-identical
                toks, lps = streams[rid]
                rt, rl = ref_streams[rid]
                n = min(len(toks), len(rt))
                assert toks[:n] == rt[:n], key
                assert lps[:n] == pytest.approx(rl[:n]), key
            else:
                assert streams[rid] == ref_streams[rid], (key, i)
        if key[0]:  # cache-on runs must actually exercise the machinery
            assert pc.stats.promotions > 0
            assert pc.stats.hits > 0
            assert pc.stats.evictions > 0


@pytest.mark.parametrize("kv_layout", ["dense", "paged"])
def test_cached_streams_match_generate_oracle(setup, kv_layout):
    """Beyond on/off equality: greedy cached streams equal dedicated
    ``generate`` over the full prompt (the absolute reference) — under
    the paged layout the hits are zero-copy page aliases, and the
    streams must not notice."""
    cfg, params = setup
    pc = _make_cache(cfg)
    cb = _batcher(params, cfg, pc, kv_layout=kv_layout)
    sys_p = _prompt(40, 20, cfg)
    prompts = {}
    # sequential waves so later submissions really hit the cache
    for i, (k, n, new) in enumerate([(41, 5, 5), (42, 4, 4)]):
        p = sys_p + _prompt(k, n, cfg)
        rid = cb.submit(p, max_new=new)
        prompts[rid] = (p, new)
        cb.run()
    assert pc.stats.hits >= 1 and pc.stats.tokens_saved > 0
    for rid, (p, new) in prompts.items():
        assert cb.done[rid] == _oracle(params, p, cfg, new), rid


def test_auto_match_never_crosses_adapters(setup):
    """The automatic path inherits the weights guard BY KEY: a prefix
    promoted under the base model is invisible to adapter requests (and
    vice versa), so submit's PrefixState.adapter check can never trip on
    a cache hit."""
    cfg, params = setup
    pc = _make_cache(cfg)
    cb = _batcher(params, cfg, pc)
    p = _prompt(50, 20, cfg)
    cb.submit(p + _prompt(51, 4, cfg), max_new=4)
    cb.run()
    assert pc.stats.entries > 0
    # same tokens, different adapter key: pure miss, no exception
    assert pc.match(p + [1, 2], adapter=0) is None


def test_prefill_token_accounting(setup):
    """prefill_tokens_total{source}: the cached run reports fewer
    computed tokens and a nonzero reused count; the cold run reuses
    nothing (satellite: tokens saved directly observable)."""
    cfg, params = setup

    class Rec:
        computed = reused = 0

        def on_prefill_tokens(self, n, source):
            if source == "computed":
                Rec.computed += n
            else:
                Rec.reused += n

        def on_submit(self): ...
        def on_prefill_chunk(self): ...
        def on_first_token(self): ...
        def on_step(self, *a): ...
        def on_finish(self, reason): ...

    def run(cache_on):
        Rec.computed = Rec.reused = 0
        pc = _make_cache(cfg) if cache_on else None
        cb = ContinuousBatcher(
            params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
            chunked_prefill=8, prefix_cache=pc, metrics=Rec(),
        )
        sys_p = _prompt(60, 16, cfg)
        for k in (61, 62):
            cb.submit(sys_p + _prompt(k, 5, cfg), max_new=3)
            cb.run()
        return Rec.computed, Rec.reused

    cold_computed, cold_reused = run(False)
    cached_computed, cached_reused = run(True)
    assert cold_reused == 0
    assert cached_reused > 0
    assert cached_computed < cold_computed


def test_serving_metrics_prefix_surface():
    """The prometheus side of the new counters registers, updates and
    unregisters cleanly (labelled prefill_tokens_total included)."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.on_prefill_tokens(40, "computed")
    m.on_prefill_tokens(24, "prefix_reused")
    m.on_prefix_hit(24)
    m.on_prefix_miss()
    m.on_prefix_evict(4096)
    m.set_prefix_resident_bytes(8192, 2)
    g = reg.get_sample_value
    pre = "tpu_serving"
    assert g(f"{pre}_prefill_tokens_total",
             {"source": "computed"}) == 40
    assert g(f"{pre}_prefill_tokens_total",
             {"source": "prefix_reused"}) == 24
    assert g(f"{pre}_prefix_cache_hits_total") == 1
    assert g(f"{pre}_prefix_cache_misses_total") == 1
    assert g(f"{pre}_prefix_cache_evictions_total") == 1
    assert g(f"{pre}_prefix_cache_tokens_saved_total") == 24
    assert g(f"{pre}_prefix_cache_resident_bytes") == 8192
    assert g(f"{pre}_prefix_cache_entries") == 2
    m.close()
    m2 = ServingMetrics(registry=reg)  # re-register on the same registry
    m2.close()


# --- satellite: precompute_prefix compiles per bucket, not per length -------


def test_precompute_prefix_shares_compiles_within_bucket(setup):
    """Two prefixes of different lengths inside one bucket must reuse a
    single _precompute_prefix trace (the padded forward); the padded
    rows are sliced back so PrefixState still covers exactly the real
    tokens."""
    cfg, params = setup
    base = _precompute_prefix._cache_size()
    s1 = precompute_prefix(params, _prompt(70, 10, cfg), cfg,
                           prompt_buckets=BUCKETS)
    after_first = _precompute_prefix._cache_size()
    assert after_first == base + 1
    s2 = precompute_prefix(params, _prompt(71, 13, cfg), cfg,
                           prompt_buckets=BUCKETS)
    assert _precompute_prefix._cache_size() == after_first  # shared trace
    assert s1.rows.k.shape[2] == 10 and s2.rows.k.shape[2] == 13
    # a third length in ANOTHER bucket traces again
    precompute_prefix(params, _prompt(72, 20, cfg), cfg,
                      prompt_buckets=BUCKETS)
    assert _precompute_prefix._cache_size() == after_first + 1


def test_padded_precompute_presence_masks_padding(setup):
    """The padding tokens (id 0) must not count as 'seen' for the
    repetition penalty unless they appear in the real prefix."""
    cfg, params = setup
    toks = [t if t != 0 else 1 for t in _prompt(73, 10, cfg)]
    st = precompute_prefix(params, toks, cfg, prompt_buckets=BUCKETS)
    presence = np.asarray(st.presence)
    assert not presence[0]  # padding id, absent from the real tokens
    assert all(presence[t] for t in toks)


# (Padded precompute serving exactness end-to-end is pinned by
# test_batching.py::test_shared_prefix_matches_generate: its 13-token
# prefix pads to the 32-bucket under the default ladder and must still
# match dedicated generate.)


# --- engine/HTTP wiring -----------------------------------------------------


def test_engine_reports_cached_tokens(setup):
    """The serving engine surfaces per-request reuse: the second request
    over a shared prefix retires with cached_tokens > 0 (the field the
    native API and OpenAI usage report)."""
    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        drain_queue,
    )

    cfg, params = setup
    pc = _make_cache(cfg)
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
        prefix_cache=pc,
    )
    # the batcher REBINDS the cache's ladder to its own buckets (the
    # default ladder capped by max_len: (32, 64)), so the shared prefix
    # must span the 32-boundary to be promotable
    assert pc.buckets == (32, 64)
    sys_p = _prompt(80, 40, cfg)

    async def body():
        eid1, q1 = engine.submit(sys_p + _prompt(81, 4, cfg), 4)
        await drain_queue(q1)
        eid2, q2 = engine.submit(sys_p + _prompt(82, 5, cfg), 4)
        await drain_queue(q2)
        return engine.pop_request_info(eid1), engine.pop_request_info(eid2)

    try:
        info1, info2 = asyncio.run(asyncio.wait_for(body(), timeout=300))
    finally:
        engine.shutdown()
    assert info1.get("cached_tokens") == 0
    # matched 32, all of it below the finish window (45 - 8): full reuse
    assert info2.get("cached_tokens", 0) == 32
    assert engine.pop_request_info(9999) == {}  # unknown eid: empty
    stats_pc = pc.stats.as_dict()
    assert stats_pc["hits"] == 1 and stats_pc["tokens_saved"] == 32


def test_engine_rejects_prefix_cache_with_injected_batcher(setup):
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params = setup
    cb = _batcher(params, cfg, None)
    with pytest.raises(ValueError, match="injected batcher"):
        InferenceEngine(params, cfg, batcher=cb,
                        prefix_cache=_make_cache(cfg))
