"""Replica router (serving/router.py + serving/fleet.py): affinity,
drain semantics, failover bookkeeping, and byte-transparent proxying.

Real fleets: two (or one) InferenceServers on ephemeral ports behind a
ReplicaRouter, all in-process on the CPU backend — the assertions pin
the fleet API contract AND token/logprob parity with direct-to-replica
submission (the router must be invisible to outputs)."""

import asyncio
import json

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.fleet import (
    FleetRegistry,
    HashRing,
    affinity_key,
)
from k8s_gpu_device_plugin_tpu.serving.router import ReplicaRouter
from k8s_gpu_device_plugin_tpu.serving.testing import inprocess_fleet

BUCKETS = (8, 16, 32)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


async def _with_fleet(setup, body, n_replicas=2, policy="affinity",
                      router_kw=None, engine_kw=None):
    """Run ``body(session, router_base, fleet_ctx)`` against a real
    in-process fleet (serving/testing.py — the same harness the CPU
    benches use)."""
    cfg, params = setup
    async with inprocess_fleet(
        params, cfg, n_replicas=n_replicas,
        engine_kw=dict(
            dict(n_slots=2, max_len=64, chunked_prefill=8),
            **(engine_kw or {}),
        ),
        router_kw=dict(
            dict(policy=policy, prompt_buckets=BUCKETS,
                 health_interval_s=0.1, drain_timeout_s=30.0),
            **(router_kw or {}),
        ),
    ) as ctx:
        async with aiohttp.ClientSession() as session:
            await body(session, ctx.base, ctx)


async def _sse_events(resp) -> list[dict]:
    events = []
    async for line in resp.content:
        line = line.decode().strip()
        if line.startswith("data: "):
            events.append(json.loads(line[len("data: "):]))
    return events


# --- pure routing state (no engines) --------------------------------------


def test_affinity_key_bucket_alignment():
    buckets = (8, 16, 32)
    base = list(range(100, 116))  # 16 tokens: covers the 16 boundary
    # divergence past the last covered boundary does not split the key
    assert affinity_key(base + [1, 2], buckets) == \
        affinity_key(base + [3, 4], buckets)
    # divergence inside it does
    assert affinity_key([0] + base[1:], buckets) != \
        affinity_key(base, buckets)
    # strings bucket on bytes; structures on canonical JSON
    assert affinity_key("a" * 16 + "xx", buckets) == \
        affinity_key("a" * 16 + "yy", buckets)
    msgs = [{"role": "system", "content": "s" * 40}]
    assert affinity_key(msgs, buckets) == affinity_key(list(msgs), buckets)
    # no prefix-bearing field -> no key (balance-only routing)
    assert affinity_key(None, buckets) is None
    assert affinity_key("", buckets) is None


def test_hash_ring_stable_and_spreads():
    ring = HashRing(["a", "b", "c"])
    keys = [affinity_key(list(range(i, i + 20)), BUCKETS)
            for i in range(200)]
    homes = [ring.candidates(k)[0] for k in keys]
    # every candidate list is a permutation of the membership
    for k in keys[:10]:
        assert sorted(ring.candidates(k)) == ["a", "b", "c"]
    # stable across rebuilds (hashlib, not the salted builtin hash)
    ring2 = HashRing(["a", "b", "c"])
    assert homes == [ring2.candidates(k)[0] for k in keys]
    # no replica owns everything
    assert len(set(homes)) == 3


def test_fleet_registry_spec_and_duplicates():
    fleet = FleetRegistry.from_spec(
        "r0=http://127.0.0.1:8001, http://127.0.0.1:8002"
    )
    assert fleet.ids() == ["r0", "127.0.0.1:8002"]
    with pytest.raises(ValueError):
        FleetRegistry.from_spec("")
    with pytest.raises(ValueError):
        FleetRegistry.from_spec(
            "x=http://h:1,x=http://h:2"
        )
    with pytest.raises(ValueError):
        ReplicaRouter(fleet, policy="random")
    with pytest.raises(ValueError):
        ReplicaRouter(fleet, load_factor=1.0)


# --- proxy parity ---------------------------------------------------------


def test_streams_via_router_bit_identical(setup):
    """Token AND logprob streams through the router equal direct-to-
    replica submission (and the generate oracle) in both JSON and SSE
    modes — the router is byte-transparent."""
    cfg, params = setup
    p = _prompt(310, 6, cfg)
    oracle = _oracle(params, p, cfg, 5)

    async def body(session, base, ctx):
        direct = f"http://127.0.0.1:{ctx.servers[0].bound_port}"
        payload = {"prompt": p, "max_new": 5, "logprobs": True}
        async with session.post(f"{direct}/v1/generate", json=payload) as r:
            assert r.status == 200
            d_direct = await r.json()
        async with session.post(f"{base}/v1/generate", json=payload) as r:
            assert r.status == 200
            d_routed = await r.json()
        assert d_routed["tokens"] == d_direct["tokens"] == oracle
        assert d_routed["logprobs"] == d_direct["logprobs"]

        sse = dict(payload, stream=True)
        async with session.post(f"{direct}/v1/generate", json=sse) as r:
            ev_direct = await _sse_events(r)
        async with session.post(f"{base}/v1/generate", json=sse) as r:
            assert r.headers["Content-Type"].startswith("text/event-stream")
            ev_routed = await _sse_events(r)
        assert ev_routed == ev_direct
        assert [e["token"] for e in ev_routed[:-1]] == oracle
        assert ev_routed[-1]["done"] is True

        # the OpenAI surface proxies identically (id-list prompt)
        oai = {"prompt": p, "max_tokens": 4, "model": "tpu-serving"}
        async with session.post(f"{direct}/v1/completions", json=oai) as r:
            c_direct = await r.json()
        async with session.post(f"{base}/v1/completions", json=oai) as r:
            c_routed = await r.json()
        assert c_routed["choices"][0] == c_direct["choices"][0]
        assert c_routed["usage"] == c_direct["usage"]

    run(_with_fleet(setup, body))


def test_affinity_routes_shared_prefix_to_one_replica(setup):
    """Six requests sharing a bucket-covering prefix (distinct tails)
    must all land on ONE replica — the one holding their cache — and
    count as affinity hits."""
    cfg, params = setup

    async def body(session, base, ctx):
        shared = _prompt(320, 16, cfg)  # covers the 16 boundary
        for i in range(6):
            tail = _prompt(330 + i, 4, cfg)
            async with session.post(f"{base}/v1/generate", json={
                "prompt": shared + tail, "max_new": 2,
            }) as r:
                assert r.status == 200
        relayed = {rep.rid: rep.relayed for rep in ctx.fleet.all()}
        assert sorted(relayed.values()) == [0, 6], relayed
        stats = ctx.router.router_stats()
        assert stats["affinity_hits"] == 6
        assert stats["failovers"] == 0
        # distinct prefixes spread: at least one of a handful of other
        # prefixes hashes to the idle replica
        for i in range(8):
            q = _prompt(400 + i, 20, cfg)
            async with session.post(f"{base}/v1/generate", json={
                "prompt": q, "max_new": 2,
            }) as r:
                assert r.status == 200
        relayed2 = {rep.rid: rep.relayed for rep in ctx.fleet.all()}
        assert all(v > 0 for v in relayed2.values()), relayed2

    run(_with_fleet(setup, body))


# --- drain semantics (the rolling-update satellite) -----------------------


def test_drain_finishes_inflight_stream_and_refuses_new(setup):
    """Drain mid-stream: the in-flight stream delivers EVERY token and
    its done event; while draining, new submits answer a structured 503
    {"code": "draining"} on BOTH API surfaces; un-drain restores
    admission."""
    cfg, params = setup
    p = _prompt(340, 3, cfg)

    async def body(session, base, ctx):
        # (a) stream in flight, then drain: the stream must finish
        resp = await session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 60, "stream": True,
        })
        assert resp.status == 200
        first = None
        async for line in resp.content:
            line = line.decode().strip()
            if line.startswith("data: "):
                first = json.loads(line[len("data: "):])
                break
        assert first is not None and "token" in first

        async def _drain():
            async with session.post(f"{base}/fleet/drain/r0") as r:
                return r.status, await r.json()

        drain = asyncio.create_task(_drain())
        toks = [first["token"]]
        async for line in resp.content:
            line = line.decode().strip()
            if not line.startswith("data: "):
                continue
            evt = json.loads(line[len("data: "):])
            if evt.get("done"):
                break
            toks.append(evt["token"])
        assert len(toks) == 60  # zero dropped tokens across the drain
        resp.release()
        status, d = await drain
        assert status == 200
        assert d["drained"] is True and d["replica"] == "r0"
        assert d["drain_seconds"] >= 0.0

        # (b) still draining: both surfaces refuse with code=draining
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 2,
        }) as r:
            assert r.status == 503
            refuse = await r.json()
            assert refuse["code"] == "draining"
        async with session.post(f"{base}/v1/completions", json={
            "prompt": p, "max_tokens": 2,
        }) as r:
            assert r.status == 503
            refuse = await r.json()
            assert refuse["error"]["code"] == "draining"
            assert refuse["error"]["type"] == "server_error"
        async with session.post(f"{base}/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
        }) as r:
            assert r.status == 503
            assert (await r.json())["error"]["code"] == "draining"
        # metadata reads survive the drain window: only new GENERATION
        # admissions are refused
        async with session.get(f"{base}/v1/models") as r:
            assert r.status == 200

        # (c) un-drain restores admission
        async with session.post(f"{base}/fleet/undrain/r0") as r:
            assert r.status == 200
            assert (await r.json())["draining"] is False
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 3,
        }) as r:
            assert r.status == 200
            assert (await r.json())["tokens"] == _oracle(params, p, cfg, 3)

    run(_with_fleet(setup, body, n_replicas=1))


def test_drain_spills_new_work_to_the_survivor(setup):
    """With a second live replica, draining one refuses nothing: new
    requests route to the survivor while the drained one empties."""
    cfg, params = setup

    async def body(session, base, ctx):
        async with session.post(f"{base}/fleet/drain/r0") as r:
            assert r.status == 200
            assert (await r.json())["drained"] is True
        for i in range(4):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(350 + i, 5, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        reps = {rep.rid: rep for rep in ctx.fleet.all()}
        assert reps["r0"].relayed == 0
        assert reps["r1"].relayed == 4
        snap = ctx.fleet.snapshot()
        assert snap["replicas"]["r0"]["draining"] is True
        async with session.post(f"{base}/fleet/drain/nope") as r:
            assert r.status == 404

    run(_with_fleet(setup, body))


# --- failover + fleet surfaces --------------------------------------------


def test_dead_replica_fails_over_and_health_aggregates(setup):
    """Killing a replica mid-service: requests keep succeeding via the
    survivor (failovers counted), /fleet/health reports the death, and
    /v1/models keeps answering."""
    cfg, params = setup

    async def body(session, base, ctx):
        # both replicas warm + the poller has seen them
        for i in range(4):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(360 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        await asyncio.sleep(0.25)
        snap = ctx.fleet.snapshot()
        assert snap["live"] == 2
        # reported ids round-tripped from each replica's /v1/health
        assert {v["reported_id"] for v in snap["replicas"].values()} == \
            {"r0", "r1"}

        await ctx.kill_replica(0)
        served = 0
        for i in range(8):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(370 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
                served += 1
        assert served == 8
        stats = ctx.router.router_stats()
        assert stats["outcomes"].get("unreachable", 0) >= 1
        assert stats["failovers"] >= 1
        # the poller marks it dead shortly after
        for _ in range(40):
            if ctx.fleet.snapshot()["live"] == 1:
                break
            await asyncio.sleep(0.05)
        snap = ctx.fleet.snapshot()
        assert snap["live"] == 1
        assert snap["replicas"]["r0"]["alive"] is False
        async with session.get(f"{base}/fleet/health") as r:
            agg = await r.json()
            assert agg["live"] == 1 and agg["router"]["failovers"] >= 1
        async with session.get(f"{base}/v1/models") as r:
            assert r.status == 200
            assert (await r.json())["data"][0]["id"] == "tpu-serving"
        async with session.get(f"{base}/v1/health") as r:
            assert r.status == 200
            h = await r.json()
            assert h["router"] is True and h["live"] == 1

    run(_with_fleet(setup, body))


def test_backend_429_forwarded_with_retry_after(setup):
    """A single overloaded replica's 429 reaches the client verbatim
    (body + Retry-After) instead of a router-invented 503 — and the
    cooldown must not wedge the fleet afterwards."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import Scheduler

    cfg, params = setup
    p = _prompt(380, 9, cfg)

    async def body(session, base, ctx):
        posts = [
            session.post(f"{base}/v1/generate", json={
                "prompt": list(p), "max_new": 40,
            })
            for _ in range(8)
        ]
        results = await asyncio.gather(*posts)
        rejected = [r for r in results if r.status == 429]
        served = [r for r in results if r.status == 200]
        assert rejected and served
        for r in rejected:
            assert int(r.headers["Retry-After"]) >= 1
            payload = await r.json()
            assert payload["code"] == "overloaded"
        for r in results:
            await r.release()
        # cooldown is advisory: the fleet still answers (the backend's
        # own 429 or a 200, never a no_replica 503)
        async with session.post(f"{base}/v1/generate", json={
            "prompt": list(p), "max_new": 2,
        }) as r:
            assert r.status in (200, 429)

    run(_with_fleet(
        setup, body, n_replicas=1,
        engine_kw={"scheduler": Scheduler(max_queue=1)},
    ))


# --- robustness: wedged replicas, hardened polling, injected faults -------


def test_header_timeout_default_is_finite():
    """A replica that accepts the connection but never answers headers
    must not hang clients forever: the DEFAULT header timeout is
    finite (0 = unbounded stays an explicit opt-out)."""
    fleet = FleetRegistry.from_spec("r0=http://127.0.0.1:1")
    router = ReplicaRouter(fleet)
    assert router.header_timeout_s > 0


def test_wedged_replica_fails_over_within_header_timeout():
    """One wedged backend (socket accepts, never writes) + one healthy
    stub: every request lands on the healthy one within the header
    timeout, counted as a failover — the hang-forever satellite pin."""
    from aiohttp import web

    async def body():
        # the wedge: accept and hold the connection open silently
        async def wedge(reader, writer):
            try:
                await asyncio.sleep(3600)
            except asyncio.CancelledError:
                writer.close()
                raise

        wedged = await asyncio.start_server(wedge, "127.0.0.1", 0)
        wedged_port = wedged.sockets[0].getsockname()[1]

        # the healthy stub: the router proxies byte-transparently, so a
        # canned JSON body stands in for a real engine
        app = web.Application()

        async def gen(request):
            return web.json_response({"id": 0, "tokens": [1, 2]})

        app.router.add_post("/v1/generate", gen)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        ok_port = runner.addresses[0][1]

        fleet = FleetRegistry.from_spec(
            f"w=http://127.0.0.1:{wedged_port},"
            f"ok=http://127.0.0.1:{ok_port}"
        )
        # polling OFF the fast path (long interval): the PROXY's header
        # timeout must do the failing over, not the health poller
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, policy="rr",
            header_timeout_s=0.4, health_interval_s=60.0,
        )
        stop = asyncio.Event()
        task = asyncio.create_task(router.run(stop))
        while router.bound_port is None:
            await asyncio.sleep(0.01)
        try:
            async with aiohttp.ClientSession() as session:
                t0 = asyncio.get_event_loop().time()
                for i in range(2):  # rr: one of these starts on the wedge
                    async with session.post(
                        f"http://127.0.0.1:{router.bound_port}/v1/generate",
                        json={"prompt": [1, 2, 3], "max_new": 2},
                    ) as r:
                        assert r.status == 200
                        assert (await r.json())["tokens"] == [1, 2]
                elapsed = asyncio.get_event_loop().time() - t0
            assert elapsed < 5.0  # bounded by the header timeout, not 3600
            assert router.router_stats()["failovers"] >= 1
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)
            wedged.close()
            await wedged.wait_closed()
            await runner.cleanup()

    run(body())


def test_poll_loop_survives_probe_exceptions_and_marks_down():
    """The hardened poller: an exception inside one replica's probe
    iteration must neither kill the poller task nor hide the replica —
    it is marked down (note_failure toward dead_after) while the other
    replica keeps being polled."""

    async def body():
        fleet = FleetRegistry.from_spec(
            "bad=http://127.0.0.1:1,good=http://127.0.0.1:2",
            dead_after=3,
        )
        router = ReplicaRouter(fleet, health_interval_s=0.02)
        probed = {"good": 0}

        async def fake_probe(rep):
            if rep.rid == "bad":
                raise RuntimeError("raised inside the poll iteration")
            probed["good"] += 1
            fleet.note_success(rep, {"alive": True})
            return {"alive": True}

        router._probe_health = fake_probe
        task = asyncio.create_task(router._poll_loop())
        try:
            await asyncio.sleep(0.3)
            assert not task.done()  # the poller survived every raise
            bad = fleet.get("bad")
            assert bad.consecutive_failures >= 3
            assert bad.alive is False  # marked down, not forgotten
            good = fleet.get("good")
            assert good.alive is True and probed["good"] >= 3
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    run(body())


# --- cross-replica stream resume (the fleet recovery tentpole) ------------


async def _read_stream(resp, on_token=None) -> list[dict]:
    """Drain one SSE stream, invoking ``on_token(count)`` after each
    token event (the mid-stream kill hook)."""
    events = []
    n = 0
    async for line in resp.content:
        line = line.decode().strip()
        if not line.startswith("data: "):
            continue
        evt = json.loads(line[len("data: "):])
        events.append(evt)
        if "token" in evt:
            n += 1
            if on_token is not None:
                await on_token(n)
        if evt.get("done") or "error" in evt:
            break
    return events


def _toks_lps(events):
    return ([e["token"] for e in events if "token" in e],
            [e.get("logprob") for e in events if "token" in e])


@pytest.mark.parametrize("seeded", [False, True])
def test_midstream_replica_kill_resumes_bit_identical(setup, seeded):
    """THE acceptance pin: kill the replica serving a stream mid-relay;
    the client-visible token AND logprob stream continues bit-identical
    to an uninterrupted run (greedy + seeded), with zero re-emitted
    tokens and a clean done event — the death is invisible."""
    cfg, params = setup
    p = _prompt(500 + int(seeded), 6, cfg)
    body = {"prompt": p, "max_new": 24, "stream": True, "logprobs": True}
    if seeded:
        body.update(temperature=0.8, seed=77)

    async def run_test(session, base, ctx):
        # warm both replicas' compiles direct, then an uninterrupted
        # baseline stream through the router
        for i in range(2):
            async with session.post(
                f"{ctx.replica_base(i)}/v1/generate",
                json=dict(body, stream=False),
            ) as r:
                assert r.status == 200
        async with session.post(f"{base}/v1/generate", json=body) as r:
            baseline = await _read_stream(r)
        base_toks, base_lps = _toks_lps(baseline)
        assert baseline[-1].get("done") and len(base_toks) == 24

        killed = []

        async def kill_at_3(n):
            if n != 3 or killed:
                return
            serving = next(
                i for i in range(2)
                if ctx.fleet.get(f"r{i}").inflight > 0
            )
            killed.append(serving)
            await ctx.kill_replica(serving)

        resp = await session.post(f"{base}/v1/generate", json=body)
        events = await _read_stream(resp, on_token=kill_at_3)
        assert killed, "the kill hook never fired"
        toks, lps = _toks_lps(events)
        assert events[-1].get("done") is True          # no error frame
        assert toks == base_toks                       # bit-identical...
        assert lps == base_lps                         # ...logprobs too
        assert len(toks) == 24                         # zero re-emitted
        stats = ctx.router.router_stats()
        assert stats["resumes"] == 1
        assert stats["resume_failures"] == 0
        assert stats["fleet_budget"]["charged_total"] == 1

    run(_with_fleet(setup, run_test, policy="rr",
                    router_kw={"health_interval_s": 0.05}))


def test_resume_seam_http_continuation(setup):
    """The native resume seam direct: POST resume_out = the first k
    tokens of a finished run and get back EXACTLY the remaining
    tokens/logprobs — greedy and seeded, streamed and not."""
    cfg, params = setup
    p = _prompt(520, 6, cfg)

    async def body(session, base, ctx):
        for seeded in (False, True):
            req = {"prompt": p, "max_new": 10, "logprobs": True}
            if seeded:
                req.update(temperature=0.9, seed=11)
            async with session.post(f"{base}/v1/generate", json=req) as r:
                assert r.status == 200
                full = await r.json()
            for k in (1, 4, 9):
                res = dict(req, resume_out=full["tokens"][:k],
                           resume_logprobs=full["logprobs"][:k])
                async with session.post(
                    f"{base}/v1/generate", json=res
                ) as r:
                    assert r.status == 200
                    cont = await r.json()
                assert cont["tokens"] == full["tokens"][k:]
                assert cont["logprobs"] == full["logprobs"][k:]
                # streamed continuation: same tokens, then done
                async with session.post(
                    f"{base}/v1/generate", json=dict(res, stream=True)
                ) as r:
                    events = await _read_stream(r)
                toks, lps = _toks_lps(events)
                assert toks == full["tokens"][k:]
                assert lps == full["logprobs"][k:]
                assert events[-1].get("done") is True
        # validation: resuming the whole budget is refused, not hung
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 3, "resume_out": [1, 2, 3],
        }) as r:
            assert r.status == 422
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 8, "resume_out": [1], "n": 2,
        }) as r:
            assert r.status == 400
        # malformed resume fields through the ROUTER: not journaled
        # (the journal's casts must never 500), forwarded, and the
        # replica's clean 400 comes back
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 8, "stream": True,
            "resume_out": ["x"],
        }) as r:
            assert r.status == 400
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 8, "stream": True,
            "resume_out": 5,
        }) as r:
            assert r.status == 400

    run(_with_fleet(setup, body, n_replicas=1))


def test_resume_refusal_keeps_replica_alive_and_fails_fast():
    """A candidate that answers a resume with a 4xx gave an APP-LEVEL
    answer: it proves the engine alive (no liveness failure — one
    journaled stream's death must never mark healthy replicas dead)
    and the refusal is deterministic, so the resume fails fast with
    the structured error frame instead of hammering it for the whole
    resume window."""
    from aiohttp import web as aweb

    async def body():
        # replica b: streams two tokens, then ends with no done frame
        # (the mid-stream death shape); replica a: 422s every resume
        async def gen_b(request):
            resp = aweb.StreamResponse(
                headers={"Content-Type": "text/event-stream"})
            await resp.prepare(request)
            await resp.write(b'data: {"token": 1}\n\n')
            await resp.write(b'data: {"token": 2}\n\n')
            return resp  # no done event: the backend gave up

        async def gen_a(request):
            return aweb.json_response({"error": "no resume here"},
                                      status=422)

        apps = []
        for handler in (gen_a, gen_b):
            app = aweb.Application()
            app.router.add_post("/v1/generate", handler)
            runner = aweb.AppRunner(app)
            await runner.setup()
            site = aweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            apps.append((runner, runner.addresses[0][1]))
        fleet = FleetRegistry.from_spec(
            f"a=http://127.0.0.1:{apps[0][1]},"
            f"b=http://127.0.0.1:{apps[1][1]}",
            dead_after=3,
        )
        # rr's first pick is the SECOND replica (b) — deterministic
        router = ReplicaRouter(fleet, host="127.0.0.1", port=0,
                               policy="rr", health_interval_s=60.0,
                               resume_timeout_s=30.0)
        stop = asyncio.Event()
        task = asyncio.create_task(router.run(stop))
        while router.bound_port is None:
            await asyncio.sleep(0.01)
        try:
            t0 = asyncio.get_event_loop().time()
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{router.bound_port}/v1/generate",
                    json={"prompt": [1, 2, 3], "max_new": 8,
                          "stream": True},
                ) as r:
                    events = await _read_stream(r)
            elapsed = asyncio.get_event_loop().time() - t0
            # the stream ended on the structured frame, fast (the 422
            # is deterministic — no 30s scan window burned)
            assert events[-1]["error"]["code"] == "resume_failed"
            assert elapsed < 5.0, elapsed
            # and the refusing replica is still ALIVE with a clean
            # failure ledger (the 4xx proved its engine up)
            a = fleet.get("a")
            assert a.alive is True
            assert a.consecutive_failures == 0
            assert router.router_stats()["resume_failures"] == 1
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)
            for runner, _ in apps:
                await runner.cleanup()

    run(body())


def test_injected_midstream_fault_resumes_on_other_replica(setup):
    """The router.midstream fault point now rehearses the resume path:
    an injected mid-relay death splices the continuation from another
    replica — the client still sees every token exactly once."""
    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    cfg, params = setup
    p = _prompt(530, 6, cfg)
    body = {"prompt": p, "max_new": 12, "stream": True, "logprobs": True}

    async def run_test(session, base, ctx):
        for i in range(2):
            async with session.post(
                f"{ctx.replica_base(i)}/v1/generate",
                json=dict(body, stream=False),
            ) as r:
                assert r.status == 200
                oracle = (await r.json())["tokens"]
        async with session.post(f"{base}/v1/generate", json=body) as r:
            events = await _read_stream(r)
        toks, _ = _toks_lps(events)
        assert events[-1].get("done") is True
        assert toks == oracle and len(toks) == 12
        assert ctx.router.router_stats()["resumes"] == 1

    run(_with_fleet(
        setup, run_test, policy="rr",
        router_kw={"faults": FaultPlane.from_spec("router.midstream:nth=2")},
    ))


def test_fleet_budget_exhausted_ends_with_error_frame(setup):
    """Budget 0 = cross-replica resume off: a mid-stream death then
    ends the stream with the PR-12 structured error frame — visibly,
    never as a clean short completion."""
    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    cfg, params = setup
    p = _prompt(540, 6, cfg)

    async def run_test(session, base, ctx):
        for i in range(2):
            async with session.post(
                f"{ctx.replica_base(i)}/v1/generate",
                json={"prompt": p, "max_new": 2},
            ) as r:
                assert r.status == 200
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 12, "stream": True,
        }) as r:
            assert r.status == 200
            events = await _read_stream(r)
        assert not any(e.get("done") for e in events)
        assert events[-1]["error"]["code"] == "fleet_budget_exhausted"
        stats = ctx.router.router_stats()
        assert stats["resumes"] == 0
        assert stats["resume_failures"] == 1

    run(_with_fleet(
        setup, run_test, policy="rr",
        router_kw={
            "fleet_restart_budget": 0,
            "faults": FaultPlane.from_spec("router.midstream:nth=2"),
        },
    ))


def test_fleet_restart_budget_charges_per_death_not_per_stream():
    from k8s_gpu_device_plugin_tpu.serving.fleet import (
        FleetRestartBudget,
        Replica,
    )

    budget = FleetRestartBudget(max_restarts=2, window_s=60.0)
    r0, r1, r2 = (Replica(f"r{i}", f"http://h:{i}") for i in range(3))
    # N streams dying from ONE replica death share one charge
    assert all(budget.charge(r0) for _ in range(5))
    assert budget.stats()["window_used"] == 1
    assert budget.charge(r1)
    # budget full: a third replica's death cannot resume...
    assert not budget.charge(r2)
    # ...but streams of the already-charged deaths still can
    assert budget.charge(r0) and budget.charge(r1)
    # a REVIVED replica's next death is a new event (epoch bump)
    r0.epoch += 1
    assert not budget.charge(r0)
    with pytest.raises(ValueError):
        FleetRestartBudget(max_restarts=-1)


def test_flapping_replica_burns_budget_per_death():
    """A replica that dies mid-stream, heals (a successful health poll
    — WITHOUT ever reaching dead_after), and dies again must charge the
    budget AGAIN: recovery from any observed failure closes the death
    epoch, so --fleetRestartBudget actually bounds a flapper instead
    of granting it unlimited resumes on the first epoch's charge."""
    from k8s_gpu_device_plugin_tpu.serving.fleet import (
        FleetRegistry,
        FleetRestartBudget,
    )

    fleet = FleetRegistry.from_spec("a=http://h:1,b=http://h:2",
                                    dead_after=3)
    budget = FleetRestartBudget(max_restarts=1, window_s=60.0)
    a = fleet.get("a")
    # death 1: one proxy-observed failure (alive stays True), charged
    fleet.note_failure(a)
    assert a.alive is True
    assert budget.charge(a)
    # a successful poll heals the flap: the epoch closes
    fleet.note_success(a, {"alive": True})
    assert a.consecutive_failures == 0
    # death 2 is a NEW event — the budget (1) is spent, resume refused
    fleet.note_failure(a)
    assert not budget.charge(a)
    # repeated successes with a clean ledger do NOT churn the epoch
    e = a.epoch
    fleet.note_success(a, {"alive": True})
    fleet.note_success(a, {"alive": True})
    assert a.epoch == e + 1  # one bump for closing death 2, then stable


# --- warm spares ----------------------------------------------------------


def test_warm_spare_promotion(setup):
    """--warmSpares: the spare is registered-but-unrouted until an
    active replica dies, then promoted into the ring (affinity keys
    remapped) — pinned via /fleet/health, router stats, and traffic
    landing on the promoted spare."""
    cfg, params = setup

    async def body(session, base, ctx):
        assert [r.rid for r in ctx.fleet.active()] == ["r0", "r1"]
        assert [r.rid for r in ctx.fleet.spares()] == ["r2"]
        # warm all three (the spare serves the moment it is promoted)
        for i in range(3):
            async with session.post(
                f"{ctx.replica_base(i)}/v1/generate",
                json={"prompt": _prompt(550, 8, cfg), "max_new": 2},
            ) as r:
                assert r.status == 200
        # spares take no traffic while both actives live
        for i in range(6):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(560 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        assert ctx.fleet.get("r2").relayed == 0

        await ctx.kill_replica(0)
        for _ in range(100):
            if ctx.router.router_stats()["promotions"] >= 1:
                break
            await asyncio.sleep(0.05)
        stats = ctx.router.router_stats()
        assert stats["promotions"] == 1
        assert {r.rid for r in ctx.fleet.active()} == {"r1", "r2"}
        assert ctx.fleet.get("r0").spare  # demoted: revival re-enters as spare
        # the ring now routes onto the promoted spare too
        for i in range(8):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(570 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        assert ctx.fleet.get("r2").relayed > 0
        async with session.get(f"{base}/fleet/health") as r:
            snap = await r.json()
        assert snap["router"]["promotions"] == 1
        assert snap["replicas"]["r0"]["spare"] is True
        assert snap["replicas"]["r2"]["spare"] is False
        assert snap["spares"] == 1

    run(_with_fleet(setup, body, n_replicas=3,
                    router_kw={"warm_spares": 1,
                               "health_interval_s": 0.05}))


def test_mark_spares_must_leave_an_active_replica():
    from k8s_gpu_device_plugin_tpu.serving.fleet import FleetRegistry

    fleet = FleetRegistry.from_spec("a=http://h:1,b=http://h:2")
    with pytest.raises(ValueError, match="active replica"):
        fleet.mark_spares(2)


# --- rolling restart ------------------------------------------------------


def test_rolling_restart_zero_drops(setup):
    """POST /fleet/rolling-restart drains -> undrains every active
    replica in sequence while streams are in flight and new submits
    keep arriving: zero dropped tokens, zero resumes (nothing ever
    dies), admission restored everywhere."""
    cfg, params = setup

    async def body(session, base, ctx):
        for i in range(2):
            async with session.post(
                f"{ctx.replica_base(i)}/v1/generate",
                json={"prompt": _prompt(580, 5, cfg), "max_new": 2},
            ) as r:
                assert r.status == 200

        async def stream_one(k):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(590 + k, 5, cfg), "max_new": 40,
                "stream": True,
            }) as r:
                assert r.status == 200
                events = await _read_stream(r)
            toks, _ = _toks_lps(events)
            return len(toks), bool(events[-1].get("done"))

        streams = [asyncio.create_task(stream_one(k)) for k in range(4)]
        await asyncio.sleep(0.2)  # streams mid-flight
        async with session.post(f"{base}/fleet/rolling-restart") as r:
            assert r.status == 200
            cycle = await r.json()
        assert cycle["completed"] is True
        assert set(cycle["replicas"]) == {"r0", "r1"}
        assert all(v["drained"] for v in cycle["replicas"].values())
        # submits mid- and post-cycle keep succeeding
        async with session.post(f"{base}/v1/generate", json={
            "prompt": _prompt(599, 5, cfg), "max_new": 2,
        }) as r:
            assert r.status == 200
        results = await asyncio.gather(*streams)
        assert all(done and toks == 40 for toks, done in results), results
        assert not any(rep.draining for rep in ctx.fleet.all())
        stats = ctx.router.router_stats()
        assert stats["resumes"] == 0  # a drain is not a death

    run(_with_fleet(setup, body, policy="rr",
                    router_kw={"health_interval_s": 0.05}))


def test_rolling_restart_wait_restart_detects_new_process():
    """wait_restart_s: the cycle recognizes a restarted replica by its
    uptime_s resetting (probe-level unit test, no real restart)."""

    async def body():
        from k8s_gpu_device_plugin_tpu.serving.fleet import FleetRegistry

        fleet = FleetRegistry.from_spec("a=http://127.0.0.1:1")
        router = ReplicaRouter(fleet, health_interval_s=0.01)
        rep = fleet.get("a")
        rep.health = {"uptime_s": 120.0}
        uptimes = [150.0, 3.0]  # old process, then the restarted one

        async def fake_probe(r):
            up = uptimes.pop(0) if uptimes else 4.0
            return {"uptime_s": up}

        router._probe_health = fake_probe
        assert await router._wait_restart(rep, timeout_s=5.0) is True
        # never restarts: times out False
        router._probe_health = lambda r: _const({"uptime_s": 500.0})
        rep.health = {"uptime_s": 120.0}
        assert await router._wait_restart(rep, timeout_s=0.05) is False

    async def _const(v):
        return v

    run(body())


# --- satellite pins -------------------------------------------------------


def test_client_disconnect_cancels_upstream(setup):
    """A client that aborts its SSE stream mid-generation must free the
    replica's slot: the router closes the backend connection hard and
    the replica's active count returns to zero well before the token
    budget would have drained."""
    cfg, params = setup

    async def body(session, base, ctx):
        engine = ctx.servers[0].engine
        async with session.post(
            f"{ctx.replica_base(0)}/v1/generate",
            json={"prompt": _prompt(600, 5, cfg), "max_new": 2},
        ) as r:
            assert r.status == 200
        resp = await session.post(f"{base}/v1/generate", json={
            "prompt": _prompt(601, 5, cfg), "max_new": 2000,
            "stream": True,
        })
        assert resp.status == 200
        # read a couple of tokens, then vanish
        seen = 0
        async for line in resp.content:
            if line.decode().strip().startswith("data: "):
                seen += 1
                if seen >= 2:
                    break
        resp.close()  # the client-side abort
        for _ in range(200):
            st = engine.stats()
            if st["active"] == 0 and st["queued"] == 0 \
                    and st["prefilling"] == 0:
                break
            await asyncio.sleep(0.05)
        st = engine.stats()
        assert st["active"] == 0 and st["queued"] == 0, st

    run(_with_fleet(setup, body, n_replicas=1,
                    engine_kw={"max_len": 4096, "chunked_prefill": 8}))


def test_parse_retry_after_accepts_http_dates():
    import datetime

    from k8s_gpu_device_plugin_tpu.serving.fleet import parse_retry_after

    # delta-seconds (the common case)
    assert parse_retry_after("30") == 30.0
    assert parse_retry_after("0") == 0.0
    # RFC 9110 HTTP-date, ~45s in the future
    when = datetime.datetime.now(datetime.timezone.utc) \
        + datetime.timedelta(seconds=45)
    got = parse_retry_after(email_format_date(when))
    assert 40.0 <= got <= 46.0
    # a date in the past: retry now-ish (the default), never negative
    past = datetime.datetime.now(datetime.timezone.utc) \
        - datetime.timedelta(seconds=600)
    assert parse_retry_after(email_format_date(past), default=2.0) == 2.0
    # garbage falls back to the capped default instead of raising
    assert parse_retry_after("soon", default=3.0) == 3.0
    assert parse_retry_after("", default=1.0) == 1.0
    assert parse_retry_after(None, default=1.0) == 1.0
    # negative delta: default; giant delta: capped
    assert parse_retry_after("-5", default=1.0) == 1.0
    assert parse_retry_after("999999999", max_s=3600.0) == 3600.0
    # NaN/inf parse as floats but are garbage: default, never poison
    # the arithmetic downstream (cooldowns, ceil())
    assert parse_retry_after("NaN", default=1.5) == 1.5
    assert parse_retry_after("inf", default=1.5) == 1.5
    assert parse_retry_after("-inf", default=1.5) == 1.5


def email_format_date(dt):
    import email.utils

    return email.utils.format_datetime(dt, usegmt=True)


def test_health_poll_phase_jitter_deterministic():
    """Per-replica poll phases spread inside the interval and are
    stable across router restarts (blake2b, not the salted hash)."""
    from k8s_gpu_device_plugin_tpu.serving.fleet import poll_phase

    interval = 1.0
    phases = [poll_phase(f"replica-{i}", interval) for i in range(16)]
    assert all(0.0 <= p < interval for p in phases)
    assert len(set(phases)) > 8  # spread, not synchronized
    assert phases == [poll_phase(f"replica-{i}", interval)
                      for i in range(16)]  # deterministic
    # phases scale with the interval; degenerate interval is safe
    assert poll_phase("r0", 2.0) == 2.0 * poll_phase("r0", 1.0)
    assert poll_phase("r0", 0.0) == 0.0


def test_injected_router_connect_fault_fails_over(setup):
    """The router.connect fault point: an injected pre-dispatch
    connection failure moves the request to the next ring candidate
    (counted), and the client still gets its answer."""
    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    cfg, params = setup

    async def body(session, base, ctx):
        for i in range(3):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(400 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        stats = ctx.router.router_stats()
        assert stats["failovers"] >= 1
        assert stats["outcomes"].get("unreachable", 0) >= 1

    run(_with_fleet(
        setup, body,
        router_kw={"faults": FaultPlane.from_spec("router.connect:nth=1")},
    ))
