"""Replica router (serving/router.py + serving/fleet.py): affinity,
drain semantics, failover bookkeeping, and byte-transparent proxying.

Real fleets: two (or one) InferenceServers on ephemeral ports behind a
ReplicaRouter, all in-process on the CPU backend — the assertions pin
the fleet API contract AND token/logprob parity with direct-to-replica
submission (the router must be invisible to outputs)."""

import asyncio
import json

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.fleet import (
    FleetRegistry,
    HashRing,
    affinity_key,
)
from k8s_gpu_device_plugin_tpu.serving.router import ReplicaRouter
from k8s_gpu_device_plugin_tpu.serving.testing import inprocess_fleet

BUCKETS = (8, 16, 32)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


async def _with_fleet(setup, body, n_replicas=2, policy="affinity",
                      router_kw=None, engine_kw=None):
    """Run ``body(session, router_base, fleet_ctx)`` against a real
    in-process fleet (serving/testing.py — the same harness the CPU
    benches use)."""
    cfg, params = setup
    async with inprocess_fleet(
        params, cfg, n_replicas=n_replicas,
        engine_kw=dict(
            dict(n_slots=2, max_len=64, chunked_prefill=8),
            **(engine_kw or {}),
        ),
        router_kw=dict(
            dict(policy=policy, prompt_buckets=BUCKETS,
                 health_interval_s=0.1, drain_timeout_s=30.0),
            **(router_kw or {}),
        ),
    ) as ctx:
        async with aiohttp.ClientSession() as session:
            await body(session, ctx.base, ctx)


async def _sse_events(resp) -> list[dict]:
    events = []
    async for line in resp.content:
        line = line.decode().strip()
        if line.startswith("data: "):
            events.append(json.loads(line[len("data: "):]))
    return events


# --- pure routing state (no engines) --------------------------------------


def test_affinity_key_bucket_alignment():
    buckets = (8, 16, 32)
    base = list(range(100, 116))  # 16 tokens: covers the 16 boundary
    # divergence past the last covered boundary does not split the key
    assert affinity_key(base + [1, 2], buckets) == \
        affinity_key(base + [3, 4], buckets)
    # divergence inside it does
    assert affinity_key([0] + base[1:], buckets) != \
        affinity_key(base, buckets)
    # strings bucket on bytes; structures on canonical JSON
    assert affinity_key("a" * 16 + "xx", buckets) == \
        affinity_key("a" * 16 + "yy", buckets)
    msgs = [{"role": "system", "content": "s" * 40}]
    assert affinity_key(msgs, buckets) == affinity_key(list(msgs), buckets)
    # no prefix-bearing field -> no key (balance-only routing)
    assert affinity_key(None, buckets) is None
    assert affinity_key("", buckets) is None


def test_hash_ring_stable_and_spreads():
    ring = HashRing(["a", "b", "c"])
    keys = [affinity_key(list(range(i, i + 20)), BUCKETS)
            for i in range(200)]
    homes = [ring.candidates(k)[0] for k in keys]
    # every candidate list is a permutation of the membership
    for k in keys[:10]:
        assert sorted(ring.candidates(k)) == ["a", "b", "c"]
    # stable across rebuilds (hashlib, not the salted builtin hash)
    ring2 = HashRing(["a", "b", "c"])
    assert homes == [ring2.candidates(k)[0] for k in keys]
    # no replica owns everything
    assert len(set(homes)) == 3


def test_fleet_registry_spec_and_duplicates():
    fleet = FleetRegistry.from_spec(
        "r0=http://127.0.0.1:8001, http://127.0.0.1:8002"
    )
    assert fleet.ids() == ["r0", "127.0.0.1:8002"]
    with pytest.raises(ValueError):
        FleetRegistry.from_spec("")
    with pytest.raises(ValueError):
        FleetRegistry.from_spec(
            "x=http://h:1,x=http://h:2"
        )
    with pytest.raises(ValueError):
        ReplicaRouter(fleet, policy="random")
    with pytest.raises(ValueError):
        ReplicaRouter(fleet, load_factor=1.0)


# --- proxy parity ---------------------------------------------------------


def test_streams_via_router_bit_identical(setup):
    """Token AND logprob streams through the router equal direct-to-
    replica submission (and the generate oracle) in both JSON and SSE
    modes — the router is byte-transparent."""
    cfg, params = setup
    p = _prompt(310, 6, cfg)
    oracle = _oracle(params, p, cfg, 5)

    async def body(session, base, ctx):
        direct = f"http://127.0.0.1:{ctx.servers[0].bound_port}"
        payload = {"prompt": p, "max_new": 5, "logprobs": True}
        async with session.post(f"{direct}/v1/generate", json=payload) as r:
            assert r.status == 200
            d_direct = await r.json()
        async with session.post(f"{base}/v1/generate", json=payload) as r:
            assert r.status == 200
            d_routed = await r.json()
        assert d_routed["tokens"] == d_direct["tokens"] == oracle
        assert d_routed["logprobs"] == d_direct["logprobs"]

        sse = dict(payload, stream=True)
        async with session.post(f"{direct}/v1/generate", json=sse) as r:
            ev_direct = await _sse_events(r)
        async with session.post(f"{base}/v1/generate", json=sse) as r:
            assert r.headers["Content-Type"].startswith("text/event-stream")
            ev_routed = await _sse_events(r)
        assert ev_routed == ev_direct
        assert [e["token"] for e in ev_routed[:-1]] == oracle
        assert ev_routed[-1]["done"] is True

        # the OpenAI surface proxies identically (id-list prompt)
        oai = {"prompt": p, "max_tokens": 4, "model": "tpu-serving"}
        async with session.post(f"{direct}/v1/completions", json=oai) as r:
            c_direct = await r.json()
        async with session.post(f"{base}/v1/completions", json=oai) as r:
            c_routed = await r.json()
        assert c_routed["choices"][0] == c_direct["choices"][0]
        assert c_routed["usage"] == c_direct["usage"]

    run(_with_fleet(setup, body))


def test_affinity_routes_shared_prefix_to_one_replica(setup):
    """Six requests sharing a bucket-covering prefix (distinct tails)
    must all land on ONE replica — the one holding their cache — and
    count as affinity hits."""
    cfg, params = setup

    async def body(session, base, ctx):
        shared = _prompt(320, 16, cfg)  # covers the 16 boundary
        for i in range(6):
            tail = _prompt(330 + i, 4, cfg)
            async with session.post(f"{base}/v1/generate", json={
                "prompt": shared + tail, "max_new": 2,
            }) as r:
                assert r.status == 200
        relayed = {rep.rid: rep.relayed for rep in ctx.fleet.all()}
        assert sorted(relayed.values()) == [0, 6], relayed
        stats = ctx.router.router_stats()
        assert stats["affinity_hits"] == 6
        assert stats["failovers"] == 0
        # distinct prefixes spread: at least one of a handful of other
        # prefixes hashes to the idle replica
        for i in range(8):
            q = _prompt(400 + i, 20, cfg)
            async with session.post(f"{base}/v1/generate", json={
                "prompt": q, "max_new": 2,
            }) as r:
                assert r.status == 200
        relayed2 = {rep.rid: rep.relayed for rep in ctx.fleet.all()}
        assert all(v > 0 for v in relayed2.values()), relayed2

    run(_with_fleet(setup, body))


# --- drain semantics (the rolling-update satellite) -----------------------


def test_drain_finishes_inflight_stream_and_refuses_new(setup):
    """Drain mid-stream: the in-flight stream delivers EVERY token and
    its done event; while draining, new submits answer a structured 503
    {"code": "draining"} on BOTH API surfaces; un-drain restores
    admission."""
    cfg, params = setup
    p = _prompt(340, 3, cfg)

    async def body(session, base, ctx):
        # (a) stream in flight, then drain: the stream must finish
        resp = await session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 60, "stream": True,
        })
        assert resp.status == 200
        first = None
        async for line in resp.content:
            line = line.decode().strip()
            if line.startswith("data: "):
                first = json.loads(line[len("data: "):])
                break
        assert first is not None and "token" in first

        async def _drain():
            async with session.post(f"{base}/fleet/drain/r0") as r:
                return r.status, await r.json()

        drain = asyncio.create_task(_drain())
        toks = [first["token"]]
        async for line in resp.content:
            line = line.decode().strip()
            if not line.startswith("data: "):
                continue
            evt = json.loads(line[len("data: "):])
            if evt.get("done"):
                break
            toks.append(evt["token"])
        assert len(toks) == 60  # zero dropped tokens across the drain
        resp.release()
        status, d = await drain
        assert status == 200
        assert d["drained"] is True and d["replica"] == "r0"
        assert d["drain_seconds"] >= 0.0

        # (b) still draining: both surfaces refuse with code=draining
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 2,
        }) as r:
            assert r.status == 503
            refuse = await r.json()
            assert refuse["code"] == "draining"
        async with session.post(f"{base}/v1/completions", json={
            "prompt": p, "max_tokens": 2,
        }) as r:
            assert r.status == 503
            refuse = await r.json()
            assert refuse["error"]["code"] == "draining"
            assert refuse["error"]["type"] == "server_error"
        async with session.post(f"{base}/v1/chat/completions", json={
            "messages": [{"role": "user", "content": "hi"}],
        }) as r:
            assert r.status == 503
            assert (await r.json())["error"]["code"] == "draining"
        # metadata reads survive the drain window: only new GENERATION
        # admissions are refused
        async with session.get(f"{base}/v1/models") as r:
            assert r.status == 200

        # (c) un-drain restores admission
        async with session.post(f"{base}/fleet/undrain/r0") as r:
            assert r.status == 200
            assert (await r.json())["draining"] is False
        async with session.post(f"{base}/v1/generate", json={
            "prompt": p, "max_new": 3,
        }) as r:
            assert r.status == 200
            assert (await r.json())["tokens"] == _oracle(params, p, cfg, 3)

    run(_with_fleet(setup, body, n_replicas=1))


def test_drain_spills_new_work_to_the_survivor(setup):
    """With a second live replica, draining one refuses nothing: new
    requests route to the survivor while the drained one empties."""
    cfg, params = setup

    async def body(session, base, ctx):
        async with session.post(f"{base}/fleet/drain/r0") as r:
            assert r.status == 200
            assert (await r.json())["drained"] is True
        for i in range(4):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(350 + i, 5, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        reps = {rep.rid: rep for rep in ctx.fleet.all()}
        assert reps["r0"].relayed == 0
        assert reps["r1"].relayed == 4
        snap = ctx.fleet.snapshot()
        assert snap["replicas"]["r0"]["draining"] is True
        async with session.post(f"{base}/fleet/drain/nope") as r:
            assert r.status == 404

    run(_with_fleet(setup, body))


# --- failover + fleet surfaces --------------------------------------------


def test_dead_replica_fails_over_and_health_aggregates(setup):
    """Killing a replica mid-service: requests keep succeeding via the
    survivor (failovers counted), /fleet/health reports the death, and
    /v1/models keeps answering."""
    cfg, params = setup

    async def body(session, base, ctx):
        # both replicas warm + the poller has seen them
        for i in range(4):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(360 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        await asyncio.sleep(0.25)
        snap = ctx.fleet.snapshot()
        assert snap["live"] == 2
        # reported ids round-tripped from each replica's /v1/health
        assert {v["reported_id"] for v in snap["replicas"].values()} == \
            {"r0", "r1"}

        await ctx.kill_replica(0)
        served = 0
        for i in range(8):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(370 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
                served += 1
        assert served == 8
        stats = ctx.router.router_stats()
        assert stats["outcomes"].get("unreachable", 0) >= 1
        assert stats["failovers"] >= 1
        # the poller marks it dead shortly after
        for _ in range(40):
            if ctx.fleet.snapshot()["live"] == 1:
                break
            await asyncio.sleep(0.05)
        snap = ctx.fleet.snapshot()
        assert snap["live"] == 1
        assert snap["replicas"]["r0"]["alive"] is False
        async with session.get(f"{base}/fleet/health") as r:
            agg = await r.json()
            assert agg["live"] == 1 and agg["router"]["failovers"] >= 1
        async with session.get(f"{base}/v1/models") as r:
            assert r.status == 200
            assert (await r.json())["data"][0]["id"] == "tpu-serving"
        async with session.get(f"{base}/v1/health") as r:
            assert r.status == 200
            h = await r.json()
            assert h["router"] is True and h["live"] == 1

    run(_with_fleet(setup, body))


def test_backend_429_forwarded_with_retry_after(setup):
    """A single overloaded replica's 429 reaches the client verbatim
    (body + Retry-After) instead of a router-invented 503 — and the
    cooldown must not wedge the fleet afterwards."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import Scheduler

    cfg, params = setup
    p = _prompt(380, 9, cfg)

    async def body(session, base, ctx):
        posts = [
            session.post(f"{base}/v1/generate", json={
                "prompt": list(p), "max_new": 40,
            })
            for _ in range(8)
        ]
        results = await asyncio.gather(*posts)
        rejected = [r for r in results if r.status == 429]
        served = [r for r in results if r.status == 200]
        assert rejected and served
        for r in rejected:
            assert int(r.headers["Retry-After"]) >= 1
            payload = await r.json()
            assert payload["code"] == "overloaded"
        for r in results:
            await r.release()
        # cooldown is advisory: the fleet still answers (the backend's
        # own 429 or a 200, never a no_replica 503)
        async with session.post(f"{base}/v1/generate", json={
            "prompt": list(p), "max_new": 2,
        }) as r:
            assert r.status in (200, 429)

    run(_with_fleet(
        setup, body, n_replicas=1,
        engine_kw={"scheduler": Scheduler(max_queue=1)},
    ))


# --- robustness: wedged replicas, hardened polling, injected faults -------


def test_header_timeout_default_is_finite():
    """A replica that accepts the connection but never answers headers
    must not hang clients forever: the DEFAULT header timeout is
    finite (0 = unbounded stays an explicit opt-out)."""
    fleet = FleetRegistry.from_spec("r0=http://127.0.0.1:1")
    router = ReplicaRouter(fleet)
    assert router.header_timeout_s > 0


def test_wedged_replica_fails_over_within_header_timeout():
    """One wedged backend (socket accepts, never writes) + one healthy
    stub: every request lands on the healthy one within the header
    timeout, counted as a failover — the hang-forever satellite pin."""
    from aiohttp import web

    async def body():
        # the wedge: accept and hold the connection open silently
        async def wedge(reader, writer):
            try:
                await asyncio.sleep(3600)
            except asyncio.CancelledError:
                writer.close()
                raise

        wedged = await asyncio.start_server(wedge, "127.0.0.1", 0)
        wedged_port = wedged.sockets[0].getsockname()[1]

        # the healthy stub: the router proxies byte-transparently, so a
        # canned JSON body stands in for a real engine
        app = web.Application()

        async def gen(request):
            return web.json_response({"id": 0, "tokens": [1, 2]})

        app.router.add_post("/v1/generate", gen)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        ok_port = runner.addresses[0][1]

        fleet = FleetRegistry.from_spec(
            f"w=http://127.0.0.1:{wedged_port},"
            f"ok=http://127.0.0.1:{ok_port}"
        )
        # polling OFF the fast path (long interval): the PROXY's header
        # timeout must do the failing over, not the health poller
        router = ReplicaRouter(
            fleet, host="127.0.0.1", port=0, policy="rr",
            header_timeout_s=0.4, health_interval_s=60.0,
        )
        stop = asyncio.Event()
        task = asyncio.create_task(router.run(stop))
        while router.bound_port is None:
            await asyncio.sleep(0.01)
        try:
            async with aiohttp.ClientSession() as session:
                t0 = asyncio.get_event_loop().time()
                for i in range(2):  # rr: one of these starts on the wedge
                    async with session.post(
                        f"http://127.0.0.1:{router.bound_port}/v1/generate",
                        json={"prompt": [1, 2, 3], "max_new": 2},
                    ) as r:
                        assert r.status == 200
                        assert (await r.json())["tokens"] == [1, 2]
                elapsed = asyncio.get_event_loop().time() - t0
            assert elapsed < 5.0  # bounded by the header timeout, not 3600
            assert router.router_stats()["failovers"] >= 1
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)
            wedged.close()
            await wedged.wait_closed()
            await runner.cleanup()

    run(body())


def test_poll_loop_survives_probe_exceptions_and_marks_down():
    """The hardened poller: an exception inside one replica's probe
    iteration must neither kill the poller task nor hide the replica —
    it is marked down (note_failure toward dead_after) while the other
    replica keeps being polled."""

    async def body():
        fleet = FleetRegistry.from_spec(
            "bad=http://127.0.0.1:1,good=http://127.0.0.1:2",
            dead_after=3,
        )
        router = ReplicaRouter(fleet, health_interval_s=0.02)
        probed = {"good": 0}

        async def fake_probe(rep):
            if rep.rid == "bad":
                raise RuntimeError("raised inside the poll iteration")
            probed["good"] += 1
            fleet.note_success(rep, {"alive": True})
            return {"alive": True}

        router._probe_health = fake_probe
        task = asyncio.create_task(router._poll_loop())
        try:
            await asyncio.sleep(0.3)
            assert not task.done()  # the poller survived every raise
            bad = fleet.get("bad")
            assert bad.consecutive_failures >= 3
            assert bad.alive is False  # marked down, not forgotten
            good = fleet.get("good")
            assert good.alive is True and probed["good"] >= 3
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    run(body())


def test_injected_router_connect_fault_fails_over(setup):
    """The router.connect fault point: an injected pre-dispatch
    connection failure moves the request to the next ring candidate
    (counted), and the client still gets its answer."""
    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    cfg, params = setup

    async def body(session, base, ctx):
        for i in range(3):
            async with session.post(f"{base}/v1/generate", json={
                "prompt": _prompt(400 + i, 12, cfg), "max_new": 2,
            }) as r:
                assert r.status == 200
        stats = ctx.router.router_stats()
        assert stats["failovers"] >= 1
        assert stats["outcomes"].get("unreachable", 0) >= 1

    run(_with_fleet(
        setup, body,
        router_kw={"faults": FaultPlane.from_spec("router.connect:nth=1")},
    ))
