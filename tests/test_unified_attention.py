"""The unified ragged-paged attention kernel + its dispatcher
(ops/ragged_paged_attention.py, ops/attention.serving_cache_attention).

Four layers of claims:

- **Kernel parity**: the one body matches the XLA gather's attention
  semantics across all three grid specializations (decode T=1 / verify
  / prefill-chunk) x dense/paged x GQA group sizes (interpret mode on
  CPU, max-abs error vs an f32 reference), and is BITWISE the legacy
  per-variant kernels it replaced (the shims cannot drift).
- **shard_map bit-identity**: under tp=2/4 on the conftest-forced
  8-device platform, the dispatcher keeps the kernel per-shard and the
  output is bitwise the tp=1 kernel's — and end-to-end, batcher
  token+logprob streams with ``decode_attn="ragged"`` are pinned
  bit-identical across tp=1/2/4 for dense AND paged layouts (the PR-8
  matrix, now WITH the kernel instead of the gather fallback).
- **Dispatch gates**: every fallback is explicit — unsupported
  geometry, missing mesh, opt-outs — and visible: the startup plan
  names backend + reason, feeds the ``decode_attn_backend`` gauge, and
  rides /v1/health. Quantized caches are NOT a fallback anymore: their
  scale planes ride extra block operands and the one body dequantizes
  in its DMA'd blocks (parity + routing pinned below; streams in
  tests/test_quantized_serving.py).
- **Autotuner cache**: winners persist per device generation
  (ops/tunings.py), reload into block resolution, and the kernel's
  block_k=0 path dispatches on them (pinned bitwise against the same
  block passed explicitly).
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.ops import tunings
from k8s_gpu_device_plugin_tpu.ops.attention import (
    attention_backend_plan,
    serving_cache_attention,
)
from k8s_gpu_device_plugin_tpu.ops.ragged_paged_attention import (
    MAX_PREFILL_T,
    MAX_VERIFY_T,
    ragged_paged_attention,
    supports,
)
from k8s_gpu_device_plugin_tpu.parallel.tp_serving import serving_mesh

HD = 64


def _ref(q, k, v, base, scale, window=0):
    """f32 plain-softmax oracle: the gather path's exact masking."""
    b, t, hq, hd = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    s = k.shape[1]
    qg = q.reshape(b, t, hkv, g, hd).astype(jnp.float32)
    sc = jnp.einsum("btkgd,bskd->btkgs", qg, k.astype(jnp.float32)) * scale
    q_pos = jnp.maximum(
        base[:, None, None, None, None]
        + jnp.arange(t)[None, :, None, None, None], 0
    )
    k_pos = jnp.arange(s)[None, None, None, None, :]
    keep = k_pos <= q_pos
    if window > 0:
        keep &= q_pos - k_pos < window
    sc = jnp.where(keep, sc, -1e30)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum(
        "btkgs,bskd->btkgd", p, v.astype(jnp.float32)
    ).reshape(b, t, hq, hd)


def _dense(b=3, s=128, hq=8, hkv=4):
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    k = jax.random.normal(kk, (b, s, hkv, HD), jnp.bfloat16)
    v = jax.random.normal(kv, (b, s, hkv, HD), jnp.bfloat16)
    return kq, k, v


def _paged(k, v, ps=16):
    """Repack a dense cache into a pool + identity-permuted tables."""
    b, s, hkv, hd = k.shape
    n = b * (s // ps)
    kp = jnp.concatenate(
        [jnp.zeros((1, ps, hkv, hd), k.dtype), k.reshape(n, ps, hkv, hd)]
    )
    vp = jnp.concatenate(
        [jnp.zeros((1, ps, hkv, hd), v.dtype), v.reshape(n, ps, hkv, hd)]
    )
    table = jnp.arange(1, n + 1, dtype=jnp.int32).reshape(b, s // ps)
    return kp, vp, table


# --- kernel parity ---------------------------------------------------------


@pytest.mark.parametrize("hq,hkv", [(8, 8), (8, 4), (8, 2)])
@pytest.mark.parametrize("mode,t", [("decode", 1), ("verify", 4),
                                    ("prefill", 32)])
def test_kernel_matches_gather_reference(mode, t, hq, hkv):
    kq, k, v = _dense(hq=hq, hkv=hkv)
    kp, vp, table = _paged(k, v)
    q = jax.random.normal(kq, (3, t, hq, HD), jnp.bfloat16)
    base = jnp.asarray([1, 40, 128 - t], jnp.int32)
    want = _ref(q, k, v, base, HD ** -0.5)
    for pages, kk_, vv_ in ((None, k, v), (table, kp, vp)):
        got = ragged_paged_attention(
            q, kk_, vv_, base, pages, scale=HD ** -0.5, block_k=32,
            interpret=True,
        )
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
        assert err < 0.02, (mode, pages is not None, err)


def test_kernel_windowed_matches_reference():
    kq, k, v = _dense()
    q = jax.random.normal(kq, (3, 8, 8, HD), jnp.bfloat16)
    base = jnp.asarray([10, 60, 120], jnp.int32)
    got = ragged_paged_attention(
        q, k, v, base, scale=HD ** -0.5, window=24, block_k=16,
        interpret=True,
    )
    want = _ref(q, k, v, base, HD ** -0.5, window=24)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
    assert err < 0.02, err


def test_prefill_t_tiling_past_max_window():
    """Chunks wider than MAX_PREFILL_T route through the T-tile grid:
    gather parity holds at t=512, the tile width is bitwise
    output-invariant (each query row meets its live kv blocks in the
    same ascending order whatever the tiling), and the paged route
    tiles identically."""
    from k8s_gpu_device_plugin_tpu.ops.ragged_paged_attention import (
        fit_prefill_tile,
    )

    assert fit_prefill_tile(512) == 256
    assert fit_prefill_tile(320) == 160
    assert fit_prefill_tile(64) == 64          # fits: no tiling
    assert fit_prefill_tile(MAX_PREFILL_T + 1) is None  # prime chunk

    kq, k, v = _dense(b=2, s=1024, hq=8, hkv=4)
    t = 512
    q = jax.random.normal(kq, (2, t, 8, HD), jnp.bfloat16)
    base = jnp.asarray([0, 1024 - t], jnp.int32)
    assert supports(q, k, require_pltpu=False)
    want = _ref(q, k, v, base, HD ** -0.5)
    got = ragged_paged_attention(q, k, v, base, scale=HD ** -0.5,
                                 block_k=128, interpret=True)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
    assert err < 0.02, err
    # the tile width is a pure performance knob, never a numerics one
    for bt in (128, 64):
        alt = ragged_paged_attention(q, k, v, base, scale=HD ** -0.5,
                                     block_k=128, block_t=bt,
                                     interpret=True)
        assert bool(jnp.all(alt == got)), bt
    # paged pool: same tiled grid, page-table indirection
    kp, vp, table = _paged(k, v)
    gotp = ragged_paged_attention(q, kp, vp, base, table,
                                  scale=HD ** -0.5, interpret=True)
    errp = float(jnp.max(jnp.abs(gotp.astype(jnp.float32) - want)))
    assert errp < 0.02, errp


@pytest.mark.parametrize("qdtype", [jnp.int8, jnp.int4])
def test_kernel_dequantizes_codes_in_block(qdtype):
    """The quantized specialization: int8/int4 codes + per-(token, head)
    f32 scale planes through the SAME kernel body match the f32
    reference on the manually dequantized cache — dense and paged, the
    decode and verify grids."""
    from k8s_gpu_device_plugin_tpu.models.generate import _quantize_kv

    kq, k, v = _dense()
    kc, ks = _quantize_kv(k, qdtype)
    vc, vs = _quantize_kv(v, qdtype)
    k_deq = kc.astype(jnp.float32) * ks
    v_deq = vc.astype(jnp.float32) * vs
    kcp, vcp, table = _paged(kc, vc)
    ksp, vsp, _ = _paged(ks, vs)
    for t in (1, 4):
        q = jax.random.normal(kq, (3, t, 8, HD), jnp.bfloat16)
        base = jnp.asarray([1, 40, 128 - t], jnp.int32)
        want = _ref(q, k_deq, v_deq, base, HD ** -0.5)
        for pages, kk_, vv_, ks_, vs_ in (
            (None, kc, vc, ks, vs),
            (table, kcp, vcp, ksp, vsp),
        ):
            got = ragged_paged_attention(
                q, kk_, vv_, base, pages, scale=HD ** -0.5, block_k=32,
                interpret=True, k_scale=ks_, v_scale=vs_,
            )
            err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
            assert err < 0.02, (t, pages is not None, err)


def test_legacy_kernels_are_bitwise_the_unified_one():
    """The compat shims (ops/ragged_decode, ops/paged_attention) must be
    pure re-parameterizations: byte-equal outputs, so no stream pinned
    on the old entry points can move."""
    from k8s_gpu_device_plugin_tpu.ops.paged_attention import (
        paged_decode_attention,
        paged_verify_attention,
    )
    from k8s_gpu_device_plugin_tpu.ops.ragged_decode import (
        ragged_decode_attention,
    )

    kq, k, v = _dense()
    kp, vp, table = _paged(k, v)
    q = jax.random.normal(kq, (3, 1, 8, HD), jnp.bfloat16)
    lens = jnp.asarray([0, 33, 128], jnp.int32)  # empty slot included
    old = ragged_decode_attention(q, k, v, lens, scale=HD ** -0.5,
                                  block_k=32, interpret=True)
    new = ragged_paged_attention(q, k, v, lens - 1, scale=HD ** -0.5,
                                 block_k=32, interpret=True)
    assert bool(jnp.all(old == new))
    lens = jnp.asarray([5, 33, 128], jnp.int32)
    oldp = paged_decode_attention(q, kp, vp, table, lens,
                                  scale=HD ** -0.5, interpret=True)
    newp = ragged_paged_attention(q, kp, vp, lens - 1, table,
                                  scale=HD ** -0.5, interpret=True)
    assert bool(jnp.all(oldp == newp))
    qv = jax.random.normal(kq, (3, 4, 8, HD), jnp.bfloat16)
    base = jnp.asarray([3, 50, 100], jnp.int32)
    oldv = paged_verify_attention(qv, kp, vp, table, base,
                                  scale=HD ** -0.5, interpret=True)
    newv = ragged_paged_attention(qv, kp, vp, base, table,
                                  scale=HD ** -0.5, interpret=True)
    assert bool(jnp.all(oldv == newv))


def test_supports_gates():
    kq, k, v = _dense()
    q = jax.random.normal(kq, (3, 1, 8, HD), jnp.bfloat16)
    assert supports(q, k, require_pltpu=False)
    # lane alignment
    bad_hd = jax.random.normal(kq, (3, 1, 8, 16), jnp.bfloat16)
    assert not supports(bad_hd, k[..., :16], require_pltpu=False)
    # GQA divisibility
    qg = jax.random.normal(kq, (3, 1, 6, HD), jnp.bfloat16)
    assert not supports(qg, k, require_pltpu=False)
    # window width caps
    qt = jax.random.normal(kq, (3, MAX_PREFILL_T + 1, 8, HD), jnp.bfloat16)
    assert not supports(qt, k, require_pltpu=False)
    # paged: sublane-aligned page size required
    kp, vp, table = _paged(k, v)
    assert supports(q, kp, table, require_pltpu=False)
    bad_ps = kp[:, :12]
    assert not supports(q, bad_ps, table, require_pltpu=False)
    # dense: some sublane block must divide the cache length
    assert not supports(q, k[:, :100], require_pltpu=False)


# --- dispatcher gates + shard_map bit-identity -----------------------------


def test_dispatcher_gates_and_modes():
    kq, k, v = _dense(b=2)
    q = jax.random.normal(kq, (2, 1, 8, HD), jnp.bfloat16)
    base = jnp.asarray([5, 99], jnp.int32)
    # opt-outs and hard gates return None (the caller's gather runs)
    assert serving_cache_attention(q, k, v, base) is None
    assert serving_cache_attention(q, k, v, base, decode_attn="xla") is None
    # quantized caches ROUTE now: scale operands ride along instead of
    # forcing the gather (one per K and V, or the call is malformed)
    ks = jnp.ones(k.shape[:-1] + (1,), jnp.float32)
    assert serving_cache_attention(
        q, k.astype(jnp.int8), v.astype(jnp.int8), base,
        decode_attn="ragged", k_scale=ks, v_scale=ks,
    ) is not None
    with pytest.raises(ValueError, match="k_scale"):
        serving_cache_attention(
            q, k.astype(jnp.int8), v.astype(jnp.int8), base,
            decode_attn="ragged", k_scale=ks,
        )
    # tp>1 with no ambient mesh: graceful fallback, not a crash
    assert serving_cache_attention(
        q, k, v, base, decode_attn="ragged", tp=2
    ) is None
    # decode routes; verify width bounds respected; prefill needs its
    # own opt-in
    assert serving_cache_attention(
        q, k, v, base, decode_attn="ragged"
    ) is not None
    qv = jax.random.normal(kq, (2, MAX_VERIFY_T + 2, 8, HD), jnp.bfloat16)
    assert serving_cache_attention(
        qv, k, v, base - MAX_VERIFY_T, verify=True, decode_attn="ragged"
    ) is None
    qp = jax.random.normal(kq, (2, 16, 8, HD), jnp.bfloat16)
    assert serving_cache_attention(
        qp, k, v, base - 16, decode_attn="ragged"
    ) is None
    assert serving_cache_attention(
        qp, k, v, base - 16, decode_attn="ragged", prefill_attn="ragged"
    ) is not None


@pytest.mark.parametrize("tp", [2, 4])
def test_dispatcher_shard_map_bitwise(tp):
    """The kernel under shard_map at tp=2/4 is bitwise the tp=1 kernel:
    attention never crosses a KV head, so each shard's heads are the
    tp=1 heads — the structural fact the serving stream pin rests on."""
    kq, k, v = _dense(b=2)
    kp, vp, table = _paged(k, v)
    q = jax.random.normal(kq, (2, 1, 8, HD), jnp.bfloat16)
    base = jnp.asarray([5, 99], jnp.int32)
    one = serving_cache_attention(q, k, v, base, decode_attn="ragged")
    mesh = serving_mesh(tp, k.shape[2])
    with mesh:
        many = jax.jit(
            lambda *a: serving_cache_attention(*a, decode_attn="ragged",
                                               tp=tp)
        )(q, k, v, base)
    assert bool(jnp.all(one == many))
    # paged verify, the speculative window
    qv = jax.random.normal(kq, (2, 4, 8, HD), jnp.bfloat16)
    onev = serving_cache_attention(qv, kp, vp, base - 4, pages=table,
                                   verify=True, decode_attn="ragged")
    with mesh:
        manyv = jax.jit(
            lambda qq, kk_, vv_, bb, pp: serving_cache_attention(
                qq, kk_, vv_, bb, pages=pp, verify=True,
                decode_attn="ragged", tp=tp,
            )
        )(qv, kp, vp, base - 4, table)
    assert bool(jnp.all(onev == manyv))


# --- end-to-end serving streams --------------------------------------------


@pytest.fixture(scope="module")
def kernel_setup():
    # head_dim_override=64 puts the tiny config ON the kernel's gates
    # (the stock tiny head_dim of 16 is exactly the documented fallback)
    cfg = LlamaConfig.tiny(n_layers=2, head_dim_override=HD,
                           decode_attn="ragged")
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _streams(params, cfg, tp, layout, depth=1):
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(8, 16, 32),
        chunked_prefill=8, pipeline_depth=depth, kv_layout=layout,
        kv_page_size=16 if layout == "paged" else None, tp=tp,
    )
    prompts = [
        jax.random.randint(jax.random.key(40 + i), (n,), 1,
                           cfg.vocab_size, jnp.int32).tolist()
        for i, n in enumerate([5, 12, 3, 9])
    ]
    rids = [
        cb.submit(p, max_new=6, seed=11 if i % 2 else None)
        for i, p in enumerate(prompts)
    ]
    cb.cancel(rids[2])  # a cancel mid-queue rides the pin matrix
    cb.run()
    return {
        r: (tuple(cb.done[r]),
            tuple(round(x, 12) for x in cb.done_requests[r].out_logp))
        for r in rids
    }, cb


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_stream_bit_identity_tp_with_kernel(kernel_setup, layout):
    """The acceptance pin: with decode_attn='ragged' ROUTING (plan says
    pallas), token AND logprob streams are bit-identical across
    tp=1/2/4 on both KV layouts — the PR-8 matrix with the kernel."""
    cfg, params = kernel_setup
    base, cb = _streams(params, cfg, 1, layout)
    assert cb.attn_plan["decode"]["backend"] == "pallas"
    assert cb.attn_plan["verify"]["backend"] == "pallas"
    for tp in (2, 4):
        got, cb_tp = _streams(params, cfg, tp, layout)
        assert cb_tp.attn_plan["decode"]["backend"] == "pallas"
        assert got == base, (layout, tp)


def test_prefill_kernel_stream_tp_identity(kernel_setup):
    """prefill_attn='ragged' (chunk windows through the kernel): the
    same structural pin — tp=2 streams bitwise tp=1's, and the plan
    reports the prefill route."""
    cfg, params = kernel_setup
    pcfg = replace(cfg, prefill_attn="ragged")
    base, cb = _streams(params, pcfg, 1, "dense")
    assert cb.attn_plan["prefill"]["backend"] == "pallas"
    got, _ = _streams(params, pcfg, 2, "dense")
    assert got == base


def test_kernel_actually_traces_in_decode_step(kernel_setup, monkeypatch):
    """Belt for the routing claim: the unified kernel is CALLED when the
    decode step traces (a fresh cfg forces a fresh trace — the jit
    cache would otherwise satisfy the step without re-entering the
    dispatcher)."""
    import k8s_gpu_device_plugin_tpu.ops.ragged_paged_attention as rpa

    cfg, _ = kernel_setup
    cfg = replace(cfg, vocab_size=520)  # unique static cfg: fresh traces
    params = init_params(jax.random.key(1), cfg)
    calls = []
    real = rpa.ragged_paged_attention

    def spy(*a, **kw):
        calls.append(kw.get("block_k"))
        return real(*a, **kw)

    monkeypatch.setattr(rpa, "ragged_paged_attention", spy)
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           prompt_buckets=(8, 16), chunked_prefill=8)
    cb.submit([1, 2, 3], max_new=3)
    cb.run()
    assert calls, "decode step traced without entering the kernel"


# --- fallback visibility ---------------------------------------------------


def test_backend_plan_reasons():
    common = dict(n_heads=8, n_kv_heads=4, head_dim=HD, max_len=64)
    plan = attention_backend_plan(decode_attn="ragged", tp=2, **common)
    assert plan["decode"]["backend"] == "pallas"
    assert "shard_map" in plan["decode"]["reason"]
    assert plan["prefill"]["backend"] == "xla"  # needs its own opt-in
    # a quantized cache is no longer a fallback: it plans onto the same
    # kernel (in-kernel dequant); only the narrow-dtype tile can gate it
    # on hardware (interpret mode has no tiling — qsub is 1 here)
    plan = attention_backend_plan(decode_attn="ragged", cache_quant="int8",
                                  **common)
    assert plan["decode"]["backend"] == "pallas"
    plan = attention_backend_plan(
        decode_attn="ragged",
        **{**common, "head_dim": 16},
    )
    assert "head_dim" in plan["decode"]["reason"]
    plan = attention_backend_plan(decode_attn="ragged", kv_layout="paged",
                                  page_size=12, **common)
    assert "kv_page_size" in plan["decode"]["reason"]
    plan = attention_backend_plan(decode_attn="ragged",
                                  prefill_attn="ragged",
                                  chunk=MAX_PREFILL_T + 1, **common)
    assert plan["prefill"]["backend"] == "xla"
    assert "MAX_PREFILL_T" in plan["prefill"]["reason"]
    # a chunk that TILES cleanly past the window plans onto the kernel
    plan = attention_backend_plan(decode_attn="ragged",
                                  prefill_attn="ragged",
                                  chunk=2 * MAX_PREFILL_T, **common)
    assert plan["prefill"]["backend"] == "pallas"
    plan = attention_backend_plan(**common)
    assert plan["decode"]["reason"].startswith("decode_attn=")


def test_batcher_fallback_logs_and_gauge(kernel_setup, captured_log_records):
    """An opted-in kernel that falls back WARNS with the reason (the
    previously-silent degradation) and the gauge carries the per-mode
    backend; attn_backend_stats() is the health payload."""
    cfg, _ = kernel_setup
    bad = replace(cfg, head_dim_override=0)  # tiny's hd=16: off the gates
    params = init_params(jax.random.key(0), bad)

    class Gauge:
        def __init__(self):
            self.plans = []

        def set_decode_attn_backend(self, plan):
            self.plans.append(plan)

    g = Gauge()
    cb = ContinuousBatcher(params, bad, n_slots=1, max_len=32,
                           prompt_buckets=(8, 16), metrics=g)
    warns = [r for r in captured_log_records
             if r.levelname == "WARNING"
             and "attention backend" in r.getMessage()]
    assert warns, "fallback under an explicit opt-in must warn"
    assert any("head_dim" in r.getMessage() for r in warns)
    assert g.plans and g.plans[0]["decode"]["backend"] == "xla"
    stats = cb.attn_backend_stats()
    assert set(stats) == {"decode", "verify", "prefill"}
    assert stats["decode"]["backend"] == "xla"
    stats["decode"]["backend"] = "mutated"  # a copy: plan is immutable
    assert cb.attn_plan["decode"]["backend"] == "xla"


def test_serving_metrics_gauge_and_health_surface():
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.set_decode_attn_backend({
        "decode": {"backend": "pallas", "reason": "x"},
        "verify": {"backend": "pallas", "reason": "x"},
        "prefill": {"backend": "xla", "reason": "y"},
    })
    val = reg.get_sample_value(
        "tpu_serving_decode_attn_backend",
        {"mode": "decode", "backend": "pallas"},
    )
    assert val == 1
    assert reg.get_sample_value(
        "tpu_serving_decode_attn_backend",
        {"mode": "prefill", "backend": "pallas"},
    ) == 0
    m.close()

    # /v1/health carries the plan (engine stats() duck-types it)
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg = LlamaConfig.tiny(n_layers=1)
    params = init_params(jax.random.key(0), cfg)
    engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                             chunked_prefill=8)
    try:
        stats = engine.stats()
        assert set(stats["decode_attn"]) == {"decode", "verify", "prefill"}
        assert stats["decode_attn"]["decode"]["backend"] == "xla"
    finally:
        engine.shutdown()


# --- autotuner cache -------------------------------------------------------


def test_tunings_record_resolve_round_trip(tmp_path, monkeypatch):
    path = tmp_path / "tilings.json"
    monkeypatch.setenv(tunings.TUNINGS_FILE_ENV, str(path))
    tunings.clear_cache()
    try:
        assert tunings.resolve("rpa:decode:hkv4:hd64", 128) is None
        out = tunings.record({"rpa:decode:hkv4:hd64:128": (32,)},
                             generation="v5e")
        assert out == str(path)
        # wrong generation sees nothing; the right one resolves exact
        # and nearest-smaller seq
        assert tunings.lookup("rpa:decode:hkv4:hd64:128",
                              generation="v6e") is None
        assert tunings.resolve("rpa:decode:hkv4:hd64", 128,
                               generation="v5e") == (32,)
        assert tunings.resolve("rpa:decode:hkv4:hd64", 512,
                               generation="v5e") == (32,)
        assert tunings.resolve("rpa:decode:hkv4:hd64", 64,
                               generation="v5e") is None
        # malformed entries degrade to nothing, never raise
        path.write_text("{\"v5e\": {\"rpa:x:1\": [\"bad\"]}, \"y\": 3}")
        tunings.clear_cache()
        assert tunings.resolve("rpa:x", 1, generation="v5e") is None
    finally:
        tunings.clear_cache()


def test_kernel_loads_tuned_block(tmp_path, monkeypatch):
    """block_k=0 resolves through the cache: output is bitwise the same
    block passed explicitly (proof the persisted winner is what the
    kernel dispatches on)."""
    path = tmp_path / "tilings.json"
    monkeypatch.setenv(tunings.TUNINGS_FILE_ENV, str(path))
    tunings.clear_cache()
    try:
        gen = tunings.device_generation()
        tunings.record({"rpa:decode:hkv4:hd64:128": [16]}, generation=gen)
        kq, k, v = _dense()
        q = jax.random.normal(kq, (3, 1, 8, HD), jnp.bfloat16)
        base = jnp.asarray([5, 40, 127], jnp.int32)
        tuned = ragged_paged_attention(q, k, v, base, scale=HD ** -0.5,
                                       interpret=True)
        explicit = ragged_paged_attention(q, k, v, base, scale=HD ** -0.5,
                                          block_k=16, interpret=True)
        assert bool(jnp.all(tuned == explicit))
        # a two-element prefill row carries the measured T tile too
        tunings.record({"rpa:prefill:hkv4:hd64:128": [16, 32]},
                       generation=gen)
        tunings.clear_cache()
        qp = jax.random.normal(kq, (3, 64, 8, HD), jnp.bfloat16)
        basep = jnp.asarray([0, 32, 64], jnp.int32)
        tunedp = ragged_paged_attention(qp, k, v, basep, scale=HD ** -0.5,
                                        interpret=True)
        explicitp = ragged_paged_attention(
            qp, k, v, basep, scale=HD ** -0.5, block_k=16, block_t=32,
            interpret=True,
        )
        assert bool(jnp.all(tunedp == explicitp))
    finally:
        tunings.clear_cache()


def test_generation_for_device_kind():
    from k8s_gpu_device_plugin_tpu.device.topology import (
        generation_for_device_kind,
    )

    assert generation_for_device_kind("TPU v4") == "v4"
    assert generation_for_device_kind("TPU v5 lite") == "v5e"
    assert generation_for_device_kind("TPU v5p") == "v5p"
    assert generation_for_device_kind("TPU v6 lite") == "v6e"
    assert generation_for_device_kind("gollychip 9000") is None
    # the CPU test platform lands in its own bucket
    assert tunings.device_generation() == "cpu"


def test_fallback_streams_bitwise_equal_auto(kernel_setup):
    """A ragged opt-in OFF the kernel's gates (hd=16) serves BITWISE the
    auto path's streams — the documented graceful-fallback contract at
    the stream level (the op-level pin lives in test_paged_kv)."""
    cfg, _ = kernel_setup
    bad = replace(cfg, head_dim_override=0, decode_attn="ragged")
    auto = replace(bad, decode_attn="auto")
    params = init_params(jax.random.key(2), bad)
    got, _ = _streams(params, bad, 1, "dense")
    want, _ = _streams(params, auto, 1, "dense")
    assert got == want
