"""Daemon self-profiler: the block.prof/mutex.prof analogue
(≙ /root/reference/benchmark/benchmark.go:74-85) plus the cpu/mem flush.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time

from k8s_gpu_device_plugin_tpu.benchmark.profiler import BlockSampler, Profiler


def test_block_sampler_measures_loop_lag():
    """A deliberately blocked event loop shows up as scheduling lag."""
    sampler = BlockSampler(interval=0.02)

    async def body():
        sampler.watch_loop(asyncio.get_running_loop())
        sampler.start()
        await asyncio.sleep(0.1)   # healthy: probes land fast
        # graftlint: disable=blocking-in-async (the sin being metered)
        time.sleep(0.3)            # deliberately block the loop
        await asyncio.sleep(0.1)
        sampler.stop()

    asyncio.run(body())
    assert sampler.samples > 0
    assert sampler.loop_lags, "no probes landed"
    assert max(sampler.loop_lags) >= 0.15, sampler.loop_lags
    assert min(sampler.loop_lags) < 0.05, sampler.loop_lags
    assert "loop lag" in sampler.report()


def test_block_sampler_tallies_lock_waits():
    """A thread parked in a pure-Python wait (Event.wait — the
    synchronization the daemon's threads actually use) is attributed to
    ITS call site, not to threading.py internals. Raw C-level
    Lock.acquire is unobservable by design (no Python frame exists while
    it blocks), mirroring pprof's need for runtime cooperation."""
    sampler = BlockSampler(interval=0.02)
    gate = threading.Event()
    done = threading.Event()

    def contender():
        gate.wait()  # blocks until the main thread sets it
        done.set()

    thread = threading.Thread(target=contender, daemon=True)
    sampler.start()
    thread.start()
    time.sleep(0.3)  # let the sampler observe the blocked thread
    gate.set()
    assert done.wait(5)
    sampler.stop()
    thread.join(5)

    assert sampler.lock_waits, "no lock waits observed"
    assert any("contender" in site for site in sampler.lock_waits), (
        dict(sampler.lock_waits)
    )
    assert "contender" in sampler.report()


def test_profiler_flushes_all_three_profiles(tmp_path):
    profiler = Profiler(out_dir=str(tmp_path))

    async def body():
        profiler.watch_loop(asyncio.get_running_loop())
        profiler.run()
        await asyncio.sleep(0.15)
        paths = profiler.stop()
        return paths

    paths = asyncio.run(body())
    assert set(paths) == {"cpu", "mem", "block"}
    for p in paths.values():
        assert os.path.exists(p), p
    with open(paths["block"]) as f:
        text = f.read()
    assert "loop lag" in text and "samples:" in text
    # idempotent stop
    assert profiler.stop() == {}


def test_block_sampler_restartable():
    """A second run()/stop() cycle must actually sample again (the stop
    event is cleared on start), and the lag window stays bounded."""
    sampler = BlockSampler(interval=0.01)

    async def burst():
        sampler.watch_loop(asyncio.get_running_loop())
        sampler.start()
        await asyncio.sleep(0.1)
        sampler.stop()

    asyncio.run(burst())
    first = sampler.samples
    assert first > 0
    asyncio.run(burst())
    assert sampler.samples > first, "second start() never sampled"
    assert sampler.loop_lags.maxlen == BlockSampler.LAG_WINDOW
