"""Allocator unit tests over simulated topologies (≙ plugin.go:248-326 logic).

≙ SURVEY §4 "multi-node without a cluster": sub-slice selection is pure logic
over a topology description, so it is tested here with zero hardware.
"""

from k8s_gpu_device_plugin_tpu.device.chip import AnnotatedID, Chips
from k8s_gpu_device_plugin_tpu.device.chip_map import new_chip_map
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.plugin.allocator import (
    aligned_alloc,
    distributed_alloc,
    preferred_allocation,
)
from k8s_gpu_device_plugin_tpu.resource.resources import discover_resources


def build(topology: str, shared_replicas: int = 0):
    backend = FakeBackend(topology)
    cm = new_chip_map(
        backend, discover_resources("none"), "none", shared_replicas=shared_replicas
    )
    return backend.host_topology(), cm["google.com/tpu"]


def coords_of(chips: Chips, ids):
    return sorted(chips[i].coords[0] for i in ids)


def test_aligned_prefers_submesh_over_scattered():
    topo, chips = build("v5e-8")  # 2x4
    ids = preferred_allocation(chips, chips.ids(), [], 4, topo)
    coords = coords_of(chips, ids)
    # must be a contiguous 2x2 (or 1x4/2x2-shaped) sub-mesh: 4 internal edges
    xs = {c[0] for c in coords}
    ys = {c[1] for c in coords}
    assert len(xs) * len(ys) == 4
    assert max(ys) - min(ys) == len(ys) - 1


def test_aligned_respects_must_include():
    topo, chips = build("v5e-8")
    corner = chips.get_by_index(3)  # coord (0, 3)
    ids = preferred_allocation(chips, chips.ids(), [corner.id], 2, topo)
    assert corner.id in ids
    coords = coords_of(chips, ids)
    # partner must be an ICI neighbor of (0,3)
    other = [c for c in coords if c != (0, 3)][0]
    assert other in topo.neighbors((0, 3))


def test_aligned_with_partial_availability_falls_back_greedy():
    topo, chips = build("v5e-8")
    # remove chips so no full 2x2 sub-mesh of 4 is available: keep a ragged L
    keep = [
        c.id
        for c in chips.values()
        if c.coords[0] in [(0, 0), (0, 1), (1, 1), (1, 2), (0, 3)]
    ]
    ids = preferred_allocation(chips, keep, [], 4, topo)
    assert len(ids) == 4
    assert set(ids) <= set(keep)
    # greedy should pick the connected L-cluster, not the isolated (0,3)
    coords = coords_of(chips, ids)
    assert (0, 3) not in coords


def test_aligned_size_exceeding_available_clamps():
    topo, chips = build("v5e-4")
    ids = preferred_allocation(chips, chips.ids()[:2], [], 99, topo)
    assert len(ids) == 2


def test_aligned_3d_topology():
    topo, chips = build("v5p-8")  # 2x2x2
    ids = preferred_allocation(chips, chips.ids(), [], 4, topo)
    coords = coords_of(chips, ids)
    # 4 chips in a 2x2x1-shaped plane: bounding box volume 4
    vol = 1
    for axis in range(3):
        vals = [c[axis] for c in coords]
        vol *= max(vals) - min(vals) + 1
    assert vol == 4


def test_distributed_spreads_over_physical_chips():
    _, chips = build("v5e-4", shared_replicas=2)  # 8 annotated over 4 chips
    ids = preferred_allocation(chips, chips.ids(), [], 4, None)
    physical = {AnnotatedID.parse(i).device_id for i in ids}
    assert len(physical) == 4  # one replica from each chip, not two from two


def test_distributed_prefers_least_loaded():
    _, chips = build("v5e-4", shared_replicas=2)
    # one of chip 0's two replicas is already taken (unavailable)
    phys0 = chips.physical_ids()[0]
    available = [
        i
        for i in chips.ids()
        if AnnotatedID.parse(i).device_id != phys0 or i.endswith("::0")
    ]
    ids = distributed_alloc(chips, available, [], 3)
    # least-loaded chips (full availability) picked before the loaded one
    picked_phys = [AnnotatedID.parse(i).device_id for i in ids]
    assert phys0 not in picked_phys


def test_distributed_must_include_first():
    _, chips = build("v5e-4", shared_replicas=2)
    target = chips.ids()[5]
    ids = distributed_alloc(chips, chips.ids(), [target], 2)
    assert target in ids


def test_empty_and_zero_size():
    topo, chips = build("v5e-4")
    assert preferred_allocation(chips, chips.ids(), [], 0, topo) == []
    assert preferred_allocation(chips, [], [], 2, topo) == []


def test_aligned_alloc_numa_tiebreak():
    topo, chips = build("v5e-8")
    # size 2: many 1x2/2x1 placements tie on edges; NUMA concentration and
    # low indices break the tie deterministically
    a = aligned_alloc(chips, chips.ids(), [], 2, topo)
    b = aligned_alloc(chips, chips.ids(), [], 2, topo)
    assert a == b
    assert len({chips[i].numa_node for i in a}) == 1


# --- torus wraparound (r2 verdict weak #3: the torus path was dead code) ---


def test_wraparound_ring_beats_open_chain():
    """On the v5e 4x4 torus a full boundary column closes into a 4-edge ring,
    tying the interior 2x2 block; the lowest-index tie-break then picks the
    column. Without wraparound the block's 4 edges beat the open chain's 3 —
    so this placement flips exactly when the wrap links are scored."""
    from dataclasses import replace

    topo, chips = build("v5e-16")  # 4x4, wraparound (True, True)
    assert topo.wraparound == (True, True)
    col = [c.id for c in chips.values() if c.coords[0][1] == 0]
    # y∈{2,3} so no mixed col+block 2x2 placement exists
    block = [
        c.id for c in chips.values()
        if c.coords[0] in [(1, 2), (1, 3), (2, 2), (2, 3)]
    ]
    avail = col + block

    ids = preferred_allocation(chips, avail, [], 4, topo)
    assert sorted(ids) == sorted(col)

    mesh_topo = replace(topo, wraparound=(False, False))
    ids = preferred_allocation(chips, avail, [], 4, mesh_topo)
    assert sorted(ids) == sorted(block)


def test_wraparound_submesh_across_boundary():
    """A 2x2 placement crossing the torus seam (x=3..0) is found by the
    exact-placement phase and scores its two wrap links."""
    from k8s_gpu_device_plugin_tpu.plugin.allocator import _edges_within

    topo, chips = build("v5e-16")
    cells = [(0, 0), (0, 1), (3, 0), (3, 1)]
    avail = [c.id for c in chips.values() if c.coords[0] in cells]

    ids = preferred_allocation(chips, avail, [], 4, topo)
    assert coords_of(chips, ids) == sorted(cells)
    assert _edges_within(set(cells), topo) == 4  # 2 mesh + 2 wrap links


def test_wraparound_scoring_native_matches_python():
    """The C++ scorer and the Python fallback agree on torus edge counts."""
    from k8s_gpu_device_plugin_tpu.device.native import native_internal_edges

    topo, chips = build("v5e-16")
    ring = [(0, 0), (1, 0), (2, 0), (3, 0)]
    native = native_internal_edges(ring, topo.bounds, topo.wraparound)
    if native is None:  # library not built in this environment
        return
    assert native == 4
    assert native_internal_edges(ring, topo.bounds, (False, False)) == 3
