"""Gathered O(active) multi-LoRA serving (ROADMAP 3b): the compact
(L, K, ...) stacks, AdapterStore residency, and the admission gate.

The load-bearing pin: the GATHERED path (K compact slots, sel remapped
to stack positions) produces token AND logprob streams bit-identical to
the dense-N path (every adapter resident, sel over registry indices) —
the gather is an exact copy and the one-hot contraction makes
non-selected fold terms exact ±0.0, so K-vs-N is a cost choice, never a
numerics choice. Pinned across the serving composition matrix (paged
KV, int8 cache, tensor parallel, pipelined decode) and under seeded
sampling, not just greedy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.lora import (
    LoraConfig,
    init_lora_params,
    merge_lora,
)
from k8s_gpu_device_plugin_tpu.models.lora_serving import (
    AdapterStore,
    stack_adapters,
)
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler


def _rand_b(lp, seed):
    out = {}
    for i, (t, ab) in enumerate(sorted(lp.items())):
        k = jax.random.fold_in(jax.random.key(seed), i)
        out[t] = {
            "a": ab["a"],
            "b": 0.3 * jax.random.normal(k, ab["b"].shape, ab["b"].dtype),
        }
    return out


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    lcs = [
        LoraConfig(rank=4, alpha=8.0, targets=("wq", "wo", "w2")),
        LoraConfig(rank=8, alpha=16.0),
        LoraConfig(rank=2, alpha=4.0, targets=("wq", "wk")),
    ]
    lps = [
        _rand_b(init_lora_params(jax.random.key(i + 1), cfg, lc), 20 + i)
        for i, lc in enumerate(lcs)
    ]
    entries = [(f"ad{i}", lp, lc) for i, (lp, lc) in enumerate(zip(lps, lcs))]
    aset = stack_adapters(cfg, entries)
    merged = {-1: params}
    for i, (lp, lc) in enumerate(zip(lps, lcs)):
        merged[i] = merge_lora(params, lp, lc)
    return cfg, params, aset, merged, entries


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(params, jnp.asarray([prompt], jnp.int32), cfg,
                   max_new=max_new)
    return np.asarray(out)[0].tolist()


# --- the composition matrix: gathered ≡ dense-N, bitwise -----------------


MATRIX = [
    # (kv_layout, cache_quant, tp, pipeline_depth).  The tier-1 gate runs
    # the rows that together touch every axis value (dense/paged,
    # bf16/int8, tp 1/2, pipeline 0/1); the remaining cross-combos carry
    # the slow mark so the full matrix still runs outside -m 'not slow'
    # without blowing the gate's wall-clock budget on duplicate compiles.
    ("dense", None, 1, 1),
    ("dense", None, 1, 0),
    pytest.param("paged", None, 1, 1, marks=pytest.mark.slow),
    pytest.param("paged", "int8", 1, 1, marks=pytest.mark.slow),
    pytest.param("dense", None, 2, 1, marks=pytest.mark.slow),
    ("paged", "int8", 2, 0),
]


def _mk(params, cfg, aset, *, gathered, kv_layout, tp, pipeline):
    return ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
        adapters=aset, pipeline_depth=pipeline, tp=tp,
        kv_layout=kv_layout,
        kv_page_size=16 if kv_layout == "paged" else None,
        # lora_slots=0 keeps the legacy dense-N attach (every adapter in
        # the stacks, sel over registry indices) — the baseline arm
        lora_slots=None if gathered else 0,
    )


@pytest.mark.parametrize("kv_layout,cache_quant,tp,pipeline", MATRIX)
def test_gathered_matches_dense_across_matrix(
    setup, kv_layout, cache_quant, tp, pipeline
):
    """Both arms serve the same mixed batch — greedy adapter rows, a
    SEEDED sampled adapter row, and a base row — and the token + logprob
    streams must match bitwise, combo by combo."""
    from dataclasses import replace

    cfg, params, aset, merged, _ = setup
    if cache_quant:
        cfg = replace(cfg, cache_quant=cache_quant)
    streams = {}
    for arm in ("dense", "gathered"):
        cb = _mk(params, cfg, aset, gathered=arm == "gathered",
                 kv_layout=kv_layout, tp=tp, pipeline=pipeline)
        rids = {}
        # 3 requests over 2 slots: the queued third admits as a slot
        # frees, changing the active set mid-run (a re-gather on the
        # gathered arm — the dense arm never re-gathers; identity must
        # survive the swap)
        rids["a0"] = cb.submit(_prompt(300, 6, cfg), max_new=8, adapter=0)
        rids["a1s"] = cb.submit(
            _prompt(301, 5, cfg), max_new=6, adapter=1,
            sampler=Sampler(temperature=0.9, top_k=12), seed=7,
        )
        rids["base"] = cb.submit(_prompt(302, 4, cfg), max_new=5)
        done = cb.run()
        streams[arm] = {
            k: (done[r], cb.done_requests[r].out_logp)
            for k, r in rids.items()
        }
        if arm == "gathered":
            st = cb.adapter_stats()
            assert st["mode"] == "gathered"
            assert st["gathers"] >= 1
    for k in streams["dense"]:
        dtoks, dlogp = streams["dense"][k]
        gtoks, glogp = streams["gathered"][k]
        assert gtoks == dtoks, f"{k}: token stream diverged"
        assert glogp == dlogp, f"{k}: logprob stream diverged"
    # oracle anchor (the dense arm is itself pinned elsewhere, but keep
    # the matrix honest against merged weights on the greedy row)
    if tp == 1:
        assert streams["gathered"]["a0"][0] == _oracle(
            merged[0], _prompt(300, 6, cfg), cfg, 8
        )


# --- K-overflow: more distinct adapters than compact slots ----------------


def test_k_overflow_defers_then_serves_exactly(setup):
    """3 distinct adapters over K=2 compact slots: the third request
    defers head-of-line (adapter_slots) until a holder retires, then
    serves bit-exact — nothing is dropped, nothing is wrong."""
    cfg, params, aset, merged, _ = setup
    cb = ContinuousBatcher(params, cfg, n_slots=3, max_len=64,
                           chunked_prefill=8, adapters=aset, lora_slots=2)
    want, rids = {}, {}
    for a, seed in ((0, 310), (1, 311), (2, 312)):
        p = _prompt(seed, 5, cfg)
        rids[a] = cb.submit(p, max_new=6, adapter=a)
        want[a] = _oracle(merged[a], p, cfg, 6)
    done = cb.run()
    for a, rid in rids.items():
        assert done[rid] == want[a], f"adapter {a}"
    st = cb.adapter_stats()
    assert st["deferrals"].get("adapter_slots", 0) >= 1
    assert st["lora_slots"] == 2 and st["registered"] == 3


# --- residency-miss deferral (fault-injected) + cancel-while-deferred ----


def test_residency_miss_defers_and_stream_is_baseline_exact(setup):
    """An injected adapter.upload fault reads as an in-flight HBM
    upload: the admission defers once, retries next pass, and the
    stream is bit-identical to the unfaulted baseline."""
    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    cfg, params, aset, merged, _ = setup
    p = _prompt(320, 6, cfg)
    base = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                             chunked_prefill=8, adapters=aset, lora_slots=2)
    rid = base.submit(p, max_new=6, adapter=1)
    want = base.run()[rid]
    assert want == _oracle(merged[1], p, cfg, 6)

    plane = FaultPlane.from_spec("adapter.upload:nth=1")
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           chunked_prefill=8, adapters=aset, lora_slots=2,
                           faults=plane)
    rid = cb.submit(p, max_new=6, adapter=1)
    done = cb.run()
    assert done[rid] == want
    st = cb.adapter_stats()
    assert st["deferrals"].get("adapter_miss", 0) == 1
    assert plane.point("adapter.upload").fired == 1


def test_cancel_while_deferred_leaves_store_clean(setup):
    """A request cancelled while adapter-deferred (holding NO pages, NO
    slot) must vanish without a trace: the next request's stream is
    bit-identical to a batcher that never saw the cancelled one."""
    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    cfg, params, aset, merged, _ = setup
    p2 = _prompt(331, 5, cfg)
    base = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                             chunked_prefill=8, adapters=aset, lora_slots=2)
    base_rid = base.submit(p2, max_new=6, adapter=0)
    want = base.run()[base_rid]

    # every hit fires for a while: the first request stays deferred
    plane = FaultPlane.from_spec("adapter.upload:p=1.0:seed=1:times=4")
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           chunked_prefill=8, adapters=aset, lora_slots=2,
                           faults=plane)
    rid1 = cb.submit(_prompt(330, 5, cfg), max_new=6, adapter=2)
    cb.run(max_steps=2)
    assert rid1 in {r.rid for r in cb.pending}  # still deferred, queued
    assert cb.adapter_stats()["deferrals"].get("adapter_miss", 0) == 1
    assert cb.cancel(rid1)
    rid2 = cb.submit(p2, max_new=6, adapter=0)
    done = cb.run()
    assert done[rid2] == want == _oracle(merged[0], p2, cfg, 6)
    assert not cb.pending and not cb.running and not cb.prefilling


# --- AdapterStore: LRU residency under a budget ---------------------------


def test_lru_residency_budget_evicts_and_stays_exact(setup):
    """A budget of ONE adapter's bytes: serving 0 -> 1 -> 2 serially
    uploads on miss and LRU-evicts idle adapters; every stream stays
    oracle-exact (residency is a cost knob, not a numerics knob)."""
    cfg, params, aset, merged, entries = setup
    store = AdapterStore.from_set(cfg, aset, cache_bytes=1)
    # cache_bytes=1 < adapter_bytes: the soft-floor budget keeps exactly
    # the batch-protected + newest adapter resident
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           chunked_prefill=8, adapters=store, lora_slots=1)
    for a, seed in ((0, 340), (1, 341), (2, 342)):
        p = _prompt(seed, 5, cfg)
        rid = cb.submit(p, max_new=6, adapter=a)
        done = cb.run()
        assert done[rid] == _oracle(merged[a], p, cfg, 6), f"adapter {a}"
    st = cb.adapter_stats()
    assert st["evictions"] >= 1
    assert st["uploads"] >= 3
    assert st["resident"] <= 2  # protected + at most the newest upload
    assert st["deferrals"].get("adapter_miss", 0) >= 1  # async upload wait


# --- dynamic registration / unregistration --------------------------------


def test_dynamic_register_serve_unregister(setup):
    """Register at runtime, serve oracle-exact, unregister: the index
    tombstones (submit rejects it loudly), /v1/models-style name lists
    drop it, and re-registration appends a fresh index."""
    cfg, params, _, merged, entries = setup
    # the store's target set freezes at FIRST registration (the compact
    # stacks are static-shaped): seed it with the widest adapter (ad1's
    # default wq/wk/wv/wo) so narrower ones (ad2: wq/wk) nest
    name1, lp1, lc1 = entries[1]
    name2, lp2, lc2 = entries[2]
    store = AdapterStore(cfg)
    store.register(name1, lp1, lc1)
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           chunked_prefill=8, adapters=store, lora_slots=2)
    idx2 = cb.register_adapter(name2, lp2, lc2)
    assert idx2 == 1 and cb.adapter_names == (name1, name2)
    p = _prompt(350, 5, cfg)
    rid = cb.submit(p, max_new=6, adapter=idx2)
    assert cb.run()[rid] == _oracle(merged[2], p, cfg, 6)

    assert cb.unregister_adapter(name2) == idx2
    assert cb.adapter_names == (name1, "")  # tombstone renders ""
    with pytest.raises(ValueError, match="unregistered"):
        cb.submit(p, max_new=4, adapter=idx2)
    # indices are stable forever: a new adapter appends, never reuses
    lc9 = LoraConfig(rank=2, alpha=4.0, targets=("wv",))
    lp9 = _rand_b(init_lora_params(jax.random.key(9), cfg, lc9), 99)
    assert cb.register_adapter("ad9", lp9, lc9) == 2


def test_unregister_refuses_live_and_evicts_prefix_root(setup):
    """Unregistering an adapter with live requests refuses; after they
    drain, unregistration evicts the adapter's whole prefix-cache
    subtree (its rows can never match again)."""
    from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache

    cfg, params, aset, merged, entries = setup
    pc = PrefixCache(cfg, buckets=(8, 16), budget_bytes=1 << 26)
    store = AdapterStore.from_set(cfg, aset)
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           prompt_buckets=(8, 16), chunked_prefill=8,
                           adapters=store, lora_slots=2, prefix_cache=pc)
    sys_prompt = _prompt(360, 12, cfg)
    rid = cb.submit(sys_prompt + _prompt(361, 4, cfg), max_new=4, adapter=2)
    with pytest.raises(ValueError, match="live requests"):
        cb.unregister_adapter("ad2")
    cb.run()
    assert rid in cb.done
    base_entries = pc.stats.as_dict()["entries"]
    assert base_entries >= 1  # the finished prefill promoted rows
    cb.unregister_adapter("ad2")
    after = pc.stats.as_dict()
    assert after["entries"] < base_entries  # adapter-2 subtree gone
    assert after["evictions"] >= 1


# --- per-adapter hard quotas (serving/scheduler.py) -----------------------


def test_adapter_quota_hard_rejects_and_refunds():
    """The --adapterQuota token bucket: over-quota submits raise the
    429-mapped overload error under BOTH policies; a queued death
    refunds its charge; base/unmetered adapters never touch a bucket."""
    from k8s_gpu_device_plugin_tpu.serving.scheduler import (
        SchedulerOverloadError,
        make_scheduler,
        parse_adapter_quotas,
    )

    assert parse_adapter_quotas("") == {}
    q = parse_adapter_quotas("fr=100,de=50:burst=60")
    assert q["fr"].burst == 400.0 and q["de"].burst == 60.0
    for bad in ("noeq", "x=0", "x=1:weight=2", "=5"):
        with pytest.raises(ValueError):
            parse_adapter_quotas(bad)
    with pytest.raises(ValueError):
        make_scheduler("fifo", tenant_quota="t=5")  # fifo refuses tenant
    # ...but adapter quotas are capacity protection: fifo enforces them

    class Req:
        def __init__(self, rid, adapter, n=10):
            self.rid, self.adapter = rid, adapter
            self.prompt = [1] * n
            self.max_new = 10
            self.tenant, self.priority = "default", 1
            self.deadline, self.out = None, []
            self.preemptions, self.t_submit, self.span = 0, 0.0, None

    class CB:
        pending: list = []
        metrics = None
        adapter_names = ("fr", "de")

    import time as _time

    for policy in ("fifo", "slo"):
        s = make_scheduler(policy, adapter_quota="fr=1:burst=30")
        cb = CB()
        s.on_submit(Req(1, 0), cb)  # cost 20 of burst 30
        with pytest.raises(SchedulerOverloadError) as ei:
            s.on_submit(Req(2, 0), cb)
        assert ei.value.reason == "adapter_quota"
        assert ei.value.retry_after >= 1
        s.on_submit(Req(3, -1), cb)   # base: no bucket
        s.on_submit(Req(4, 1), cb)    # unmetered adapter: no bucket
        # queued death refunds -> the next submit passes again
        s.on_retired(Req(1, 0), cb, "cancelled", _time.perf_counter())
        s.on_submit(Req(5, 0), cb)
        st = s.sched_stats()
        assert st["adapters"]["fr"]["rejected"] == 1
        assert st["adapters"]["fr"]["submitted"] == 3
        assert st["rejections"]["adapter_quota"] == 1


# --- router affinity fold --------------------------------------------------


def test_router_folds_listed_adapters_only():
    """Both surfaces extract the adapter; LISTED names prefix-fold the
    affinity key (and count on /fleet/health); unlisted/base requests
    keep the pre-adapter key byte-identical."""
    from k8s_gpu_device_plugin_tpu.serving.fleet import (
        FleetRegistry,
        affinity_key,
    )
    from k8s_gpu_device_plugin_tpu.serving.router import ReplicaRouter

    fleet = FleetRegistry.from_spec("http://a:1,http://b:2")
    r = ReplicaRouter(fleet, adapter_names=("fr", "de"))
    bk = affinity_key([1] * 40, r.prompt_buckets)

    assert r._fold_adapter(
        "/v1/generate", {"prompt": [1] * 40, "adapter": "fr"}, bk
    ) == b"a:fr\x00" + bk
    assert r._fold_adapter(
        "/v1/chat/completions", {"model": "de", "messages": []}, bk
    ) == b"a:de\x00" + bk
    # keyless adapter request still concentrates on a home
    assert r._fold_adapter("/v1/generate", {"adapter": "fr"}, None) \
        == b"a:fr\x00"
    # byte-identical pins: unlisted name, base model id, bare request
    for body in ({"prompt": [1] * 40, "adapter": "xx"},
                 {"prompt": [1] * 40},
                 {"model": "tpu-serving"}):
        assert r._fold_adapter("/v1/generate", body, bk) == bk
    assert r.router_stats()["adapter_requests"] == {"fr": 2, "de": 1}

    # a router constructed without names is a no-op on every request
    r2 = ReplicaRouter(FleetRegistry.from_spec("http://a:1"))
    assert r2._fold_adapter(
        "/v1/generate", {"prompt": [1] * 40, "adapter": "fr"}, bk
    ) == bk
    assert r2.router_stats()["adapter_requests"] == {}


# --- metrics hooks ---------------------------------------------------------


def test_adapter_metrics_surface(setup):
    """The ServingMetrics adapter section: residency gauges track the
    store, deferral/upload counters fire through the duck-typed hooks
    the batcher and store call."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params, aset, merged, _ = setup
    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    try:
        cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                               chunked_prefill=8, adapters=aset,
                               lora_slots=2, metrics=m)
        p = _prompt(370, 5, cfg)
        rid = cb.submit(p, max_new=5, adapter=0)
        assert cb.run()[rid] == _oracle(merged[0], p, cfg, 5)
        assert reg.get_sample_value("tpu_serving_adapters_registered") == 3
        assert reg.get_sample_value("tpu_serving_adapters_resident") == 3
        assert reg.get_sample_value("tpu_serving_adapter_resident_bytes") > 0
        assert reg.get_sample_value("tpu_serving_adapter_gathers_total") >= 1
    finally:
        m.close()
