"""Chip-level observability plane (plugin/journal.py +
device/allocation.py + the federation/attribution seams).

The acceptance pin this file exists for: one ``Allocate`` through the
fake-backend plugin stack, an engine started with the resulting env
contract, and one served request yield a ``/debug/allocations`` entry,
a serving request timeline, and a stitched trace that all name the
SAME physical chip ids — and ``/fleet/metrics`` including the plugin
series parses under the strict OpenMetrics parser. Unit tests cover
the pure pieces (AllocatedDevices parsing, the journal's two-tier
ring + deterministic replay, the tp shard→chip mapping)."""

import asyncio

import aiohttp
import jax
import pytest
from prometheus_client import CollectorRegistry

from k8s_gpu_device_plugin_tpu.device.allocation import AllocatedDevices
from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import ServingMetrics
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.obs.trace import configure
from k8s_gpu_device_plugin_tpu.plugin import api
from k8s_gpu_device_plugin_tpu.plugin.api import pb
from k8s_gpu_device_plugin_tpu.plugin.journal import AllocationJournal
from k8s_gpu_device_plugin_tpu.plugin.testing import (
    start_http_stack,
    stop_http_stack,
)
from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine
from k8s_gpu_device_plugin_tpu.serving.testing import (
    inprocess_fleet,
    stream_generate,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


@pytest.fixture()
def tracer():
    t = configure(enabled=True)
    t.clear()
    yield t
    configure(enabled=False)
    t.clear()


# --- AllocatedDevices (pure) ------------------------------------------------


def test_allocated_devices_env_and_spec_parsing():
    env = {
        "TPU_VISIBLE_CHIPS": "2,0,1",
        "TPU_ALLOCATION_ID": "alloc-7",
        "TPU_ACCELERATOR_TYPE": "v5e-3",
    }
    d = AllocatedDevices.from_env(env)
    assert d is not None
    assert d.chips == (0, 1, 2)          # sorted
    assert d.allocation_id == "alloc-7"
    assert d.generation == "v5e"
    assert d.source == "env"
    assert d.chips_label() == "0,1,2"
    assert d.shard_chip(0) == 0 and d.shard_chip(2) == 2
    assert d.shard_chip(3) is None and d.shard_chip(-1) is None
    assert d.as_dict()["chips"] == [0, 1, 2]

    # absent / garbage env -> None (attribution silently off, the
    # engine must still serve)
    assert AllocatedDevices.from_env({}) is None
    assert AllocatedDevices.from_env({"TPU_VISIBLE_CHIPS": "x,y"}) is None

    # explicit spec: with and without the alloc-id prefix
    s = AllocatedDevices.from_spec("job-1:4,5")
    assert s.allocation_id == "job-1" and s.chips == (4, 5)
    bare = AllocatedDevices.from_spec("0,1")
    assert bare.allocation_id == "" and bare.chips == (0, 1)
    for garbage in ("", "a,b", "1,,2", "id:"):
        with pytest.raises(ValueError):
            AllocatedDevices.from_spec(garbage)


# --- AllocationJournal (pure) -----------------------------------------------


def test_allocation_journal_two_tier_paging_and_replay():
    j = AllocationJournal(maxlen=8, rare_maxlen=4)
    aid = j.next_allocation_id()
    assert aid == "alloc-1"
    j.emit("allocate", allocation_id=aid, resource="google.com/tpu",
           devices=["d0"], chips=[0, 1], coords=[[0, 0], [1, 0]])
    j.emit("preferred_allocation", resource="google.com/tpu", size=2,
           available=4, must_include=[], preferred=["d0"])
    # the storm: a flapping chip's transitions are the FREQUENT tier
    # here (inverted vs the fleet journal) — they must not evict the
    # allocation history
    for i in range(100):
        j.emit("health_transition", chip=i % 4, old="Healthy",
               new="Unknown", reason="stale_gauges")
    payload = j.events_payload()
    kinds = {e["kind"] for e in payload["events"]}
    assert {"allocate", "preferred_allocation"} <= kinds
    seqs = [e["seq"] for e in payload["events"]]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert payload["total"] == 102
    # paging: since walks forward, limit keeps the OLDEST of the rest
    page = j.events_payload(limit=1, since=1)
    assert [e["seq"] for e in page["events"]] == [2]
    assert page["events"][0]["kind"] == "preferred_allocation"
    # ownership: last-allocated wins per chip
    assert j.owners()[0]["allocation_id"] == aid
    assert j.owners()[1]["devices"] == ["d0"]
    # replay strips exactly the nondeterministic fields
    replay = AllocationJournal.replay(payload["events"])
    assert all("t" not in e and "trace_id" not in e for e in replay)
    assert replay[0] == {
        "seq": 1, "kind": "allocate", "allocation_id": "alloc-1",
        "resource": "google.com/tpu", "devices": ["d0"],
        "chips": [0, 1], "coords": [[0, 0], [1, 0]],
    }
    # allocation ids stay monotonic across emits
    assert j.next_allocation_id() == "alloc-2"
    assert j.stats()["allocations"] == 2


# --- engine wiring (refusal + tp shard→chip) --------------------------------


def test_injected_batcher_refuses_engine_level_devices(setup):
    cfg, params = setup
    donor = InferenceEngine(params, cfg, n_slots=2, max_len=64,
                            chunked_prefill=8)
    try:
        with pytest.raises(ValueError, match="injected batcher"):
            InferenceEngine(
                params, cfg, batcher=donor.cb,
                devices=AllocatedDevices.from_spec("0,1"),
            )
    finally:
        donor.shutdown()


def test_tp_shards_carry_chip_mapping(setup):
    """Under tp>1 each kv shard names its physical chip on /v1/health's
    kv view and the ``tpu_serving_kv_shard_chip`` gauge (shard i ->
    chips[i], the plugin's own chip indices)."""
    cfg, params = setup
    reg = CollectorRegistry()
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
        kv_layout="paged", kv_page_size=8, tp=2,
        metrics=ServingMetrics(registry=reg),
        devices=AllocatedDevices.from_spec("alloc-9:4,6"),
    )
    try:
        kv = engine.cb.kv_stats()
        assert [s["chip"] for s in kv["shards"]] == [4, 6]
        assert engine.stats()["devices"]["allocation_id"] == "alloc-9"
        sample = reg.get_sample_value(
            "tpu_serving_kv_shard_chip", {"shard": "0", "chip": "4"}
        )
        assert sample == 1.0
    finally:
        engine.shutdown()


# --- E2E: the acceptance pin ------------------------------------------------


async def _allocate_whole_host(kubelet, manager):
    """Allocate every chip of the booted stack's one plugin; returns
    the env contract the container would see."""
    await kubelet.wait_for_registrations(1)
    reg = kubelet.registrations[0]
    chips = manager.plugins[0].chips
    async with kubelet.plugin_channel(reg.endpoint) as channel:
        stub = api.DevicePluginStub(channel)
        resp = await stub.Allocate(
            pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devicesIDs=chips.ids())
            ])
        )
    return dict(resp.container_responses[0].envs)


def test_chip_attribution_end_to_end(setup, tracer, tmp_path):
    """Allocate -> engine startup -> one served request: the journal
    entry, the request timeline, and the stitched trace all name the
    SAME chip ids; /fleet/metrics with the plugin series parses under
    the strict OpenMetrics parser; /debug/topology maps ownership."""
    cfg, params = setup

    async def body():
        stack = await start_http_stack(tmp_path, "v5e-4")
        kubelet, manager, task, backend, server, http_task, stop, base = \
            stack
        try:
            envs = await _allocate_whole_host(kubelet, manager)
            devices = AllocatedDevices.from_env(envs)
            assert devices is not None
            chip_ids = list(devices.chips)
            assert chip_ids == [0, 1, 2, 3]
            assert devices.allocation_id  # the plugin stamped the key

            def engine_factory(i):
                from k8s_gpu_device_plugin_tpu.obs.attribution import (
                    RequestAttributor,
                )

                return InferenceEngine(
                    params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
                    metrics=ServingMetrics(registry=CollectorRegistry()),
                    attribution=RequestAttributor(),
                    devices=devices,
                )

            def server_factory(i, engine):
                from k8s_gpu_device_plugin_tpu.serving.server import (
                    InferenceServer,
                )

                return InferenceServer(
                    engine, host="127.0.0.1", port=0, replica_id=f"r{i}",
                    registry=engine.cb.metrics._registry,
                )

            async with inprocess_fleet(
                params, cfg, n_replicas=1,
                engine_factory=engine_factory,
                server_factory=server_factory,
                router_kw=dict(health_interval_s=0.1,
                               plugins=[("node0", base)]),
            ) as ctx:
                async with aiohttp.ClientSession() as session:
                    stream = await stream_generate(
                        session, ctx.base, prompt=[5, 6, 7, 8], max_new=4,
                    )
                    assert stream["done"] and len(stream["tokens"]) == 4

                    # 1) the journal entry (plugin plane)
                    async with session.get(
                        f"{base}/debug/allocations"
                    ) as r:
                        assert r.status == 200
                        alloc_page = (await r.json())["data"]
                    allocs = [e for e in alloc_page["events"]
                              if e["kind"] == "allocate"]
                    assert len(allocs) == 1
                    assert allocs[0]["chips"] == chip_ids
                    assert allocs[0]["allocation_id"] == \
                        devices.allocation_id
                    assert len(allocs[0]["coords"]) == len(chip_ids)

                    # shared query surface: paging + 400-on-garbage
                    async with session.get(
                        f"{base}/debug/allocations?limit=1"
                    ) as r:
                        page = (await r.json())["data"]
                    assert page["returned"] == 1
                    assert page["total"] == alloc_page["total"]
                    last_seq = alloc_page["events"][-1]["seq"]
                    async with session.get(
                        f"{base}/debug/allocations?since={last_seq}"
                    ) as r:
                        assert (await r.json())["data"]["events"] == []
                    for bad in ("limit=x", "limit=-1", "since=nope"):
                        async with session.get(
                            f"{base}/debug/allocations?{bad}"
                        ) as r:
                            assert r.status == 400

                    # 2) the request timeline (serving plane)
                    async with session.get(
                        f"{ctx.replica_base(0)}/debug/requests"
                    ) as r:
                        reqs = (await r.json())["requests"]
                    assert reqs
                    record = reqs[0]
                    assert record["chips"] == devices.chips_label()
                    assert record["allocation_id"] == \
                        devices.allocation_id
                    tid = record["trace_id"]
                    assert tid

                    # /v1/health carries the frozen device set
                    async with session.get(
                        f"{ctx.replica_base(0)}/v1/health"
                    ) as r:
                        health = await r.json()
                    assert health["devices"]["chips"] == chip_ids
                    assert health["devices"]["allocation_id"] == \
                        devices.allocation_id

                    # 3) the stitched trace names the same chips
                    await asyncio.sleep(0.3)  # span tree closes async
                    async with session.get(
                        f"{ctx.base}/fleet/debug/traces/{tid}"
                    ) as r:
                        assert r.status == 200
                        stitched = await r.json()
                    chip_spans = [
                        e for e in stitched["traceEvents"]
                        if e.get("ph") == "X"
                        and e["args"].get("chips")
                    ]
                    assert chip_spans
                    assert {e["args"]["chips"] for e in chip_spans} == \
                        {devices.chips_label()}
                    assert {e["args"]["allocation_id"]
                            for e in chip_spans} == \
                        {devices.allocation_id}

                    # 4) /fleet/events merges the plugin journal in
                    async with session.get(
                        f"{ctx.base}/fleet/events"
                    ) as r:
                        events = await r.json()
                    assert events["plugin_nodes"] == ["node0"]
                    plugin_events = [e for e in events["events"]
                                     if e.get("plane") == "plugin"]
                    assert plugin_events
                    assert {e["node"] for e in plugin_events} == {"node0"}
                    merged_alloc = next(
                        e for e in plugin_events if e["kind"] == "allocate"
                    )
                    assert merged_alloc["chips"] == chip_ids
                    assert all(e.get("plane") == "fleet"
                               for e in events["events"]
                               if "node" not in e)

                    # 5) federation: plugin series + chip aggregates
                    # parse under BOTH parsers (strict OpenMetrics pinned)
                    async with session.get(
                        f"{ctx.base}/fleet/metrics"
                    ) as r:
                        assert r.status == 200
                        classic = await r.text()
                    async with session.get(
                        f"{ctx.base}/fleet/metrics",
                        headers={
                            "Accept": "application/openmetrics-text"
                        },
                    ) as r:
                        assert "openmetrics" in r.headers["Content-Type"]
                        om = await r.text()
                    from prometheus_client.openmetrics.parser import (
                        text_string_to_metric_families as parse_om,
                    )
                    from prometheus_client.parser import (
                        text_string_to_metric_families as parse_classic,
                    )

                    for fams in (
                        {f.name: f for f in parse_classic(classic)},
                        {f.name: f for f in parse_om(om)},
                    ):
                        chips_fam = fams["tpu_plugin_chips"]
                        assert all(
                            s.labels.get("node") == "node0"
                            for s in chips_fam.samples
                        )
                        healthy = next(
                            s for s in fams["tpu_fleet_chips"].samples
                            if s.labels["state"] == "healthy"
                        )
                        assert healthy.value == 4
                        assert fams["tpu_fleet_plugin_nodes"] \
                            .samples[0].value == 1
                        assert fams["tpu_fleet_plugin_scrape_errors"] \
                            .samples[0].value == 0
                        # serving series still replica-labeled alongside
                        tok = fams["tpu_serving_generated_tokens"]
                        assert {s.labels["replica"]
                                for s in tok.samples} == {"r0"}

                    # 6) /debug/topology: grid + links + ownership
                    async with session.get(f"{base}/debug/topology") as r:
                        assert r.status == 200
                        topo = (await r.json())["data"]
                    assert topo["num_chips"] == 4
                    assert len(topo["chips"]) == 4
                    for chip in topo["chips"]:
                        assert chip["health"] == "Healthy"
                        assert chip["owner"]["allocation_id"] == \
                            devices.allocation_id
                        assert chip["device"]["resource"]
                    assert topo["links"]  # a v5e-4 grid has ICI edges
                    assert all(
                        0 <= a < 4 and 0 <= b < 4
                        for a, b in topo["links"]
                    )
        finally:
            await stop_http_stack(kubelet, manager, task, http_task, stop)

    run(body())


# --- replay determinism (the fleet journal pin, plugin plane) ---------------


def test_plugin_journal_replay_determinism_under_health_flap(tmp_path):
    """Two same-seed runs — Allocate, chip 2 dies, chip 2 recovers —
    replay IDENTICAL plugin journals (wall time and trace ids are the
    only divergence), the fleet journal's determinism contract extended
    to the plugin plane."""

    async def one_run(socket_dir):
        stack = await start_http_stack(socket_dir, "v5e-4",
                                       health_interval=0.05)
        kubelet, manager, task, backend, server, http_task, stop, base = \
            stack
        try:
            await _allocate_whole_host(kubelet, manager)

            async def wait_health(idx, state):
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    chips = manager.plugins[0].chips
                    by_idx = {
                        i: c.health for c in chips.values()
                        for i in c.chip_indices
                    }
                    if by_idx.get(idx) == state:
                        return
                raise AssertionError(
                    f"chip {idx} never reached {state}"
                )

            backend.set_unhealthy(2)
            await wait_health(2, "Unhealthy")
            backend.set_healthy(2)
            await wait_health(2, "Healthy")
            return manager.journal.events_payload()["events"]
        finally:
            await stop_http_stack(kubelet, manager, task, http_task, stop)

    events_a = run(one_run(tmp_path / "a"))
    events_b = run(one_run(tmp_path / "b"))
    replay_a = AllocationJournal.replay(events_a)
    replay_b = AllocationJournal.replay(events_b)
    assert replay_a == replay_b
    kinds = [e["kind"] for e in replay_a]
    assert kinds.count("health_transition") == 2
    flips = [e for e in replay_a if e["kind"] == "health_transition"]
    assert [e["reason"] for e in flips] == ["node_unhealthy", "recovered"]
    assert all(e["chip"] == 2 for e in flips)
