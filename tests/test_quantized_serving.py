"""Weight-only int8 serving: accuracy band, decode paths, composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.generate import KVCache, generate, prefill
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
    is_quantized_leaf,
    qmatmul,
    quantize_weights_int8,
)


def _setup():
    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def test_qmatmul_matches_float_within_band():
    kx, kw = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (8, 64), jnp.float32)
    w = jax.random.normal(kw, (64, 32), jnp.float32)
    from k8s_gpu_device_plugin_tpu.ops.quant import quantize_int8

    q, s = quantize_int8(w, axis=0)
    got = qmatmul(x, {"q": q, "s": s})
    ref = x @ w
    # per-element weight error <= scale/2; accumulated over K=64 gaussian
    # terms the relative output error stays well under 1%
    err = float(jnp.max(jnp.abs(got - ref)) / jnp.max(jnp.abs(ref)))
    assert err < 0.02, err
    # float weights pass through untouched
    np.testing.assert_array_equal(np.asarray(qmatmul(x, w)), np.asarray(ref))


def test_quantize_structure_and_memory():
    cfg, params = _setup()
    qp = quantize_weights_int8(params)
    for name in ("wq", "wk", "wv", "wo", "w1", "w2", "w3"):
        leaf = qp["layers"][name]
        assert is_quantized_leaf(leaf)
        assert leaf["q"].dtype == jnp.int8
        assert leaf["s"].dtype == jnp.float32
        assert leaf["q"].shape == params["layers"][name].shape
    assert is_quantized_leaf(qp["lm_head"])
    # norms/embed untouched
    assert qp["embed"].dtype == cfg.dtype
    assert qp["layers"]["attn_norm"].dtype == cfg.dtype


def test_quantized_prefill_logits_close_and_decode_runs():
    cfg, params = _setup()
    qp = quantize_weights_int8(params)
    prompt = jax.random.randint(
        jax.random.key(2), (2, 10), 0, cfg.vocab_size, jnp.int32
    )
    ref, _ = prefill(params, prompt, KVCache.init(cfg, 2, 16), cfg)
    got, _ = prefill(qp, prompt, KVCache.init(cfg, 2, 16), cfg)
    # logits within the per-channel int8 band: measured 0.0068 max abs on
    # this model's O(1) logits (~1%); 0.02 leaves 3x headroom while still
    # failing loudly on an order-of-magnitude accuracy regression
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.02)
    toks = generate(qp, prompt, cfg, max_new=6)
    base = generate(params, prompt, cfg, max_new=6)
    agree = float(np.mean(np.asarray(toks) == np.asarray(base)))
    assert agree >= 0.5, agree  # near-lossless on most steps


def test_quantized_weights_compose_with_decode_features():
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.beam import beam_search
    from k8s_gpu_device_plugin_tpu.models.rolling import rolling_generate

    cfg, params = _setup()
    qp = quantize_weights_int8(params)
    prompt = jnp.arange(1, 7, dtype=jnp.int32)[None, :]

    seqs, scores = beam_search(qp, prompt, cfg, max_new=4, beam=3)
    assert seqs.shape == (3, 4) and bool(jnp.isfinite(scores).all())

    cfg_w = replace(cfg, sliding_window=8)
    toks = rolling_generate(qp, prompt, cfg_w, max_new=12)
    assert toks.shape == (1, 12)

    cfg_c = replace(cfg, cache_quant="int8")
    toks = generate(qp, prompt, cfg_c, max_new=4)
    assert toks.shape == (1, 4)


def test_moe_quantized_structure():
    cfg = LlamaConfig.tiny(n_layers=1, n_experts=4)
    params = init_params(jax.random.key(0), cfg)
    qp = quantize_weights_int8(params)
    for name in ("moe_w1", "moe_w3", "moe_w2"):
        leaf = qp["layers"][name]
        assert is_quantized_leaf(leaf)
        assert leaf["q"].dtype == jnp.int8
        # per-(layer, expert, output-channel) scales
        L, E, _, out = params["layers"][name].shape
        assert leaf["s"].shape == (L, E, 1, out)


def test_moe_quantized_decode_close_to_float():
    """MoE expert stacks quantize per-(expert, output-channel); decode over
    the quantized Mixtral-style model stays within the int8 band of the
    float path and routing still works (greedy tokens mostly agree)."""
    cfg = LlamaConfig.tiny(
        n_layers=2, n_experts=4, capacity_factor=8.0, dtype=jnp.float32
    )
    params = init_params(jax.random.key(0), cfg)
    qp = quantize_weights_int8(params)
    assert is_quantized_leaf(qp["layers"]["moe_w1"])
    assert qp["layers"]["router"].dtype == jnp.float32  # router stays float
    prompt = jax.random.randint(
        jax.random.key(3), (1, 10), 0, cfg.vocab_size, jnp.int32
    )
    ref, _ = prefill(params, prompt, KVCache.init(cfg, 1, 16), cfg)
    got, _ = prefill(qp, prompt, KVCache.init(cfg, 1, 16), cfg)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=0.05
    )
    base = generate(params, prompt, cfg, max_new=8)
    toks = generate(qp, prompt, cfg, max_new=8)
    assert float(np.mean(np.asarray(toks) == np.asarray(base))) >= 0.5


# ---------------- int4 (group-wise) ----------------


def test_q4_matmul_matches_manual_dequant_exactly():
    """The grouped-contraction einsum must equal the mathematically
    identical dequantize-then-matmul reference (same f32 ops reassociated;
    tolerance covers reassociation only)."""
    from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
        _q4_matmul,
    )
    from k8s_gpu_device_plugin_tpu.ops.quant import quantize_int4_grouped

    kx, kw = jax.random.split(jax.random.key(4))
    x = jax.random.normal(kx, (8, 64), jnp.float32)
    w = jax.random.normal(kw, (64, 32), jnp.float32)
    q, s = quantize_int4_grouped(w, group=16)
    deq = (
        q.astype(jnp.float32).reshape(4, 16, 32) * s[:, None, :]
    ).reshape(64, 32)
    got = _q4_matmul(x, {"q4": q, "s": s})
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ deq),
                               rtol=1e-5, atol=1e-5)


def test_q4_quantize_structure():
    from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
        is_quantized4_leaf,
        quantize_weights_int4,
    )

    cfg, params = _setup()
    qp = quantize_weights_int4(params, group=32)
    for name in ("wq", "wk", "wv", "wo", "w1", "w2", "w3"):
        leaf = qp["layers"][name]
        assert is_quantized4_leaf(leaf)
        assert leaf["q4"].dtype == jnp.int4
        assert leaf["s"].dtype == jnp.float32
        L, k, out = params["layers"][name].shape
        assert leaf["s"].shape == (L, k // 32, out)
    assert is_quantized4_leaf(qp["lm_head"])
    assert qp["embed"].dtype == cfg.dtype


def test_q4_prefill_logits_close_and_decode_agrees():
    """int4-g32 stays within the group-wise band (looser than int8 —
    4-bit weights — but the decode argmax should still mostly agree on
    the tiny model)."""
    from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
        quantize_weights_int4,
    )

    cfg, params = _setup()
    qp = quantize_weights_int4(params, group=32)
    prompt = jax.random.randint(
        jax.random.key(5), (2, 10), 0, cfg.vocab_size, jnp.int32
    )
    ref, _ = prefill(params, prompt, KVCache.init(cfg, 2, 16), cfg)
    got, _ = prefill(qp, prompt, KVCache.init(cfg, 2, 16), cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.12)
    # No argmax-agreement assertion: a RANDOM-init tiny model has near-
    # uniform logits, so the (legitimate) int4 band scrambles its argmax
    # even though the band is small in absolute terms. On trained models
    # int4-g128 is the standard near-lossless serving recipe; here the
    # meaningful pin is the logit band above plus decode running at all.
    toks = generate(qp, prompt, cfg, max_new=6)
    assert toks.shape == (2, 6)


def test_q4_composes_with_decode_features():
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
    from k8s_gpu_device_plugin_tpu.models.beam import beam_search
    from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
        quantize_weights_int4,
    )
    from k8s_gpu_device_plugin_tpu.models.rolling import rolling_generate

    cfg, params = _setup()
    qp = quantize_weights_int4(params, group=32)
    prompt = jnp.arange(1, 7, dtype=jnp.int32)[None, :]

    seqs, scores = beam_search(qp, prompt, cfg, max_new=4, beam=3)
    assert seqs.shape == (3, 4) and bool(jnp.isfinite(scores).all())

    cfg_w = replace(cfg, sliding_window=8)
    toks = rolling_generate(qp, prompt, cfg_w, max_new=12)
    assert toks.shape == (1, 12)

    # int4 weights + int8 KV cache + continuous batching, token-identical
    # to dedicated generate on the SAME quantized params
    cfg_c = replace(cfg, cache_quant="int8")
    cb = ContinuousBatcher(qp, cfg_c, n_slots=2, max_len=32,
                           prompt_buckets=(8,))
    rid = cb.submit(list(range(1, 7)), max_new=4)
    got = cb.run()[rid]
    base = generate(qp, prompt, cfg_c, max_new=4)
    assert got == np.asarray(base)[0].tolist()


# ---------------- quantized caches on the page pool ----------------


def test_cache_write_same_codes_same_scales_across_layouts():
    """The unit-level half of the paged-quant pin: `_cache_write`
    produces bitwise-identical int8 codes and f32 scales whether the
    destination is a dense cache row or a paged pool — the quantize
    happens BEFORE the scatter, so the layout can only move bytes,
    never change them."""
    from k8s_gpu_device_plugin_tpu.models.generate import _cache_write

    B, T, H, hd, ps = 2, 4, 2, 16, 8
    x = jax.random.normal(jax.random.key(7), (B, T, H, hd), jnp.float32)
    length = jnp.asarray([0, 8], jnp.int32)

    dense_c = jnp.zeros((B, 32, H, hd), jnp.int8)
    dense_s = jnp.zeros((B, 32, H, 1), jnp.float32)
    dc, ds = _cache_write(dense_c, dense_s, x, length)

    # page table: slot 0 -> pages [1, 2], slot 1 -> pages [3, 4]
    pages = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    pool_c = jnp.zeros((6, ps, H, hd), jnp.int8)
    pool_s = jnp.zeros((6, ps, H, 1), jnp.float32)
    pc, psc = _cache_write(pool_c, pool_s, x, length, pages=pages,
                           page_size=ps)
    # gather the paged view back through the table: bitwise the dense one
    gat_c = pc[pages].reshape(B, -1, H, hd)[:, :32]
    gat_s = psc[pages].reshape(B, -1, H, 1)[:, :32]
    np.testing.assert_array_equal(np.asarray(dc), np.asarray(gat_c))
    np.testing.assert_array_equal(np.asarray(ds), np.asarray(gat_s))


@pytest.mark.parametrize("cache_quant", ["int8", "int4"])
def test_quantized_paged_decode_bit_identical_to_dense(cache_quant):
    """The acceptance pin: int8-paged decode is bit-identical to
    int8-dense decode — same codes, same scales, so the same tokens AND
    the same logprobs, greedy and seeded alike (int4 rides the same
    assertion)."""
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher

    cfg, params = _setup()
    cfg_q = replace(cfg, cache_quant=cache_quant)

    def streams(layout):
        cb = ContinuousBatcher(
            params, cfg_q, n_slots=2, max_len=64,
            prompt_buckets=(8, 16, 32), chunked_prefill=8,
            pipeline_depth=1, kv_layout=layout,
            kv_page_size=16 if layout == "paged" else None,
        )
        prompts = [
            jax.random.randint(jax.random.key(60 + i), (n,), 1,
                               cfg.vocab_size, jnp.int32).tolist()
            for i, n in enumerate([5, 12, 3, 9])
        ]
        rids = [cb.submit(p, max_new=6, seed=13 if i % 2 else None)
                for i, p in enumerate(prompts)]
        cb.run()
        return [
            (tuple(cb.done[r]), tuple(cb.done_requests[r].out_logp))
            for r in rids
        ]

    assert streams("paged") == streams("dense")


def test_q4_moe_decode_close_to_float():
    from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
        is_quantized4_leaf,
        quantize_weights_int4,
    )

    cfg = LlamaConfig.tiny(
        n_layers=2, n_experts=4, capacity_factor=8.0, dtype=jnp.float32
    )
    params = init_params(jax.random.key(0), cfg)
    qp = quantize_weights_int4(params, group=32)
    assert is_quantized4_leaf(qp["layers"]["moe_w1"])
    L, E, k, out = params["layers"]["moe_w1"].shape
    assert qp["layers"]["moe_w1"]["s"].shape == (L, E, k // 32, out)
    assert qp["layers"]["router"].dtype == jnp.float32
    prompt = jax.random.randint(
        jax.random.key(6), (1, 10), 0, cfg.vocab_size, jnp.int32
    )
    ref, _ = prefill(params, prompt, KVCache.init(cfg, 1, 16), cfg)
    got, _ = prefill(qp, prompt, KVCache.init(cfg, 1, 16), cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=0.2)
    toks = generate(qp, prompt, cfg, max_new=8)
    assert toks.shape == (1, 8)
