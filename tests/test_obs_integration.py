"""End-to-end observability: a serving request through the
ContinuousBatcher produces the admit->prefill->decode->retire span tree,
retrievable as Perfetto JSON via GET /debug/traces, with the same
trace_id injected into the JSON log records emitted during the request;
TTFT/inter-token histograms fill; /debug/profile serves the live
BlockSampler summary; traceparent joins HTTP traces end to end.
"""

import asyncio
import json
import logging

import aiohttp
import jax
import jax.numpy as jnp
import pytest
from prometheus_client import CollectorRegistry

from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.obs.trace import configure, parse_traceparent
from k8s_gpu_device_plugin_tpu.utils.log import JsonFormatter, get_logger


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


@pytest.fixture
def tracer():
    tr = configure(enabled=True)
    tr.clear()
    try:
        yield tr
    finally:
        tr.enabled = False
        tr.clear()


@pytest.fixture
def debug_log_records():
    """Capture DEBUG-and-up records off the project logger (the shared
    captured_log_records fixture filters at INFO; the batcher's
    per-request lines are debug-level)."""
    records: list[logging.LogRecord] = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = Capture(level=logging.DEBUG)
    logger = get_logger()
    logger.addHandler(handler)
    try:
        yield records
    finally:
        logger.removeHandler(handler)


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _span_names(spans):
    return {s["name"] for s in spans}


def test_batcher_request_span_tree_bucketed(setup, tracer, debug_log_records):
    """The acceptance tree on the bucketed-prefill path, plus trace_id
    correlation in the JSON log records emitted during the request."""
    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           prompt_buckets=(8, 16))
    rid = cb.submit(_prompt(1, 5, cfg), max_new=4)
    cb.run()

    (summary,) = tracer.traces()
    assert summary["root"] == "request" and summary["status"] == "ok"
    spans = tracer.get_trace(summary["trace_id"])
    assert {"request", "admit", "prefill", "decode", "retire"} <= \
        _span_names(spans)
    by_name = {s["name"]: s for s in spans}
    root = by_name["request"]
    assert root["parent_id"] is None and root["attrs"]["rid"] == rid
    for child in ("admit", "prefill", "decode", "retire"):
        assert by_name[child]["parent_id"] == root["span_id"]
        assert by_name[child]["trace_id"] == root["trace_id"]
    assert by_name["retire"]["attrs"]["reason"] == "budget"
    assert by_name["decode"]["attrs"]["tokens"] == 4

    # the request's log records carry the SAME trace_id once formatted
    fmt = JsonFormatter()
    entries = [json.loads(fmt.format(r)) for r in debug_log_records]
    correlated = [e for e in entries if e.get("trace_id") == root["trace_id"]]
    assert {e["msg"] for e in correlated} >= {
        "request submitted", "request retired",
    }


def test_batcher_request_span_tree_chunked(setup, tracer):
    """Chunked-prefill admission: prefill_chunk spans replace the
    bucketed prefill span; multi-chunk prompts produce several."""
    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           chunked_prefill=4)
    cb.submit(_prompt(2, 10, cfg), max_new=3)
    cb.run()
    spans = tracer.get_trace(tracer.traces()[0]["trace_id"])
    chunks = [s for s in spans if s["name"] == "prefill_chunk"]
    assert len(chunks) >= 2  # 10 tokens / C=4 -> intermediate + final
    assert any(s["attrs"].get("final") for s in chunks)
    assert {"request", "admit", "decode", "retire"} <= _span_names(spans)


def test_cancel_closes_span_tree(setup, tracer):
    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           prompt_buckets=(8,))
    rid = cb.submit(_prompt(3, 4, cfg), max_new=32)
    cb.step()  # admit + first decode
    assert cb.cancel(rid)
    (summary,) = tracer.traces()  # cancel completes the trace
    spans = tracer.get_trace(summary["trace_id"])
    by_name = {s["name"]: s for s in spans}
    assert by_name["retire"]["attrs"]["reason"] == "cancelled"


def test_ttft_and_inter_token_histograms(setup):
    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    reg = CollectorRegistry()
    metrics = ServingMetrics(registry=reg)
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                           prompt_buckets=(8,), metrics=metrics)
    cb.submit(_prompt(4, 5, cfg), max_new=4)
    cb.submit(_prompt(5, 6, cfg), max_new=3)
    cb.run()

    def sample(name):
        return reg.get_sample_value(name)

    assert sample("tpu_serving_ttft_seconds_count") == 2
    # 2 requests emit 4+3 tokens; the first of each arrives at prefill,
    # so inter-token gaps = (4-1) + (3-1)
    assert sample("tpu_serving_inter_token_seconds_count") == 5
    assert sample("tpu_serving_ttft_seconds_sum") > 0
    metrics.close()


def test_batcher_disabled_tracing_leaves_no_traces(setup):
    cfg, params = setup
    tr = configure(enabled=False)
    tr.clear()
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           prompt_buckets=(8,))
    cb.submit(_prompt(6, 4, cfg), max_new=2)
    cb.run()
    assert tr.traces() == []


# --- control-plane HTTP surface -------------------------------------------


async def _control_plane(tmp_path, profiler=None, **cfg_kwargs):
    from k8s_gpu_device_plugin_tpu.config import Config
    from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
    from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
    from k8s_gpu_device_plugin_tpu.server.server import Server
    from k8s_gpu_device_plugin_tpu.utils.latch import Latch

    cfg = Config(
        kubelet_socket_dir=str(tmp_path),
        web_listen_address="127.0.0.1:0",
        libtpu_path="",
        **cfg_kwargs,
    )
    ready = Latch()
    manager = PluginManager(cfg, ready, backend=FakeBackend("v5e-4"))
    registry = CollectorRegistry()
    server = Server(cfg, manager, ready, registry=registry,
                    profiler=profiler)
    stop = asyncio.Event()
    mtask = asyncio.create_task(manager.start())
    stask = asyncio.create_task(server.run(stop))
    for _ in range(100):
        if server.port:
            break
        await asyncio.sleep(0.05)
    assert server.port, "server did not bind"

    async def teardown():
        stop.set()
        await manager.stop()
        await asyncio.gather(mtask, stask, return_exceptions=True)

    return f"http://127.0.0.1:{server.port}", registry, teardown


def test_debug_traces_endpoint_serves_batcher_trace(setup, tracer, tmp_path):
    """The acceptance path: drive a request through the batcher, then
    fetch its span tree over GET /debug/traces as Perfetto JSON."""
    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           prompt_buckets=(8,))
    cb.submit(_prompt(7, 5, cfg), max_new=3)
    cb.run()
    want = next(t for t in tracer.traces() if t["root"] == "request")

    async def body():
        base, _, teardown = await _control_plane(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/debug/traces") as resp:
                    assert resp.status == 200
                    data = (await resp.json())["data"]
                    assert data["enabled"] is True
                    ids = [t["trace_id"] for t in data["traces"]]
                    assert want["trace_id"] in ids
                async with session.get(
                    f"{base}/debug/traces/{want['trace_id']}"
                ) as resp:
                    assert resp.status == 200
                    chrome = await resp.json()
                # valid Chrome/Perfetto trace-event JSON with the tree
                events = chrome["traceEvents"]
                complete = [e for e in events if e["ph"] == "X"]
                names = {e["name"] for e in complete}
                assert {"request", "admit", "prefill", "decode",
                        "retire"} <= names
                assert all(
                    e["args"]["trace_id"] == want["trace_id"]
                    for e in complete
                )
                async with session.get(
                    f"{base}/debug/traces/{'0' * 32}"
                ) as resp:
                    assert resp.status == 404
        finally:
            await teardown()

    run(body())


def test_control_plane_traceparent_and_span_metrics(tracer, tmp_path):
    """HTTP middleware: an inbound W3C traceparent re-parents the
    request span (response echoes the same trace id), and span-duration
    histograms land on the server registry."""
    caller = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"

    async def body():
        base, registry, teardown = await _control_plane(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(
                    f"{base}/health", headers={"traceparent": caller}
                ) as resp:
                    assert resp.status == 200
                    echoed = parse_traceparent(resp.headers["traceparent"])
            assert echoed is not None and echoed.trace_id == "ab" * 16
            # the joined trace is in the buffer under the CALLER's id
            spans = tracer.get_trace("ab" * 16)
            assert spans and spans[0]["name"] == "GET /health"
            assert spans[0]["parent_id"] == "cd" * 8
            count = registry.get_sample_value(
                "tpu_obs_span_duration_seconds_count",
                {"component": "http", "operation": "GET /health"},
            )
            assert count == 1
        finally:
            await teardown()

    run(body())


def test_debug_profile_endpoint(tmp_path):
    from k8s_gpu_device_plugin_tpu.benchmark.profiler import Profiler

    profiler = Profiler(out_dir=str(tmp_path / "prof"))
    profiler.run()
    try:
        async def body():
            base, _, teardown = await _control_plane(
                tmp_path, profiler=profiler
            )
            try:
                async with aiohttp.ClientSession() as session:
                    async with session.get(f"{base}/debug/profile") as resp:
                        assert resp.status == 200
                        data = (await resp.json())["data"]
                    assert data["running"] is True
                    assert {"p50", "p99", "max"} <= set(
                        data["block"]["loop_lag_ms"]
                    )
                    assert isinstance(data["block"]["lock_waits"], list)
            finally:
                await teardown()

        run(body())
    finally:
        profiler.stop()


def test_debug_profile_404_without_profiler(tmp_path):
    async def body():
        base, _, teardown = await _control_plane(tmp_path)
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(f"{base}/debug/profile") as resp:
                    assert resp.status == 404
        finally:
            await teardown()

    run(body())


# --- serving HTTP plane ----------------------------------------------------


def test_serving_http_request_joins_batcher_tree(setup, tracer):
    """Full serving path: HTTP POST -> engine thread hop -> batcher
    span tree under the serving_http root, fetched back over the
    serving server's own /debug/traces."""
    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        InferenceServer,
    )

    cfg, params = setup
    engine = InferenceEngine(params, cfg, n_slots=2, max_len=64,
                             chunked_prefill=8)
    server = InferenceServer(engine, host="127.0.0.1", port=0)
    prompt = _prompt(8, 5, cfg)

    async def body():
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as session:
                async with session.post(f"{base}/v1/generate", json={
                    "prompt": prompt, "max_new": 3,
                }) as resp:
                    assert resp.status == 200
                    parent = parse_traceparent(resp.headers["traceparent"])
                assert parent is not None
                # the HTTP span's trace completes once the request
                # retires on the engine thread; poll the buffer briefly
                spans = None
                for _ in range(100):
                    spans = tracer.get_trace(parent.trace_id)
                    if spans and any(
                        s["name"] == "retire" for s in spans
                    ) and any(
                        s["name"].startswith("POST") for s in spans
                    ):
                        break
                    await asyncio.sleep(0.05)
                by_name = {s["name"]: s for s in spans}
                assert {"request", "admit", "decode", "retire"} <= set(by_name)
                http_root = by_name["POST /v1/generate"]
                assert http_root["parent_id"] is None
                # the thread hop preserved parentage: batcher root under
                # the HTTP span
                assert by_name["request"]["parent_id"] == http_root["span_id"]
                async with session.get(f"{base}/debug/traces") as resp:
                    assert resp.status == 200
                    listed = await resp.json()
                assert parent.trace_id in [
                    t["trace_id"] for t in listed["traces"]
                ]
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    run(body())


def test_serving_debug_traces_limit_and_since(setup, tracer):
    """GET /debug/traces pagination: ?limit= caps the summary count
    (keeping the newest), ?since= filters on start_us, `total` still
    reports the full buffer population, and malformed values answer
    400 — a long-running server never ships its whole ring per poll."""
    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        InferenceServer,
    )

    cfg, params = setup
    engine = InferenceEngine(params, cfg, n_slots=2, max_len=64,
                             chunked_prefill=8)
    server = InferenceServer(engine, host="127.0.0.1", port=0)

    async def body():
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as session:
                for i in range(3):
                    async with session.post(f"{base}/v1/generate", json={
                        "prompt": _prompt(40 + i, 5, cfg), "max_new": 2,
                    }) as resp:
                        assert resp.status == 200
                # traces complete on the engine thread: poll until the
                # buffer holds all three request trees. (Every fetch is
                # itself traced, so `total` keeps growing — assertions
                # below avoid cross-fetch total equality.)
                def n_posts(payload):
                    return sum(
                        1 for t in payload["traces"]
                        if t["root"].startswith("POST")
                    )

                for _ in range(200):
                    async with session.get(f"{base}/debug/traces") as resp:
                        full = await resp.json()
                    if n_posts(full) >= 3:
                        break
                    await asyncio.sleep(0.05)
                assert n_posts(full) >= 3
                assert full["total"] == len(full["traces"])
                assert full["returned"] == len(full["traces"])

                async with session.get(
                    f"{base}/debug/traces?limit=1"
                ) as resp:
                    assert resp.status == 200
                    page = await resp.json()
                assert page["returned"] == len(page["traces"]) == 1
                # total reports the buffer population, not the page size
                assert page["total"] >= full["total"]
                # newest-first: the limited page's entry is at least as
                # new as everything the earlier full fetch returned
                assert page["traces"][0]["start_us"] >= \
                    full["traces"][0]["start_us"]

                # since= on the middle trace's start: only newer ones
                cutoff = full["traces"][1]["start_us"]
                async with session.get(
                    f"{base}/debug/traces?since={cutoff}"
                ) as resp:
                    newer = await resp.json()
                assert all(
                    t["start_us"] > cutoff for t in newer["traces"]
                )
                assert full["traces"][1]["trace_id"] not in [
                    t["trace_id"] for t in newer["traces"]
                ]

                async with session.get(
                    f"{base}/debug/traces?limit=0"
                ) as resp:
                    empty = await resp.json()
                assert empty["traces"] == [] and empty["total"] >= 3

                for bad in ("limit=x", "limit=-1", "since=nope"):
                    async with session.get(
                        f"{base}/debug/traces?{bad}"
                    ) as resp:
                        assert resp.status == 400

                # the control-plane shares the same parser: covered by
                # obs.http.parse_trace_query unit behavior above
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    run(body())
