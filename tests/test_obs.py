"""Span tracing subsystem: core semantics, propagation across asyncio
tasks and thread hops, log correlation, Perfetto export, the
/debug/traces + /debug/profile HTTP surface, serving latency
histograms, and the disabled-path overhead bound.
"""

import asyncio
import json
import logging
import time

import pytest

from k8s_gpu_device_plugin_tpu.obs.export import to_chrome_trace, write_trace_file
from k8s_gpu_device_plugin_tpu.obs.trace import (
    NOOP_SPAN,
    Tracer,
    attach,
    configure,
    current_context,
    current_trace_ids,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)


@pytest.fixture
def tracer():
    """The global tracer, enabled for one test and restored after.

    The GLOBAL one on purpose: instrumentation sites bind it at import,
    so these tests must prove the real wiring, not a lookalike."""
    tr = configure(enabled=True)
    tr.clear()
    try:
        yield tr
    finally:
        tr.enabled = False
        tr.clear()


# --- core semantics -------------------------------------------------------


def test_span_tree_and_ring_buffer(tracer):
    with tracer.span("root", component="test", k="v") as root:
        with tracer.span("child", component="test") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_id == root.span_id
        grand = tracer.span("manual", component="test", parent=child)
        grand.set(extra=1).end()
        assert grand.parent_id == child.span_id

    summaries = tracer.traces()
    assert len(summaries) == 1
    top = summaries[0]
    assert top["root"] == "root" and top["n_spans"] == 3
    assert top["status"] == "ok"
    spans = tracer.get_trace(top["trace_id"])
    assert {s["name"] for s in spans} == {"root", "child", "manual"}
    roots = [s for s in spans if s["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "root"


def test_trace_completes_when_last_span_ends(tracer):
    """Completion is structural (open-span count), not root-order: a
    child ending AFTER its root — the serving thread-hop shape — still
    finishes the trace."""
    root = tracer.span("root", component="test")
    child = tracer.span("child", component="test", parent=root)
    root.end()
    assert tracer.traces() == []  # child still open
    child.end()
    assert len(tracer.traces()) == 1


def test_exception_marks_span_error(tracer):
    with pytest.raises(ValueError):
        with tracer.span("boom", component="test"):
            raise ValueError("nope")
    top = tracer.traces()[0]
    assert top["status"] == "error"
    (span,) = tracer.get_trace(top["trace_id"])
    assert "ValueError" in span["attrs"]["error"]


def test_ring_buffer_bounded():
    tr = Tracer(max_traces=3)
    tr.enabled = True
    for i in range(10):
        tr.span(f"t{i}", component="test").end()
    assert len(tr.traces()) == 3
    assert tr.traces()[0]["root"] == "t9"  # newest first


def test_live_table_bounded_by_eviction():
    """A span leaked open (instrumented code died without ending it)
    must not pin its trace in memory forever: past max_live_traces the
    oldest live trace is evicted to the ring marked incomplete."""
    tr = Tracer(max_traces=4)
    tr.max_live_traces = 8
    tr.enabled = True
    leaked = [tr.span(f"leak{i}", component="test") for i in range(20)]
    assert len(tr._live) <= 8
    evicted = [t for t in tr.traces() if t["incomplete"]]
    # the leaked span never ended, so an evicted trace has no finished
    # span records — only the incomplete marker
    assert evicted and all(t["n_spans"] == 0 for t in evicted)
    for span in leaked:  # ending an evicted span is harmless
        span.end()
    assert len(tr._live) == 0


def test_span_cap_per_trace():
    tr = Tracer(max_spans_per_trace=4)
    tr.enabled = True
    with tr.span("root", component="test"):
        for i in range(10):
            tr.span(f"s{i}", component="test").end()
    top = tr.traces()[0]
    assert top["n_spans"] == 4 and top["dropped_spans"] == 7


# --- propagation ----------------------------------------------------------


def test_propagation_across_create_task(tracer):
    """contextvars flow into asyncio.create_task automatically: a span
    started in the child task parents under the caller's span."""

    async def main():
        with tracer.span("parent", component="test") as parent:
            async def child():
                with tracer.span("child", component="test") as span:
                    return span.trace_id, span.parent_id

            return parent, await asyncio.create_task(child())

    parent, (trace_id, parent_id) = asyncio.run(main())
    assert trace_id == parent.trace_id
    assert parent_id == parent.span_id


def test_propagation_across_run_in_executor(tracer):
    """Thread hops do NOT inherit contextvars: prove the capture/attach
    pattern carries the trace across loop.run_in_executor."""

    async def main():
        with tracer.span("parent", component="test") as parent:
            ctx = current_context()

            def worker():
                # a bare thread sees no ambient span...
                assert current_context() is None
                with attach(ctx):
                    with tracer.span("in_thread", component="test") as span:
                        return span.trace_id, span.parent_id

            loop = asyncio.get_running_loop()
            return parent, await loop.run_in_executor(None, worker)

    parent, (trace_id, parent_id) = asyncio.run(main())
    assert trace_id == parent.trace_id
    assert parent_id == parent.span_id


def test_traceparent_roundtrip_and_validation(tracer):
    with tracer.span("s", component="test") as span:
        header = format_traceparent(span)
    ctx = parse_traceparent(header)
    assert ctx is not None
    assert ctx.trace_id == span.trace_id and ctx.span_id == span.span_id
    # a remote parent re-parents a local span under the caller's trace
    child = tracer.span("remote_child", component="test", parent=ctx)
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    child.end()

    for bad in (
        None, "", "garbage", "00-short-span-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
        "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",  # reserved version
        "zz-" + "a" * 32 + "-" + "b" * 16 + "-01",  # non-hex version
    ):
        assert parse_traceparent(bad) is None, bad


# --- log correlation ------------------------------------------------------


def _json_record(msg="hello", **fields) -> dict:
    from k8s_gpu_device_plugin_tpu.utils.log import JsonFormatter, get_logger

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = get_logger()
    handler = Capture()
    logger.addHandler(handler)
    try:
        logger.info(msg, extra={"fields": fields} if fields else None)
    finally:
        logger.removeHandler(handler)
    return json.loads(JsonFormatter().format(records[-1]))


def test_log_records_carry_trace_ids_inside_span(tracer):
    with tracer.span("op", component="test") as span:
        entry = _json_record("traced line", k="v")
    assert entry["trace_id"] == span.trace_id
    assert entry["span_id"] == span.span_id
    assert entry["k"] == "v"  # structured fields unaffected


def test_log_records_clean_outside_span(tracer):
    entry = _json_record("untraced line")
    assert "trace_id" not in entry and "span_id" not in entry


def test_current_trace_ids_is_none_when_idle(tracer):
    assert current_trace_ids() is None
    with tracer.span("op", component="test") as span:
        assert current_trace_ids() == (span.trace_id, span.span_id)
    assert current_trace_ids() is None


# --- exporter -------------------------------------------------------------


def test_chrome_trace_export(tracer, tmp_path):
    with tracer.span("root", component="serving", rid=7):
        with tracer.span("child", component="http"):
            pass
    trace_id = tracer.traces()[0]["trace_id"]
    payload = to_chrome_trace(tracer.get_trace(trace_id))
    events = payload["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(complete) == 2
    assert all(e["dur"] >= 1 and isinstance(e["ts"], int) for e in complete)
    # components render as named rows
    assert {m["args"]["name"] for m in meta} == {"serving", "http"}
    tids = {m["args"]["name"]: m["tid"] for m in meta}
    by_name = {e["name"]: e for e in complete}
    assert by_name["root"]["tid"] == tids["serving"]
    assert by_name["root"]["args"]["rid"] == 7

    path = write_trace_file(
        tracer.get_trace(trace_id), str(tmp_path / "t.json")
    )
    with open(path) as f:
        assert json.load(f)["traceEvents"]


# --- disabled-path overhead -----------------------------------------------


def test_disabled_tracer_is_noop_and_cheap():
    tr = get_tracer()
    assert tr.enabled is False
    # no allocation: every disabled span() is the ONE shared no-op
    assert tr.span("x", component="y") is NOOP_SPAN
    assert tr.span("z") is tr.span("w")

    # The decode-loop instrumentation shape: one enabled check per
    # potential span. 200k checks must be noise (<0.25s even on a busy
    # CI box) — the "compiles down to a no-op span check" bound.
    spans = 0
    t0 = time.perf_counter()
    for _ in range(200_000):
        if tr.enabled:  # the per-site guard models/batching.py uses
            spans += 1
    elapsed = time.perf_counter() - t0
    assert spans == 0
    assert elapsed < 0.25, f"disabled-path guard too slow: {elapsed:.3f}s"
    # and the buffer stays untouched
    assert tr.traces() == []
