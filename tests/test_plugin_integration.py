"""End-to-end control-plane tests against a fake kubelet (BASELINE config #1).

≙ SURVEY §4 integration strategy: an in-process gRPC kubelet drives the full
register/ListAndWatch/GetPreferredAllocation/Allocate handshake against a
plugin manager backed by a fake chip backend — every layer, zero accelerators.
"""

import asyncio

import grpc
import pytest

from k8s_gpu_device_plugin_tpu.plugin import api
from k8s_gpu_device_plugin_tpu.plugin.api import pb
from k8s_gpu_device_plugin_tpu.plugin.testing import (
    FakeKubelet,
    start_stack,
    stop_stack,
)

assert FakeKubelet is not None  # re-exported for the other test modules


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=60))


def test_register_and_list_and_watch(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(tmp_path)
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            assert reg.resource_name == "google.com/tpu"
            assert reg.version == api.VERSION
            assert reg.options.get_preferred_allocation_available

            async with kubelet.plugin_channel(reg.endpoint) as channel:
                stub = api.DevicePluginStub(channel)
                stream = stub.ListAndWatch(pb.Empty())
                first = await asyncio.wait_for(stream.read(), 5)
                assert len(first.devices) == 4
                assert all(d.health == api.HEALTHY for d in first.devices)
                assert all(d.topology.nodes for d in first.devices)
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_allocate_wires_devices_and_envs(tmp_path, captured_log_records):
    records = captured_log_records

    async def body():
        kubelet, manager, task, _ = await start_stack(tmp_path)
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            chips = manager.plugins[0].chips
            ids = chips.ids()[:2]

            async with kubelet.plugin_channel(reg.endpoint) as channel:
                stub = api.DevicePluginStub(channel)
                resp = await stub.Allocate(
                    pb.AllocateRequest(
                        container_requests=[
                            pb.ContainerAllocateRequest(devicesIDs=ids)
                        ]
                    )
                )
                (cresp,) = resp.container_responses
                envs = dict(cresp.envs)
                assert envs["TPU_VISIBLE_CHIPS"]
                assert envs["TPU_CHIPS_PER_PROCESS_BOUNDS"]
                assert envs["TPU_ACCELERATOR_TYPE"].startswith("v5e-")
                assert envs["TPU_SKIP_MDS_QUERY"] == "true"
                assert len(cresp.devices) == 2
                for spec in cresp.devices:
                    assert spec.host_path.startswith("/dev/accel")
                    assert spec.permissions == "rw"
            # RPC audit log: the allocated device IDs must be in the record
            audits = [r for r in records if r.getMessage() == "Allocate"]
            assert audits and audits[-1].fields["devices"] == ids
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_allocate_unknown_id_rejected(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(tmp_path)
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            async with kubelet.plugin_channel(reg.endpoint) as channel:
                stub = api.DevicePluginStub(channel)
                with pytest.raises(grpc.aio.AioRpcError) as err:
                    await stub.Allocate(
                        pb.AllocateRequest(
                            container_requests=[
                                pb.ContainerAllocateRequest(devicesIDs=["nope"])
                            ]
                        )
                    )
                assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                assert "nope" in err.value.details()
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_preferred_allocation_is_ici_contiguous(tmp_path, captured_log_records):
    records = captured_log_records

    async def body():
        kubelet, manager, task, _ = await start_stack(tmp_path, topology="v5e-8")
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            chips = manager.plugins[0].chips
            async with kubelet.plugin_channel(reg.endpoint) as channel:
                stub = api.DevicePluginStub(channel)
                resp = await stub.GetPreferredAllocation(
                    pb.PreferredAllocationRequest(
                        container_requests=[
                            pb.ContainerPreferredAllocationRequest(
                                available_deviceIDs=chips.ids(),
                                allocation_size=4,
                            )
                        ]
                    )
                )
                ids = list(resp.container_responses[0].deviceIDs)
                assert len(ids) == 4
                coords = sorted(chips[i].coords[0] for i in ids)
                # a 2x2 sub-mesh of the 2x4 host
                xs = {c[0] for c in coords}
                ys = {c[1] for c in coords}
                assert len(xs) == 2 and len(ys) == 2
                assert max(ys) - min(ys) == 1
            audits = [
                r for r in records if r.getMessage() == "GetPreferredAllocation"
            ]
            assert audits and sorted(audits[-1].fields["preferred"]) == sorted(ids)
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_health_transition_pushes_update(tmp_path):
    async def body():
        kubelet, manager, task, backend = await start_stack(tmp_path)
        try:
            await kubelet.wait_for_registrations(1)
            reg = kubelet.registrations[0]
            async with kubelet.plugin_channel(reg.endpoint) as channel:
                stub = api.DevicePluginStub(channel)
                stream = stub.ListAndWatch(pb.Empty())
                first = await asyncio.wait_for(stream.read(), 5)
                assert all(d.health == api.HEALTHY for d in first.devices)

                backend.set_unhealthy(0)
                second = await asyncio.wait_for(stream.read(), 5)
                unhealthy = [d for d in second.devices if d.health == api.UNHEALTHY]
                assert len(unhealthy) == 1

                backend.set_healthy(0)
                third = await asyncio.wait_for(stream.read(), 5)
                assert all(d.health == api.HEALTHY for d in third.devices)
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_health_fanout_keys_by_resource_name(tmp_path):
    """Health deltas must route by resource NAME, not list position.

    Regression (r2 verdict weak #5): ``_health_loop`` used to pair plugins
    with chip sets via ``zip(self.plugins, sorted(chip_map.items()))`` —
    any ordering divergence silently pushed one resource's chips into
    another plugin's ListAndWatch stream.
    """
    from k8s_gpu_device_plugin_tpu.device.chip import HEALTHY, UNHEALTHY

    async def body():
        kubelet, manager, task, backend = await start_stack(
            tmp_path,
            topology="v5e-8",
            slice_strategy="mixed",
            slice_plan="2x2,1x2,1x2",
        )
        try:
            await kubelet.wait_for_registrations(2)
            # Force the plugins list out of sorted-map order — exactly the
            # divergence the positional zip mis-paired.
            manager.plugins = list(reversed(manager.plugins))
            by_name = {p.resource_name: p for p in manager.plugins}
            affected = by_name["google.com/tpu-slice-2x2"]
            other = by_name["google.com/tpu-slice-1x2"]
            # chip index 0 is a member of the 2x2 slice only
            assert any(0 in c.chip_indices for c in affected.chips.values())
            assert all(0 not in c.chip_indices for c in other.chips.values())

            backend.set_unhealthy(0)
            for _ in range(50):
                await asyncio.sleep(0.1)
                if any(c.health == UNHEALTHY for c in affected.chips.values()):
                    break
            assert any(c.health == UNHEALTHY for c in affected.chips.values())
            assert all(c.health == HEALTHY for c in other.chips.values())
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_kubelet_restart_triggers_reregistration(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(tmp_path)
        try:
            await kubelet.wait_for_registrations(1)
            # Simulate kubelet restart: close + re-create kubelet.sock.
            await kubelet.stop()
            await kubelet.start()
            await kubelet.wait_for_registrations(2)
            assert kubelet.registrations[-1].resource_name == "google.com/tpu"
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_manual_restart_reregisters(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(tmp_path)
        try:
            await kubelet.wait_for_registrations(1)
            manager.restart()  # HTTP /restart path (router/api.go:50-54)
            await kubelet.wait_for_registrations(2)
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())


def test_mixed_strategy_registers_per_profile(tmp_path):
    async def body():
        kubelet, manager, task, _ = await start_stack(
            tmp_path,
            topology="v5e-8",
            slice_strategy="mixed",
            slice_plan="2x2,1x2,1x2",
        )
        try:
            await kubelet.wait_for_registrations(2)
            names = {r.resource_name for r in kubelet.registrations}
            assert names == {
                "google.com/tpu-slice-2x2",
                "google.com/tpu-slice-1x2",
            }
        finally:
            await stop_stack(kubelet, manager, task)

    run(body())
