"""NativeBackend <-> libtpuenum.so integration over a synthetic host tree.

The C++ core roots all filesystem access at $TPUENUM_ROOT, so these tests
build a fake /dev + /sys + /etc tree and exercise the full ctypes path.
Skipped if the shared library has not been built (``make -C
k8s_gpu_device_plugin_tpu/native``).
"""

import os
import subprocess

import pytest

from k8s_gpu_device_plugin_tpu.device.native import NativeBackend, _load_library

NATIVE_DIR = os.path.join(
    os.path.dirname(__file__), "..", "k8s_gpu_device_plugin_tpu", "native"
)


def ensure_lib():
    if _load_library() is None:
        build = subprocess.run(
            ["make", "-C", NATIVE_DIR], capture_output=True, text=True
        )
        _load_library.cache_clear()  # the None result was memoized
        if build.returncode != 0 or _load_library() is None:
            pytest.skip("libtpuenum.so not buildable in this environment")


@pytest.fixture
def fake_host(tmp_path, monkeypatch):
    ensure_lib()
    (tmp_path / "dev").mkdir()
    (tmp_path / "etc").mkdir()
    (tmp_path / "etc" / "machine-id").write_text("0123456789abcdef\n")
    accel_root = tmp_path / "sys" / "class" / "accel"
    for i in range(4):
        (tmp_path / "dev" / f"accel{i}").write_text("")
        dev_dir = accel_root / f"accel{i}" / "device"
        dev_dir.mkdir(parents=True)
        (dev_dir / "numa_node").write_text("0\n" if i < 2 else "1\n")
        (dev_dir / "device").write_text("0x0063\n")  # v5e
    monkeypatch.setenv("TPUENUM_ROOT", str(tmp_path))
    return tmp_path


def test_native_enumeration(fake_host):
    backend = NativeBackend()
    assert backend.available()
    topo = backend.host_topology()
    assert topo.generation.name == "v5e"
    assert topo.num_chips == 4

    chips = backend.enumerate_chips()
    assert len(chips) == 4
    assert [c.index for c in chips] == [0, 1, 2, 3]
    assert all(c.uuid.startswith("TPU-") for c in chips)
    assert len({c.uuid for c in chips}) == 4
    assert chips[0].numa_node == 0 and chips[3].numa_node == 1
    # coords assigned row-major over the inferred 2x2 mesh
    assert sorted(c.coord for c in chips) == [(0, 0), (0, 1), (1, 0), (1, 1)]
    # HBM filled from the generation table when sysfs has none
    assert chips[0].hbm_bytes == 16 * 1024**3


def test_native_health_follows_device_nodes(fake_host):
    backend = NativeBackend(topology_override="v5e-4")
    health = backend.check_health()
    assert health == {0: True, 1: True, 2: True, 3: True}
    # A removed node drops out of enumeration; its index must vanish from the
    # health map, and the manager treats absent indices as unhealthy.
    os.unlink(fake_host / "dev" / "accel3")
    assert backend._lib.tpuenum_chip_count() == 3
    health = backend.check_health()
    assert 3 not in health
    assert health == {0: True, 1: True, 2: True}


def test_manager_marks_missing_chip_unhealthy(fake_host):
    """End of the pipeline: a vanished device node turns its advertised
    device Unhealthy through PluginManager._with_health."""
    from k8s_gpu_device_plugin_tpu.config import Config
    from k8s_gpu_device_plugin_tpu.device.chip import UNHEALTHY
    from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
    from k8s_gpu_device_plugin_tpu.utils.latch import Latch

    backend = NativeBackend(topology_override="v5e-4")
    manager = PluginManager(
        Config(backend="native"), Latch(), backend=backend
    )
    manager._load_plugins()
    assert all(
        c.health != UNHEALTHY for c in manager.plugins[0].chips.values()
    )
    os.unlink(fake_host / "dev" / "accel3")
    manager._chip_health = backend.check_health()
    refreshed = manager._with_health(manager.chip_map["google.com/tpu"])
    unhealthy = [c for c in refreshed.values() if c.health == UNHEALTHY]
    assert len(unhealthy) == 1
    assert unhealthy[0].chip_indices == (3,)


def test_native_topology_override(fake_host):
    backend = NativeBackend(topology_override="v5e-2x2")
    assert backend.host_topology().bounds == (2, 2)


def test_native_unavailable_without_devices(tmp_path, monkeypatch):
    ensure_lib()
    monkeypatch.setenv("TPUENUM_ROOT", str(tmp_path))  # empty tree
    backend = NativeBackend()
    assert not backend.available()


def test_internal_edges_matches_python(fake_host):
    import ctypes

    backend = NativeBackend()
    lib = backend._lib
    coords = (ctypes.c_int32 * 8)(0, 0, 0, 1, 1, 0, 1, 1)
    bounds = (ctypes.c_int32 * 2)(2, 4)
    assert lib.tpuenum_internal_edges(coords, 4, bounds, 2) == 4


# --- metadata hardening (r2 verdict weak #6) ---


@pytest.fixture
def vfio_host(tmp_path, monkeypatch):
    """Synthetic VFIO host: chips behind /dev/vfio with sysfs metadata
    reachable through the IOMMU group's member PCI device."""
    ensure_lib()
    (tmp_path / "dev" / "vfio").mkdir(parents=True)
    (tmp_path / "etc").mkdir()
    (tmp_path / "etc" / "machine-id").write_text("fedcba9876543210\n")
    for group, (numa, pci_id) in enumerate([("0", "0x0063"), ("1", "0x0063")]):
        (tmp_path / "dev" / "vfio" / str(group)).write_text("")
        member = (
            tmp_path / "sys" / "kernel" / "iommu_groups" / str(group)
            / "devices" / f"0000:00:0{group + 4}.0"
        )
        member.mkdir(parents=True)
        (member / "numa_node").write_text(numa + "\n")
        (member / "device").write_text(pci_id + "\n")
    monkeypatch.setenv("TPUENUM_ROOT", str(tmp_path))
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    return tmp_path


def test_vfio_enumeration_recovers_sysfs_metadata(vfio_host):
    backend = NativeBackend()
    assert backend.available()
    topo = backend.host_topology()
    assert topo.generation.name == "v5e"      # measured via IOMMU-group PCI id
    assert backend.generation_source == "pci"
    chips = backend.enumerate_chips()
    assert [c.numa_node for c in chips] == [0, 1]
    assert all(c.paths[0].startswith("/dev/vfio/") for c in chips)


def test_generation_env_fallback_is_flagged_as_guess(
    tmp_path, monkeypatch, captured_log_records
):
    """No PCI ids anywhere: TPU_ACCELERATOR_TYPE is trusted but flagged."""
    ensure_lib()
    (tmp_path / "dev").mkdir()
    for i in range(4):
        (tmp_path / "dev" / f"accel{i}").write_text("")
    monkeypatch.setenv("TPUENUM_ROOT", str(tmp_path))
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-8")
    backend = NativeBackend()
    topo = backend.host_topology()
    assert topo.generation.name == "v5p"
    assert backend.generation_source == "env"
    warnings = [
        r for r in captured_log_records
        if "GUESSED" in r.getMessage() and r.fields["source"] == "env"
    ]
    assert warnings


def test_generation_unknown_defaults_loudly(
    tmp_path, monkeypatch, captured_log_records
):
    ensure_lib()
    (tmp_path / "dev").mkdir()
    (tmp_path / "dev" / "accel0").write_text("")
    monkeypatch.setenv("TPUENUM_ROOT", str(tmp_path))
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    backend = NativeBackend()
    assert backend.host_topology().generation.name == "v5e"  # default guess
    assert backend.generation_source == "unknown"
    assert any("GUESSED" in r.getMessage() for r in captured_log_records)


def test_sysfs_hbm_size_overrides_generation_table(fake_host):
    """A driver exposing per-chip memory beats the spec-table fallback."""
    attr = (
        fake_host / "sys" / "class" / "accel" / "accel0" / "device" / "hbm_bytes"
    )
    attr.write_text(str(32 * 1024**3) + "\n")
    backend = NativeBackend(topology_override="v5e-4")
    chips = backend.enumerate_chips()
    assert chips[0].hbm_bytes == 32 * 1024**3
    assert chips[1].hbm_bytes == 16 * 1024**3  # others still from the table


def test_generation_guessed_metric():
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.device_metrics import DeviceMetrics

    reg = CollectorRegistry()
    m = DeviceMetrics(registry=reg)
    m.set_generation_source("v5e", "env")
    assert reg.get_sample_value(
        "tpu_plugin_generation_guessed", {"generation": "v5e", "source": "env"}
    ) == 1
    m.set_generation_source("v5e", "pci")
    assert reg.get_sample_value(
        "tpu_plugin_generation_guessed", {"generation": "v5e", "source": "pci"}
    ) == 0
    m.set_generation_source("v5e", "fake")
    assert reg.get_sample_value(
        "tpu_plugin_generation_guessed", {"generation": "v5e", "source": "fake"}
    ) == 0


def test_topology_override_sets_config_source(
    tmp_path, monkeypatch, captured_log_records
):
    """An explicit topology override is a deliberate claim: source 'config'
    (not a guess, no GUESSED warning) when PCI ids cannot confirm; a PCI
    contradiction is honored but warned about."""
    ensure_lib()
    (tmp_path / "dev").mkdir()
    for i in range(4):
        (tmp_path / "dev" / f"accel{i}").write_text("")
    monkeypatch.setenv("TPUENUM_ROOT", str(tmp_path))
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
    backend = NativeBackend(topology_override="v5e-4")
    assert backend.host_topology().generation.name == "v5e"
    assert backend.generation_source == "config"
    assert not any("GUESSED" in r.getMessage() for r in captured_log_records)

    # PCI says v5e but config pins v5p: config wins, loudly
    accel_root = tmp_path / "sys" / "class" / "accel"
    for i in range(4):
        dev_dir = accel_root / f"accel{i}" / "device"
        dev_dir.mkdir(parents=True)
        (dev_dir / "device").write_text("0x0063\n")  # v5e
    backend2 = NativeBackend(topology_override="v5p-4")
    assert backend2.host_topology().generation.name == "v5p"
    assert backend2.generation_source == "config"
    assert any("disagrees" in r.getMessage() for r in captured_log_records)
