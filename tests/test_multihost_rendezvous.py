"""REAL-process multi-host rendezvous (r2 verdict: the contract's envs were
built and string-asserted but ``jax.distributed.initialize`` never actually
ran across processes).

Each test boots fake-kubelet-backed daemons configured as members of one
distributed job, Allocates every chip the host owns (the whole-host path
that emits the worker contract, plugin/plugin.py:_container_allocate), then
spawns one SUBPROCESS per worker wearing exactly those envs. The subprocess
is the shipped preflight tool (parallel/rendezvous_check.py): it calls
``jax.distributed.initialize`` (CPU backend, gloo collectives) and psums
across processes. A wrong coordinator, rank, or world size fails the
rendezvous or the in-check assertions — exactly the hang-shaped bugs the r2
verdict called out as untestable before.
"""

import os
import subprocess
import sys
import time

from k8s_gpu_device_plugin_tpu.plugin.testing import (
    allocate_whole_host as _allocate_whole_host,
    free_port as _free_port,
    join_json_workers,
)

from tests.test_plugin_integration import run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_worker(
    envs: dict[str, str], port: int, init_timeout: int = 120
) -> subprocess.Popen:
    env = {**os.environ, **envs}
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}{existing}" if existing else REPO
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [
            sys.executable, "-m",
            "k8s_gpu_device_plugin_tpu.parallel.rendezvous_check",
            "--port", str(port),
            "--init-timeout", str(init_timeout),
        ],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


_join_all = join_json_workers  # one shared join/kill-on-hang implementation


def test_two_host_slice_rendezvous_and_psum(tmp_path):
    """Slice workers 0/1 rendezvous from plugin-injected envs and psum."""
    port = _free_port()

    async def allocate_both():
        out = []
        for wid in (0, 1):
            envs = await _allocate_whole_host(
                tmp_path / f"w{wid}",
                topology="v5e-4",
                slice_topology="v5e-8",    # (2,4) slice of (2,2) hosts = 2 hosts
                worker_id=wid,
                worker_hostnames="127.0.0.1,127.0.0.1",
            )
            out.append(envs)
        return out

    env0, env1 = run(allocate_both())
    # contract sanity before spending subprocess time
    assert env0["TPU_WORKER_ID"] == "0" and env1["TPU_WORKER_ID"] == "1"
    assert env0["TPU_WORKER_HOSTNAMES"] == env1["TPU_WORKER_HOSTNAMES"]
    assert env0["TPU_PROCESS_BOUNDS"] == env1["TPU_PROCESS_BOUNDS"]

    workers = [_spawn_worker(env0, port), _spawn_worker(env1, port)]
    reports = _join_all(workers, timeout=180)
    assert all(r["ok"] and r["distributed"] for r in reports)
    assert {r["rank"] for r in reports} == {0, 1}
    assert all(r["nprocs"] == 2 for r in reports)
    # every process saw the full world's devices and the psum agreed
    ndev = reports[0]["ndev"]
    assert ndev >= 2
    assert all(r["psum"] == ndev * (ndev - 1) // 2 for r in reports)


def test_multislice_rendezvous_over_megascale_envs(tmp_path):
    """Two single-host slices rendezvous via the MEGASCALE_* contract."""
    port = _free_port()

    async def allocate_both():
        out = []
        for sid in (0, 1):
            envs = await _allocate_whole_host(
                tmp_path / f"s{sid}",
                topology="v5e-4",
                num_slices=2,
                slice_id=sid,
                worker_hostnames="127.0.0.1",
                megascale_coordinator="127.0.0.1:8476",
            )
            out.append(envs)
        return out

    env0, env1 = run(allocate_both())
    assert env0["MEGASCALE_SLICE_ID"] == "0" and env1["MEGASCALE_SLICE_ID"] == "1"
    assert env0["MEGASCALE_NUM_SLICES"] == "2"
    assert env0["MEGASCALE_COORDINATOR_ADDRESS"] == "127.0.0.1:8476"

    workers = [_spawn_worker(env0, port), _spawn_worker(env1, port)]
    reports = _join_all(workers, timeout=180)
    assert all(r["ok"] and r["distributed"] for r in reports)
    assert {r["rank"] for r in reports} == {0, 1}  # process_id == slice_id


def test_duplicate_rank_breaks_rendezvous(tmp_path):
    """Sensitivity control: a mis-injected rank must NOT rendezvous cleanly.

    Both workers wear worker 0's envs (duplicate process_id, same
    coordinator), with the preflight's short init fuse so the botched
    rendezvous fails in seconds instead of jax's 300s default. If both ever
    exit 0 the contract check proves nothing and this test fails.
    """
    port = _free_port()

    async def allocate_w0():
        return await _allocate_whole_host(
            tmp_path / "w0",
            topology="v5e-4",
            slice_topology="v5e-8",
            worker_id=0,
            worker_hostnames="127.0.0.1,127.0.0.1",
        )

    env0 = run(allocate_w0())
    workers = [
        _spawn_worker(env0, port, init_timeout=15),
        _spawn_worker(env0, port, init_timeout=15),
    ]
    try:
        deadline = time.monotonic() + 120
        failed = None
        while time.monotonic() < deadline:
            for p in workers:
                rc = p.poll()
                if rc is not None and rc != 0:
                    failed = p
                    break
            if failed is not None:
                break
            if all(p.poll() is not None for p in workers):
                break  # both exited (would mean both rc==0 -> assert below)
            time.sleep(0.25)
        assert failed is not None, (
            "duplicate-rank workers both rendezvoused cleanly: "
            f"rcs={[p.poll() for p in workers]}"
        )
    finally:
        for p in workers:
            if p.poll() is None:
                p.kill()
            p.communicate(timeout=30)
