"""ChipMap strategy tests (≙ device/device_map.go dispatch + matching)."""

import pytest

from k8s_gpu_device_plugin_tpu.device.chip import AnnotatedID
from k8s_gpu_device_plugin_tpu.device.chip_map import new_chip_map
from k8s_gpu_device_plugin_tpu.device.fake import FakeBackend
from k8s_gpu_device_plugin_tpu.resource.naming import Resource
from k8s_gpu_device_plugin_tpu.resource.resources import discover_resources


def test_strategy_none_whole_chips():
    backend = FakeBackend("v5e-4")
    resources = discover_resources("none")
    chip_map = new_chip_map(backend, resources, "none")
    assert list(chip_map) == ["google.com/tpu"]
    chips = chip_map["google.com/tpu"]
    assert len(chips) == 4
    assert all(not c.is_slice for c in chips.values())
    assert chips.all_paths() == [f"/dev/accel{i}" for i in range(4)]


def test_strategy_single_slices_under_plain_name():
    backend = FakeBackend("v5e-8")
    resources = discover_resources("single")
    chip_map = new_chip_map(backend, resources, "single", slice_shape="2x2")
    chips = chip_map["google.com/tpu"]
    assert len(chips) == 2
    for chip in chips.values():
        assert chip.slice_profile == "2x2"
        assert chip.num_chips == 4
        assert chip.total_memory == 4 * 16 * 1024**3


def test_strategy_single_without_shape_falls_back_to_chips():
    backend = FakeBackend("v5e-4")
    chip_map = new_chip_map(backend, discover_resources("single"), "single")
    assert len(chip_map["google.com/tpu"]) == 4


def test_strategy_mixed_one_resource_per_profile():
    backend = FakeBackend("v5e-8")
    resources = discover_resources(
        "mixed", backend.host_topology(), slice_plan="2x2,1x2,1x2"
    )
    chip_map = new_chip_map(
        backend, resources, "mixed", slice_plan="2x2,1x2,1x2"
    )
    assert set(chip_map) == {
        "google.com/tpu-slice-2x2",
        "google.com/tpu-slice-1x2",
    }
    assert len(chip_map["google.com/tpu-slice-2x2"]) == 1
    assert len(chip_map["google.com/tpu-slice-1x2"]) == 2
    # all 8 chips covered, disjointly
    indices = [
        i
        for chips in chip_map.values()
        for c in chips.values()
        for i in c.chip_indices
    ]
    assert sorted(indices) == list(range(8))


def test_strategy_mixed_default_plan_halves_host():
    backend = FakeBackend("v5p-8")
    topo = backend.host_topology()
    resources = discover_resources("mixed", topo)
    chip_map = new_chip_map(backend, resources, "mixed")
    assert len(chip_map) == 1
    (chips,) = chip_map.values()
    assert len(chips) == 2  # two half-host slices


def test_shared_replicas_annotated_ids():
    backend = FakeBackend("v5e-4")
    chip_map = new_chip_map(
        backend, discover_resources("none"), "none", shared_replicas=2
    )
    chips = chip_map["google.com/tpu"]
    assert len(chips) == 8
    assert all(AnnotatedID.is_annotated(i) for i in chips)
    assert len(chips.physical_ids()) == 4
    assert all(c.replicas == 2 for c in chips.values())


def test_unmatched_pattern_is_hard_error():
    backend = FakeBackend("v5e-4")
    bad = [Resource.new("h100*", "tpu")]
    with pytest.raises(ValueError, match="no resource pattern"):
        new_chip_map(backend, bad, "none")


def test_slice_ids_stable_across_rebuilds():
    backend = FakeBackend("v5e-8")
    kwargs = dict(
        resources=discover_resources("single"),
        strategy="single",
        slice_shape="2x2",
    )
    a = new_chip_map(backend, **kwargs)
    b = new_chip_map(backend, **kwargs)
    assert a["google.com/tpu"].ids() == b["google.com/tpu"].ids()
