"""Paged KV cache (models/paging.py + the kv_layout="paged" batcher
path + ops/paged_attention.py).

Three layers of claims:

- **Bit-exactness**: greedy and seeded token AND logprob streams are
  identical between the dense and paged layouts across admit/retire/
  cancel/stop/chunked-prefill/prefix-eviction interleavings — the paged
  gather reproduces the dense view value-for-value, and every garbage
  row sits behind an exact-zero softmax weight in both layouts.
- **Zero-copy prefix sharing**: automatic cache hits and promotions
  move NO KV rows (asserted via the batching.kv_copy_counts hook);
  the only copy left is the tail-page COW when a promotion boundary is
  not page-aligned — asserted to be exactly one page.
- **Pool discipline**: refcount invariants hold under prefix hit +
  cancel + eviction races, pool exhaustion defers (transient) or
  refuses (request outsizes the pool), and retirement drains the pool.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models import batching
from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.paging import PagePool, kv_token_bytes
from k8s_gpu_device_plugin_tpu.serving.prefix_cache import (
    PrefixCache,
    prefix_kv_bytes,
)

BUCKETS = (8, 16, 32)
PS = 16  # page size: divides max_len=64; boundary 8 is page-UNALIGNED


@pytest.fixture(scope="module")
def setup():
    # same tiny config as the neighboring serving modules so the shared
    # (dense) compiles are reused; the paged twins compile once here
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=max_new
    )
    return np.asarray(out)[0].tolist()


def _batcher(params, cfg, layout, pc=None, depth=1, n_slots=2, chunk=8,
             **kw):
    return ContinuousBatcher(
        params, cfg, n_slots=n_slots, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=chunk, pipeline_depth=depth, prefix_cache=pc,
        kv_layout=layout, kv_page_size=PS if layout == "paged" else None,
        **kw,
    )


# --- host allocator ---------------------------------------------------------


def test_page_pool_mechanics():
    pool = PagePool(8, 16)  # 7 allocatable + trap
    assert pool.capacity == 7 and pool.free_pages == 7
    a = pool.alloc(3)
    assert 0 not in a and pool.in_use == 3
    pool.incref(a[:2])            # share two pages
    freed = pool.decref(a)        # slot retires: only the unshared frees
    assert freed == [a[2]]
    assert pool.in_use == 2
    assert pool.decref(a[:2]) == a[:2]
    assert pool.in_use == 0 and pool.peak_in_use == 3
    pool.check()
    assert pool.pages_for_tokens(1) == 1
    assert pool.pages_for_tokens(16) == 1
    assert pool.pages_for_tokens(17) == 2
    with pytest.raises(RuntimeError, match="exhausted"):
        # the alloc RAISES (nothing allocated): the unpaired-retain rule
        # is exactly what this line exists to provoke
        pool.alloc(8)  # graftlint: disable=refcount-pairing
    with pytest.raises(ValueError):
        pool.decref([3])  # not allocated
    with pytest.raises(ValueError):
        PagePool(1, 16)   # no allocatable page besides the trap


# --- bit-exactness: dense vs paged -----------------------------------------
#
# One scheduling scenario run per layout (both pipelined and sync):
# staggered waves over shared system prompts with greedy and SEEDED
# requests mixed in one batch, a stop sequence, a mid-flight cancel, and
# a prefix-cache byte budget small enough that promotions evict live
# entries mid-run. Completed requests must produce identical tokens AND
# logprobs across all runs; the cancelled request's partial stream must
# agree on the common prefix.


def _scenario(params, cfg, layout, depth):
    b = prefix_kv_bytes(cfg, 8) + prefix_kv_bytes(cfg, 16)
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=b)
    cb = _batcher(params, cfg, layout, pc=pc, depth=depth)
    sys_a = _prompt(20, 17, cfg)
    sys_b = _prompt(21, 18, cfg)
    rids = []

    def sub(base, tail_key, tail_n, new, seed=None, stop=None):
        p = base + _prompt(tail_key, tail_n, cfg)
        rids.append(cb.submit(p, max_new=new, seed=seed, stop=stop))

    # wave 1: two requests sharing sys_a (promotions happen here); one
    # greedy, one seeded — both exactness regimes in one batch
    sub(sys_a, 30, 5, 5)
    sub(sys_a, 31, 4, 4, seed=4)
    for _ in range(7):
        cb.step()
    # wave 2: sys_a again (hit) + sys_b (miss, then promote + evict)
    sub(sys_a, 32, 6, 5, seed=5)
    sub(sys_b, 33, 5, 6)
    for _ in range(4):
        cb.step()
    cancelled = rids[2]
    cb.cancel(cancelled)
    # wave 3: both prefixes again (hits + re-misses after eviction); one
    # request carries a stop sequence that can't fire (exercises the
    # matching) — interleavings identical across layouts by construction
    sub(sys_b, 34, 4, 4, seed=7)
    sub(sys_a, 35, 3, 5, stop=[[cfg.vocab_size - 1, cfg.vocab_size - 1]])
    cb.run()
    streams = {
        rid: (list(req.out), list(req.out_logp))
        for rid, req in cb.done_requests.items()
    }
    if cb.pool is not None:
        cb.pool.check()
    return rids, cancelled, streams, pc, cb


def test_dense_paged_bit_identical_streams(setup):
    cfg, params = setup
    # (paged, 0) is omitted: paged==dense at depth 0 is already covered
    # per-request by the kv_layout-parameterized oracle tests in
    # test_batching.py — here the pipelined paged engine (the serving
    # default) is the axis, against the sync dense reference
    runs = {
        (layout, depth): _scenario(params, cfg, layout, depth)
        for layout, depth in [("dense", 0), ("paged", 1)]
    }
    ref_rids, ref_cancel, ref_streams, _, _ = runs[("dense", 0)]
    for key, (rids, cancelled, streams, pc, cb) in runs.items():
        assert rids == ref_rids and cancelled == ref_cancel
        for rid in rids:
            if rid == cancelled:
                # the cancel lands at a run-dependent depth; the common
                # prefix must still be bit-identical
                toks, lps = streams[rid]
                rt, rl = ref_streams[rid]
                n = min(len(toks), len(rt))
                assert toks[:n] == rt[:n], key
                assert lps[:n] == rl[:n], key
            else:
                assert streams[rid][0] == ref_streams[rid][0], key
                # logprobs bit-identical, not approx: the paged gather
                # feeds the SAME einsum the dense layout runs
                assert streams[rid][1] == ref_streams[rid][1], key
        if key[0] == "paged":  # the machinery must actually be exercised
            assert pc.stats.promotions > 0 and pc.stats.hits > 0
            assert pc.stats.evictions > 0


def test_paged_streams_match_generate_oracle(setup):
    """Beyond layout equality: paged greedy streams equal dedicated
    ``generate`` over the full prompt (the absolute reference), bucketed
    admission included."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        kv_layout="paged", kv_page_size=PS,
    )
    prompts = {}
    for key, plen, new in [(1, 5, 6), (2, 12, 4), (3, 3, 8)]:
        p = _prompt(key, plen, cfg)
        prompts[cb.submit(p, max_new=new)] = (p, new)
    results = cb.run()
    for rid, (p, new) in prompts.items():
        assert results[rid] == _oracle(params, p, cfg, new), rid
    cb.pool.check()
    assert cb.pool.in_use == 0  # every retirement drained its pages


# --- zero-copy prefix sharing ----------------------------------------------


def test_prefix_hits_copy_zero_kv_rows(setup):
    """The acceptance claim, asserted through the copy-counter hook: a
    page-aligned promotion + hit moves no KV rows at all (dense would
    copy the boundary rows twice: extract at promotion, insert at hit)."""
    cfg, params = setup
    batching.reset_kv_copy_counts()
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 26)
    cb = _batcher(params, cfg, "paged", pc=pc)
    sys_p = _prompt(40, 20, cfg)
    prompts = {}
    for k, n, new in [(41, 5, 5), (42, 4, 4)]:
        p = sys_p + _prompt(k, n, cfg)
        rid = cb.submit(p, max_new=new)
        prompts[rid] = (p, new)
        cb.run()
    assert pc.stats.hits >= 1 and pc.stats.promotions >= 1
    counts = batching.kv_copy_counts()
    assert counts["rows"] == 0, counts
    assert counts["cow_pages"] == 0, counts  # 16-boundary: page-aligned
    for rid, (p, new) in prompts.items():
        assert cb.done[rid] == _oracle(params, p, cfg, new), rid

    # the dense twin of the same traffic DOES copy rows — the counter
    # measures the thing paging removes
    batching.reset_kv_copy_counts()
    pc_d = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 26)
    cb_d = _batcher(params, cfg, "dense", pc=pc_d)
    for k, n, new in [(41, 5, 5), (42, 4, 4)]:
        cb_d.submit(sys_p + _prompt(k, n, cfg), max_new=new)
        cb_d.run()
    assert batching.kv_copy_counts()["rows"] > 0


def test_cow_on_unaligned_tail_page(setup):
    """A promotion boundary inside a page (boundary 8, page size 16)
    aliases zero full pages and copy-on-writes exactly the tail page;
    the hitting stream still equals the oracle and the donor's stream
    is untouched (shared page content never mutated through the COW)."""
    cfg, params = setup
    batching.reset_kv_copy_counts()
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 26)
    cb = _batcher(params, cfg, "paged", pc=pc)
    base = _prompt(50, 8, cfg)
    p1 = base + _prompt(51, 5, cfg)
    r1 = cb.submit(p1, max_new=8)
    # drive p1 past its prefill (promotion happens at the finish chunk)
    # but keep it DECODING, so the donor is still writing into the
    # shared tail page while p2 aliases it
    while cb.prefilling or cb.pending:
        cb.step()
    assert cb.running and pc.stats.promotions >= 1
    p2 = base + _prompt(52, 6, cfg)
    r2 = cb.submit(p2, max_new=4)
    cb.run()
    assert pc.stats.hits == 1
    counts = batching.kv_copy_counts()
    assert counts["cow_pages"] == 1, counts
    assert counts["rows"] == 0, counts
    assert cb.done[r1] == _oracle(params, p1, cfg, 8)  # donor unharmed
    assert cb.done[r2] == _oracle(params, p2, cfg, 4)
    cb.pool.check()


def test_refcount_invariants_under_hit_cancel_evict(setup):
    """Prefix hit + mid-flight cancel + LRU eviction racing: every page
    reference balances — after retiring everything and evicting the
    surviving entries, the pool is exactly drained."""
    cfg, params = setup
    b = prefix_kv_bytes(cfg, 8) + prefix_kv_bytes(cfg, 16)
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=b)
    cb = _batcher(params, cfg, "paged", pc=pc)
    sys_a, sys_b = _prompt(60, 17, cfg), _prompt(61, 18, cfg)
    r_cancel = cb.submit(sys_a + _prompt(62, 4, cfg), max_new=6)
    cb.submit(sys_a + _prompt(63, 5, cfg), max_new=4)
    for _ in range(5):
        cb.step()
    cb.cancel(r_cancel)  # mid-flight: its pages must free, pins balance
    cb.submit(sys_b + _prompt(64, 5, cfg), max_new=4)  # promotes + evicts
    cb.submit(sys_a + _prompt(65, 3, cfg), max_new=3)
    cb.run()
    cb.pool.check()
    # whatever is still in use is exactly the surviving entries' pages
    entry_pages = set()
    for root in pc._roots.values():
        stack = [root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.entry is not None:
                entry_pages.update(node.entry.page_ids)
    assert cb.pool.in_use == len(entry_pages)
    # cancel-while-PENDING with a matched (pinned) prefix must unpin
    cb2 = _batcher(params, cfg, "paged", pc=None, n_slots=1)
    before = cb2.pool.in_use
    r_a = cb2.submit(_prompt(66, 9, cfg), max_new=40)  # hogs the slot
    r_b = cb2.submit(_prompt(67, 9, cfg), max_new=4)   # stays pending
    for _ in range(3):
        cb2.step()
    assert cb2.cancel(r_b) is True
    cb2.cancel(r_a)
    cb2.run()
    cb2.pool.check()
    assert cb2.pool.in_use == before == 0


# --- admission: pool pressure ----------------------------------------------


class _KvRec:
    """metrics duck-type recording only the KV hooks."""

    def __init__(self):
        self.rejected = []
        self.pages = None
        self.reserved = None

    def on_kv_admission_rejected(self, reason):
        self.rejected.append(reason)

    def set_kv_pages(self, total, in_use, frag):
        self.pages = (total, in_use, frag)

    def set_kv_reserved_bytes(self, nbytes):
        self.reserved = nbytes

    def on_submit(self): ...
    def on_prefill_chunk(self): ...
    def on_first_token(self): ...
    def on_step(self, *a): ...
    def on_finish(self, reason): ...


def test_pool_exhaustion_defers_then_admits(setup):
    """A pool with room for ONE request at a time: the second request
    waits under pool pressure (counted once) and admits after the first
    retires — streams exact throughout."""
    cfg, params = setup
    rec = _KvRec()
    # 4 pages: one 9-token + 4-new request needs ceil(13/16)=1 page...
    # use budgets that need 2 pages each so two can't coexist (pool 3)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, kv_layout="paged", kv_page_size=PS,
        kv_pages=3 + 1, metrics=rec,  # 3 allocatable + trap
    )
    p1, p2 = _prompt(70, 9, cfg), _prompt(71, 10, cfg)
    r1 = cb.submit(p1, max_new=20)  # ceil(29/16) = 2 pages
    r2 = cb.submit(p2, max_new=20)  # 2 pages: must wait for r1
    results = cb.run()
    assert results[r1] == _oracle(params, p1, cfg, 20)
    assert results[r2] == _oracle(params, p2, cfg, 20)
    assert rec.rejected.count("pool_pressure") == 1  # one deferred spell
    assert rec.pages is not None and rec.pages[0] == 3
    assert rec.reserved == 4 * PS * kv_token_bytes(cfg)
    cb.pool.check()


def test_pool_pressure_evicts_cached_prefixes(setup):
    """Promoted prefixes pin pool pages; when those pins are what stands
    between a non-matching request and its reservation, admission must
    evict LRU cache entries instead of deferring forever (the dense
    layout would have admitted the same request)."""
    cfg, params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, prefix_cache=pc, kv_layout="paged",
        kv_page_size=PS, kv_pages=4 + 1,  # 4 allocatable + trap
    )
    # promote prefixes at buckets 8 and 16: both entries pin the slot's
    # first page, which survives the slot's retirement
    p_a = _prompt(80, 17, cfg)
    r_a = cb.submit(p_a, max_new=7)  # ceil(24/16) = 2 pages
    assert cb.run(max_steps=100)[r_a] == _oracle(params, p_a, cfg, 7)
    assert pc.stats.entries == 2 and cb.pool.in_use == 1
    # a non-matching request needing the WHOLE pool: only eviction of
    # the pinned entries can free its fourth page
    p_b = _prompt(81, 33, cfg)
    r_b = cb.submit(p_b, max_new=31)  # ceil(64/16) = 4 pages
    results = cb.run(max_steps=200)
    assert results[r_b] == _oracle(params, p_b, cfg, 31)
    # both pinned entries went to the relief valve (r_b's own prefill
    # re-promoted its boundaries afterwards — that's the cache working)
    assert pc.stats.evictions == 2
    cb.pool.check()


def test_futile_eviction_is_skipped(setup):
    """When the pages a deferred request is short of are held by RUNNING
    slots, destroying the prefix cache frees nothing — the relief valve
    must leave the cache alone and just wait for a slot to retire."""
    cfg, params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, prefix_cache=pc, kv_layout="paged",
        kv_page_size=PS, kv_pages=6,  # 5 allocatable + trap
    )
    p_x = _prompt(90, 17, cfg)
    r_x = cb.submit(p_x, max_new=7)  # promotes: entries pin 1 page
    cb.run(max_steps=100)
    assert pc.stats.entries == 2 and cb.pool.in_use == 1
    p_l = _prompt(91, 9, cfg)
    r_l = cb.submit(p_l, max_new=40)  # 4 pages: drains the free list
    p_m = _prompt(92, 9, cfg)
    r_m = cb.submit(p_m, max_new=20)  # 2 pages: must wait, NOT evict
    for _ in range(6):
        cb.step()
    # m is deferred behind l's pages; full cache destruction could free
    # at most 1 page — evicting would be futile and must not happen
    assert pc.stats.evictions == 0 and pc.stats.entries >= 2
    results = cb.run(max_steps=400)
    assert results[r_x] == _oracle(params, p_x, cfg, 7)
    assert results[r_l] == _oracle(params, p_l, cfg, 40)
    assert results[r_m] == _oracle(params, p_m, cfg, 20)
    assert pc.stats.evictions == 0  # m admitted off l's retirement alone
    cb.pool.check()


def test_cancel_while_deferred_counts_no_hit(setup):
    """A matched request cancelled while deferred under pool pressure
    never ran: its hit (and tokens-saved) must not be recorded — the
    disposition commits only when a request takes a slot."""
    cfg, params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, prefix_cache=pc, kv_layout="paged",
        kv_page_size=PS, kv_pages=6,
    )
    sys_p = _prompt(95, 17, cfg)
    cb.submit(sys_p, max_new=7)
    cb.run(max_steps=100)          # promotes sys_p's boundaries
    cb.submit(_prompt(96, 9, cfg), max_new=40)  # hogs the free list
    r_h = cb.submit(sys_p + _prompt(97, 4, cfg), max_new=20)  # a hit...
    for _ in range(4):
        cb.step()                  # ...matched + pinned, then deferred
    hits_before = pc.stats.hits
    assert cb.cancel(r_h) is True  # cancelled while still pending
    assert pc.stats.hits == hits_before == 0
    assert pc.stats.tokens_saved == 0
    cb.run(max_steps=400)
    cb.pool.check()                # the match-time pins were returned


def test_paged_cache_cannot_move_between_batchers(setup):
    """A cache holding paged entries is bound to the pool that promoted
    them: re-attaching it to any new batcher must fail loudly (its page
    ids index the OLD pool), and an emptied cache re-attached to a dense
    batcher must shed the paged entry hooks."""
    cfg, params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = _batcher(params, cfg, "paged", pc=pc)
    p = _prompt(85, 17, cfg)
    cb.submit(p, max_new=4)
    cb.run(max_steps=100)
    assert pc.stats.entries > 0
    with pytest.raises(ValueError, match="paged entries"):
        _batcher(params, cfg, "paged", pc=pc)
    # drain the cache: a fresh DENSE batcher may then take it, and must
    # restore the dense row-entry hooks the paged batcher rebound
    while pc.evict_one():
        pass
    assert pc.stats.entries == 0
    cb2 = _batcher(params, cfg, "dense", pc=pc)
    assert pc.entry_factory is batching.PrefixState
    assert pc.release_entry is None
    r = cb2.submit(p, max_new=4)
    assert cb2.run(max_steps=100)[r] == _oracle(params, p, cfg, 4)


def test_request_outsizing_pool_is_refused(setup):
    cfg, params = setup
    rec = _KvRec()
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, kv_layout="paged", kv_page_size=PS,
        kv_pages=2 + 1, metrics=rec,  # 2 allocatable pages = 32 tokens
    )
    with pytest.raises(ValueError, match="KV pages"):
        cb.submit(_prompt(72, 20, cfg), max_new=20)  # needs 3 pages
    assert rec.rejected == ["request_too_large"]
    # a fitting request still sails through
    p = _prompt(73, 9, cfg)
    rid = cb.submit(p, max_new=4)
    assert cb.run()[rid] == _oracle(params, p, cfg, 4)


# --- opt-outs ---------------------------------------------------------------


@pytest.mark.parametrize("cache_quant", ["int8", "int4"])
def test_quantized_cache_pages_scale_planes(setup, cache_quant):
    """The old refusal is GONE: int8/int4 caches ride the page pool.
    The codes quantize into the pool's narrow dtype and the f32 scale
    planes ride the SAME page geometry — (L, n_pages, page_size, Hkv, 1)
    — so one table lookup addresses a page's codes and its scale rows
    alike, and the stream matches the dense quantized batcher
    token-for-token (one pinned test per code width)."""
    cfg, params = setup
    cfg_q = LlamaConfig.tiny(n_layers=2, cache_quant=cache_quant)
    cb = ContinuousBatcher(
        params, cfg_q, n_slots=1, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, kv_layout="paged", kv_page_size=PS,
    )
    qdtype = jnp.int8 if cache_quant == "int8" else jnp.int4
    cache = cb.state.cache
    assert cache.k.dtype == qdtype and cache.v.dtype == qdtype
    assert cache.k_scale is not None and cache.v_scale is not None
    assert cache.k_scale.dtype == jnp.float32
    # scale planes share the page geometry with a scalar trailing dim
    assert cache.k_scale.shape == cache.k.shape[:-1] + (1,)
    p = _prompt(91, 9, cfg_q)
    rid = cb.submit(p, max_new=4)
    dense = ContinuousBatcher(
        params, cfg_q, n_slots=1, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8,
    )
    rid_d = dense.submit(p, max_new=4)
    assert cb.run()[rid] == dense.run()[rid_d]


def test_speculative_batcher_supports_paged(setup):
    """Speculative decoding joined the paged fast path: construction
    pages BOTH caches (the draft gets its own pool with the same
    trap-page/refcount semantics). Stream exactness across the full
    dense/paged x cache x pipeline matrix is pinned in
    tests/test_spec_fastpath.py; here the old refusal is pinned GONE."""
    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    cfg, params = setup
    draft_cfg = LlamaConfig.tiny(n_layers=1)
    draft_params = init_params(jax.random.key(9), draft_cfg)
    assert SpeculativeBatcher.supports_paged_kv is True
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=2, max_len=64, gamma=2, chunked_prefill=8,
        kv_layout="paged", kv_page_size=PS,
    )
    assert sb.pool is not None and sb.draft_pool is not None
    assert sb.draft_state.pages is not None
    assert sb.draft_pool.page_size == PS
    # the pools are independent: draft capacity defaults to the draft's
    # dense-equivalent page count (same geometry, far fewer bytes)
    assert sb.draft_pool.capacity == sb.pool.capacity
    assert sb.kv_stats()["draft_reserved_bytes"] < (
        sb.kv_stats()["target_reserved_bytes"]
    )


def test_page_size_must_divide_max_len(setup):
    cfg, params = setup
    with pytest.raises(ValueError, match="divide"):
        ContinuousBatcher(
            params, cfg, n_slots=1, max_len=60, prompt_buckets=BUCKETS,
            kv_layout="paged", kv_page_size=PS,
        )


def test_pinned_tail_on_tight_pool_admits_cold(setup):
    """Futile-deferral escape: on an IDLE server, a prefix hit whose
    partial tail page is pinned can occupy the very capacity its own
    reservation needs (pool of 3, entry holds 1, cold need is 3). The
    batcher must not defer forever — it drops the hit, reclaims the now
    unpinned entry, and admits COLD (the stream still matches the
    oracle; only the reuse is lost)."""
    cfg, params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, kv_layout="paged", kv_page_size=PS,
        kv_pages=3 + 1, prefix_cache=pc,  # 3 allocatable = 48 tokens
    )
    p_a = _prompt(90, 9, cfg)
    r_a = cb.submit(p_a, max_new=4)
    assert cb.run(max_steps=100)[r_a] == _oracle(params, p_a, cfg, 4)
    assert pc.stats.promotions == 1  # boundary 8: one PARTIAL page
    assert cb.pool.in_use == 1       # pinned by the entry alone (idle)
    # shares the 8-token boundary; worst case 9 + 26 = 35 tokens = all
    # 3 pages, while the matched entry pins 1 of them
    p_b = p_a[:8] + _prompt(91, 1, cfg)
    r_b = cb.submit(p_b, max_new=26)
    assert cb.run(max_steps=200)[r_b] == _oracle(params, p_b, cfg, 26)
    assert pc.stats.evictions == 1   # A's entry was sacrificed
    assert pc.stats.hits == 0        # ... so B ran cold, counted a miss
    assert pc.stats.misses == 2
    cb.pool.check()
    # B's own completed prefill re-promoted at the same boundary: the
    # one resident page is the NEW entry's, everything else returned
    assert pc.stats.entries == 1 and cb.pool.in_use == 1


def test_manual_paged_prefix_refused(setup):
    """PagedPrefixState entries hold pool-internal page references the
    attached cache owns; submitting one manually would reach admission
    unpinned, where pressure-relief eviction could free and reallocate
    its pages — the submit wall must refuse it."""
    cfg, params = setup
    cb = _batcher(params, cfg, "paged")
    entry = batching.PagedPrefixState(
        page_ids=(1,), tokens=tuple(_prompt(95, 8, cfg)),
        presence=jnp.zeros((64,), bool), adapter=-1,
    )
    with pytest.raises(ValueError, match="manually"):
        cb.submit(_prompt(96, 9, cfg), max_new=4, prefix=entry)


def test_negative_kv_pages_refused(setup):
    """A negative pool size must fail loudly, not silently fall back to
    the dense-equivalent default (the refuse-loudly posture every other
    invalid knob on this path takes)."""
    cfg, params = setup
    with pytest.raises(ValueError, match="kv_pages"):
        ContinuousBatcher(
            params, cfg, n_slots=1, max_len=64, prompt_buckets=BUCKETS,
            kv_layout="paged", kv_page_size=PS, kv_pages=-512,
        )


# --- the paged Pallas kernel ------------------------------------------------


def test_paged_attention_kernel_matches_gather(setup):
    """ops/paged_attention.py in interpret mode vs the XLA gather
    reference _cached_attention falls back to — same table, same
    lengths, windowed and unwindowed."""
    from k8s_gpu_device_plugin_tpu.ops import paged_attention

    b, ps, n_pages, hkv, hq, hd, npg = 3, 8, 16, 2, 8, 64, 4
    kp = jax.random.normal(
        jax.random.key(1), (n_pages, ps, hkv, hd), jnp.bfloat16
    )
    vp = jax.random.normal(
        jax.random.key(2), (n_pages, ps, hkv, hd), jnp.bfloat16
    )
    q = jax.random.normal(jax.random.key(3), (b, 1, hq, hd), jnp.bfloat16)
    table = jnp.asarray(
        np.random.RandomState(0).choice(
            np.arange(1, n_pages), (b, npg), replace=False
        ),
        jnp.int32,
    )
    lengths = jnp.asarray([5, 17, 32], jnp.int32)
    assert paged_attention.supports(q, kp, table, require_pltpu=False)

    def ref(window):
        kd = kp[table].reshape(b, npg * ps, hkv, hd).astype(jnp.float32)
        vd = vp[table].reshape(b, npg * ps, hkv, hd).astype(jnp.float32)
        qf = q.astype(jnp.float32).reshape(b, hkv, hq // hkv, hd)
        s = jnp.einsum("bkgd,bskd->bkgs", qf, kd) * hd ** -0.5
        pos = jnp.arange(npg * ps)[None, None, None, :]
        keep = pos < lengths[:, None, None, None]
        if window:
            keep &= pos >= jnp.maximum(lengths - window, 0)[
                :, None, None, None
            ]
        s = jnp.where(keep, s, -1e30)
        pr = jax.nn.softmax(s, -1)
        return jnp.einsum("bkgs,bskd->bkgd", pr, vd).reshape(b, 1, hq, hd)

    for window in (0, 12):
        out = paged_attention.paged_decode_attention(
            q, kp, vp, table, lengths, scale=hd ** -0.5, window=window,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref(window)),
            atol=5e-2, rtol=5e-2,
        )

    # shape gates: T>1 and ragged page sizes are refused
    assert not paged_attention.supports(
        jnp.zeros((b, 2, hq, hd), jnp.bfloat16), kp, table,
        require_pltpu=False,
    )
    assert not paged_attention.supports(
        q, jnp.zeros((n_pages, 12, hkv, hd), jnp.bfloat16), table,
        require_pltpu=False,
    )


def test_paged_ragged_fallback_at_attention_level(setup):
    """decode_attn='ragged' + paged with an UNSUPPORTED head dim (the
    tiny preset's 16) must fall back to the gather path and agree with
    decode_attn='auto' bitwise — pinned at the _cached_attention level
    so the fallback costs no extra whole-model compile in the suite."""
    from dataclasses import replace

    from k8s_gpu_device_plugin_tpu.models.generate import _cached_attention

    cfg, _ = setup
    pcfg = replace(cfg, kv_layout="paged", kv_page_size=PS)
    b, hkv, hd, n_pages, npg = 2, pcfg.n_kv_heads, pcfg.head_dim, 9, 4
    q = jax.random.normal(
        jax.random.key(1), (b, 1, pcfg.n_heads, hd), jnp.bfloat16
    )
    kp = jax.random.normal(
        jax.random.key(2), (n_pages, PS, hkv, hd), jnp.bfloat16
    )
    vp = jax.random.normal(
        jax.random.key(3), (n_pages, PS, hkv, hd), jnp.bfloat16
    )
    pages = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    lens = jnp.asarray([7, 40], jnp.int32)
    auto = _cached_attention(q, kp, vp, None, None, lens, pcfg, pages=pages)
    ragged = _cached_attention(
        q, kp, vp, None, None, lens,
        replace(pcfg, decode_attn="ragged"), pages=pages,
    )
    assert np.array_equal(
        np.asarray(auto, np.float32), np.asarray(ragged, np.float32)
    )


# --- stats & health surfaces ------------------------------------------------


def test_kv_stats_both_layouts(setup):
    cfg, params = setup
    dense = _batcher(params, cfg, "dense")
    s = dense.kv_stats()
    assert s["layout"] == "dense"
    assert s["reserved_bytes"] == 2 * 64 * kv_token_bytes(cfg)
    paged = _batcher(params, cfg, "paged")
    s = paged.kv_stats()
    assert s["layout"] == "paged" and s["page_size"] == PS
    assert s["pages_in_use"] == 0 and s["fragmentation_pct"] == 0.0
    assert s["reserved_bytes"] == paged.pool.n_pages * PS * kv_token_bytes(cfg)
    rid = paged.submit(_prompt(90, 9, cfg), max_new=4)
    paged.step()
    s = paged.kv_stats()
    assert s["pages_in_use"] >= 1 and 0.0 <= s["fragmentation_pct"] <= 100.0
    paged.cancel(rid)


def test_serving_metrics_kv_surface():
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.set_kv_pages(128, 16, 12.5)
    m.on_kv_admission_rejected("pool_pressure")
    m.set_kv_reserved_bytes(1 << 20)
    g = reg.get_sample_value
    pre = "tpu_serving"
    assert g(f"{pre}_kv_pages_total") == 128
    assert g(f"{pre}_kv_pages_in_use") == 16
    assert g(f"{pre}_kv_page_fragmentation_pct") == 12.5
    assert g(f"{pre}_kv_admission_rejected_total",
             {"reason": "pool_pressure"}) == 1
    assert g(f"{pre}_kv_reserved_bytes") == 1 << 20
    m.close()
    m2 = ServingMetrics(registry=reg)  # names freed by close()
    m2.close()


def test_engine_health_reports_kv(setup):
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params = setup
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
        kv_layout="paged", kv_page_size=PS,
    )
    try:
        kv = engine.stats()["kv"]
        assert kv["layout"] == "paged" and kv["pages_total"] > 0
    finally:
        engine.shutdown()
    with pytest.raises(ValueError, match="injected batcher"):
        InferenceEngine(
            params, cfg,
            batcher=ContinuousBatcher(
                params, cfg, n_slots=1, max_len=64, prompt_buckets=BUCKETS,
            ),
            kv_layout="paged",
        )


def test_prefix_kv_bytes_rounds_to_pages(setup):
    cfg, _ = setup
    from dataclasses import replace

    pcfg = replace(cfg, kv_layout="paged", kv_page_size=PS)
    assert prefix_kv_bytes(pcfg, 8) == prefix_kv_bytes(pcfg, 16)
    assert prefix_kv_bytes(pcfg, 8) == prefix_kv_bytes(cfg, 16)
    assert prefix_kv_bytes(pcfg, 17) == prefix_kv_bytes(cfg, 32)


def test_paged_kv_bench_machinery():
    """The CI microbench's host pieces at tiny scale (the full bench
    runs as `make bench-paged-kv`; here only the allocator half — the
    gather A/B would recompile a third config in the suite)."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.paged_kv_bench import (
        allocator_bench,
    )

    out = allocator_bench(n_ops=50, n_pages=64, page_size=16)
    assert out["page_alloc_free_us"] > 0
    assert out["page_incref_decref_us"] > 0


def test_pool_free_returns_to_baseline_after_promotion_failure(setup):
    """Induced failure paths must not strand page references.

    The promotion extractor used to push KV gauges BETWEEN taking page
    refs and handing them to the cache entry; a raising (duck-typed)
    metrics hook in that window stranded the refs with no owner — found
    by graftlint's refcount-pairing checker, fixed by making the
    incref->record window call-free (gauges move after on_prefill_done).
    Pinned here: even when the gauge push raises mid-step, every
    reference stays owned, and draining slots + cache returns the pool
    to its free-count baseline."""
    cfg, params = setup

    class _ArmedRaiser(_KvRec):
        armed = False

        def set_kv_pages(self, *a):
            if self.armed:
                raise RuntimeError("scrape backend down")
            super().set_kv_pages(*a)

    rec = _ArmedRaiser()
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = _batcher(params, cfg, "paged", pc=pc, metrics=rec)
    baseline = cb.pool.free_pages
    rid = cb.submit(_prompt(120, 17, cfg), max_new=7)
    cb.step()  # admission + first chunk: gauges healthy here
    rec.armed = True
    with pytest.raises(RuntimeError, match="scrape backend down"):
        for _ in range(50):
            cb.step()  # finish chunk promotes -> the gauge push raises
    rec.armed = False
    # the promotion itself completed BEFORE the raise: both boundary
    # entries own their refs (the old code died inside the extractor,
    # leaving 0 entries and the increfs stranded)
    assert pc.stats.entries == 2
    cb.pool.check()
    cb.cancel(rid)
    while pc.evict_one():
        pass
    cb.run(max_steps=50)
    cb.pool.check()
    assert cb.pool.free_pages == baseline  # every failure path balanced

    # submit-side refusal (request_too_large): no pages move at all
    small = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=8, kv_layout="paged", kv_page_size=PS, kv_pages=3,
    )
    base2 = small.pool.free_pages
    with pytest.raises(ValueError, match="pool"):
        small.submit(_prompt(121, 30, cfg), max_new=30)
    assert small.pool.free_pages == base2 == 2
    small.pool.check()
