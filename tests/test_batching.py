"""Continuous batching (slot-based serving) vs the single-request oracle.

The engine's claim is exact: a request decoded in a shared slotted batch
— at whatever slot, alongside whatever neighbors, admitted whenever —
produces the SAME greedy tokens as a dedicated ``generate`` call. Masked
attention makes neighbor rows and padded/garbage cache rows exact zeros
in the softmax, so parity is bitwise, not approximate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import (
    ContinuousBatcher,
    _bucket,
)
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=max_new
    )
    return np.asarray(out)[0].tolist()


@pytest.mark.parametrize("depth,kv_layout", [
    (0, "dense"), (1, "dense"), (1, "paged"),
])
def test_more_requests_than_slots_matches_generate(setup, depth, kv_layout):
    """4 requests, 2 slots, mixed prompt lengths and budgets: every
    request's stream must equal its dedicated-generate tokens (slot reuse
    and batch neighbors must be invisible) — pipelined or not, dense or
    paged KV (the paged pool reuses pages as slots retire)."""
    cfg, params = setup
    specs = [(1, 5, 6), (2, 12, 4), (3, 3, 8), (4, 9, 5)]  # (key, plen, new)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64,
        prompt_buckets=(4, 8, 16, 32), pipeline_depth=depth,
        kv_layout=kv_layout,
        kv_page_size=16 if kv_layout == "paged" else None,
    )
    prompts = {}
    for key, plen, max_new in specs:
        p = _prompt(key, plen, cfg)
        rid = cb.submit(p, max_new=max_new)
        prompts[rid] = (p, max_new)
    results = cb.run()
    assert set(results) == set(prompts)
    for rid, (p, max_new) in prompts.items():
        assert results[rid] == _oracle(params, p, cfg, max_new), rid


@pytest.mark.parametrize("depth", [0, 1])
def test_midstream_admission(setup, depth):
    """A request submitted while others are mid-decode must not perturb
    them — and must itself decode exactly (pipelined mode flushes the
    in-flight step before admitting)."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=3, max_len=64, prompt_buckets=(8, 16),
        pipeline_depth=depth,
    )
    p1 = _prompt(10, 6, cfg)
    r1 = cb.submit(p1, max_new=10)
    for _ in range(4):
        cb.step()
    p2 = _prompt(11, 8, cfg)
    r2 = cb.submit(p2, max_new=6)
    results = cb.run()
    assert results[r1] == _oracle(params, p1, cfg, 10)
    assert results[r2] == _oracle(params, p2, cfg, 6)


@pytest.mark.parametrize("depth", [0, 1])
def test_eos_frees_slot_for_queued_request(setup, depth):
    """EOS retirement: pick the token the model actually emits second for
    request A as the EOS id; A must stop right after it (EOS kept,
    nothing beyond — pipelined mode drops A's lagging in-flight token),
    and the queued request C must then run in A's slot and still match
    its oracle."""
    cfg, params = setup
    pa = _prompt(20, 5, cfg)
    oracle_a = _oracle(params, pa, cfg, 6)
    eos = oracle_a[1]
    pb = _prompt(21, 7, cfg)
    oracle_b = _oracle(params, pb, cfg, 6)
    if eos in oracle_b[:-1]:  # keep B un-stopped for a clean comparison
        pytest.skip("random oracle collision: eos appears in B's stream")

    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, eos_id=eos,
        prompt_buckets=(8, 16), pipeline_depth=depth,
    )
    ra = cb.submit(pa, max_new=6)
    rb = cb.submit(pb, max_new=6)
    results = cb.run()
    assert results[ra] == oracle_a[:2]        # stopped AT the eos token
    assert results[rb][: len(results[rb])] == oracle_b[: len(results[rb])]
    assert len(results[rb]) >= 5              # b ran to (near) budget


@pytest.mark.parametrize("cache_quant", ["int8", "int4"])
def test_quantized_cache_parity(setup, cache_quant):
    """The quantized-KV paths ride the same per-row machinery: batcher
    tokens must equal dedicated-generate tokens under cache_quant
    (both sides quantized — parity is within the cache numerics, which
    the generate-vs-oracle tests already bound)."""
    cfg, _ = setup
    cfg_q = LlamaConfig.tiny(n_layers=2, cache_quant=cache_quant)
    params = init_params(jax.random.key(0), cfg_q)
    p = _prompt(30, 6, cfg_q)
    cb = ContinuousBatcher(
        params, cfg_q, n_slots=2, max_len=64, prompt_buckets=(8,),
    )
    rid = cb.submit(p, max_new=5)
    results = cb.run()
    assert results[rid] == _oracle(params, p, cfg_q, 5)


def test_sampled_batching_runs(setup):
    """Sampled decoding (top-k + repetition penalty) through the batcher:
    streams complete, tokens in range, repetition-penalty presence stays
    per-slot (no cross-request bleed crashes)."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64,
        sampler=Sampler(temperature=0.8, top_k=20, repetition_penalty=1.2),
        prompt_buckets=(8,),
    )
    rids = [cb.submit(_prompt(40 + i, 5, cfg), max_new=6) for i in range(3)]
    results = cb.run()
    for rid in rids:
        assert len(results[rid]) == 6
        assert all(0 <= t < cfg.vocab_size for t in results[rid])


def test_bucket_selection():
    assert _bucket(5, (8, 16)) == 8
    assert _bucket(8, (8, 16)) == 8
    assert _bucket(9, (8, 16)) == 16
    with pytest.raises(ValueError):
        _bucket(17, (8, 16))


def test_capacity_guard(setup):
    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=16,
                           prompt_buckets=(8, 16))
    with pytest.raises(ValueError):
        cb.submit(list(range(1, 13)), max_new=8)  # 12 + 8 > 16


def test_submit_rejects_prompt_over_largest_bucket(setup):
    """A prompt that fits max_len but no bucket must fail at submit time,
    not mid-run (where it would strand in-flight neighbors)."""
    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           prompt_buckets=(8,))
    with pytest.raises(ValueError):
        cb.submit(list(range(1, 11)), max_new=4)  # len 10 > bucket 8
    with pytest.raises(ValueError):
        ContinuousBatcher(params, cfg, n_slots=1, max_len=4,
                          prompt_buckets=(8,))  # no bucket fits


def test_serve_bench_machinery(setup):
    """serve_bench end-to-end at tiny scale: positive throughput numbers,
    request accounting adds up."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.serve_bench import (
        serve_bench,
    )

    cfg, params = setup
    r = serve_bench(
        cfg, n_slots=2, n_requests=4, max_len=32,
        prompt_lens=(4, 7), max_new=4, params=params,
        prompt_buckets=(8, 16), chunked_prefill=8,
        sched_base_s=0.5, sched_overload_s=0.5,
    )
    assert r.tokens_per_second > 0
    assert r.requests_per_second > 0
    assert r.decode_step_ms > 0
    assert r.total_new_tokens == 16
    # the slo-vs-fifo open-loop A/B ran: both arms produced goodput and
    # the offered load was calibrated off the measured capacity
    assert r.openloop_requests > 0
    assert r.openloop_base_rps > 0
    assert r.goodput_tokens_fifo > 0
    assert r.goodput_tokens_slo > 0


def test_tp_sharded_batching_matches_unsharded():
    """Continuous batching with tp-sharded params (GSPMD propagates from
    the param shardings; no batching-specific annotations) must emit the
    same greedy tokens as the unsharded batcher."""
    from jax.sharding import PartitionSpec as P

    from k8s_gpu_device_plugin_tpu.models.llama import param_shardings
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    mesh = make_mesh(MeshSpec(tp=4), jax.devices()[:4])
    sharded = jax.device_put(params, param_shardings(cfg, mesh))

    prompts = [_prompt(60, 5, cfg), _prompt(61, 9, cfg)]

    def run(p):
        cb = ContinuousBatcher(p, cfg, n_slots=2, max_len=32,
                               prompt_buckets=(16,))
        rids = [cb.submit(x, max_new=5) for x in prompts]
        res = cb.run()
        return [res[r] for r in rids]

    assert run(sharded) == run(params)


@pytest.mark.parametrize("depth,kv_layout", [
    (0, "dense"), (1, "dense"), (0, "paged"), (1, "paged"),
])
def test_chunked_prefill_matches_generate(setup, depth, kv_layout):
    """chunked_prefill=C must change scheduling only: every request's
    stream still equals its dedicated-generate tokens (intermediate
    chunks attend exactly the slot's own earlier rows — under the paged
    layout, through the slot's page table). The paged legs use C=8, the
    one paged chunk size the whole suite compiles (test_paged_kv.py and
    the prefix-cache slice share it)."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64,
        chunked_prefill=4 if kv_layout == "dense" else 8,
        pipeline_depth=depth, kv_layout=kv_layout,
        kv_page_size=16 if kv_layout == "paged" else None,
    )
    specs = [(70, 11, 5), (71, 3, 6), (72, 9, 4)]  # (key, plen, new)
    prompts = {}
    for key, plen, max_new in specs:
        p = _prompt(key, plen, cfg)
        rid = cb.submit(p, max_new=max_new)
        prompts[rid] = (p, max_new)
    results = cb.run()
    for rid, (p, max_new) in prompts.items():
        assert results[rid] == _oracle(params, p, cfg, max_new), rid


def test_chunked_prefill_interleaves_with_decode(setup):
    """While a long prompt prefills chunk-by-chunk, an already-running
    request keeps emitting tokens — the whole point of chunking."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=4,
    )
    pa = _prompt(80, 4, cfg)
    ra = cb.submit(pa, max_new=12)
    cb.step()  # admits A; finish-chunk prefill (prompt 4 <= C) -> running
    assert cb.running and not cb.prefilling
    a_tokens_before = len(cb.running[0].out)
    pb = _prompt(81, 16, cfg)  # 16 tokens = 4 chunks of 4
    rb = cb.submit(pb, max_new=4)
    cb.step()  # B chunk 1 + A decodes
    cb.step()  # B chunk 2 + A decodes
    assert cb.prefilling, "B should still be mid-prefill"
    a_tokens_during = len(cb.running[0].out)
    assert a_tokens_during > a_tokens_before, "A stalled behind B's prefill"
    results = cb.run()
    assert results[ra] == _oracle(params, pa, cfg, 12)
    # the stream the interleaving can corrupt is B's: decode steps ran
    # WHILE B was mid-prefill (regression: inactive-slot decode writes
    # used to clobber freshly prefilled rows at the stale length)
    assert results[rb] == _oracle(params, pb, cfg, 4)


def test_chunked_prefill_only_state_terminates(setup):
    """run() must drive requests that are mid-prefill even when nothing
    is pending or running (regression: the drain condition)."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, chunked_prefill=4,
    )
    p = _prompt(82, 10, cfg)
    rid = cb.submit(p, max_new=3)
    cb._admit()  # move to prefilling without stepping
    assert cb.prefilling and not cb.pending and not cb.running
    results = cb.run()
    assert results[rid] == _oracle(params, p, cfg, 3)


def test_chunked_prefill_unaligned_near_capacity(setup):
    """Finish-chunk scheduling: a prompt whose forward-padded final chunk
    would straddle max_len (61 tokens, C=10, max_len=64) must still
    decode exactly — the finish chunk runs at plen-C with identical-K/V
    overlap instead of clamp-shifting rows."""
    cfg, params = setup
    p = _prompt(90, 61, cfg)
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, chunked_prefill=10,
    )
    rid = cb.submit(p, max_new=3)
    results = cb.run()
    assert results[rid] == _oracle(params, p, cfg, 3)


def test_chunked_slot_reuse_resets_presence(setup):
    """Repetition penalty must not leak the previous occupant's seen-token
    set into a reused slot (chunked path rebuilds presence from zeros on
    the first chunk). Pin: chunked slot-reuse == dedicated generate with
    the same penalized sampler, greedy-ized via temperature 0."""
    cfg, params = setup
    sampler = Sampler(repetition_penalty=1.5)
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, chunked_prefill=4,
        sampler=sampler,
    )
    p1 = _prompt(91, 9, cfg)
    p2 = _prompt(92, 6, cfg)
    r1 = cb.submit(p1, max_new=5)
    r2 = cb.submit(p2, max_new=5)
    results = cb.run()

    def oracle(p, n):
        out = generate(params, jnp.asarray([p], jnp.int32), cfg,
                       max_new=n, sampler=sampler)
        return np.asarray(out)[0].tolist()

    assert results[r1] == oracle(p1, 5)
    assert results[r2] == oracle(p2, 5)  # fails if r1's tokens leak in


def test_shared_prefix_matches_generate(setup):
    """Two requests sharing a precomputed prefix must each match
    dedicated generate over (prefix + suffix) — one prefix prefill total,
    slot reuse included (1 slot)."""
    from k8s_gpu_device_plugin_tpu.models.batching import precompute_prefix

    cfg, params = setup
    prefix_toks = _prompt(100, 13, cfg)
    prefix = precompute_prefix(params, prefix_toks, cfg)
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, chunked_prefill=4,
    )
    s1 = _prompt(101, 6, cfg)
    s2 = _prompt(102, 2, cfg)  # suffix < chunk: finish recomputes across
    r1 = cb.submit(s1, max_new=4, prefix=prefix)
    r2 = cb.submit(s2, max_new=5, prefix=prefix)
    results = cb.run()
    assert results[r1] == _oracle(params, prefix_toks + s1, cfg, 4)
    assert results[r2] == _oracle(params, prefix_toks + s2, cfg, 5)


def test_shared_prefix_presence_feeds_penalty(setup):
    """The prefix's tokens must count as 'seen' for the repetition
    penalty in every request that uses it (pin vs dedicated generate
    with the same sampler over the full prompt)."""
    from k8s_gpu_device_plugin_tpu.models.batching import precompute_prefix

    cfg, params = setup
    sampler = Sampler(repetition_penalty=1.5)
    prefix_toks = _prompt(110, 9, cfg)
    prefix = precompute_prefix(params, prefix_toks, cfg)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=4,
        sampler=sampler,
    )
    s = _prompt(111, 5, cfg)
    rid = cb.submit(s, max_new=5, prefix=prefix)
    results = cb.run()
    out = generate(params, jnp.asarray([prefix_toks + s], jnp.int32), cfg,
                   max_new=5, sampler=sampler)
    assert results[rid] == np.asarray(out)[0].tolist()


def test_prefix_requires_chunked_and_fits(setup):
    from k8s_gpu_device_plugin_tpu.models.batching import precompute_prefix

    cfg, params = setup
    prefix = precompute_prefix(params, _prompt(120, 8, cfg), cfg)
    cb_unchunked = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                                     prompt_buckets=(16,))
    with pytest.raises(ValueError):
        cb_unchunked.submit([1, 2], max_new=2, prefix=prefix)
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=16,
                           chunked_prefill=4)
    with pytest.raises(ValueError):
        cb.submit([1] * 6, max_new=4, prefix=prefix)  # 8+6+4 > 16


def test_serving_metrics_track_lifecycle(setup):
    """ServingMetrics wired into the batcher: counters/gauges reflect the
    run (tokens emitted, retirement reasons, chunks, final idle gauges)."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    reg = CollectorRegistry()
    metrics = ServingMetrics(registry=reg)
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=4,
        metrics=metrics,
    )
    rids = [cb.submit(_prompt(130 + i, 9, cfg), max_new=4) for i in range(3)]
    cb.run()

    def val(name, **labels):
        return reg.get_sample_value(name, labels or None)

    assert val("tpu_serving_requests_submitted_total") == 3
    assert val("tpu_serving_requests_finished_total", reason="budget") == 3
    # every generated token counts, including each request's first
    # (sampled at prefill-finish via on_first_token)
    assert val("tpu_serving_generated_tokens_total") == 3 * 4
    assert val("tpu_serving_prefill_chunks_total") >= 3  # 9 tokens = 2 chunks
    assert val("tpu_serving_queue_depth") == 0
    assert val("tpu_serving_slots_active") == 0


def test_serving_metrics_close_and_idle():
    """close() unregisters the fixed-name collectors (a second instance on
    the same registry no longer raises); on_idle() zeroes the throughput
    gauge instead of freezing it at the last busy window's value."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m1 = ServingMetrics(registry=reg)
    with pytest.raises(ValueError):
        ServingMetrics(registry=reg)  # duplicate names on one registry
    m1.close()
    m2 = ServingMetrics(registry=reg)  # fine after close()

    m2._win_t0 -= 2.0  # age the window so on_step closes it
    m2.on_step(emitted=10, queue=0, active=1, prefilling=0)
    assert reg.get_sample_value("tpu_serving_tokens_per_second") > 0
    m2.on_idle()
    assert reg.get_sample_value("tpu_serving_tokens_per_second") == 0.0
    m2.close()


@pytest.mark.parametrize("depth", [0, 1])
def test_stop_sequences_retire_requests(setup, depth):
    """A request stops when its output ends with a stop sequence (tokens
    kept, the pipelined in-flight token past the match dropped);
    unrelated requests run to budget. Metrics record the reason."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    p = _prompt(300, 5, cfg)
    oracle = _oracle(params, p, cfg, 6)
    stop = [oracle[1], oracle[2]]  # the model WILL emit this bigram

    reg = CollectorRegistry()
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=4,
        metrics=ServingMetrics(registry=reg), pipeline_depth=depth,
    )
    r1 = cb.submit(p, max_new=6, stop=[stop])
    p2 = _prompt(301, 4, cfg)
    r2 = cb.submit(p2, max_new=5)
    results = cb.run()
    assert results[r1] == oracle[:3]  # stopped right after the bigram
    assert results[r2] == _oracle(params, p2, cfg, 5)
    assert reg.get_sample_value(
        "tpu_serving_requests_finished_total", {"reason": "stop"}
    ) == 1


def test_logprobs_match_full_context_forward(setup):
    """Per-token logprobs from the batcher equal log-softmax of the
    full-context forward at each emitted position (raw model
    distribution, independent of sampler settings)."""
    from k8s_gpu_device_plugin_tpu.models.llama import forward

    cfg, params = setup
    p = _prompt(310, 6, cfg)
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=64,
                           chunked_prefill=4)
    rid = cb.submit(p, max_new=4)
    cb.run()
    req = cb.done_requests[rid]
    assert len(req.out_logp) == len(req.out) == 4

    tokens = jnp.asarray([p], jnp.int32)
    for i, (tok, lp) in enumerate(zip(req.out, req.out_logp)):
        logits = forward(params, tokens, cfg)[:, -1]
        expected = float(
            jax.nn.log_softmax(logits.astype(jnp.float32))[0, tok]
        )
        assert abs(lp - expected) < 5e-2, (i, lp, expected)
        tokens = jnp.concatenate(
            [tokens, jnp.asarray([[tok]], jnp.int32)], axis=1
        )


@pytest.mark.parametrize("depth", [0, 1])
def test_cancel_in_every_state_frees_slot_and_records(setup, depth):
    """cancel() retires a request from pending, mid-prefill, and decoding;
    the slot is reusable, neighbors are untouched (token parity with the
    oracle), tokens-so-far land in done, and metrics count 'cancelled'."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    reg = CollectorRegistry()
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, chunked_prefill=4,
        metrics=ServingMetrics(registry=reg), pipeline_depth=depth,
    )

    # pending: the single slot is busy, second submit queues
    p1, p2 = _prompt(400, 5, cfg), _prompt(401, 6, cfg)
    r1 = cb.submit(p1, max_new=4)
    r2 = cb.submit(p2, max_new=4)
    assert cb.cancel(r2) is True
    assert cb.done[r2] == []
    results = cb.run()
    assert results[r1] == _oracle(params, p1, cfg, 4)

    # mid-prefill: step until the request is prefilling, then cancel
    p3 = _prompt(402, 9, cfg)  # 9 tokens = 3 chunks of 4
    r3 = cb.submit(p3, max_new=4)
    cb.step()  # admits + first chunk
    assert cb.prefilling
    assert cb.cancel(r3) is True
    assert not cb.prefilling and r3 in cb.done

    # decoding: cancel after a couple of emitted tokens
    p4 = _prompt(403, 5, cfg)
    r4 = cb.submit(p4, max_new=8)
    for _ in range(6):
        cb.step()
        if cb.running and cb.done.get(r4) is None and len(
            next(iter(cb.running.values())).out
        ) >= 2:
            break
    assert cb.cancel(r4) is True
    got = cb.done[r4]
    assert 1 <= len(got) < 8
    assert got == _oracle(params, p4, cfg, 8)[:len(got)]  # prefix parity

    # the slot is reusable after each cancel
    p5 = _prompt(404, 5, cfg)
    r5 = cb.submit(p5, max_new=3)
    assert cb.run()[r5] == _oracle(params, p5, cfg, 3)

    # idempotent: unknown / already-finished rids
    assert cb.cancel(r4) is False
    assert cb.cancel(9999) is False
    assert reg.get_sample_value(
        "tpu_serving_requests_finished_total", {"reason": "cancelled"}
    ) == 3


def test_per_request_samplers_mix_in_one_batch(setup):
    """Mixed sampling settings decode side by side in one compiled step:
    a greedy request among sampled neighbors still matches its dedicated-
    generate oracle exactly, sampled requests emit valid in-range tokens,
    and a per-request greedy override on a SAMPLED-default batcher is
    likewise oracle-exact."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=3, max_len=64,
        sampler=Sampler(),  # greedy default
        prompt_buckets=(8,),
    )
    pg = _prompt(600, 5, cfg)
    rg = cb.submit(pg, max_new=5)  # default greedy
    rs1 = cb.submit(
        _prompt(601, 5, cfg), max_new=5,
        sampler=Sampler(temperature=0.9, top_k=20),
    )
    rs2 = cb.submit(
        _prompt(602, 6, cfg), max_new=5,
        sampler=Sampler(temperature=1.2, top_p=0.8,
                        repetition_penalty=1.3),
    )
    results = cb.run()
    assert results[rg] == _oracle(params, pg, cfg, 5)
    for rid in (rs1, rs2):
        assert len(results[rid]) == 5
        assert all(0 <= t < cfg.vocab_size for t in results[rid])

    # sampled default + greedy override
    cb2 = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64,
        sampler=Sampler(temperature=1.0),
        prompt_buckets=(8,),
    )
    p2 = _prompt(603, 5, cfg)
    r_greedy = cb2.submit(p2, max_new=4, sampler=Sampler())
    r_sampled = cb2.submit(_prompt(604, 5, cfg), max_new=4)
    results2 = cb2.run()
    assert results2[r_greedy] == _oracle(params, p2, cfg, 4)
    assert len(results2[r_sampled]) == 4


def test_per_request_sampler_chunked_prefill_first_token(setup):
    """The override must govern the FIRST token too (sampled at prefill
    finish), in both chunked and bucketed admission."""
    cfg, params = setup
    for kwargs in ({"chunked_prefill": 4}, {"prompt_buckets": (16,)}):
        cb = ContinuousBatcher(
            params, cfg, n_slots=2, max_len=64,
            sampler=Sampler(temperature=1.5),  # noisy default
            **kwargs,
        )
        p = _prompt(610, 9, cfg)
        rid = cb.submit(p, max_new=3, sampler=Sampler())  # greedy override
        assert cb.run()[rid] == _oracle(params, p, cfg, 3)


def test_speculative_batcher_rejects_per_request_sampler(setup):
    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    cfg, params = setup
    draft_cfg = LlamaConfig.tiny(n_layers=1)
    draft_params = init_params(jax.random.key(9), draft_cfg)
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=2, max_len=64, gamma=2, chunked_prefill=8,
    )
    assert sb.per_request_sampler is False
    with pytest.raises(ValueError, match="per-request"):
        sb.submit([1, 2, 3], max_new=4, sampler=Sampler(temperature=0.5))
