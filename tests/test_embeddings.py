"""/v1/embeddings (serving/embeddings.py + the OpenAI facade): unit-norm
mean-pooled hidden states, bucket padding invariance, input forms, and
the HTTP envelope."""

import asyncio

import aiohttp
import jax
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.serving.embeddings import Embedder
from k8s_gpu_device_plugin_tpu.serving.server import (
    InferenceEngine,
    InferenceServer,
)
from k8s_gpu_device_plugin_tpu.serving.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def test_embedder_basics(setup):
    cfg, params = setup
    emb = Embedder(params, cfg, buckets=(8, 16))
    ids = [3, 9, 4, 1, 7]
    v = emb.embed(ids)
    assert v.shape == (cfg.d_model,)
    assert np.isclose(np.linalg.norm(v), 1.0, atol=1e-5)  # unit norm

    # bucket-padding invariance: the same ids through a bigger bucket
    # (different padded shape) give the same embedding — padding is
    # masked out of the mean
    v16 = Embedder(params, cfg, buckets=(16,)).embed(ids)
    np.testing.assert_allclose(v, v16, rtol=2e-5, atol=2e-5)

    # deterministic and input-sensitive
    np.testing.assert_array_equal(v, emb.embed(ids))
    assert not np.allclose(v, emb.embed([3, 9, 4, 1, 8]))

    with pytest.raises(ValueError, match="exceeds"):
        emb.embed(list(range(17)))
    with pytest.raises(ValueError, match="empty"):
        emb.embed([])


def test_embeddings_http(setup):
    cfg, params = setup
    tok = ByteTokenizer()

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        server = InferenceServer(
            engine, host="127.0.0.1", port=0, tokenizer=tok,
            embedder=Embedder(params, cfg, buckets=(32,)),
        )
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as s:
                # list of strings
                r = await s.post(f"{base}/v1/embeddings", json={
                    "input": ["hello", "world"],
                })
                assert r.status == 200, await r.text()
                p = await r.json()
                assert p["object"] == "list"
                assert [d["index"] for d in p["data"]] == [0, 1]
                assert len(p["data"][0]["embedding"]) == cfg.d_model
                assert p["usage"]["prompt_tokens"] == 10  # 5 bytes each

                # token-id list and list of lists agree
                r1 = await s.post(f"{base}/v1/embeddings",
                                  json={"input": [5, 6, 7]})
                r2 = await s.post(f"{base}/v1/embeddings",
                                  json={"input": [[5, 6, 7]]})
                e1 = (await r1.json())["data"][0]["embedding"]
                e2 = (await r2.json())["data"][0]["embedding"]
                assert e1 == e2

                # unknown model 404; bad input 400
                r = await s.post(f"{base}/v1/embeddings", json={
                    "model": "nope", "input": "x",
                })
                assert r.status == 404
                r = await s.post(f"{base}/v1/embeddings", json={"input": []})
                assert r.status == 400
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=300))


def test_embeddings_disabled_is_400(setup):
    cfg, params = setup

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        server = InferenceServer(engine, host="127.0.0.1", port=0)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            async with aiohttp.ClientSession() as s:
                r = await s.post(
                    f"http://127.0.0.1:{server.bound_port}/v1/embeddings",
                    json={"input": [1, 2]},
                )
                assert r.status == 400
                assert "not enabled" in (await r.json())["error"]["message"]
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=120))


def test_embedding_input_id_validation(setup):
    """Out-of-range / negative / boolean 'ids' are a 400, never a wrong
    vector from a clamped gather."""
    cfg, params = setup

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=1, max_len=32,
                                 chunked_prefill=8)
        server = InferenceServer(
            engine, host="127.0.0.1", port=0,
            embedder=Embedder(params, cfg, buckets=(32,)),
        )
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as s:
                for bad in ([cfg.vocab_size], [-1], [True, False]):
                    r = await s.post(f"{base}/v1/embeddings",
                                     json={"input": bad})
                    assert r.status == 400, bad
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=120))
