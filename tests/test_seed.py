"""Per-request sampling seed: a seeded request's sampled stream depends
only on its seed and its own prompt — NOT on batch composition,
admission order, or neighbors (stronger than OpenAI's best-effort
``seed``). Draw i uses fold_in(key(seed), i), with i = tokens generated
so far, tracked host-side."""

import asyncio

import aiohttp
import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler
from k8s_gpu_device_plugin_tpu.serving.server import (
    InferenceEngine,
    InferenceServer,
)

SAMPLER = Sampler(temperature=0.9)


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def test_seeded_stream_is_batch_composition_invariant(setup):
    """The same (seed, prompt) produces the SAME sampled tokens when run
    alone, alongside other traffic, and in a different admission order."""
    cfg, params = setup
    prompt = _prompt(1, 5, cfg)
    other = _prompt(2, 7, cfg)

    def run_scenario(build):
        cb = ContinuousBatcher(params, cfg, n_slots=3, max_len=48,
                               chunked_prefill=8, sampler=SAMPLER)
        rid = build(cb)
        return cb.run()[rid]

    alone = run_scenario(lambda cb: cb.submit(prompt, max_new=6, seed=42))

    def with_traffic(cb):
        cb.submit(other, max_new=8, seed=7)
        rid = cb.submit(prompt, max_new=6, seed=42)
        cb.submit(other, max_new=3)  # unseeded neighbor
        return rid

    assert run_scenario(with_traffic) == alone

    # bucketed (non-chunked) prefill path too
    cb = ContinuousBatcher(params, cfg, n_slots=2, max_len=48,
                           prompt_buckets=(8,), sampler=SAMPLER)
    rid = cb.submit(prompt, max_new=6, seed=42)
    assert cb.run()[rid] == alone


def test_distinct_seeds_differ_and_repeat(setup):
    cfg, params = setup
    prompt = _prompt(3, 5, cfg)
    cb = ContinuousBatcher(params, cfg, n_slots=3, max_len=48,
                           chunked_prefill=8, sampler=SAMPLER)
    r1 = cb.submit(prompt, max_new=8, seed=1)
    r2 = cb.submit(prompt, max_new=8, seed=2)
    r3 = cb.submit(prompt, max_new=8, seed=1)
    done = cb.run()
    assert done[r1] == done[r3]  # same seed, same prompt: identical
    assert done[r1] != done[r2]  # different seed: different stream


def test_seed_validation_and_speculative_reject(setup):
    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    cfg, params = setup
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                           chunked_prefill=8)
    with pytest.raises(ValueError, match="seed"):
        cb.submit([1, 2], max_new=2, seed=-5)
    with pytest.raises(ValueError, match="seed"):
        cb.submit([1, 2], max_new=2, seed=2**31)
    sb = SpeculativeBatcher(params, cfg, params, cfg, n_slots=1,
                            max_len=32, chunked_prefill=8)
    with pytest.raises(ValueError, match="seed"):
        sb.submit([1, 2], max_new=2, seed=3)


def test_seed_over_http_both_apis(setup):
    cfg, params = setup
    prompt = _prompt(9, 4, cfg)

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=2, max_len=32,
                                 chunked_prefill=8, sampler=SAMPLER)
        server = InferenceServer(engine, host="127.0.0.1", port=0)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as s:
                async def native(seed):
                    r = await s.post(f"{base}/v1/generate", json={
                        "prompt": prompt, "max_new": 5, "seed": seed,
                        "temperature": 0.9,
                    })
                    assert r.status == 200, await r.text()
                    return (await r.json())["tokens"]

                a = await native(11)
                b = await native(11)
                c = await native(12)
                assert a == b
                assert a != c

                # OpenAI field rides through (usage proves it generated)
                r = await s.post(f"{base}/v1/completions", json={
                    "prompt": prompt, "max_tokens": 5, "seed": 11,
                    "temperature": 0.9,
                })
                assert r.status == 200
                assert (await r.json())["usage"]["completion_tokens"] == 5

                r = await s.post(f"{base}/v1/generate", json={
                    "prompt": prompt, "max_new": 2, "seed": -1,
                })
                assert r.status in (400, 422)
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=300))


def test_n_gt_1_with_seed_gives_distinct_reproducible_choices(setup):
    """n>1 + seed: choices are distinct (per-choice derived seeds) yet
    the whole response reproduces exactly on resubmission."""
    cfg, params = setup
    prompt = _prompt(15, 4, cfg)

    async def body():
        engine = InferenceEngine(params, cfg, n_slots=2, max_len=32,
                                 chunked_prefill=8, sampler=SAMPLER)
        server = InferenceServer(engine, host="127.0.0.1", port=0)
        stop = asyncio.Event()
        task = asyncio.create_task(server.run(stop))
        for _ in range(100):
            if server.bound_port:
                break
            await asyncio.sleep(0.05)
        try:
            base = f"http://127.0.0.1:{server.bound_port}"
            async with aiohttp.ClientSession() as s:
                async def once():
                    r = await s.post(f"{base}/v1/generate", json={
                        "prompt": prompt, "max_new": 6, "n": 2,
                        "seed": 5, "temperature": 0.9,
                    })
                    assert r.status == 200, await r.text()
                    return (await r.json())["completions"]

                first = await once()
                assert first[0] != first[1]   # distinct choices
                assert await once() == first  # whole response reproduces
        finally:
            stop.set()
            await asyncio.wait_for(task, 30)

    asyncio.run(asyncio.wait_for(body(), timeout=300))
