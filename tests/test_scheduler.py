"""SLO-aware request scheduler (serving/scheduler.py) + the batcher's
preempt/resume machinery.

Three layers of claims:

- **FIFO back-compat**: with the fifo Scheduler attached (the server
  default), greedy and seeded token AND logprob streams are
  bit-identical to a scheduler-less batcher across dense/paged x cache
  on/off x pipeline 0/1 — the seam adds accounting, never behavior.
- **Policy semantics**: strict priority classes, EDF within a class,
  token-bucket demotion for over-quota tenants, queue-cap and
  defer-budget overload rejection, and pressure-triggered preemption of
  the longest-running lower-class decode.
- **Preempt/resume exactness**: a preempted request requeues with its
  output folded into its prompt, re-prefills through the normal chunk
  scheduler (prefix cache serving what the original prefill promoted),
  and finishes with a stream bit-identical to an uninterrupted run —
  tokens and logprobs, greedy and seeded, dense and paged.
"""

import asyncio

import jax
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler
from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache
from k8s_gpu_device_plugin_tpu.serving.scheduler import (
    Scheduler,
    SchedulerOverloadError,
    SloScheduler,
    TenantQuota,
    make_scheduler,
    parse_tenant_quotas,
)

BUCKETS = (8, 16, 32)
PS = 16


@pytest.fixture(scope="module")
def setup():
    # the tiny config every serving test module shares (compile reuse)
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, "int32"
    ).tolist()


def _batcher(params, cfg, sched=None, layout="dense", pc=None, depth=1,
             n_slots=2, chunk=8, **kw):
    return ContinuousBatcher(
        params, cfg, n_slots=n_slots, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=chunk, pipeline_depth=depth, prefix_cache=pc,
        scheduler=sched, kv_layout=layout,
        kv_page_size=PS if layout == "paged" else None, **kw,
    )


def _streams(cb, submits):
    """Run a mixed workload and collect {rid: (tokens, logprobs)}.
    ``submits`` is a list of (prompt, max_new, kwargs)."""
    rids = [cb.submit(p, max_new=m, **kw) for p, m, kw in submits]
    cb.run()
    return {
        r: (tuple(cb.done[r]), tuple(cb.done_requests[r].out_logp))
        for r in rids
    }


# --- config surface -------------------------------------------------------


def test_parse_tenant_quotas():
    q = parse_tenant_quotas("gold=100:burst=500:weight=4, bronze=20")
    assert q["gold"] == TenantQuota(rate=100.0, burst=500.0, weight=4.0)
    assert q["bronze"] == TenantQuota(rate=20.0, burst=80.0, weight=1.0)
    assert parse_tenant_quotas("") == {}
    for bad in ("gold", "gold=x", "gold=5:frob=2", "=5", "g=-1",
                "g=1:weight=0"):
        with pytest.raises(ValueError):
            parse_tenant_quotas(bad)


def test_make_scheduler():
    assert make_scheduler("fifo").policy == "fifo"
    slo = make_scheduler("slo", tenant_quota="a=5", max_queue=3)
    assert slo.policy == "slo" and slo.max_queue == 3
    with pytest.raises(ValueError, match="slo"):
        make_scheduler("fifo", tenant_quota="a=5")  # silently unenforced
    with pytest.raises(ValueError, match="policy"):
        make_scheduler("wfq")


def test_validate_sched_rule():
    v = ContinuousBatcher.validate_sched
    assert v(None, None, None) == ("default", 1, None)
    assert v("", 0, 0) == ("default", 0, None)  # 0 deadline = none
    assert v("t", 9, 250) == ("t", 9, 250)
    with pytest.raises(ValueError, match="priority"):
        v("t", 10, None)
    with pytest.raises(ValueError, match="priority"):
        v("t", -1, None)
    with pytest.raises(ValueError, match="deadline"):
        v("t", 1, -5)
    with pytest.raises(ValueError, match="tenant"):
        v("x" * 65, 1, None)


# --- FIFO back-compat: the seam changes nothing -----------------------------


@pytest.mark.parametrize("layout", ["dense", "paged"])
@pytest.mark.parametrize("depth", [0, 1])
@pytest.mark.parametrize("cache_on", [False, True])
def test_fifo_scheduler_streams_bit_identical(setup, layout, depth,
                                              cache_on):
    """The acceptance pin: --schedPolicy fifo (a Scheduler object with
    ledgers) must emit bit-identical greedy+seeded token/logprob streams
    to the scheduler-less batcher, across dense/paged x cache on/off x
    pipeline 0/1."""
    cfg, params = setup
    shared = _prompt(50, 12, cfg)
    s = Sampler(temperature=0.8, top_k=7)
    submits = [
        (shared + _prompt(51, 5, cfg), 6, {}),
        (_prompt(52, 9, cfg), 5, {"seed": 11, "sampler": s}),
        (shared + _prompt(53, 6, cfg), 4, {"seed": 3, "sampler": s}),
        (_prompt(54, 17, cfg), 6, {}),
        (shared + _prompt(55, 4, cfg), 5, {}),
    ]

    def pc():
        return PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20) \
            if cache_on else None

    base = _streams(
        _batcher(params, cfg, sched=None, layout=layout, depth=depth,
                 pc=pc()),
        submits,
    )
    with_sched = _streams(
        _batcher(params, cfg, sched=Scheduler(), layout=layout,
                 depth=depth, pc=pc()),
        submits,
    )
    assert base == with_sched


def test_fifo_scheduler_accounts_without_reordering(setup):
    cfg, params = setup
    sched = Scheduler()
    cb = _batcher(params, cfg, sched=sched)
    r0 = cb.submit(_prompt(60, 9, cfg), max_new=4, tenant="a",
                   deadline_ms=60_000)
    r1 = cb.submit(_prompt(61, 9, cfg), max_new=4, tenant="b", priority=0)
    cb.run()
    assert set(cb.done) == {r0, r1}
    st = sched.sched_stats()
    assert st["policy"] == "fifo"
    assert st["tenants"]["a"]["goodput_tokens"] == 4  # met its deadline
    assert st["tenants"]["b"]["goodput_tokens"] == 4  # no deadline: counts
    assert st["tenants"]["a"]["deadline_misses"] == 0
    assert st["preemptions"] == 0


# --- slo policy ordering ---------------------------------------------------


def _fill_slots(cb, cfg, n=2, max_new=48):
    rids = [
        cb.submit(_prompt(70 + i, 9, cfg), max_new=max_new,
                  tenant="bulk", priority=2)
        for i in range(n)
    ]
    guard = 0
    while cb.pending or cb.prefilling:
        cb.step()
        guard += 1
        assert guard < 500
    return rids


def test_priority_class_orders_admission(setup):
    cfg, params = setup
    cb = _batcher(params, cfg, sched=SloScheduler(preempt=False))
    _fill_slots(cb, cfg)
    lo = cb.submit(_prompt(80, 9, cfg), max_new=3, priority=2)
    hi = cb.submit(_prompt(81, 9, cfg), max_new=3, priority=0)
    cb.run()
    # the high class reached a slot first despite queueing second
    assert cb.done_requests[hi].t_first_tok < cb.done_requests[lo].t_first_tok


def test_edf_within_class(setup):
    cfg, params = setup
    cb = _batcher(params, cfg, sched=SloScheduler(preempt=False))
    _fill_slots(cb, cfg)
    late = cb.submit(_prompt(82, 9, cfg), max_new=3, deadline_ms=500_000)
    soon = cb.submit(_prompt(83, 9, cfg), max_new=3, deadline_ms=90_000)
    none = cb.submit(_prompt(84, 9, cfg), max_new=3)  # no deadline: last
    cb.run()
    t = {r: cb.done_requests[r].t_first_tok for r in (late, soon, none)}
    assert t[soon] < t[late] < t[none]


def test_quota_demotes_behind_inquota_classes(setup):
    cfg, params = setup
    # "hog" has a tiny bucket it immediately exhausts; "meek" has none
    sched = SloScheduler(
        quotas={"hog": TenantQuota(rate=1.0, burst=10.0)}, preempt=False,
    )
    cb = _batcher(params, cfg, sched=sched)
    _fill_slots(cb, cfg)
    hog = cb.submit(_prompt(85, 9, cfg), max_new=3, tenant="hog",
                    priority=0)  # over quota: demoted despite class 0
    meek = cb.submit(_prompt(86, 9, cfg), max_new=3, tenant="meek",
                     priority=2)
    cb.run()
    assert cb.done_requests[meek].t_first_tok \
        < cb.done_requests[hog].t_first_tok
    st = sched.sched_stats()
    assert st["tenants"]["hog"]["quota_level"] < 0  # in debt, not dropped


def test_wfq_interleaves_tenants_fairly(setup):
    cfg, params = setup
    sched = SloScheduler(preempt=False)
    cb = _batcher(params, cfg, sched=sched, n_slots=1)
    # tenant a floods 3 requests before b's lands; same class — WFQ must
    # not serve all of a first (virtual time charges per admitted token)
    a = [cb.submit(_prompt(90 + i, 9, cfg), max_new=3, tenant="a")
         for i in range(3)]
    b = cb.submit(_prompt(95, 9, cfg), max_new=3, tenant="b")
    cb.run()
    tb = cb.done_requests[b].t_first_tok
    later_a = sum(1 for r in a if cb.done_requests[r].t_first_tok > tb)
    assert later_a >= 2, "tenant b should overtake most of a's backlog"


# --- overload valves -------------------------------------------------------


def test_queue_cap_rejects_at_submit(setup):
    cfg, params = setup
    sched = Scheduler(max_queue=2)
    cb = _batcher(params, cfg, sched=sched)
    for i in range(2):
        cb.submit(_prompt(100 + i, 9, cfg), max_new=2)
    with pytest.raises(SchedulerOverloadError) as ei:
        cb.submit(_prompt(105, 9, cfg), max_new=2)
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after >= 1
    cb.run()  # the queued two still complete


def test_defer_budget_rejects_pool_pressured_head(setup):
    cfg, params = setup

    class _Rec:
        finished: list = []

        def on_submit(self): ...
        def on_prefill_chunk(self): ...
        def on_prefill_tokens(self, n, source): ...
        def on_first_token(self): ...
        def on_step(self, *a): ...

        def on_finish(self, reason):
            self.finished.append(reason)

    rec = _Rec()
    rec.finished = []
    sched = SloScheduler(defer_budget_ms=1, preempt=False)
    # THREE slots over a pool that only fits two requests: the third
    # has a free slot but defers on POOL pressure (the defer-budget
    # clock only runs for pool-deferred heads, not slot waits),
    # outlives the 1ms budget, and must be REJECTED
    cb = _batcher(params, cfg, sched=sched, layout="paged", n_slots=3,
                  metrics=rec, kv_pages=7)  # 6 allocatable pages
    busy = [cb.submit(_prompt(110 + i, 9, cfg), max_new=38)
            for i in range(2)]
    starved = cb.submit(_prompt(115, 9, cfg), max_new=38)
    guard = 0
    while starved not in cb.done:
        cb.step()
        guard += 1
        assert guard < 2000, "starved request neither ran nor rejected"
    req = cb.done_requests[starved]
    assert req.reject_reason == "pool_pressure"
    assert cb.done[starved] == []
    assert "rejected" in rec.finished
    assert sched.sched_stats()["rejections"]["defer_budget"] == 1
    cb.run()
    for r in busy:
        assert len(cb.done[r]) == 38  # neighbors unharmed
    cb.pool.check()


def test_cancel_while_queued_frees_pages_and_quota(setup):
    """The PR-6 leak-pinning pattern, scheduler edition: cancelling
    requests still held by the scheduler — across priority classes,
    some holding match-time page pins — returns the pool free-count to
    baseline and refunds the tenants' quota charges."""
    cfg, params = setup
    sched = SloScheduler(
        quotas={"a": TenantQuota(rate=10.0, burst=200.0),
                "b": TenantQuota(rate=10.0, burst=200.0)},
        preempt=False,
    )
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20)
    cb = _batcher(params, cfg, sched=sched, layout="paged", pc=pc,
                  n_slots=2)
    baseline = cb.pool.free_pages
    shared = _prompt(120, 17, cfg)
    # promote the shared prefix so later submits can PIN its pages
    warm = cb.submit(shared + _prompt(121, 4, cfg), max_new=2)
    cb.run()
    assert warm in cb.done and pc.stats.entries >= 1
    after_promo = cb.pool.free_pages
    # saturate the slots so the queued victims never admit
    busy = [cb.submit(_prompt(125 + i, 9, cfg), max_new=30)
            for i in range(2)]
    for _ in range(8):
        cb.step()
    victims = [
        cb.submit(shared + _prompt(130, 6, cfg), max_new=4, tenant="a",
                  priority=0),
        cb.submit(shared + _prompt(131, 6, cfg), max_new=4, tenant="b",
                  priority=2),
        cb.submit(_prompt(132, 9, cfg), max_new=4, tenant="a", priority=1),
    ]
    level_a = sched.sched_stats()["tenants"]["a"]["quota_level"]
    for _ in range(4):
        cb.step()  # let admission passes run their match/pin logic
    for rid in victims:
        assert cb.cancel(rid)
    cb.run()
    for r in busy:
        assert len(cb.done[r]) == 30
    # every pin and reservation returned; promoted entries still alive
    while pc.evict_one():
        pass
    assert cb.pool.free_pages == baseline
    cb.pool.check()
    st = sched.sched_stats()["tenants"]
    # quota charges refunded: each tenant's bucket is back at (or above,
    # via refill) where it stood before its victims were charged
    assert st["a"]["quota_level"] >= level_a
    assert st["b"]["quota_level"] >= 200.0 - 1e-6 or \
        st["b"]["quota_level"] == 200.0
    assert cb.pool.free_pages == baseline or after_promo >= baseline


# --- preemption + resume ---------------------------------------------------


@pytest.mark.parametrize("layout", ["dense", "paged"])
@pytest.mark.parametrize("depth", [0, 1])
def test_preempt_resume_streams_bit_identical(setup, layout, depth):
    """The acceptance pin: a preempted-then-resumed request's final
    token AND logprob stream is bit-identical to an uninterrupted run —
    seeded sampling included (the resumed finish chunk continues the
    seeded draw sequence exactly)."""
    cfg, params = setup
    p_low, p_hi = _prompt(140, 9, cfg), _prompt(141, 9, cfg)
    s = Sampler(temperature=0.9, top_k=5)

    base = _streams(
        _batcher(params, cfg, layout=layout, depth=depth, n_slots=1),
        [(p_low, 20, {"seed": 7, "sampler": s})],
    )
    cb2 = _batcher(params, cfg, layout=layout, depth=depth, n_slots=1)
    hi_base = _streams(cb2, [(p_hi, 6, {})])

    sched = SloScheduler()
    cb = _batcher(params, cfg, sched=sched, layout=layout, depth=depth,
                  n_slots=1)
    low = cb.submit(p_low, max_new=20, seed=7, sampler=s, tenant="bronze",
                    priority=2)
    for _ in range(12):
        cb.step()
    assert cb.running, "low-priority request should be decoding"
    hi = cb.submit(p_hi, max_new=6, tenant="gold", priority=0,
                   deadline_ms=1)
    cb.run()
    req = cb.done_requests[low]
    assert req.preemptions >= 1, "pressure + deadline must preempt"
    assert sched.preemptions == req.preemptions
    bronze = sched._tenants["bronze"]
    # a resume is NOT a second admission: the WFQ virtual time charged
    # exactly once, for the ORIGINAL worst-case work (re-charging the
    # output-inflated resumed prompt would demote preemption victims)
    assert bronze.admitted == 1
    assert bronze.vtime == pytest.approx(len(p_low) + 20)
    assert (tuple(cb.done[hi]),
            tuple(cb.done_requests[hi].out_logp)) == hi_base[next(iter(hi_base))]
    assert (tuple(cb.done[low]),
            tuple(req.out_logp)) == base[next(iter(base))]


def test_preempt_resume_rides_prefix_cache(setup):
    """A resumed request re-matches the prefix cache: the boundaries its
    ORIGINAL prefill promoted serve the resume, so only the uncached
    tail recomputes — and the stream stays bit-identical to the
    cache-off resume."""
    cfg, params = setup
    p_low, p_hi = _prompt(150, 20, cfg), _prompt(151, 9, cfg)

    def run(with_cache: bool):
        pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 20) \
            if with_cache else None
        cb = _batcher(params, cfg, sched=SloScheduler(), pc=pc, n_slots=1)
        low = cb.submit(p_low, max_new=16, seed=5,
                        sampler=Sampler(temperature=0.7),
                        tenant="bronze", priority=2)
        for _ in range(14):
            cb.step()
        cb.submit(p_hi, max_new=4, tenant="gold", priority=0,
                  deadline_ms=1)
        cb.run()
        req = cb.done_requests[low]
        assert req.preemptions >= 1
        return tuple(cb.done[low]), tuple(req.out_logp), req.cached_tokens

    cold = run(False)
    cached = run(True)
    assert cold[:2] == cached[:2]
    # the original prefill promoted boundaries the resume then hit: the
    # resumed admission reports served-from-cache tokens
    assert cached[2] > 0
    assert cold[2] == 0


def test_preemption_requires_support_and_chunking(setup):
    cfg, params = setup
    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    with pytest.raises(ValueError, match="preemption"):
        SpeculativeBatcher(
            params, cfg, params, cfg, n_slots=2, max_len=64,
            chunked_prefill=8, prompt_buckets=BUCKETS,
            scheduler=SloScheduler(),
        )
    # preempt=False composes: ordering/quotas without eviction
    sb = SpeculativeBatcher(
        params, cfg, params, cfg, n_slots=2, max_len=64,
        chunked_prefill=8, prompt_buckets=BUCKETS,
        scheduler=SloScheduler(preempt=False),
    )
    rid = sb.submit(_prompt(160, 9, cfg), max_new=4, tenant="gold",
                    priority=0)
    sb.run()
    assert rid in sb.done
    # a BUCKETED (chunk=0) batcher constructs fine with the slo policy
    # but its plan() never proposes preemption (resume needs the chunk
    # scheduler) — deadlined pressure must not evict anything
    sched = SloScheduler()
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=BUCKETS,
        chunked_prefill=0, scheduler=sched,
    )
    lo = cb.submit(_prompt(161, 9, cfg), max_new=12, priority=2)
    for _ in range(4):
        cb.step()
    hi = cb.submit(_prompt(162, 9, cfg), max_new=4, priority=0,
                   deadline_ms=1)
    cb.run()
    assert sched.preemptions == 0
    assert len(cb.done[lo]) == 12 and len(cb.done[hi]) == 4


# --- engine / health surface -----------------------------------------------


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


def test_engine_submit_defaults_and_health(setup):
    cfg, params = setup
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
        scheduler=SloScheduler(preempt=False), default_deadline_ms=60_000,
    )
    try:
        async def body():
            eid, q = engine.submit(_prompt(170, 9, cfg), 3, tenant="gold",
                                   priority=0)
            toks = []
            while True:
                item = await q.get()
                if item is None:
                    break
                toks.append(item[0])
            return toks

        toks = _run(body())
        assert len(toks) == 3
        stats = engine.stats()
        sched = stats["sched"]
        assert sched["policy"] == "slo"
        gold = sched["tenants"]["gold"]
        assert gold["submitted"] == gold["retired"] == 1
        # the edge default deadline applied and was met: goodput
        assert gold["goodput_tokens"] == 3
        assert gold["deadline_misses"] == 0
    finally:
        engine.shutdown()


def test_engine_queue_cap_raises_on_request_thread(setup):
    cfg, params = setup
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    engine = InferenceEngine(
        params, cfg, n_slots=1, max_len=64, chunked_prefill=8,
        scheduler=Scheduler(max_queue=1),
    )
    try:
        async def body():
            subs = []
            raised = None
            for i in range(6):
                try:
                    subs.append(engine.submit(_prompt(180 + i, 9, cfg), 2))
                except SchedulerOverloadError as e:
                    raised = e
            assert raised is not None, "queue cap never fired"
            assert raised.reason == "queue_full"
            for _, q in subs:
                while await q.get() is not None:
                    pass

        _run(body())
    finally:
        engine.shutdown()


def test_openloop_trace_clamps_shared_prefix(setup):
    """A sys_len >= prompt_len must clamp, not grow gold prompts past
    the caller's capacity budget (every prompt is exactly prompt_len)."""
    cfg, _ = setup
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.serve_bench import (
        openloop_trace,
    )

    trace = openloop_trace(
        cfg, seed=1, base_s=0.5, overload_s=0.5, base_rps=40.0,
        prompt_len=8, sys_len=48, max_new=4, gold_deadline_ms=100,
    )
    assert trace, "empty trace"
    assert {len(e["prompt"]) for e in trace} == {8}
    assert {e["tenant"] for e in trace} == {"gold", "bronze"}


def test_open_loop_run_retries_429_with_capped_backoff(setup):
    """The open-loop harness client honors Retry-After on a queue-full
    429 with a capped retry instead of a terminal drop: a burst over
    the queue cap reports ``retried_ok`` for requests a retry got in,
    and ``rejected`` only for retry-exhausted ones. ``retries=0``
    restores the old drop-on-first-429 accounting."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.serve_bench import (
        open_loop_run,
    )

    cfg, params = setup

    def burst_trace(n):
        return [
            {"t": 0.0, "tenant": "t", "priority": 1, "deadline_ms": None,
             "prompt": _prompt(700 + i, 9, cfg), "max_new": 2,
             "phase": "base"}
            for i in range(n)
        ]

    cb = _batcher(params, cfg, sched=Scheduler(max_queue=1), n_slots=1)
    out = open_loop_run(cb, burst_trace(4), retries=3,
                        max_retry_wait_s=0.1)
    assert out["retried_ok"] >= 1, out
    assert out["submitted"] + out["rejected"] == out["offered"] == 4
    assert out["submitted"] >= 1 + out["retried_ok"]

    # retries=0: every queue-full contact is a terminal drop (and the
    # field is still reported, as 0)
    cb0 = _batcher(params, cfg, sched=Scheduler(max_queue=1), n_slots=1)
    out0 = open_loop_run(cb0, burst_trace(4), retries=0)
    assert out0["retried_ok"] == 0
    assert out0["rejected"] >= 1
    assert out0["submitted"] + out0["rejected"] == 4
    # the scheduler's own ledger counts the terminal drops
    assert (out0["sched_stats"]["rejections"]["queue_full"]
            == out0["rejected"])


def test_returning_idle_tenant_refloors_vtime(setup):
    """A tenant that went idle while a peer kept admitting rejoins at
    the system virtual time instead of replaying banked credit (which
    would let it monopolize admission)."""
    cfg, params = setup
    sched = SloScheduler(preempt=False)
    cb = _batcher(params, cfg, sched=sched, n_slots=1)
    for i in range(3):
        cb.submit(_prompt(200 + i, 9, cfg), max_new=2, tenant="busy")
    cb.run()
    busy_vt = sched._tenants["busy"].vtime
    assert busy_vt > 0
    # "idler" was created long ago (vtime 0) and went idle
    sched._tenants["idler"] = type(sched._tenants["busy"])(
        TenantQuota(), 0.0
    )
    assert sched._tenants["idler"].vtime == 0.0
    # busy keeps live work; idler returns — it must rejoin at busy's
    # virtual time, not at its banked 0
    r = cb.submit(_prompt(210, 9, cfg), max_new=2, tenant="busy")
    cb.submit(_prompt(211, 9, cfg), max_new=2, tenant="idler")
    assert sched._tenants["idler"].vtime >= busy_vt
    cb.run()
    assert r in cb.done


def test_sched_bench_machinery():
    """The make bench-sched smoke is importable and its determinism
    checks hold (plan cost + forced preemption + queue-cap rejection).
    The full main() open-loop smoke runs in CI via make bench-sched."""
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.sched_bench import (
        determinism_checks,
        plan_cost_bench,
    )

    out = plan_cost_bench(depth=32, passes=5)
    assert out["plan_us"] > 0
    checks = determinism_checks()
    assert checks["forced_preemptions"] >= 1
    assert checks["queue_cap_rejected"] >= 1
