"""Seeded fault-injection plane (serving/faults.py).

The plane's whole value is determinism and refusal-to-lie: schedules
replay identically run to run, a typo'd point name refuses instead of
silently disarming, and a disarmed plane is literally absent (None at
every seam)."""

import time

import pytest

from k8s_gpu_device_plugin_tpu.serving.faults import (
    KNOWN_POINTS,
    FaultError,
    FaultPlane,
    FaultPoint,
)


def test_empty_spec_is_no_plane():
    assert FaultPlane.from_spec("") is None
    assert FaultPlane.from_spec("   ") is None
    assert FaultPlane.from_spec(None) is None


def test_nth_fires_once_on_the_nth_hit():
    pt = FaultPoint("decode.apply", nth=3)
    pt.fire()
    pt.fire()
    with pytest.raises(FaultError) as ei:
        pt.fire()
    assert ei.value.point == "decode.apply"
    # times defaults to 1 for nth: later hits pass clean
    for _ in range(5):
        pt.fire()
    assert pt.stats() == {
        "hits": 8, "fired": 1, "schedule": {"nth": 3}, "times": 1,
        "delay_ms": 0.0,
    }


def test_nth_with_times_keeps_firing_up_to_the_cap():
    pt = FaultPoint("decode.apply", nth=2, times=3)
    pt.fire()
    fired = 0
    for _ in range(6):
        try:
            pt.fire()
        except FaultError:
            fired += 1
    assert fired == 3  # the cap, not every hit past nth


def test_probability_schedule_is_seed_deterministic():
    def sequence(seed):
        pt = FaultPoint("pool.alloc", p=0.5, seed=seed)
        out = []
        for _ in range(64):
            try:
                pt.fire()
                out.append(0)
            except FaultError:
                out.append(1)
        return out

    a, b = sequence(7), sequence(7)
    assert a == b  # identical replay under one seed
    assert sum(a) > 0  # ...and it actually fires
    assert sequence(8) != a  # a different seed deals a different hand
    # two points under ONE seed draw independent sequences (the name
    # folds into the rng seed)
    pt2 = FaultPoint("decode.apply", p=0.5, seed=7)
    seq2 = []
    for _ in range(64):
        try:
            pt2.fire()
            seq2.append(0)
        except FaultError:
            seq2.append(1)
    assert seq2 != a


def test_delay_mode_sleeps_instead_of_raising():
    pt = FaultPoint("router.connect", nth=1, delay_ms=30.0)
    t0 = time.perf_counter()
    pt.fire()  # no raise
    assert time.perf_counter() - t0 >= 0.025
    assert pt.stats()["fired"] == 1


def test_spec_parsing_and_plane_resolution():
    plane = FaultPlane.from_spec(
        "decode.apply:nth=40,pool.alloc:p=0.25:seed=3:times=6"
    )
    d = plane.point("decode.apply")
    assert d is not None and d.nth == 40 and d.times == 1
    p = plane.point("pool.alloc")
    assert p is not None and p.p == 0.25 and p.times == 6
    # disarmed points resolve to None — the is-not-None hot-path guard
    assert plane.point("router.connect") is None
    # a bare name defaults to nth=1 (fire on first hit)
    bare = FaultPlane.from_spec("health.handler").point("health.handler")
    assert bare.nth == 1
    # plane stats name every armed point
    assert set(plane.stats()) == {"decode.apply", "pool.alloc"}


def test_typos_refuse_instead_of_silently_disarming():
    with pytest.raises(ValueError, match="unknown fault point"):
        FaultPlane.from_spec("decode.appply:nth=1")
    plane = FaultPlane.from_spec("decode.apply:nth=1")
    with pytest.raises(ValueError, match="unknown fault point"):
        plane.point("decode.appply")
    with pytest.raises(ValueError, match="key=value"):
        FaultPlane.from_spec("decode.apply:nth")
    with pytest.raises(ValueError, match="known keys"):
        FaultPlane.from_spec("decode.apply:bogus=1")
    with pytest.raises(ValueError, match="armed twice"):
        FaultPlane.from_spec("decode.apply:nth=1,decode.apply:nth=2")
    with pytest.raises(ValueError, match="exactly one schedule"):
        FaultPoint("decode.apply", nth=1, p=0.5)
    with pytest.raises(ValueError, match="exactly one schedule"):
        FaultPoint("decode.apply")
    for name in KNOWN_POINTS:  # every documented point constructs
        FaultPoint(name, nth=1)


def test_error_handle_rides_the_plane():
    # the duck-typed exception handle models/batching.py catches
    # injected pool faults through (no serving import on that side)
    assert FaultPlane.error is FaultError
    assert FaultPlane.from_spec("pool.alloc:nth=1").error is FaultError
