"""Per-request latency attribution, flight recorder, MFU accounting.

Pins the tentpole contracts of the observability layer:

- a retired request's phase breakdown SUMS to its measured wall time
  (exact by the cursor construction, within float rounding) across
  dense/paged x prefix cache on/off x pipeline 0/1 x speculative;
- token/logprob streams are bit-identical with the layer on or off
  (it never touches device state — the house pin);
- the flight recorder retains step-level detail exactly for threshold
  breachers / deadline misses / p99-of-window outliers;
- /metrics parses as valid Prometheus AND OpenMetrics text with
  trace-id exemplars on the TTFT/inter-token/phase buckets, and the
  kv_shard_*/spec_*/tenant-labeled series survive both parsers with
  gnarly (printable) label values;
- the roofline cost model prices prefill/decode per the config math
  against device/topology.py spec peaks, tp-aware;
- the serving HTTP surface exports timelines (opt-in done field,
  /debug/requests{,/rid}, /debug/slow) and /v1/health carries the live
  MFU view.
"""

import asyncio
import json
import time

import aiohttp
import jax
import jax.numpy as jnp
import pytest

from k8s_gpu_device_plugin_tpu.metrics.roofline import (
    MfuAccumulator,
    ServingCostModel,
)
from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler
from k8s_gpu_device_plugin_tpu.models.spec_batching import SpeculativeBatcher
from k8s_gpu_device_plugin_tpu.obs.attribution import (
    RequestAttributor,
    RequestTimeline,
)
from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache

BUCKETS = (8, 16, 32)
PS = 16


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    draft_cfg = LlamaConfig.tiny(n_layers=1, d_model=64, n_heads=4,
                                 n_kv_heads=2, d_ff=128, dtype=jnp.float32)
    draft_params = init_params(jax.random.key(1), draft_cfg)
    return cfg, params, draft_cfg, draft_params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _batcher(setup, layout, cache, depth, spec, attribution=None, mfu=None):
    cfg, params, draft_cfg, draft_params = setup
    pc = PrefixCache(cfg, buckets=BUCKETS, budget_bytes=1 << 22) \
        if cache else None
    kw = dict(
        n_slots=2, max_len=64, chunked_prefill=8, prompt_buckets=BUCKETS,
        pipeline_depth=depth, prefix_cache=pc,
        kv_layout=layout, kv_page_size=PS if layout == "paged" else None,
        attribution=attribution, mfu=mfu,
    )
    if spec:
        return SpeculativeBatcher(
            params, cfg, draft_params, draft_cfg, gamma=3, **kw
        )
    return ContinuousBatcher(params, cfg, **kw)


MATRIX = [
    ("dense", False, 0, False),
    ("dense", False, 1, False),
    ("dense", True, 1, False),
    ("paged", False, 1, False),
    ("paged", True, 0, False),
    ("paged", True, 1, False),
    ("dense", False, 1, True),
    ("paged", True, 1, True),
]


@pytest.mark.parametrize("layout,cache,depth,spec", MATRIX)
def test_phase_breakdown_sums_to_wall_time(setup, layout, cache, depth, spec):
    """The acceptance pin: every retired request's segments (and the
    aggregated phases) sum to its measured submit->done wall time,
    across the whole serving feature matrix."""
    cfg = setup[0]
    att = RequestAttributor()
    cb = _batcher(setup, layout, cache, depth, spec, attribution=att)
    rids = [
        cb.submit(_prompt(7, 12, cfg), max_new=5),
        # the speculative engine shares one sampler/key stream: no
        # per-request seed on that arm
        cb.submit(_prompt(8, 20, cfg), max_new=4,
                  seed=None if spec else 11, tenant="gold", priority=0),
        cb.submit(_prompt(7, 12, cfg), max_new=3),  # same prompt: cache hit
    ]
    cb.run()
    stats = att.request_stats()
    assert stats["retired"] == len(rids)
    for rec in stats["requests"]:
        seg_sum = sum(d for _, _, d in rec["segments"])
        phase_sum = sum(rec["phases"].values())
        assert seg_sum == pytest.approx(rec["total_s"], abs=5e-5)
        assert phase_sum == pytest.approx(rec["total_s"], abs=5e-5)
        # contiguity: each segment starts where the previous ended
        cursor = 0.0
        for _name, start, dur in rec["segments"]:
            assert start == pytest.approx(cursor, abs=5e-5)
            cursor = start + dur
        # TTFT is the queue_wait + prefill share (no preemptions here)
        assert rec["ttft_s"] == pytest.approx(
            rec["phases"]["queue_wait"] + rec["phases"]["prefill"],
            abs=5e-5,
        )
        assert rec["phases"]["decode"] >= 0.0
        assert rec["detail"]["itl"]["count"] == max(0, rec["tokens"] - 1)
        if spec:
            assert rec["spec_rounds"] >= 1
    # the cache-on arm's repeat prompt reused its prefix
    if cache and not spec:
        by_rid = {r["rid"]: r for r in stats["requests"]}
        assert by_rid[rids[2]]["cached_tokens"] >= 0  # effective-reuse capped


@pytest.mark.parametrize("layout,depth", [("dense", 1), ("paged", 0)])
def test_streams_bit_identical_attribution_on_off(setup, layout, depth):
    """The house pin: attribution attached or absent, greedy AND seeded
    token/logprob streams are bit-identical (the layer never touches
    device state)."""
    cfg = setup[0]

    def run(att):
        cb = _batcher(setup, layout, False, depth, False, attribution=att)
        cb.submit(_prompt(21, 12, cfg), max_new=6)
        cb.submit(_prompt(22, 9, cfg), max_new=5, seed=7,
                  sampler=Sampler(temperature=0.8, top_k=8))
        cb.run()
        return {
            rid: (tuple(r.out), tuple(r.out_logp))
            for rid, r in cb.done_requests.items()
        }

    assert run(None) == run(RequestAttributor())


# --- flight recorder ------------------------------------------------------


class _FakeReq:
    def __init__(self, rid, t_submit, tenant="default"):
        self.rid = rid
        self.tenant = tenant
        self.priority = 1
        self.t_submit = t_submit
        self.t_first_tok = t_submit
        self.out = [1, 2]
        self.prompt = [3] * 4
        self.cached_tokens = 0
        self.prefill_computed = 4
        self.prefilled_out = 0
        self.preemptions = 0
        self.deadline = None
        self.timeline = None


def _retire(att, rid, total_s, missed=False):
    t0 = time.perf_counter()
    req = _FakeReq(rid, t0)
    req.timeline = att.start(req)
    req.timeline.advance("prefill", t0)
    req.timeline.advance("decode", t0)
    att.on_retired(req, "budget", t0 + total_s, deadline_missed=missed)
    return req


def test_flight_recorder_threshold_and_deadline():
    att = RequestAttributor(slow_ms=5.0, window_min=10_000)  # p99 off
    _retire(att, 0, 0.001)           # fast: summary only
    _retire(att, 1, 0.050)           # breaches 5ms: full detail
    _retire(att, 2, 0.001, missed=True)  # deadline miss: always kept
    slow = att.slow_stats()
    assert slow["captured"] == 2
    kept = {r["rid"] for r in slow["requests"]}
    assert kept == {1, 2}
    for r in slow["requests"]:
        assert r["slow"] is True and "steps" in r
    # the fast request still has a summary (no step detail)
    rec = att.get(0)
    assert rec is not None and "steps" not in rec
    # get() prefers the slow-ring record (with detail)
    assert "steps" in att.get(1)


def test_flight_recorder_p99_auto_trigger():
    att = RequestAttributor(slow_ms=0.0, window=64, window_min=8)
    for i in range(10):
        _retire(att, i, 0.001)
    assert att.slow_stats()["captured"] == 0 or \
        att.slow_stats()["captured"] <= 2  # equal-latency ties may capture
    _retire(att, 99, 0.500)  # 500x the window p99: must be captured
    assert any(r["rid"] == 99 for r in att.slow_stats()["requests"])


def test_recent_ring_is_bounded():
    att = RequestAttributor(recent=4, slow_ms=10_000.0, window_min=10_000)
    for i in range(10):
        _retire(att, i, 0.001)
    stats = att.request_stats()
    assert stats["retired"] == 10
    assert [r["rid"] for r in stats["requests"]] == [9, 8, 7, 6]


def test_timeline_cursor_exactness_across_preemption_shape():
    """Synthetic preempt/resume cycle: queue->prefill->decode->queue->
    prefill->decode still sums exactly."""
    att = RequestAttributor()
    t0 = 100.0
    req = _FakeReq(0, t0)
    tl = att.start(req)
    req.timeline = tl
    tl.advance("prefill", t0 + 1)     # admitted
    tl.advance("decode", t0 + 3)      # first token
    tl.advance("queue_wait", t0 + 4)  # preempted
    tl.advance("prefill", t0 + 6)     # re-admitted
    tl.advance("decode", t0 + 7)      # resumed first token
    rec = att.on_retired(req, "budget", t0 + 9)
    assert rec["phases"] == {
        "queue_wait": pytest.approx(3.0),
        "prefill": pytest.approx(3.0),
        "decode": pytest.approx(3.0),
    }
    assert sum(d for _, _, d in rec["segments"]) == pytest.approx(9.0)


# --- metrics: exemplars, exposition, escaping -----------------------------


def _populated_metrics():
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    reg = CollectorRegistry()
    m = ServingMetrics(registry=reg)
    m.observe_ttft(0.05, "ab" * 16)
    m.observe_ttft(0.2)  # no exemplar
    m.observe_inter_token(0.004, "cd" * 16)
    m.observe_phase("queue_wait", 0.001, "ab" * 16)
    m.observe_phase("decode", 0.01, None)
    m.set_kv_shards([
        {"shard": 0, "reserved_bytes": 1024, "pages_in_use": 3,
         "in_use_bytes": 512},
        {"shard": 1, "reserved_bytes": 1024, "pages_in_use": 3,
         "in_use_bytes": 512},
    ])
    m.on_spec_round(4, [2, 3])
    # printable-but-gnarly tenant: quotes and backslashes must escape
    # identically in both expositions (the satellite's parse pin)
    tenant = 'we"ird\\tenant'
    m.on_goodput(tenant, "0", 7)
    m.on_deadline_miss(tenant, 0.5)
    m.on_tenant_flops(tenant, 1e9)
    m.set_mfu(12.5, 40.0)
    m.on_model_work(1e9, 2e9)
    return reg, m


def test_metrics_exposition_parses_classic_and_openmetrics():
    from prometheus_client import generate_latest
    from prometheus_client.openmetrics.exposition import (
        generate_latest as om_latest,
    )
    from prometheus_client.openmetrics.parser import (
        text_string_to_metric_families as om_parse,
    )
    from prometheus_client.parser import text_string_to_metric_families

    reg, m = _populated_metrics()
    try:
        classic = generate_latest(reg).decode()
        fams = {f.name: f for f in text_string_to_metric_families(classic)}
        # the kv_shard gauges and spec counters render + parse with
        # consistent label escaping
        assert "tpu_serving_kv_shard_reserved_bytes" in fams
        assert "tpu_serving_spec_rounds" in fams
        good = fams["tpu_serving_sched_goodput_tokens"]
        assert any(
            s.labels.get("tenant") == 'we"ird\\tenant' for s in good.samples
        )

        om = om_latest(reg).decode()
        assert om.endswith("# EOF\n")
        om_fams = {f.name: f for f in om_parse(om)}
        good_om = om_fams["tpu_serving_sched_goodput_tokens"]
        assert any(
            s.labels.get("tenant") == 'we"ird\\tenant'
            for s in good_om.samples
        )
        # exemplars present on the TTFT/ITL/phase buckets
        def exemplars(name):
            return [
                s.exemplar for s in om_fams[name].samples
                if s.name.endswith("_bucket") and s.exemplar
            ]

        assert any(
            e.labels == {"trace_id": "ab" * 16}
            for e in exemplars("tpu_serving_ttft_seconds")
        )
        assert exemplars("tpu_serving_inter_token_seconds")
        assert exemplars("tpu_serving_request_phase_seconds")
    finally:
        m.close()


def test_tenant_label_rejects_control_characters():
    """The one admission rule keeps control characters out of metric
    labels and JSON logs (escaping-consistency satellite)."""
    with pytest.raises(ValueError):
        ContinuousBatcher.validate_sched("a\nb", 1, None)
    with pytest.raises(ValueError):
        ContinuousBatcher.validate_sched("a\tb", 1, None)
    tenant, _, _ = ContinuousBatcher.validate_sched('we"ird\\tenant', 1, None)
    assert tenant == 'we"ird\\tenant'


# --- roofline cost model --------------------------------------------------


def test_cost_model_prices_from_config_math():
    cfg = LlamaConfig.tiny()
    model = ServingCostModel.for_config(cfg, generation="v5e")
    # inference forward = one third of the 6N (fwd+bwd) training figure
    assert model.flops_per_token == pytest.approx(cfg.flops_per_token() / 3)
    # weight stream = matmul params x dtype width (bf16 = 2 bytes)
    assert model.weight_bytes == int(model.flops_per_token / 2) * 2
    assert model.prefill_flops(100) == pytest.approx(
        100 * model.flops_per_token
    )
    # the step's byte roofline: weights once + live KV read + write rows
    b = model.decode_step_bytes(active=2, live_tokens=50)
    assert b == model.weight_bytes + 52 * model.kv_token_bytes
    # utilization algebra: peak for one second == 100%
    assert model.mfu_pct(model.peak_tflops * 1e12, 1.0) == pytest.approx(100.0)
    assert model.hbm_bw_util_pct(model.hbm_gbps * 1e9, 1.0) == \
        pytest.approx(100.0)


def test_cost_model_is_tp_aware():
    cfg = LlamaConfig.tiny()
    m1 = ServingCostModel.for_config(cfg, generation="v5e", tp=1)
    m2 = ServingCostModel.for_config(cfg, generation="v5e", tp=2)
    # the same achieved FLOP/s is half the utilization on twice the chips
    assert m2.mfu_pct(1e12, 1.0) == pytest.approx(m1.mfu_pct(1e12, 1.0) / 2)


def test_mfu_accumulator_totals_and_tenants():
    cfg = LlamaConfig.tiny()
    model = ServingCostModel.for_config(cfg, generation="v5e")
    acc = MfuAccumulator(model)
    acc.on_prefill_tokens(10)
    acc.on_step(emitted=2, active=2, live_tokens=20)
    flops, nbytes = acc.totals()
    assert flops == pytest.approx(12 * model.flops_per_token)
    assert nbytes == pytest.approx(model.decode_step_bytes(2, 20))
    req = _FakeReq(0, 0.0, tenant="gold")
    acc.on_retired(req, goodput_tokens=2)
    stats = acc.mfu_stats()
    assert stats["generation"] == "v5e"
    assert stats["tenants"]["gold"]["goodput_tokens"] == 2
    assert stats["tenants"]["gold"]["model_tflops"] > 0
    acc.on_idle()
    assert acc.mfu_stats()["serving_mfu_pct"] == 0.0


def test_mfu_window_closes_and_pushes_gauges():
    class _Rec:
        def __init__(self):
            self.mfu = None
            self.work = []

        def set_mfu(self, mfu_pct, bw_pct):
            self.mfu = (mfu_pct, bw_pct)

        def on_model_work(self, flops, nbytes):
            self.work.append((flops, nbytes))

    cfg = LlamaConfig.tiny()
    rec = _Rec()
    acc = MfuAccumulator(
        ServingCostModel.for_config(cfg, generation="v5e"),
        metrics=rec, window_s=0.0,  # every step closes a window
    )
    acc.on_step(emitted=1, active=1, live_tokens=10)
    assert rec.mfu is not None and rec.mfu[1] > 0
    assert rec.work


# --- serving HTTP surface -------------------------------------------------


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, timeout=300))


async def _with_server(setup, body, attribution="on", registry=None,
                       metrics=None):
    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        InferenceServer,
    )

    cfg, params = setup[0], setup[1]
    att = mfu = None
    if attribution == "on":
        att = RequestAttributor(window_min=4, metrics=metrics)
        mfu = MfuAccumulator(
            ServingCostModel.for_config(cfg, generation="v5e"),
            metrics=metrics,
        )
    engine = InferenceEngine(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=8,
        attribution=att, mfu=mfu, metrics=metrics,
    )
    server = InferenceServer(engine, host="127.0.0.1", port=0,
                             registry=registry)
    stop = asyncio.Event()
    task = asyncio.create_task(server.run(stop))
    for _ in range(100):
        if server.bound_port:
            break
        await asyncio.sleep(0.05)
    try:
        base = f"http://127.0.0.1:{server.bound_port}"
        async with aiohttp.ClientSession() as session:
            await body(session, base)
    finally:
        stop.set()
        await asyncio.wait_for(task, 30)


def test_generate_timeline_opt_in_and_debug_endpoints(setup):
    cfg = setup[0]
    prompt = _prompt(31, 10, cfg)

    async def body(session, base):
        # without the opt-in field: no timeline key
        async with session.post(f"{base}/v1/generate", json={
            "prompt": prompt, "max_new": 4,
        }) as resp:
            assert resp.status == 200
            plain = await resp.json()
            assert "timeline" not in plain
        async with session.post(f"{base}/v1/generate", json={
            "prompt": prompt, "max_new": 4, "timeline": True,
        }) as resp:
            assert resp.status == 200
            payload = await resp.json()
        tl = payload["timeline"]
        assert tl["tokens"] == 4
        assert set(tl["phases"]) == {"queue_wait", "prefill", "decode"}
        assert sum(tl["phases"].values()) == pytest.approx(
            tl["total_s"], abs=5e-5
        )
        rid = tl["rid"]
        # /debug/requests lists it; /debug/requests/{rid} serves it
        async with session.get(f"{base}/debug/requests") as resp:
            assert resp.status == 200
            listing = await resp.json()
        assert listing["retired"] >= 2
        assert any(r["rid"] == rid for r in listing["requests"])
        async with session.get(f"{base}/debug/requests/{rid}") as resp:
            assert resp.status == 200
            one = await resp.json()
        assert one["rid"] == rid
        async with session.get(f"{base}/debug/requests/notanint") as resp:
            assert resp.status == 400
        async with session.get(f"{base}/debug/requests/999999") as resp:
            assert resp.status == 404
        # the flight recorder answers (capture depends on the window)
        async with session.get(f"{base}/debug/slow") as resp:
            assert resp.status == 200
            slow = await resp.json()
        assert "requests" in slow and "captured" in slow
        # /v1/health carries the live MFU view + attribution counts
        async with session.get(f"{base}/v1/health") as resp:
            health = await resp.json()
        assert health["mfu"]["generation"] == "v5e"
        assert health["attribution"]["retired"] >= 2

    run(_with_server(setup, body))


def test_sse_done_event_carries_timeline(setup):
    cfg = setup[0]
    prompt = _prompt(33, 8, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": prompt, "max_new": 3, "stream": True,
            "timeline": True,
        }) as resp:
            assert resp.status == 200
            raw = (await resp.read()).decode()
        events = [
            json.loads(line[len("data: "):])
            for line in raw.splitlines() if line.startswith("data: ")
        ]
        done = events[-1]
        assert done["done"] is True
        assert done["timeline"]["tokens"] == 3

    run(_with_server(setup, body))


def test_openai_envelope_timeline_opt_in(setup):
    cfg = setup[0]
    prompt = _prompt(35, 9, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 3, "timeline": True,
        }) as resp:
            assert resp.status == 200
            payload = await resp.json()
        assert payload["timeline"]["tokens"] == 3
        async with session.post(f"{base}/v1/completions", json={
            "prompt": prompt, "max_tokens": 3,
        }) as resp:
            assert "timeline" not in await resp.json()

    run(_with_server(setup, body))


def test_debug_endpoints_404_when_attribution_off(setup):
    async def body(session, base):
        for path in ("/debug/requests", "/debug/requests/0", "/debug/slow"):
            async with session.get(f"{base}{path}") as resp:
                assert resp.status == 404

    run(_with_server(setup, body, attribution="off"))


def test_metrics_endpoint_negotiates_openmetrics_with_exemplars(setup):
    from prometheus_client import CollectorRegistry
    from prometheus_client.openmetrics.parser import (
        text_string_to_metric_families as om_parse,
    )
    from prometheus_client.parser import text_string_to_metric_families

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg = setup[0]
    reg = CollectorRegistry()
    metrics = ServingMetrics(registry=reg)
    prompt = _prompt(37, 10, cfg)

    async def body(session, base):
        async with session.post(f"{base}/v1/generate", json={
            "prompt": prompt, "max_new": 4,
        }) as resp:
            assert resp.status == 200
        # classic (no Accept): stays text/plain and parses
        async with session.get(f"{base}/metrics") as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            classic = await resp.text()
        assert list(text_string_to_metric_families(classic))
        # openmetrics: negotiated, parses, exemplars on the TTFT bucket
        async with session.get(f"{base}/metrics", headers={
            "Accept": "application/openmetrics-text; version=1.0.0",
        }) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )
            om = await resp.text()
        fams = {f.name: f for f in om_parse(om)}
        ttft = fams["tpu_serving_ttft_seconds"]
        ex = [
            s.exemplar for s in ttft.samples
            if s.name.endswith("_bucket") and s.exemplar
        ]
        assert ex and "trace_id" in ex[0].labels
        phase = fams["tpu_serving_request_phase_seconds"]
        assert any(
            s.exemplar for s in phase.samples if s.name.endswith("_bucket")
        )

    try:
        run(_with_server(setup, body, registry=reg, metrics=metrics))
    finally:
        metrics.close()


def test_engine_refuses_attribution_with_injected_batcher(setup):
    from k8s_gpu_device_plugin_tpu.serving.server import InferenceEngine

    cfg, params = setup[0], setup[1]
    cb = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                           chunked_prefill=8)
    with pytest.raises(ValueError, match="attribution"):
        InferenceEngine(params, cfg, batcher=cb,
                        attribution=RequestAttributor())
    cb2 = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                            chunked_prefill=8)
    with pytest.raises(ValueError, match="attribution"):
        InferenceEngine(
            params, cfg, batcher=cb2,
            mfu=MfuAccumulator(
                ServingCostModel.for_config(cfg, generation="v5e")
            ),
        )


# --- serve_bench integration ---------------------------------------------


@pytest.mark.slow
def test_serve_bench_reports_mfu_and_slow_timeline(setup):
    from k8s_gpu_device_plugin_tpu.benchmark.workloads.serve_bench import (
        serve_bench,
    )

    cfg, params = setup[0], setup[1]
    r = serve_bench(
        cfg, n_slots=2, n_requests=4, max_len=64,
        prompt_lens=(8, 12), max_new=4, params=params,
        prompt_buckets=BUCKETS, chunked_prefill=8,
        paged_ab=False, prefix_ab=False, spec_ab=False,
        sched_base_s=0.5, sched_overload_s=0.5,
    )
    assert r.serving_mfu_pct > 0.0
    assert r.hbm_bw_util_pct > 0.0
    assert r.goodput_tokens_per_tflop > 0.0
    assert r.mfu_generation
    # the open-loop A/B captured at least one slow-request timeline
    assert r.slow_timeline is not None
    assert "steps" in r.slow_timeline


def test_timeline_slots_bound_step_detail():
    tl = RequestTimeline(0, "rid:0", "default", 1, 0.0)
    for i in range(5000):
        tl.add_itl(float(i), 0.001)
    from k8s_gpu_device_plugin_tpu.obs.attribution import MAX_STEP_DETAIL

    assert len(tl.steps) == MAX_STEP_DETAIL
    assert tl.itl_count == 5000
