"""Sliding-window (Mistral-style) attention: reference, flash, decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, forward, init_params
from k8s_gpu_device_plugin_tpu.ops.attention import mha_reference
from k8s_gpu_device_plugin_tpu.ops.flash_attention import flash_attention


def make_qkv(key, b=1, s=512, hq=4, hkv=2, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, hq, d), dtype),
        jax.random.normal(kk, (b, s, hkv, d), dtype),
        jax.random.normal(kv, (b, s, hkv, d), dtype),
    )


def test_reference_window_masks_correctly():
    """Row i of the window-w output must equal full attention computed over
    only keys (i-w, i]."""
    q, k, v = make_qkv(jax.random.key(0), s=64, hq=2, hkv=2, d=16)
    w = 16
    out = mha_reference(q, k, v, causal=True, window=w)
    for i in (0, 15, 16, 40, 63):
        lo = max(0, i - w + 1)
        ref_row = mha_reference(
            q[:, i:i + 1], k[:, lo:i + 1], v[:, lo:i + 1], causal=False
        )
        np.testing.assert_allclose(
            np.asarray(out[:, i]), np.asarray(ref_row[:, 0]), atol=1e-5,
            err_msg=f"row {i}",
        )


def test_reference_window_requires_causal():
    q, k, v = make_qkv(jax.random.key(1), s=64, hq=2, hkv=2, d=16)
    with pytest.raises(ValueError, match="causal"):
        mha_reference(q, k, v, causal=False, window=8)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=8, interpret=True)


@pytest.mark.parametrize("window", [128, 200, 512])
def test_flash_window_matches_reference(window):
    """Multiblock shapes (s=512, 128-blocks) so whole kv blocks fall
    outside the window and the block-skip predicates engage; values AND
    grads vs the masked reference."""
    q, k, v = make_qkv(jax.random.key(2))
    expected = mha_reference(q, k, v, causal=True, window=window)
    got = flash_attention(
        q, k, v, causal=True, window=window,
        block_q=128, block_k=128, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, window=window,
                block_q=128, block_k=128,
                block_q_bwd=128, block_k_bwd=128, interpret=True,
            ) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True, window=window) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )


def test_flash_window_lse_path():
    q, k, v = make_qkv(jax.random.key(3))
    o, lse = flash_attention(
        q, k, v, causal=True, window=200, block_q=128, block_k=128,
        interpret=True, return_lse=True,
    )
    expected = mha_reference(q, k, v, causal=True, window=200)
    np.testing.assert_allclose(np.asarray(o), np.asarray(expected), atol=2e-5)
    assert lse.shape == (1, 4, 512)


def test_windowed_decode_matches_full_context_oracle():
    """Greedy KV-cache decode with a sliding window == iterative
    full-context forward with the same window (f32, token-exact)."""
    from k8s_gpu_device_plugin_tpu.models.generate import generate

    cfg = LlamaConfig.tiny(n_layers=2, sliding_window=8, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (2, 12), 0, cfg.vocab_size, jnp.int32
    )

    tokens = prompt
    expected = []
    for _ in range(6):
        logits = forward(params, tokens, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expected.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    got = generate(params, prompt, cfg, max_new=6)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(jnp.stack(expected, axis=1))
    )


def test_window_changes_output():
    """Sanity: a window smaller than the sequence must change the result
    vs full causal (else the masks are dead code)."""
    q, k, v = make_qkv(jax.random.key(4), s=128, hq=2, hkv=2, d=16)
    full = mha_reference(q, k, v, causal=True)
    windowed = mha_reference(q, k, v, causal=True, window=16)
    assert float(jnp.abs(full - windowed).max()) > 1e-3


def test_sliding_window_sp_support_matrix():
    """Windowed ring TRAINS under sp (loss finite, grads flow); Ulysses
    still rejects the combination loudly."""
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
        synthetic_batch,
    )
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    mesh = make_mesh(MeshSpec(dp=1, sp=4), jax.devices()[:4])
    optimizer = make_optimizer(total_steps=10)

    cfg = LlamaConfig.tiny(sliding_window=8, attn_impl="ring")
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    step = make_train_step(cfg, mesh, optimizer)
    state, metrics = step(state, batch)
    assert jnp.isfinite(metrics["loss"]) and float(metrics["grad_norm"]) > 0

    cfg_u = LlamaConfig.tiny(sliding_window=8, attn_impl="ulysses")
    state_u = init_train_state(jax.random.key(0), cfg_u, mesh, optimizer)
    step_u = make_train_step(cfg_u, mesh, optimizer)
    with pytest.raises(NotImplementedError, match="Ulysses"):
        step_u(state_u, batch)


def test_windowed_train_step_runs():
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
        synthetic_batch,
    )
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = make_mesh(MeshSpec(dp=2), jax.devices()[:2])
    cfg = LlamaConfig.tiny(sliding_window=16)
    optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=20)
    state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    step = make_train_step(cfg, mesh, optimizer)
    first = None
    for _ in range(5):
        state, m = step(state, batch)
        if first is None:
            first = float(m["loss"])
    assert np.isfinite(first) and float(m["loss"]) < first
