# Top-level build: native core, protobuf codegen, tests, bench.

NATIVE_DIR := k8s_gpu_device_plugin_tpu/native
API_DIR := k8s_gpu_device_plugin_tpu/plugin/api

# proto output (deviceplugin_pb2.py) is checked in; regen is opt-in via
# `make proto` so a plain `make` works without protoc installed.
all: native

native:
	$(MAKE) -C $(NATIVE_DIR)

native-test:
	$(MAKE) -C $(NATIVE_DIR) test

METRICS_DIR := k8s_gpu_device_plugin_tpu/metrics

proto:
	protoc --python_out=$(API_DIR) --proto_path=$(API_DIR) deviceplugin.proto
	protoc --python_out=$(METRICS_DIR) --proto_path=$(METRICS_DIR) runtime_metrics.proto

test: native-test
	python -m pytest tests/ -q

# Static gate: ruff (when installed — hermetic containers may lack it;
# compileall still catches syntax/indentation rot everywhere) plus a
# full bytecode compile of the package, tests, and top-level drivers.
# The rule set is PINNED in pyproject.toml [tool.ruff] so lint means the
# same thing on every machine; when ruff is absent the pinned selection
# is printed so the skip is visible in CI logs, not silent.
lint:
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check k8s_gpu_device_plugin_tpu tests bench.py tools; \
	else \
		echo "lint: ruff not installed — SKIPPING the pinned rule set" \
		     "(pyproject.toml [tool.ruff.lint]):"; \
		python -c "import re; \
s = open('pyproject.toml').read(); \
f = lambda key: (lambda m: ' '.join(re.findall(r'\"([A-Z0-9]+)\"', m.group(1))) \
    if m else '(not found in pyproject.toml)')( \
    re.search(r'(?m)^' + key + r' = \[(.*?)\]', s, re.S)); \
print('lint:   select =', f('select')); \
print('lint:   ignore =', f('ignore'))"; \
		echo "lint: compileall + make analyze still gate"; \
	fi
	python -m compileall -q k8s_gpu_device_plugin_tpu tests tools bench.py

# Project-invariant static analysis (tools/graftlint): six AST checkers
# encoding the serving-stack invariants PRs 1-5 established (hot-path
# H2D, jit recompile hazards, tracer leaks, thread ownership, page
# refcount pairing, blocking-in-async). Exits non-zero on any new
# violation; GRAFTLINT_STRICT=1 also refuses a stale baseline. Last
# stdout line is a one-line JSON summary (the bench-runner convention).
# ANALYZE_PATHS overrides the analyzed file set (used by fixture tests).
analyze:
	python -m tools.graftlint $(ANALYZE_PATHS)

san-test:
	$(MAKE) -C $(NATIVE_DIR) san-test

# Full CI gate (SURVEY §5 race-detection/sanitizer row): lint, plain native
# build + unit test, ASan/UBSan build + test, the decode-pipeline
# host-overhead smoke (CPU; exercises the pipelined AND sync serving
# loops end to end), the prefix-cache smoke (radix trie + cached-vs-cold
# serve A/B on CPU), and the Python suite (which includes the manager
# concurrency stress in tests/test_manager_stress.py).
# analyze runs right after lint — fail fast on invariant regressions
# BEFORE the (slow) native builds and CPU benches burn their minutes.
ci: lint analyze native native-test san-test bench-host-overhead \
	bench-prefix-cache bench-paged-kv bench-quant-paged bench-spec \
	bench-sched bench-tp bench-obs bench-kernels bench-router \
	bench-adapters bench-disagg bench-chaos bench-fleet-obs bench-chip-obs \
	bench-longctx
	python -m pytest tests/ -q -m "not slow"

bench:
	python bench.py

# CPU-runnable microbench: per-step host work of the continuous batcher
# with the decode pipeline on vs off (tiny model; prints one JSON line
# with decode_step_ms{,_sync}, device_step_ms, host_overhead_pct{,_sync}).
bench-host-overhead:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.host_overhead

# CPU-runnable microbench: prefix-cache radix trie match/insert
# throughput, the submit miss-path overhead (must be ~free with the
# cache off), and a tiny cached-vs-cold serve A/B (one JSON line with
# match_us/insert_us, submit_off_us/submit_miss_us, prefix_hit_rate,
# prefill_tokens_saved_pct).
bench-prefix-cache:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.prefix_cache_bench

# CPU-runnable microbench: paged-KV page alloc/free + refcount cost,
# the decode table-gather overhead vs the dense layout, and a tiny
# paged-vs-dense serve A/B (one JSON line with page_alloc_free_us,
# decode_step_ms_{dense,paged}, gather_overhead_pct, kv_hbm_saved_pct).
bench-paged-kv:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.paged_kv_bench

# CPU-runnable smoke: quantized KV caches ON the page pool — asserts a
# kernel-shaped int8+paged config plans onto the pallas backend (no
# silent XLA fallback) with a dense-identical stream, then runs the
# bf16-vs-int8-vs-int4 paged serve A/B and asserts the capacity
# multipliers (one JSON line with tokens_per_second_paged_{int8,int4},
# kv_bytes_per_slot_*, prefix_entries_per_gb_*, kv_capacity_x_* — the
# int8 multiplier is asserted >= 2x).
bench-quant-paged:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.quant_paged_bench

# CPU-runnable microbench: speculative decoding on the fast path —
# draft-loop dispatch overhead per accepted token (spec round vs plain
# decode step, self-draft full acceptance), the paged verify-window
# scatter cost, and a tiny spec-vs-plain serve A/B asserting the
# acceptance accounting (one JSON line with spec_round_ms,
# spec_ms_per_accepted_token, verify_scatter_overhead_pct,
# spec_acceptance_rate).
bench-spec:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.spec_bench

# CPU-runnable microbench: the SLO scheduler — plan-pass cost at a deep
# queue (µs of host work per batcher step), a forced-preemption and
# queue-cap-rejection determinism check, and a tiny open-loop Poisson
# two-tenant smoke through the fifo AND slo arms asserting the
# goodput/rejection/preemption A/B fields are present and sane (one
# JSON line with plan_us, forced_preemptions, queue_cap_rejected and
# the openloop/goodput/ttft field set).
bench-sched:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.sched_bench

# CPU-runnable smoke: tensor-parallel serving on the forced 8-device
# platform — a tp=1 vs tp=2 bit-identical stream check plus a tiny tp
# throughput A/B asserting the new serve-row fields are present and
# sane (one JSON line with tokens_per_second_tp, decode_step_ms_tp,
# kv_pages_peak_per_shard_tp, kv_shard_reserved_bytes_tp,
# tp_collective_overhead_pct).
bench-tp:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.tp_bench

# CPU-runnable smoke: the unified ragged-paged attention kernel —
# interpret-mode unified-vs-gather parity across decode/verify/prefill
# x dense/paged, the autotuner sweep->persist->reload round trip (a
# scratch tilings cache is written and re-resolved), and a tp=2
# shard_map bitwise-identity check on the forced 8-device platform
# (one JSON line with per-mode max_err, autotune_best_*_bk and
# tp_kernel_bitwise).
bench-kernels:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.kernel_bench

# CPU-runnable smoke: the replica router (serving/router.py) — ring
# candidate-resolution cost + consistent-hashing stability checks, a
# miniature 2-replica in-process fleet A/B asserting the affinity arm's
# aggregate prefix hit rate beats round-robin on a shared-prefix trace
# with zero dropped streams, and a failover check that kills one
# replica mid-trace and requires every request served by the survivor
# (one JSON line with route_us, fleet_prefix_hit_rate_{affinity,rr},
# fleet_failovers, failover_served).
bench-router:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.router_bench

# CPU-runnable smoke: adapter-dense serving (models/lora_serving.py) —
# per-step decode cost at N=1 vs 64 vs 256 registered adapters (K
# resident compact slots) asserting N=256 stays within 1.5x of N=1 (the
# O(active) claim: the registry never enters the per-step contraction),
# plus a 2-replica fleet A/B asserting adapter-affinity routing strictly
# beats adapter-blind routing on the aggregate prefix hit rate with zero
# failed requests (one JSON line with adapters_registered/resident,
# tokens_per_second_adapters, adapter_gather_overhead_pct,
# adapter_upload_ms_p99, adapter_affinity_hit_pct).
bench-adapters:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.adapter_bench

# CPU-runnable smoke: disaggregated prefill/decode serving — one
# open-loop mixed long-prompt/short-decode trace through a 3-replica
# in-process fleet, colocated vs role-split (--roles prefill=r0
# decode=r1,r2; long prompts prefill on r0, KV pages transfer to a
# decode worker over /v1/kv/export, streams splice across the hop) —
# asserts the short streams' steady-state inter-token p99 is strictly
# lower role-split (decode workers never step a wide prefill chunk),
# every long prompt actually took the hop, and zero streams dropped
# (one JSON line with the disagg_* serve-row fields +
# kv_transfer_ms_p50/p99, kv_transferred_pages_total).
bench-disagg:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.disagg_bench

# CPU-runnable chaos smoke: one open-loop trace through a seeded fault
# schedule (serving/faults.py + serving/supervisor.py) — an induced
# mid-decode engine crash recovered in place (dense AND paged, the
# paged arm adding transient pool-alloc failures) with token+logprob
# streams asserted bit-identical to a no-fault run, plus a 2-replica
# fleet with one replica killed mid-trace; asserts zero dropped and
# zero silently-truncated streams and bounded clean refusals (one JSON
# line with the chaos_* serve-row fields + fault_guard_ns, the
# disarmed-guard cost).
bench-chaos:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.chaos_bench

# CPU-runnable microbench: the latency-attribution layer's two cost
# claims — the disabled-path guard is nanoseconds (the whole hot-path
# cost with attribution off) and the per-retired-request record path
# stays microseconds — plus an end-to-end on-vs-off serve A/B and a
# flight-recorder retention smoke (one JSON line with
# attribution_us_per_request, attribution_record_us, noop_guard_ns,
# slow_captured, serving_mfu_pct).
bench-obs:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.obs_bench

# CPU-runnable smoke: the fleet observability plane (obs/fleet_obs.py)
# — a miniature 2-replica fleet asserting /fleet/metrics federation
# parses under BOTH content types (replica labels, exemplars, fleet
# aggregates), a killed-and-resumed stream (seeded router.midstream
# fault) yields ONE stitched Perfetto trace spanning both replicas and
# the router with zero orphan fragments + exactly one journal resume
# event + a router timeline whose integer-ns segments sum EXACTLY to
# the observed wall time, two same-seed runs replay IDENTICAL journals,
# and the disarmed timeline guard stays ~ns (one JSON line with
# fleet_obs_* fields + timeline_guard_ns).
bench-fleet-obs:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.fleet_obs_bench

# CPU-runnable smoke: the chip observability plane (plugin/journal.py +
# device/allocation.py) — two same-seed fake-backend runs (Allocate +
# a chip-2 health flap) replay IDENTICAL allocation journals with
# exactly two stream-true health transitions, the node's REAL classic
# /metrics scrape federates with a replica scrape and parses under
# BOTH content types (strict OpenMetrics pinned, node labels + fleet
# chip aggregates asserted), and the disarmed device-attribution guard
# stays ~ns (one JSON line with chip_obs_* fields + device_guard_ns).
bench-chip-obs:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.chip_obs_bench

# CPU-runnable smoke: long-context serving (sliding-window attention +
# streaming chunk-prefill over the page pool) — the windowed unified
# kernel (dense AND paged, decode and prefill-chunk T) pinned against
# the plain-softmax gather oracle in interpret mode, an O(window)
# footprint assertion (windowed peak pages obey the admission bound and
# undercut the full-causal twin, with out-of-window pages recycled),
# and the serve_bench longctx_ab arm end to end (one JSON line with
# window_parity_max_err_* + the longctx_* serve-row fields).
bench-longctx:
	JAX_PLATFORMS=cpu python -m k8s_gpu_device_plugin_tpu.benchmark.workloads.longctx_bench

clean:
	$(MAKE) -C $(NATIVE_DIR) clean

.PHONY: all native native-test proto lint analyze san-test ci test bench \
	bench-host-overhead bench-prefix-cache bench-paged-kv \
	bench-quant-paged bench-spec bench-sched bench-tp bench-obs \
	bench-kernels bench-router bench-adapters bench-disagg bench-chaos \
	bench-fleet-obs bench-chip-obs bench-longctx clean watch

# unattended hardware-window capture: probe on a loop, drain the harvest
# queue the moment the chip answers (tools/watchdog.py; stop with
# `touch .harvest_stop`)
watch:
	nohup python tools/watchdog.py >> .hwwatch.log 2>&1 &
	@echo "watchdog started; tail -f .hwwatch.log"
