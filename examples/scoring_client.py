#!/usr/bin/env python3
"""Stdlib-only loglikelihood client for the prompt-scoring endpoint.

Start the pod with scoring enabled::

    python -m k8s_gpu_device_plugin_tpu.serving.server \
        --preset tiny --tokenizer byte --scoring

then ask for the probability the served model assigns to a continuation
given a context — the exact lm-eval-harness ``loglikelihood`` recipe:
one request with ``echo=true, max_tokens=0, logprobs=1``, sum the
``token_logprobs`` over the continuation's tokens, and read ``is_greedy``
off whether each continuation token equals the model's argmax
(``top_logprobs`` entry 0).

Usage:
    python examples/scoring_client.py --base http://localhost:8000 \
        --context "The capital of France is" --continuation " Paris"
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base", default="http://localhost:8000")
    ap.add_argument("--context", required=True)
    ap.add_argument("--continuation", required=True)
    args = ap.parse_args()
    if not args.continuation:
        print("empty --continuation scores nothing", file=sys.stderr)
        return 2

    body = {
        "prompt": args.context + args.continuation,
        "echo": True,
        "max_tokens": 0,
        "logprobs": 1,
    }
    req = urllib.request.Request(
        f"{args.base.rstrip('/')}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        payload = json.load(urllib.request.urlopen(req, timeout=300))
    except urllib.error.HTTPError as e:
        print(f"HTTP {e.code}: {e.read().decode()[:300]}", file=sys.stderr)
        return 1

    lp = payload["choices"][0]["logprobs"]
    # find the continuation's token span via text offsets: the first
    # token whose offset reaches the context's character length. lm-eval
    # proper splits at a TOKEN index (it tokenizes context and
    # continuation separately); a character split can land inside a
    # subword token that straddles the boundary — detect and warn so a
    # silently-short sum never reads as a score.
    cut = len(args.context)
    start = next(
        (i for i, off in enumerate(lp["text_offset"]) if off >= cut),
        len(lp["text_offset"]),
    )
    if start == len(lp["text_offset"]):
        print("continuation produced no scored tokens", file=sys.stderr)
        return 1
    if lp["text_offset"][start] != cut:
        print(
            f"warning: token at offset {lp['text_offset'][start]} "
            f"straddles the context/continuation boundary ({cut}); "
            "the straddling token's mass is attributed to the context",
            file=sys.stderr,
        )
    cont_lps = lp["token_logprobs"][start:]
    total = sum(v for v in cont_lps if v is not None)
    # is_greedy by VALUE, not by token-string match: top_logprobs keys
    # are single-id decodes (U+FFFD for partial UTF-8), while tokens are
    # streaming-detokenizer pieces — the strings need not agree even
    # when the token IS the argmax. The argmax check that always works:
    # the token's own logprob equals the best alternative's.
    greedy = all(
        v is not None and top and v >= max(top.values()) - 1e-6
        for v, top in zip(cont_lps, lp["top_logprobs"][start:])
    )
    print(json.dumps({
        "continuation_tokens": lp["tokens"][start:],
        "loglikelihood": round(total, 6),
        "is_greedy": greedy,
    }, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
