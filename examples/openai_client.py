#!/usr/bin/env python3
"""Stdlib-only client for the OpenAI-compatible serving API.

The serving pod (serving/server.py) exposes /v1/completions,
/v1/chat/completions and /v1/models (serving/openai_api.py); real
deployments point the official ``openai`` SDK at it (base_url=...), but
this example needs nothing outside the standard library — the companion
to examples/serving_client.py (which speaks the native token-id API).

Usage:
    python examples/openai_client.py --base http://localhost:8000 \
        --model tpu-serving "tell me a story"
    python examples/openai_client.py --chat --stream "hello there"
    python examples/openai_client.py --list-models
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _post(base: str, path: str, payload: dict):
    req = urllib.request.Request(
        base.rstrip("/") + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    return urllib.request.urlopen(req)  # noqa: S310 - explicit user URL


def _stream_sse(resp) -> None:
    """Print streamed text deltas as they arrive; stop at [DONE]."""
    for raw in resp:
        line = raw.decode().strip()
        if not line.startswith("data: "):
            continue
        data = line[len("data: "):]
        if data == "[DONE]":
            print(flush=True)
            return
        evt = json.loads(data)
        choice = evt["choices"][0]
        delta = (
            choice.get("delta", {}).get("content")
            if "delta" in choice else choice.get("text")
        )
        if delta:
            print(delta, end="", flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("prompt", nargs="?", default="hello")
    ap.add_argument("--base", default="http://127.0.0.1:8000")
    ap.add_argument("--model", default="tpu-serving",
                    help="the base model id or a loaded LoRA adapter name")
    ap.add_argument("--chat", action="store_true",
                    help="use /v1/chat/completions instead of /v1/completions")
    ap.add_argument("--stream", action="store_true")
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=None)
    ap.add_argument("--list-models", action="store_true")
    args = ap.parse_args()

    if args.list_models:
        with urllib.request.urlopen(args.base.rstrip("/") + "/v1/models") as r:
            for m in json.load(r)["data"]:
                print(m["id"])
        return 0

    payload: dict = {
        "model": args.model,
        "max_tokens": args.max_tokens,
        "stream": args.stream,
    }
    if args.temperature is not None:
        payload["temperature"] = args.temperature
    if args.chat:
        payload["messages"] = [{"role": "user", "content": args.prompt}]
        path = "/v1/chat/completions"
    else:
        payload["prompt"] = args.prompt
        path = "/v1/completions"

    try:
        resp = _post(args.base, path, payload)
    except urllib.error.HTTPError as e:
        err = json.load(e)
        print(f"error {e.code}: {err['error']['message']}", file=sys.stderr)
        return 1
    with resp:
        if args.stream:
            _stream_sse(resp)
        else:
            body = json.load(resp)
            choice = body["choices"][0]
            text = (
                choice["message"]["content"] if args.chat else choice["text"]
            )
            print(text)
            print(
                f"[{body['model']} finish={choice['finish_reason']} "
                f"usage={body['usage']}]", file=sys.stderr,
            )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
