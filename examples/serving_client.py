#!/usr/bin/env python3
"""Example client for the inference HTTP server (serving/server.py).

Start a smoke server (random weights, byte tokenizer — lossless text
round-trip against the tiny preset's 512-token vocab):

    python -m k8s_gpu_device_plugin_tpu.serving.server \
        --preset tiny --tokenizer byte --port 8000

then:

    python examples/serving_client.py --port 8000 "Hello TPU"

Shows all three request shapes: text in/out (needs --tokenizer on the
server), raw token ids, and SSE streaming. Standard library only — a
client needs nothing from this repo.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def post(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


def stream(url: str, payload: dict):
    """Yield decoded SSE events (dicts) from a streaming generate."""
    req = urllib.request.Request(
        url,
        data=json.dumps({**payload, "stream": True}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        for raw in resp:
            line = raw.decode().strip()
            if line.startswith("data: "):
                yield json.loads(line[len("data: "):])


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("prompt", nargs="?", default="Hello TPU")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-new", type=int, default=16)
    args = parser.parse_args()
    base = f"http://{args.host}:{args.port}"
    gen = f"{base}/v1/generate"

    print("health:", post_health(base))

    # 1. text in/out (server must be started with --tokenizer)
    r = post(gen, {"text": args.prompt, "max_new": args.max_new})
    print("text request ->", json.dumps(r.get("text", r), ensure_ascii=False))

    # 2. raw token ids (always available)
    r = post(gen, {"prompt": [1, 2, 3, 4], "max_new": args.max_new,
                   "logprobs": True})
    print("id request   ->", r["tokens"])

    # 3. streaming with text on the closing event
    toks = []
    for evt in stream(gen, {"text": args.prompt, "max_new": args.max_new}):
        if "error" in evt:
            # abnormal close (engine died past its restart budget): a
            # structured error event, never a silent short stream
            print("stream ERROR ->", evt["error"]["code"],
                  evt["error"]["message"])
        elif evt.get("done"):
            print("stream done  ->", json.dumps(evt.get("text", ""),
                                                ensure_ascii=False))
        else:
            toks.append(evt["token"])
    print("streamed ids ->", toks)
    return 0


def post_health(base: str) -> dict:
    with urllib.request.urlopen(f"{base}/v1/health", timeout=10) as resp:
        return json.loads(resp.read())


if __name__ == "__main__":
    sys.exit(main())
