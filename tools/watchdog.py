#!/usr/bin/env python3
"""Window watchdog: probe the tunneled chip on a loop, drain the harvest
queue the moment a window opens, unattended.

The chip comes alive for ~12-15 minute windows hours apart (observed:
2026-07-30 15:03Z ~4min, 2026-07-31 03:46Z ~14.5min) and is otherwise
wedged — ``jax.devices()`` hangs, probes time out. Manually probing every
few minutes for hours (the round-3/4 vigil PERF.md describes) loses any
window that opens off-hours; this loop doesn't.

Each cycle execs ``harvest.py --resume``:
  rc 3 -> queue drained, watchdog exits (nothing left to measure)
  rc 4 -> another chip client is running (bench.py, or an older harvest)
          — back off; harvest's own guards keep libtpu single-client
  rc 1 -> wedge: dead probe, mid-harvest break, or a zero-progress pass
          (the common case) — sleep and re-loop
  rc 0 -> rows landed and the chip was still answering at pass end;
          re-loop immediately in case the window outlives one pass

Stop conditions: queue drained (rc 3), a ``.harvest_stop`` file at the
repo root, the ``--deadline-hours`` wall-clock bound, or a newer/older
duplicate watchdog (start-tick priority — exactly one survives).

Usage:
    nohup python tools/watchdog.py >> .hwwatch.log 2>&1 &
    touch .harvest_stop   # graceful stop from anywhere

The harvest children inherit stdout, so one log file carries the whole
story: probe cadence, window opening, every row landing.
Replaces the uncommitted ``.hwwatch.sh`` of rounds 3-4 (VERDICT r4 #2).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

import harvest  # noqa: E402  (guards + queue live there; one implementation)

STOP_PATH = os.path.join(REPO_ROOT, ".harvest_stop")


def log(msg: str) -> None:
    ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(f"{ts} watchdog {msg}", flush=True)


def outranked() -> bool:
    """True if an OLDER watchdog.py is already running — the start-tick
    priority rule shared with harvest (harvest.script_outranked): of two
    racing starts exactly one proceeds, and a running watchdog is never
    evicted by a newcomer."""
    return harvest.script_outranked("watchdog.py")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-hours", type=float, default=11.0,
                    help="hard wall-clock bound (default 11h)")
    ap.add_argument("--interval", type=float, default=170.0,
                    help="sleep between wedge probes, seconds")
    args = ap.parse_args()

    # Startup vs .harvest_stop and an older instance, without races:
    # - elder alive, no stop file: the elder owns the job; exit.
    # - elder alive + stop file: the file is a LIVE stop request aimed at
    #   the elder — leave it for the elder to honor, wait for the elder
    #   to exit, then take over (the touch-stop-then-relaunch sequence
    #   must end with exactly this new watchdog running).
    # - no elder + stop file: stale leftover; remove it and run —
    #   launching a watchdog IS the statement that it should run.
    waited = False
    while outranked():
        if not os.path.exists(STOP_PATH):
            if waited:
                # the elder survived the stop request (raced its own
                # removal at startup); it owns the job after all
                log("older watchdog survived the stop file; exiting")
            else:
                log("an older watchdog.py is already running — exiting")
            return 4
        if not waited:
            log("older watchdog has a pending stop request; waiting to "
                "take over")
            waited = True
        time.sleep(5)
    if os.path.exists(STOP_PATH):
        os.remove(STOP_PATH)
        log("removed stale .harvest_stop from a previous run")
    if waited:
        log("older watchdog exited; taking over")
    deadline = time.time() + args.deadline_hours * 3600.0
    log(f"started (deadline {args.deadline_hours:.1f}h, "
        f"interval {args.interval:.0f}s, queue head "
        f"{[n for n, _, _ in harvest.QUEUE[:4]]})")

    while True:
        if os.path.exists(STOP_PATH):
            log("stop file present; exiting")
            return 0
        if time.time() >= deadline:
            log("deadline reached; exiting")
            return 0
        if outranked():
            log("older watchdog appeared; yielding")
            return 4
        # the deadline is HARD: a pass started near it is killed (whole
        # process group — the runner grandchildren hold the chip, not
        # harvest itself) instead of overshooting by the queue's budget.
        # Already-landed rows are journaled per-row, so a kill loses only
        # the in-flight run.
        remaining = deadline - time.time()
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO_ROOT, "tools", "harvest.py"),
             "--resume"],
            cwd=REPO_ROOT,
            start_new_session=True,
        )
        try:
            rc = proc.wait(timeout=max(60.0, remaining))
        except subprocess.TimeoutExpired:
            log("deadline reached mid-pass; killing harvest process group")
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            return 0
        if rc == 3:
            log("harvest queue drained; exiting")
            return 0
        if rc == 0:
            # a window opened and rows landed — the window may still be
            # alive, so go straight back in (--resume skips landed rows)
            log("harvest pass landed rows; re-entering immediately")
            continue
        if rc == 4:
            log("chip busy (bench.py or older harvest); backing off")
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
