"""graftlint framework: project loading, annotations, baseline, runner.

Checkers are AST-level and never execute the analyzed code, so modules
that need a TPU (or a live jax) analyze the same as pure host code. The
framework owns everything rule-independent:

- loading a file set into :class:`Project` (one parsed
  :class:`ModuleInfo` per file, with its comment annotations extracted
  via :mod:`tokenize` so string literals can't spoof them);
- ``# graftlint: disable=<rule>`` suppression matching;
- the checked-in baseline (grandfathered violations with justification)
  and its delta semantics (strict mode refuses stale entries too);
- shared AST helpers checkers would otherwise each reinvent
  (dotted call names, function walks, def-line markers).
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

#: directories never analyzed unless explicitly given as a root (the
#: fixture tree exists to FAIL the checkers; analyzing it by default
#: would make every `make analyze` red by design)
EXCLUDED_DIR_NAMES = ("graftlint_fixtures", "__pycache__", ".git")

_COMMENT_RE = re.compile(r"#\s*graftlint:\s*([a-z-]+)(?:=([\w,.-]+))?")
_OWNER_RE = re.compile(r"owner:\s*engine\b")


@dataclass(frozen=True)
class Violation:
    """One finding. ``symbol`` (enclosing def qualname) and ``key`` (a
    checker-chosen stable token, e.g. the flagged call name) form the
    baseline fingerprint together with ``rule`` and ``path`` — line
    numbers deliberately do not: they drift with every edit."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    key: str = ""

    def fingerprint(self) -> tuple[str, str, str, str]:
        return (self.rule, self.path, self.symbol, self.key)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule}: "
            f"{self.message} [{self.symbol}]"
        )


@dataclass
class ModuleInfo:
    """One parsed source file plus its comment-level annotations."""

    path: str                      # repo-relative, forward slashes
    tree: ast.Module
    lines: list[str]
    #: line -> rule names suppressed on that line ("all" wildcard)
    suppressions: dict[int, set[str]] = field(default_factory=dict)
    #: line -> graftlint markers on that line (e.g. "hot-path")
    markers: dict[int, set[str]] = field(default_factory=dict)
    #: lines carrying an ``# owner: engine`` annotation
    owner_lines: set[int] = field(default_factory=set)

    def line_has_marker(self, line: int, marker: str) -> bool:
        return marker in self.markers.get(line, ())

    def def_has_marker(self, node: ast.AST, marker: str) -> bool:
        """Marker on the ``def`` line, any decorator line, or a
        STANDALONE comment line immediately above the first
        decorator/def (a trailing comment on the previous statement
        does not bleed down — same contract as suppressions)."""
        first = min(
            [node.lineno] + [d.lineno for d in node.decorator_list]
        )
        candidates = {node.lineno}
        candidates.update(d.lineno for d in node.decorator_list)
        if self.comment_only_line(first - 1):
            candidates.add(first - 1)
        return any(self.line_has_marker(ln, marker) for ln in candidates)

    def comment_only_line(self, line: int) -> bool:
        """True when ``line`` holds nothing but a comment — only those
        annotate the statement BELOW them; a trailing comment annotates
        its own line alone (no bleed onto the next statement)."""
        if not (1 <= line <= len(self.lines)):
            return False
        return self.lines[line - 1].lstrip().startswith("#")

    def suppressed(self, rule: str, line: int) -> bool:
        """A ``# graftlint: disable=`` comment suppresses its own line,
        plus the line below when it is a standalone comment line (the
        convention for statements too long to carry a trailing one)."""
        rules = set(self.suppressions.get(line, ()))
        if self.comment_only_line(line - 1):
            rules |= set(self.suppressions.get(line - 1, ()))
        return rule in rules or "all" in rules


@dataclass
class Project:
    """The analyzed file set. Checkers receive the whole project so
    cross-module rules (annotation collection in models/, enforcement in
    serving/) need no side channels."""

    root: str
    modules: list[ModuleInfo]
    parse_errors: list[Violation] = field(default_factory=list)



class Checker:
    """Plugin protocol: subclass, set ``name``/``description``,
    implement :meth:`run`. Suppression filtering happens in the runner —
    checkers report everything they see."""

    name = "abstract"
    description = ""

    def run(self, project: Project) -> list[Violation]:
        raise NotImplementedError


# --- comment annotation extraction ---------------------------------------


def _extract_annotations(source: str, info: ModuleInfo) -> None:
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [
            (t.start[0], t.string) for t in tokens
            if t.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # fall back to a line scan; a file this broken will also fail to
        # ast.parse and be reported as a parse error
        comments = [
            (i + 1, line) for i, line in enumerate(info.lines)
            if "#" in line
        ]
    for line_no, text in comments:
        if _OWNER_RE.search(text):
            info.owner_lines.add(line_no)
        m = _COMMENT_RE.search(text)
        if not m:
            continue
        kind, arg = m.group(1), m.group(2)
        if kind == "disable" and arg:
            info.suppressions.setdefault(line_no, set()).update(
                a.strip() for a in arg.split(",") if a.strip()
            )
        else:
            info.markers.setdefault(line_no, set()).add(
                kind if not arg else f"{kind}={arg}"
            )


# --- project loading ------------------------------------------------------


def _iter_py_files(root_arg: str) -> list[str]:
    if os.path.isfile(root_arg):
        return [root_arg] if root_arg.endswith(".py") else []
    # an excluded dir name EXPLICITLY given as a root is analyzed (this
    # is how the fixture tests point the suite at a seeded violation)
    explicit = any(part in EXCLUDED_DIR_NAMES
                   for part in os.path.abspath(root_arg).split(os.sep))
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root_arg):
        if not explicit:
            dirnames[:] = [
                d for d in dirnames if d not in EXCLUDED_DIR_NAMES
            ]
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return out


def load_project(paths: list[str], root: str | None = None) -> Project:
    """Parse every ``.py`` under ``paths`` (files or directories) into a
    :class:`Project`. Unparseable files become ``parse-error``
    violations instead of aborting the run — a syntax error in one file
    must not hide findings in the rest."""
    root = os.path.abspath(root or os.getcwd())
    project = Project(root=root, modules=[])
    seen: set[str] = set()
    for p in paths:
        for fpath in _iter_py_files(p):
            apath = os.path.abspath(fpath)
            if apath in seen:
                continue
            seen.add(apath)
            rel = os.path.relpath(apath, root).replace(os.sep, "/")
            try:
                with open(apath, encoding="utf-8") as f:
                    source = f.read()
            except OSError as e:
                project.parse_errors.append(Violation(
                    rule="parse-error", path=rel, line=0, col=0,
                    message=f"unreadable: {e}", key="unreadable",
                ))
                continue
            info = ModuleInfo(
                path=rel, tree=ast.Module(body=[], type_ignores=[]),
                lines=source.splitlines(),
            )
            _extract_annotations(source, info)
            try:
                info.tree = ast.parse(source, filename=rel)
            except SyntaxError as e:
                project.parse_errors.append(Violation(
                    rule="parse-error", path=rel, line=e.lineno or 0,
                    col=e.offset or 0, message=f"syntax error: {e.msg}",
                    key="syntax",
                ))
                continue
            project.modules.append(info)
    return project


# --- shared AST helpers ---------------------------------------------------


#: names that wrap a function into a jit-compiled callable
JIT_WRAPPERS = ("jax.jit", "jit", "jax.pjit", "pjit")


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, "" for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def is_jit_decorator(dec: ast.AST) -> bool:
    """``@jax.jit``, ``@partial(jax.jit, ...)``, ``@jax.jit(...)``."""
    if dotted_name(dec) in JIT_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        name = call_name(dec)
        if name in JIT_WRAPPERS:
            return True
        if name.rsplit(".", 1)[-1] == "partial" and dec.args:
            return dotted_name(dec.args[0]) in JIT_WRAPPERS
    return False


def walk_own(func: ast.AST):
    """Walk a function's OWN body: statements of nested defs belong to
    the nested function's report, not this one's."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(node))


def walk_functions(tree: ast.Module):
    """Yield ``(func_node, qualname, class_name)`` for every function in
    the module, depth-first. ``qualname`` joins nesting with dots
    (``Class.method.inner``); ``class_name`` is the nearest enclosing
    class or ""."""

    def visit(node, prefix: str, cls: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield child, q, cls
                yield from visit(child, q, cls)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield from visit(child, q, child.name)
            else:
                yield from visit(child, prefix, cls)

    yield from visit(tree, "", "")


# --- baseline -------------------------------------------------------------


def load_baseline(path: str) -> dict:
    """``{rule: [{path, symbol, key, count?, reason}, ...]}``. Every
    entry MUST carry a non-empty ``reason`` — a grandfathered violation
    without a written justification is itself an error. ``count``
    (default 1) is how many sites the entry covers: the fingerprint
    deliberately excludes line numbers (they drift), so the count is
    what stops a NEW violation with the same fingerprint from hiding
    behind an old one."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    for rule, entries in data.items():
        for e in entries:
            if not e.get("reason"):
                raise ValueError(
                    f"baseline entry for {rule} at {e.get('path')} has "
                    "no 'reason': every grandfathered violation needs a "
                    "written justification"
                )
    return data


def run_checkers(
    project: Project,
    checkers: list[Checker],
    baseline: dict | None = None,
) -> tuple[list[Violation], list[Violation], list[dict]]:
    """Run every checker; returns ``(new, baselined, stale)`` where
    ``new`` are unsuppressed violations absent from the baseline,
    ``baselined`` matched an entry, and ``stale`` are baseline entries
    that no longer fire (strict mode refuses those: a fixed violation
    must leave the baseline with the fix)."""
    baseline = baseline or {}
    by_path = {m.path: m for m in project.modules}
    raw: list[Violation] = list(project.parse_errors)
    for checker in checkers:
        raw.extend(checker.run(project))

    fingerprints: dict[tuple, dict] = {}
    budget: dict[tuple, int] = {}  # sites each entry may still absorb
    for rule, entries in baseline.items():
        for e in entries:
            fp = (rule, e.get("path", ""), e.get("symbol", "<module>"),
                  e.get("key", ""))
            fingerprints[fp] = e
            budget[fp] = int(e.get("count", 1))

    new: list[Violation] = []
    baselined: list[Violation] = []
    fired: dict[tuple, int] = {}
    seen_exact: set[tuple] = set()
    for v in sorted(raw, key=lambda v: (v.path, v.line, v.rule)):
        exact = (v.rule, v.path, v.line, v.col, v.symbol, v.key)
        if exact in seen_exact:
            continue
        seen_exact.add(exact)
        mod = by_path.get(v.path)
        if mod is not None and mod.suppressed(v.rule, v.line):
            continue
        fp = v.fingerprint()
        if fp in fingerprints and budget.get(fp, 0) > 0:
            # count-bounded: a NEW violation sharing an old entry's
            # fingerprint (lines excluded — they drift) must not hide
            # behind it once the entry's site count is used up
            budget[fp] -= 1
            fired[fp] = fired.get(fp, 0) + 1
            baselined.append(v)
        else:
            new.append(v)
    # staleness is only judged for entries whose file was ANALYZED this
    # run (a subset invocation must not misread the rest of the
    # baseline as fixed); an UNDER-firing count is stale too — fixing
    # one of an entry's sites must shrink its count with the fix
    stale = []
    for fp, e in fingerprints.items():
        if fp[1] not in by_path:
            continue
        n = fired.get(fp, 0)
        if n < int(e.get("count", 1)):
            stale.append(dict(e, rule=fp[0], fired=n))
    return new, baselined, stale
