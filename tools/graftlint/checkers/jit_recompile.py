"""jit-recompile-hazard: call sites that silently defeat the jit cache.

The serving stack's compile budget is engineered: one prefill compile
per bucket, two per chunk schedule, one decode step per config. The
cache key is (function identity, static args, shapes) — so a wrapper
built per call, an unhashable static, or a method closure over mutable
instance state all turn "compiled once" into "compiled per call/
per mutation", which on TPU is a multi-second stall per occurrence and
exactly the host-side overhead the pod-scaling literature says erodes
concurrency (ROADMAP: arXiv:2011.03641).

Flags:

- ``jax.jit(...)`` (or ``pjit``) EVALUATED inside a function body: the
  wrapper is rebuilt every call, so its cache starts empty every call.
  Decorators and module-scope wrapping evaluate once and are fine.
- a jit-decorated function or lambda that closes over ``self``: the
  instance is captured at wrap time; mutable state changes do not
  re-key the cache (stale compile) or, if hashed, recompile per
  mutation.
- ``static_argnames``/``static_argnums`` naming a parameter whose
  default or annotation is an unhashable container (list/dict/set):
  the first call raises or, worse, the value is rebuilt per call and
  never hits the cache.
- ``static_argnames`` naming a parameter the wrapped function does not
  even have (the typo silently makes the arg dynamic).
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    JIT_WRAPPERS,
    Checker,
    Project,
    Violation,
    call_name,
    dotted_name,
    is_jit_decorator,
    walk_functions,
    walk_own,
)

UNHASHABLE_ANNOT = {"list", "dict", "set", "List", "Dict", "Set"}


def _jit_call_parts(call: ast.Call):
    """For a Call that builds a jit wrapper, return (wrapped_fn_node,
    static_kwargs) — handles ``jax.jit(f, ...)`` and
    ``partial(jax.jit, ...)`` (no wrapped fn). None if not a jit call."""
    name = call_name(call)
    if name in JIT_WRAPPERS:
        fn = call.args[0] if call.args else None
        return fn, call.keywords
    if name.rsplit(".", 1)[-1] == "partial" and call.args:
        if dotted_name(call.args[0]) in JIT_WRAPPERS:
            return None, call.keywords
    return None


class JitRecompileHazard(Checker):
    name = "jit-recompile-hazard"
    description = (
        "jit wrappers built per call, closures over mutable instance "
        "state, or unhashable/mistyped static args"
    )

    def run(self, project: Project) -> list[Violation]:
        out: list[Violation] = []
        for mod in project.modules:
            # bench workloads and tests are one-shot processes: a sweep
            # deliberately builds one wrapper per measured variant, a
            # test builds one per assertion — the cache-reuse invariant
            # protects the long-lived serving/train processes. Fixture
            # files stay eligible (the firing fixtures live there).
            if "graftlint_fixtures" not in mod.path and (
                "benchmark/" in mod.path or mod.path.startswith("tests/")
                or "/tests/" in mod.path
            ):
                continue
            out.extend(self._check_module(mod))
        return out

    def _check_module(self, mod) -> list[Violation]:
        out: list[Violation] = []
        funcs = list(walk_functions(mod.tree))

        # (a) jit wrapper whose cache cannot survive: built-and-invoked
        # in one expression, or rebuilt every iteration of a loop. The
        # factory pattern (build once, assign/return, reuse) is fine —
        # the wrapper object persists, so its cache does.
        for func, qual, _cls in funcs:
            loop_spans: list[tuple[int, int]] = []
            for node in walk_own(func):
                if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                    loop_spans.append(
                        (node.lineno, getattr(node, "end_lineno",
                                              node.lineno))
                    )
            for node in walk_own(func):
                if not isinstance(node, ast.Call):
                    continue
                immediate = (
                    isinstance(node.func, ast.Call)
                    and call_name(node.func) in JIT_WRAPPERS
                )
                fresh_in_loop = call_name(node) in JIT_WRAPPERS and any(
                    lo < node.lineno <= hi for lo, hi in loop_spans
                ) and not self._is_decorator_of_any(node, funcs)
                if immediate:
                    out.append(Violation(
                        rule=self.name, path=mod.path, line=node.lineno,
                        col=node.col_offset, symbol=qual,
                        key="jit-immediately-invoked",
                        message=(
                            "jit wrapper built and invoked in one "
                            "expression: the wrapper (and its compile "
                            "cache) is discarded after the call, so "
                            "every occurrence recompiles — build the "
                            "jit once at module scope and reuse it"
                        ),
                    ))
                elif fresh_in_loop:
                    out.append(Violation(
                        rule=self.name, path=mod.path, line=node.lineno,
                        col=node.col_offset, symbol=qual,
                        key="jit-in-loop",
                        message=(
                            "jit wrapper rebuilt every loop iteration: "
                            "each fresh wrapper starts with an empty "
                            "cache and recompiles — hoist the jit out "
                            "of the loop"
                        ),
                    ))

        # (b) jit-decorated defs/lambdas closing over self
        for func, qual, cls in funcs:
            if not any(self._is_jit_dec(d) for d in func.decorator_list):
                continue
            params = {a.arg for a in func.args.posonlyargs
                      + func.args.args + func.args.kwonlyargs}
            if "self" in params:
                out.append(Violation(
                    rule=self.name, path=mod.path, line=func.lineno,
                    col=func.col_offset, symbol=qual, key="jit-method",
                    message=(
                        "jit applied to a method: 'self' becomes a "
                        "traced (or hashed) argument, so mutable "
                        "instance state either recompiles per mutation "
                        "or silently serves a stale compile — jit a "
                        "free function over explicit state instead"
                    ),
                ))
            elif any(
                isinstance(n, ast.Name) and n.id == "self"
                and isinstance(n.ctx, ast.Load)
                for n in ast.walk(func)
            ):
                out.append(Violation(
                    rule=self.name, path=mod.path, line=func.lineno,
                    col=func.col_offset, symbol=qual,
                    key="jit-closure-self",
                    message=(
                        "jit-decorated function closes over 'self': the "
                        "instance is captured at wrap time, so mutable "
                        "state changes never re-key the cache (stale "
                        "compile) — pass the state as an argument"
                    ),
                ))

        # (c)+(d) static_argnames hygiene on decorated defs
        for func, qual, _cls in funcs:
            statics = self._static_names(func)
            if statics is None:
                continue
            names = {a.arg for a in func.args.posonlyargs
                     + func.args.args + func.args.kwonlyargs}
            annot = {
                a.arg: a.annotation
                for a in func.args.posonlyargs + func.args.args
                + func.args.kwonlyargs
            }
            defaults = self._defaults_by_name(func)
            for s in statics:
                if s not in names:
                    out.append(Violation(
                        rule=self.name, path=mod.path, line=func.lineno,
                        col=func.col_offset, symbol=qual,
                        key=f"static-missing:{s}",
                        message=(
                            f"static_argnames names {s!r} but the "
                            "function has no such parameter: the typo "
                            "silently leaves the real arg dynamic"
                        ),
                    ))
                    continue
                problem = self._unhashable(annot.get(s), defaults.get(s))
                if problem:
                    out.append(Violation(
                        rule=self.name, path=mod.path, line=func.lineno,
                        col=func.col_offset, symbol=qual,
                        key=f"static-unhashable:{s}",
                        message=(
                            f"static arg {s!r} is {problem}: statics "
                            "must hash stably or every call misses the "
                            "cache (or raises) — use a tuple/frozen "
                            "dataclass"
                        ),
                    ))
        return out

    @staticmethod
    def _is_jit_dec(dec: ast.AST) -> bool:
        return is_jit_decorator(dec)

    @staticmethod
    def _is_decorator_of_any(node: ast.Call, funcs) -> bool:
        return any(
            node in f.decorator_list
            or any(node in ast.walk(d) for d in f.decorator_list)
            for f, _q, _c in funcs
        )

    @staticmethod
    def _static_names(func) -> "set[str] | None":
        for dec in func.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            parts = _jit_call_parts(dec)
            if parts is None:
                continue
            _, kwargs = parts
            out: set[str] = set()
            found = False
            pos = [a.arg for a in func.args.posonlyargs + func.args.args]
            for kw in kwargs:
                if kw.arg == "static_argnames":
                    found = True
                    vals = kw.value
                    if isinstance(vals, ast.Constant) and isinstance(
                        vals.value, str
                    ):
                        out.add(vals.value)
                    elif isinstance(vals, (ast.Tuple, ast.List)):
                        out.update(
                            e.value for e in vals.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                        )
                elif kw.arg == "static_argnums":
                    found = True
                    vals = kw.value
                    idxs = []
                    if isinstance(vals, ast.Constant) and isinstance(
                        vals.value, int
                    ):
                        idxs = [vals.value]
                    elif isinstance(vals, (ast.Tuple, ast.List)):
                        idxs = [
                            e.value for e in vals.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int)
                        ]
                    for i in idxs:
                        # an out-of-range index surfaces as a name the
                        # signature cannot have -> the missing-param arm
                        out.add(pos[i] if 0 <= i < len(pos)
                                else f"<argnum {i}>")
            if found:
                return out
        return None

    @staticmethod
    def _defaults_by_name(func) -> dict:
        args = func.args.posonlyargs + func.args.args
        defaults = func.args.defaults
        out = {}
        for a, d in zip(args[len(args) - len(defaults):], defaults):
            out[a.arg] = d
        for a, d in zip(func.args.kwonlyargs, func.args.kw_defaults):
            if d is not None:
                out[a.arg] = d
        return out

    @staticmethod
    def _unhashable(annotation, default) -> str:
        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
            return "defaulted to an unhashable container literal"
        base = annotation
        if isinstance(base, ast.Subscript):
            base = base.value
        if base is not None:
            nm = dotted_name(base).rsplit(".", 1)[-1]
            if nm in UNHASHABLE_ANNOT:
                return f"annotated as unhashable {nm!r}"
        return ""
