"""thread-ownership: engine-thread state crosses threads via snapshots.

The engine thread is the batcher's sole owner (serving/server.py design
note); HTTP handlers and the metrics scrape run on other threads. The
PR-4 contract for crossing that boundary: either a ``*_stats()`` method
that SNAPSHOTS engine state before returning it (``kv_stats`` list()s
the dicts it iterates), or a GIL-atomic ``len()`` of one container (the
documented approximate-read contract of ``InferenceEngine.stats``).
Anything else — iterating ``running`` mid-admission, reading the pool's
free list — races the engine thread and raises (dict mutated during
iteration) or returns torn state.

Conventions this checker reads:

- ``# owner: engine`` on a ``self.x = ...`` line (anywhere in the
  project) declares attribute ``x`` engine-thread-only.
- Cross-thread contexts are every ``async def`` plus any function whose
  ``def`` line carries ``# graftlint: cross-thread`` (the event-loop-
  side InferenceEngine methods), in the serving/metrics consumer
  modules.

In a cross-thread context, any read or write of an engine-owned
attribute is flagged unless the access is the sole argument of a bare
``len()`` call.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Checker,
    Project,
    Violation,
    walk_functions,
)

#: modules whose functions can run off the engine thread (the HTTP
#: planes and the prometheus side); models/ is engine-side by layering
CONSUMER_PATH_PARTS = ("serving/", "metrics/", "graftlint_fixtures/")


class ThreadOwnership(Checker):
    name = "thread-ownership"
    description = (
        "# owner: engine attributes read outside the engine thread "
        "without a *_stats() snapshot or an atomic len()"
    )

    def run(self, project: Project) -> list[Violation]:
        owned = self._collect_owned(project)
        if not owned:
            return []
        out: list[Violation] = []
        for mod in project.modules:
            if not any(p in mod.path for p in CONSUMER_PATH_PARTS):
                continue
            for func, qual, _cls in walk_functions(mod.tree):
                is_cross = isinstance(func, ast.AsyncFunctionDef) or \
                    mod.def_has_marker(func, "cross-thread")
                if not is_cross:
                    continue
                out.extend(self._check_func(mod, func, qual, owned))
        return out

    @staticmethod
    def _collect_owned(project: Project) -> set[str]:
        owned: set[str] = set()
        for mod in project.modules:
            if not mod.owner_lines:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    # the annotation may ride the assignment line(s) or
                    # a standalone comment line immediately above (a
                    # TRAILING comment on the previous statement does
                    # not bleed down)
                    end = getattr(node, "end_lineno", node.lineno)
                    hit = any(
                        ln in mod.owner_lines
                        for ln in range(node.lineno, end + 1)
                    ) or (
                        node.lineno - 1 in mod.owner_lines
                        and mod.comment_only_line(node.lineno - 1)
                    )
                    if not hit:
                        continue
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            owned.add(t.attr)
        return owned

    def _check_func(self, mod, func, qual, owned) -> list[Violation]:
        # attribute nodes that are the sole argument of a bare len()
        # call are the sanctioned GIL-atomic read; attribute nodes that
        # ARE a call's func are METHOD lookups on some other object
        # (task.done(), fut.result()) — the owned-name match is
        # receiver-blind, so treating those as state reads would flag
        # every asyncio future in a handler
        atomic: set[int] = set()
        method_lookups: set[int] = set()
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    method_lookups.add(id(node.func))
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "len" and len(node.args) == 1
                        and not node.keywords):
                    atomic.add(id(node.args[0]))
        out: list[Violation] = []
        # nested ASYNC defs are their own cross-thread contexts (checked
        # separately — descending again would double-report); nested
        # sync helpers run on this thread when called inline, so they
        # stay in the walk
        def walk_same_context(root):
            stack = list(ast.iter_child_nodes(root))
            while stack:
                n = stack.pop()
                if isinstance(n, ast.AsyncFunctionDef):
                    continue
                yield n
                stack.extend(ast.iter_child_nodes(n))

        for node in walk_same_context(func):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in owned or id(node) in atomic \
                    or id(node) in method_lookups:
                continue
            action = (
                "written" if isinstance(node.ctx, (ast.Store, ast.Del))
                else "read"
            )
            out.append(Violation(
                rule=self.name, path=mod.path, line=node.lineno,
                col=node.col_offset, symbol=qual, key=node.attr,
                message=(
                    f"engine-owned attribute '{node.attr}' {action} from "
                    "a cross-thread context; go through a *_stats() "
                    "snapshot (or an atomic len()) instead of touching "
                    "engine state directly"
                ),
            ))
        return out
