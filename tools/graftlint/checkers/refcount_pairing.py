"""refcount-pairing: every page retain must have a reachable release.

The page pool (models/paging.py) is a manual refcount domain: ``alloc``
and ``incref`` take references, ``decref`` gives them back, and a
reference that never reaches a ``decref`` is HBM leaked until restart
(the pool has no GC — ``check()`` asserts the books balance, but only
when a test thinks to call it). The batcher's discipline is an
ownership ledger: every retained page id list is either released in the
same function or stored into a long-lived attribute (``req._new_pages``,
``self._slot_pages[slot]``, ...) that some release path demonstrably
drains.

What this checker enforces, per call site on a ``*pool*`` receiver:

1. **No dropped retains**: an ``alloc``/``incref`` whose result/argument
   is never stored, returned, or released in that function leaks.
2. **Exception edges**: between the retain and the statement that
   records ownership there must be no call that can raise (a tiny
   allowlist of builtins excepted) — a raise in that window strands the
   references with no release path. ``x.attr = pool.alloc(n)`` (retain
   and record in one statement) is the canonical safe shape.
3. **Drained ledgers**: every attribute a retained value is stored
   under must be drained somewhere in the analyzed tree — a function
   that reads that attribute and calls ``decref`` — either directly or
   through a chain of ownership transfers (``_new_pages`` →
   ``_slot_pages`` → released at slot retirement).

A ``return`` of the retained value transfers ownership to the caller
(the promotion-extractor pattern); callers are then covered by the same
rules at their own store sites.

``PagePool`` itself (the class DEFINING alloc/decref) is exempt — its
bodies are the primitive, not call sites.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Checker,
    Project,
    Violation,
    call_name,
    dotted_name,
    walk_functions,
    walk_own,
)

RETAIN_METHODS = {"alloc", "incref"}
#: ``recycle`` is the out-of-window reclamation spelling of ``decref``
#: (models/paging.py) — same release semantics, separate tally
RELEASE_METHODS = {"decref", "recycle"}
#: calls allowed between a retain and its ownership store (cannot
#: meaningfully raise for the argument shapes used here)
SAFE_CALLS = {
    "len", "list", "tuple", "int", "min", "max", "range", "bool",
    "perf_counter", "monotonic", "time",
}


def _header_nodes(stmt: ast.stmt):
    """The nodes a statement evaluates BEFORE entering any nested
    block: compound statements contribute only their header expressions
    (their bodies are separate blocks, scanned on their own)."""
    if isinstance(stmt, (ast.If, ast.While)):
        yield from ast.walk(stmt.test)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        yield from ast.walk(stmt.iter)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            yield from ast.walk(item.context_expr)
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Try)):
        return
    else:
        yield from ast.walk(stmt)


def _is_pool_call(call: ast.Call, methods: set[str]) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in methods:
        return False
    recv = dotted_name(call.func.value)
    return "pool" in recv.lower()


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _stored_attr(target: ast.AST) -> "str | None":
    """Attribute name an assignment target records ownership under:
    ``req._new_pages = ...`` -> ``_new_pages``;
    ``self._slot_pages[slot] = ...`` -> ``_slot_pages``."""
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Subscript) and isinstance(
        target.value, ast.Attribute
    ):
        return target.value.attr
    return None


def _calls_outside_safe(node: ast.AST, extra_safe: set[str]) -> "str | None":
    """First call in ``node`` that could raise (not allowlisted)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = call_name(n)
            leaf = name.rsplit(".", 1)[-1]
            if leaf in SAFE_CALLS or name in extra_safe or \
                    leaf in RETAIN_METHODS | RELEASE_METHODS:
                continue
            return name or "<dynamic call>"
    return None


class RefcountPairing(Checker):
    name = "refcount-pairing"
    description = (
        "page-pool alloc/incref without a reachable matching release "
        "(ownership store, paired decref, or return) on all exits"
    )

    def run(self, project: Project) -> list[Violation]:
        out: list[Violation] = []
        # global ledger book-keeping for rule 3
        stores: list[tuple] = []      # (mod, line, qual, attr)
        drains: set[str] = set()      # attrs drained by a decref-holder
        edges: set[tuple[str, str]] = set()  # attr read -> attr stored

        for mod in project.modules:
            allocator_classes = self._allocator_classes(mod)
            for func, qual, cls in walk_functions(mod.tree):
                if cls in allocator_classes:
                    continue
                fout, fstores = self._check_func(mod, func, qual)
                out.extend(fout)
                stores.extend(fstores)
                reads = {
                    n.attr for n in ast.walk(func)
                    if isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Load)
                }
                stored_here = {
                    s[3] for s in fstores
                } | self._all_stored_attrs(func)
                has_release = any(
                    isinstance(n, ast.Call)
                    and _is_pool_call(n, RELEASE_METHODS)
                    for n in ast.walk(func)
                )
                if has_release:
                    drains.update(reads)
                else:
                    for r in reads:
                        for s in stored_here:
                            if r != s:
                                edges.add((r, s))

        # propagate drained-ness backwards through ownership transfers
        changed = True
        while changed:
            changed = False
            for r, s in edges:
                if s in drains and r not in drains:
                    drains.add(r)
                    changed = True

        for mod, line, qual, attr in stores:
            if attr not in drains:
                out.append(Violation(
                    rule=self.name, path=mod.path, line=line, col=0,
                    symbol=qual, key=f"undrained:{attr}",
                    message=(
                        f"retained pages stored under '{attr}' but no "
                        "analyzed function both reads that attribute "
                        "and calls decref (directly or via an ownership "
                        "transfer chain): the ledger is never drained"
                    ),
                ))
        return out

    @staticmethod
    def _allocator_classes(mod) -> set[str]:
        """Classes whose methods ARE the primitives (defining alloc AND
        incref AND decref — PagePool and fixture twins): their bodies
        are skipped, they are not call sites. One module walk, consulted
        per function."""
        out: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                names = {
                    n.name for n in node.body
                    if isinstance(n, ast.FunctionDef)
                }
                if RETAIN_METHODS <= names and "decref" in names:
                    out.add(node.name)
        return out

    @staticmethod
    def _all_stored_attrs(func) -> set[str]:
        out = set()
        for n in ast.walk(func):
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            for t in targets:
                a = _stored_attr(t)
                if a:
                    out.add(a)
        return out

    def _check_func(self, mod, func, qual):
        out: list[Violation] = []
        stores: list[tuple] = []
        has_local_release = any(
            isinstance(n, ast.Call) and _is_pool_call(n, RELEASE_METHODS)
            for n in ast.walk(func)
        )
        for block in self._blocks(func):
            for i, stmt in enumerate(block):
                for call in _header_nodes(stmt):
                    if not (isinstance(call, ast.Call)
                            and _is_pool_call(call, RETAIN_METHODS)):
                        continue
                    v, st = self._check_retain(
                        mod, func, qual, block, i, stmt, call,
                        has_local_release,
                    )
                    out.extend(v)
                    stores.extend(st)
        return out, stores

    @staticmethod
    def _blocks(func):
        """Every statement list in the function (own body only)."""
        yield func.body
        for node in walk_own(func):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # a nested def's body is ITS block, not ours
            for field in ("body", "orelse", "finalbody"):
                blk = getattr(node, field, None)
                if isinstance(blk, list) and blk and isinstance(
                    blk[0], ast.stmt
                ):
                    yield blk
            for h in getattr(node, "handlers", []) or []:
                yield h.body

    def _check_retain(self, mod, func, qual, block, i, stmt, call,
                      has_local_release):
        """Classify one retain site; returns (violations, ledger stores)."""
        out: list[Violation] = []
        stores: list[tuple] = []
        method = call.func.attr

        # retain-and-record in one statement: x.attr = pool.alloc(n)
        if isinstance(stmt, ast.Assign) and stmt.value is call:
            attrs = [_stored_attr(t) for t in stmt.targets]
            named = [a for a in attrs if a]
            if named:
                for a in named:
                    stores.append((mod, stmt.lineno, qual, a))
                return out, stores
            # plain local name: scan forward for transfer/release
            locals_ = set()
            for t in stmt.targets:
                locals_.update(_names_in(t))
            return self._scan_forward(
                mod, qual, block, i, stmt, method, locals_,
                has_local_release,
            )

        # bare expression: pool.incref(pins) — the argument names carry
        # the retained pages
        if isinstance(stmt, ast.Expr) and stmt.value is call:
            if method == "alloc":
                out.append(Violation(
                    rule=self.name, path=mod.path, line=stmt.lineno,
                    col=stmt.col_offset, symbol=qual, key="alloc-dropped",
                    message=(
                        "alloc() result discarded: the pages are "
                        "allocated at refcount 1 with no holder — "
                        "nothing can ever release them"
                    ),
                ))
                return out, stores
            names = set()
            for a in call.args:
                names.update(_names_in(a))
            names.discard("self")
            if not names:
                out.append(Violation(
                    rule=self.name, path=mod.path, line=stmt.lineno,
                    col=stmt.col_offset, symbol=qual,
                    key="incref-anonymous",
                    message=(
                        "incref() of an expression with no local name: "
                        "the extra references cannot be tracked to a "
                        "release — bind the page list to a name that an "
                        "ownership store or decref provably covers"
                    ),
                ))
                return out, stores
            return self._scan_forward(
                mod, qual, block, i, stmt, method, names,
                has_local_release,
            )

        # retain nested in a larger expression (return pool.alloc(n),
        # f(pool.alloc(n))...): a return transfers to the caller; any
        # other shape is untrackable
        if isinstance(stmt, ast.Return):
            return out, stores
        out.append(Violation(
            rule=self.name, path=mod.path, line=stmt.lineno,
            col=stmt.col_offset, symbol=qual, key=f"{method}-embedded",
            message=(
                f"{method}() embedded in a larger expression: the "
                "retained pages have no name a release path can be "
                "checked against — assign them first"
            ),
        ))
        return out, stores

    def _scan_forward(self, mod, qual, block, i, stmt, method, names,
                      has_local_release):
        """The retained pages live in local ``names``; walk the rest of
        the block for the ownership disposition and flag raising calls
        in the unprotected window."""
        out: list[Violation] = []
        stores: list[tuple] = []
        for later in block[i + 1:]:
            # disposition reached?
            if isinstance(later, ast.Assign) and (
                _names_in(later.value) & names
            ):
                attrs = [_stored_attr(t) for t in later.targets]
                named = [a for a in attrs if a]
                if named:
                    for a in named:
                        stores.append((mod, later.lineno, qual, a))
                    return out, stores
                # renamed local: follow the new name too
                for t in later.targets:
                    names |= _names_in(t)
                continue
            if isinstance(later, ast.Return):
                if later.value is not None and (
                    _names_in(later.value) & names
                ):
                    return out, stores  # ownership handed to the caller
                if not has_local_release:
                    out.append(Violation(
                        rule=self.name, path=mod.path, line=later.lineno,
                        col=later.col_offset, symbol=qual,
                        key=f"{method}-dropped-at-return",
                        message=(
                            "function returns WITHOUT the pages "
                            f"retained by {method}() above: the "
                            "references are dropped with no release "
                            "path"
                        ),
                    ))
                return out, stores
            if any(
                isinstance(n, ast.Call)
                and _is_pool_call(n, RELEASE_METHODS)
                and (_names_in(n) & names)
                for n in ast.walk(later)
            ):
                return out, stores  # released locally
            # still in the unprotected window: a raise here strands refs
            raiser = _calls_outside_safe(later, extra_safe=set())
            if raiser is not None:
                out.append(Violation(
                    rule=self.name, path=mod.path, line=later.lineno,
                    col=later.col_offset, symbol=qual,
                    key=f"raise-window:{raiser.rsplit('.', 1)[-1]}",
                    message=(
                        f"{raiser}() can raise between the {method}() "
                        "and the statement that records ownership: the "
                        "retained pages would leak — record ownership "
                        "first (or wrap with a releasing finally)"
                    ),
                ))
                return out, stores
        else:
            if not has_local_release:
                out.append(Violation(
                    rule=self.name, path=mod.path, line=stmt.lineno,
                    col=stmt.col_offset, symbol=qual,
                    key=f"{method}-unreleased",
                    message=(
                        f"{method}() result reaches the end of the "
                        "block with no ownership store, return, or "
                        "decref: the references leak"
                    ),
                ))
        return out, stores
