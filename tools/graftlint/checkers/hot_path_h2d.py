"""hot-path-h2d: zero per-step host->device transfers in the decode loop.

PR 2's contract: the steady-state decode loop performs NO host->device
transfer — membership masks, sampler knobs, bias planes, seeds and the
EOS scalar are cached device residents, and budget/draw state lives in
the jitted carry. A ``jnp.asarray`` (or friends) sneaking back into a
per-step function silently reintroduces a per-token transfer; nothing
crashes, serving just gets slower (the host-overhead bench would
eventually notice, several PRs too late).

Scope: functions whose ``def`` line carries ``# graftlint: hot-path``
(the decode-loop registry: ``decode_step``/``spec_decode_step``, the
dispatch/apply seams, the paged gather/scatter helpers in generate.py),
including any function nested inside them.

Host vs traced hot paths: a jit-DECORATED hot function (or one marked
``# graftlint: hot-path=traced`` — the undecorated helpers that only
ever run inside another function's trace, like ``_cache_write``) runs
its body at trace time, where ``jnp.arange``/``jnp.full`` build
compile-time constants, not per-step transfers. A HOST hot function
(the dispatch/apply seams) runs its body every step, where the same
constructors ARE a per-step host-array build + transfer.

Flags:

- in every hot path: calls that explicitly materialize host data onto
  the device (``jnp.array``, ``jnp.asarray``, ``jax.device_put``,
  ``np.asarray``/``np.array`` — host arrays built here transfer the
  moment they hit a jit boundary — and device scalar constructors like
  ``jnp.int32(x)``);
- in HOST hot paths only: the device-array constructor family
  (``jnp.zeros``/``ones``/``full``/``empty``/``arange``/``eye``);
- Python-scalar carry mutations: an AugAssign to ``self.X`` where
  ``self.X`` is also passed into a hot-path call in the same function —
  the pre-PR-2 budget-counter idiom (host mutates a scalar, re-uploads
  it every step).
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Checker,
    Project,
    Violation,
    call_name,
    is_jit_decorator,
    walk_functions,
)

H2D_CALLS = {
    "jnp.array", "jnp.asarray", "jax.numpy.array", "jax.numpy.asarray",
    "jax.device_put", "np.asarray", "np.array", "numpy.asarray",
    "numpy.array", "jnp.int32", "jnp.int64", "jnp.float32", "jnp.float16",
    "jnp.bfloat16", "jnp.bool_", "jax.random.key",
}
#: H2D only when evaluated on the HOST side (at trace time these build
#: compile-time constants — legitimate in the jitted step bodies)
CONSTRUCTOR_CALLS = {
    "jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty", "jnp.arange",
    "jnp.eye",
}


class HotPathH2D(Checker):
    name = "hot-path-h2d"
    description = (
        "host->device transfers or host-scalar carries inside functions "
        "registered (# graftlint: hot-path) as decode-loop hot paths"
    )

    def run(self, project: Project) -> list[Violation]:
        out: list[Violation] = []
        for mod in project.modules:
            funcs = list(walk_functions(mod.tree))
            hot: list[tuple[ast.AST, str, bool]] = []
            hot_names: set[str] = set()
            for node, qual, _cls in funcs:
                plain = mod.def_has_marker(node, "hot-path")
                traced_mark = mod.def_has_marker(node, "hot-path=traced")
                if not (plain or traced_mark):
                    continue
                traced = traced_mark or any(
                    is_jit_decorator(d) for d in node.decorator_list
                )
                hot.append((node, qual, traced))
                hot_names.add(node.name)
            for node, qual, traced in hot:
                # nested defs inherit the hot scope; walk_functions
                # already yields them separately only if they carry
                # their own marker, so walk the whole subtree here
                out.extend(self._check_func(mod, node, qual, hot_names,
                                            traced))
        return out

    def _check_func(self, mod, func, qual, hot_names,
                    traced) -> list[Violation]:
        out: list[Violation] = []
        hot_call_args: set[str] = set()  # self.X attrs fed to hot calls
        aug_assigns: list[ast.AugAssign] = []
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name in H2D_CALLS or (
                    not traced and name in CONSTRUCTOR_CALLS
                ):
                    out.append(Violation(
                        rule=self.name, path=mod.path, line=node.lineno,
                        col=node.col_offset, symbol=qual, key=name,
                        message=(
                            f"{name}() in a decode-loop hot path is a "
                            "per-step host->device transfer; cache the "
                            "device array across steps or move the value "
                            "into the jitted carry"
                        ),
                    ))
                leaf = name.rsplit(".", 1)[-1]
                if leaf in hot_names:
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if (isinstance(arg, ast.Attribute)
                                and isinstance(arg.value, ast.Name)
                                and arg.value.id == "self"):
                            hot_call_args.add(arg.attr)
            elif isinstance(node, ast.AugAssign):
                aug_assigns.append(node)
        for node in aug_assigns:
            t = node.target
            if (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                    and t.value.id == "self" and t.attr in hot_call_args):
                out.append(Violation(
                    rule=self.name, path=mod.path, line=node.lineno,
                    col=node.col_offset, symbol=qual,
                    key=f"carry:{t.attr}",
                    message=(
                        f"self.{t.attr} is mutated host-side AND passed "
                        "into a hot-path call: a Python-scalar carry "
                        "re-uploaded every step — move it into the "
                        "device-side state (the BatchState.budget/draws "
                        "pattern)"
                    ),
                ))
        return out
