"""tracer-leak: no host-state writes from inside traced function bodies.

A jitted function body runs at TRACE time with abstract tracers for
values. Writing a tracer into ``self.*`` or a module global "works"
once, then the stored tracer escapes its trace (JAX's leaked-tracer
error at best, silent staleness at worst: the attribute keeps the value
from compile #1 forever while the jit cache replays the compiled
program). The batcher keeps every jitted step purely functional over
``BatchState`` for exactly this reason.

Traced scopes: functions decorated with ``jax.jit``/``pjit`` (directly
or through ``functools.partial``), functions wrapped by name anywhere
in the module (``f = jax.jit(g)``), everything nested inside those, and
local functions handed to ``jax.lax.scan``/``while_loop``/``fori_loop``
/``cond``/``vmap``/``jax.checkpoint`` (their bodies trace the same
way).

Flags inside traced scopes: assignments/augmented assignments to
``self.<attr>`` or to attributes of any parameter, ``global``/
``nonlocal`` declarations, and subscript stores into module-level
names.
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    JIT_WRAPPERS,
    Checker,
    Project,
    Violation,
    call_name,
    dotted_name,
    is_jit_decorator,
    walk_functions,
    walk_own,
)

TRACING_CONSUMERS = {
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.vmap", "vmap", "jax.checkpoint", "jax.remat",
}


class TracerLeak(Checker):
    name = "tracer-leak"
    description = (
        "writes to self.* or module globals from inside jitted/traced "
        "function bodies"
    )

    def run(self, project: Project) -> list[Violation]:
        out: list[Violation] = []
        for mod in project.modules:
            out.extend(self._check_module(mod))
        return out

    def _check_module(self, mod) -> list[Violation]:
        module_names = self._module_level_names(mod.tree)
        # names handed to a jit wrapper or a tracing consumer anywhere
        # in the module (f = jax.jit(g); lax.scan(body, ...)); name-
        # level matching is a heuristic, which is all a linter needs
        wrapped: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                n = call_name(node)
                if n in JIT_WRAPPERS and node.args and isinstance(
                    node.args[0], ast.Name
                ):
                    wrapped.add(node.args[0].id)
                elif n in TRACING_CONSUMERS:
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            wrapped.add(arg.id)

        funcs = list(walk_functions(mod.tree))
        traced_quals: set[str] = set()
        for func, qual, _cls in funcs:
            if func.name in wrapped or any(
                is_jit_decorator(d) for d in func.decorator_list
            ):
                traced_quals.add(qual)
        out: list[Violation] = []
        for func, qual, _cls in funcs:
            traced = qual in traced_quals or any(
                qual.startswith(t + ".") for t in traced_quals
            )
            if traced:
                out.extend(self._check_traced_body(
                    mod, func, qual, module_names
                ))
        return out

    def _check_traced_body(self, mod, func, qual, module_names):
        params = {
            a.arg for a in (
                func.args.posonlyargs + func.args.args
                + func.args.kwonlyargs
            )
        }
        if func.args.vararg:
            params.add(func.args.vararg.arg)
        if func.args.kwarg:
            params.add(func.args.kwarg.arg)
        out: list[Violation] = []
        for node in walk_own(func):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(node, ast.Global) else "nonlocal"
                out.append(Violation(
                    rule=self.name, path=mod.path, line=node.lineno,
                    col=node.col_offset, symbol=qual,
                    key=f"{kw}:{','.join(node.names)}",
                    message=(
                        f"'{kw} {', '.join(node.names)}' inside a traced "
                        "body: host state written at trace time leaks "
                        "tracers (or freezes at compile #1); thread the "
                        "value through the carry instead"
                    ),
                ))
                continue
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                for leaf in self._flatten(t):
                    v = self._bad_target(mod, leaf, qual, params,
                                         module_names)
                    if v is not None:
                        out.append(v)
        return out

    @staticmethod
    def _flatten(t: ast.AST):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from TracerLeak._flatten(e)
        else:
            yield t

    def _bad_target(self, mod, t, qual, params, module_names):
        if isinstance(t, ast.Attribute):
            base = t.value
            if isinstance(base, ast.Name) and (
                base.id == "self" or base.id in params
            ):
                who = ("self" if base.id == "self"
                       else f"parameter '{base.id}'")
                return Violation(
                    rule=self.name, path=mod.path, line=t.lineno,
                    col=t.col_offset, symbol=qual, key=f"attr:{t.attr}",
                    message=(
                        f"attribute write {base.id}.{t.attr} inside a "
                        f"traced body stores a tracer on {who}; jitted "
                        "steps must stay purely functional (return the "
                        "new value in the carry)"
                    ),
                )
        if isinstance(t, ast.Subscript):
            base = dotted_name(t.value)
            if base and base.split(".", 1)[0] in module_names:
                return Violation(
                    rule=self.name, path=mod.path, line=t.lineno,
                    col=t.col_offset, symbol=qual, key=f"global:{base}",
                    message=(
                        f"subscript store into module-level '{base}' "
                        "inside a traced body runs at trace time only "
                        "(and can capture tracers); mutate it from host "
                        "code outside the jit"
                    ),
                )
        return None

    @staticmethod
    def _module_level_names(tree: ast.Module) -> set[str]:
        names: set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for leaf in TracerLeak._flatten(t):
                        if isinstance(leaf, ast.Name):
                            names.add(leaf.id)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                names.add(node.target.id)
        return names
