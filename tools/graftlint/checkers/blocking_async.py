"""blocking-in-async: no blocking work on the event loop.

One aiohttp event loop serves every stream; a single blocking call in a
handler stalls ALL of them for its duration (and an SSE consumer sees
it as a cross-request inter-token latency spike no metric attributes
correctly). Device syncs are the worst offenders: ``jax.device_get``/
``.block_until_ready()`` park the thread until the chip finishes —
that's the engine thread's job, never the handler's. The sanctioned
shapes are the engine's queue bridge (``loop.call_soon_threadsafe`` +
``await q.get()``) and ``run_in_executor`` for CPU-bound work (the
embeddings/scoring handlers).

Flags, lexically inside any ``async def`` (nested sync helpers
included — they run on the loop when called inline):

- ``time.sleep`` (asyncio.sleep exists for a reason);
- blocking device syncs: ``jax.device_get``, ``.block_until_ready()``,
  ``jax.block_until_ready``;
- sync subprocess/network/file I/O: ``subprocess.*``, ``os.system``,
  ``requests.*``, ``urllib.request.*``, ``socket.create_connection``,
  bare ``open()``;
- un-awaited ``.result()``/``.wait()`` method calls — the
  ``concurrent.futures``/``threading`` blocking waits; their awaited
  twins (``await stop.wait()`` on an asyncio.Event) are the async
  primitives and are exempt.

Functions only DEFINED in an async scope and handed to
``run_in_executor``/``asyncio.to_thread`` run off-loop; flag-free by
suppression if a checker false-positive ever matters (none today).
"""

from __future__ import annotations

import ast

from tools.graftlint.core import (
    Checker,
    Project,
    Violation,
    call_name,
    walk_functions,
)

BLOCKING_EXACT = {
    "time.sleep", "os.system", "jax.device_get", "jax.block_until_ready",
    "socket.create_connection", "open",
}
BLOCKING_PREFIXES = (
    "subprocess.", "requests.", "urllib.request.",
)
BLOCKING_METHODS = {"block_until_ready"}
#: method names that block only in their SYNC form — exempt when the
#: call is directly awaited (asyncio.Event.wait / asyncio futures)
BLOCKING_UNLESS_AWAITED = {"result", "wait"}


class BlockingInAsync(Checker):
    name = "blocking-in-async"
    description = (
        "time.sleep, blocking device syncs, or sync I/O inside "
        "async def handlers"
    )

    def run(self, project: Project) -> list[Violation]:
        out: list[Violation] = []
        for mod in project.modules:
            for func, qual, _cls in walk_functions(mod.tree):
                if not isinstance(func, ast.AsyncFunctionDef):
                    continue
                out.extend(self._check_async(mod, func, qual))
        return out

    def _check_async(self, mod, func, qual) -> list[Violation]:
        # exempt the ASYNC forms of result/wait: directly awaited calls
        # (await stop.wait() on an asyncio.Event) and the
        # coroutine-returning ``.wait()`` handed straight to a
        # scheduler (asyncio.create_task(ev.wait())). ``.result()`` is
        # never coroutine-returning, so nesting it inside an asyncio.*
        # call (asyncio.gather(fut.result())) still evaluates — and
        # blocks — eagerly on the loop, and stays flagged.
        awaited = {
            id(n.value) for n in ast.walk(func)
            if isinstance(n, ast.Await)
        }
        schedulers = {
            "asyncio.create_task", "asyncio.ensure_future",
            "asyncio.wait_for", "asyncio.shield", "asyncio.gather",
        }
        for n in ast.walk(func):
            if isinstance(n, ast.Call) and call_name(n) in schedulers:
                awaited.update(
                    id(a) for a in n.args
                    if isinstance(a, ast.Call)
                    and isinstance(a.func, ast.Attribute)
                    and a.func.attr == "wait"
                )
        out: list[Violation] = []
        for node in self._walk_loop_code(func):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            hit = None
            if name in BLOCKING_EXACT:
                hit = name
            elif any(name.startswith(p) for p in BLOCKING_PREFIXES):
                hit = name
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in BLOCKING_METHODS:
                hit = f"(...).{node.func.attr}"
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in BLOCKING_UNLESS_AWAITED
                    and id(node) not in awaited):
                hit = f"(...).{node.func.attr}"
            if hit is None:
                continue
            out.append(Violation(
                rule=self.name, path=mod.path, line=node.lineno,
                col=node.col_offset, symbol=qual, key=hit,
                message=(
                    f"{hit}() blocks the event loop: every concurrent "
                    "request stalls behind it — await the async "
                    "equivalent, or push it through "
                    "loop.run_in_executor (the embeddings-handler "
                    "pattern)"
                ),
            ))
        return out

    @staticmethod
    def _walk_loop_code(func):
        """Everything lexically in the async def, descending into
        nested SYNC defs (they run on the loop when called inline) but
        not nested ASYNC defs (checked as their own contexts)."""
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            if isinstance(node, ast.AsyncFunctionDef):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
