"""Checker registry: one module per project invariant.

Order is the report order (hot-path and thread-ownership first: those
are the two rules contractually running with an empty baseline)."""

from tools.graftlint.checkers.hot_path_h2d import HotPathH2D
from tools.graftlint.checkers.thread_ownership import ThreadOwnership
from tools.graftlint.checkers.tracer_leak import TracerLeak
from tools.graftlint.checkers.jit_recompile import JitRecompileHazard
from tools.graftlint.checkers.refcount_pairing import RefcountPairing
from tools.graftlint.checkers.blocking_async import BlockingInAsync

ALL_CHECKERS = [
    HotPathH2D(),
    ThreadOwnership(),
    TracerLeak(),
    JitRecompileHazard(),
    RefcountPairing(),
    BlockingInAsync(),
]
